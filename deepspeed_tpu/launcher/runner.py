"""Multi-node launcher CLI — `dstpu` (the reference's `deepspeed`/`ds` CLI).

Capability parity with ``deepspeed/launcher/runner.py`` (hostfile parsing,
--include/--exclude filters, single-node exec, multi-node per-host dispatch)
re-targeted at TPU pods: instead of forking one process per GPU with
RANK/LOCAL_RANK env (launch.py:129), TPU hosts run ONE process per host and
`jax.distributed.initialize` wires the multi-host runtime (the per-host device
set is what the reference calls the local world). Remote dispatch uses ssh
(the reference's PDSH runner, multinode_runner.py:45) built as an argv list.

Hostfile syntax is the reference's:
    worker-1 slots=4
    worker-2 slots=4
and --include/--exclude use `host:slot1,slot2@host2:...` filters
(runner.py:386-418).
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import shlex
import sys
from collections import OrderedDict
from typing import Dict, List, Optional

DSTPU_ENV_FILE = ".deepspeed_env"


def parse_hostfile(lines) -> "OrderedDict[str, int]":
    """'host slots=N' per line -> {host: N}; '#' comments allowed."""
    resource_pool: "OrderedDict[str, int]" = OrderedDict()
    for raw in lines:
        line = raw.split("#")[0].strip()
        if not line:
            continue
        try:
            host, slots_str = line.split()
            key, val = slots_str.split("=")
            if key != "slots":
                raise ValueError
            slots = int(val)
        except ValueError:
            raise ValueError(f"invalid hostfile line: {raw!r} "
                             "(expected 'hostname slots=N')")
        if host in resource_pool:
            raise ValueError(f"duplicate host {host} in hostfile")
        resource_pool[host] = slots
    return resource_pool


def fetch_hostfile(path: Optional[str]) -> "OrderedDict[str, int]":
    if not path or not os.path.isfile(path):
        return OrderedDict()
    with open(path) as f:
        return parse_hostfile(f)


def _parse_filter(s: str) -> Dict[str, Optional[List[int]]]:
    """'host1:0,2@host2' -> {host1: [0,2], host2: None (all slots)}."""
    out: Dict[str, Optional[List[int]]] = {}
    for part in s.split("@"):
        if not part:
            continue
        if ":" in part:
            host, slots = part.split(":")
            out[host] = [int(x) for x in slots.split(",") if x != ""]
        else:
            out[part] = None
    return out


def parse_inclusion_exclusion(resource_pool: Dict[str, int],
                              include_str: str = "",
                              exclude_str: str = "") -> "OrderedDict[str, List[int]]":
    """Apply --include/--exclude filters (reference: parse_resource_filter)."""
    active: "OrderedDict[str, List[int]]" = OrderedDict(
        (h, list(range(n))) for h, n in resource_pool.items())
    if include_str and exclude_str:
        raise ValueError("--include and --exclude are mutually exclusive")
    if include_str:
        wanted = _parse_filter(include_str)
        for h in wanted:
            if h not in active:
                raise ValueError(f"included host {h} not in hostfile")
        active = OrderedDict(
            (h, wanted[h] if wanted[h] is not None else list(range(resource_pool[h])))
            for h in wanted)
        for h, slots in active.items():
            bad = [s for s in slots if s >= resource_pool[h]]
            if bad:
                raise ValueError(f"host {h} has no slots {bad}")
    elif exclude_str:
        banned = _parse_filter(exclude_str)
        for h, slots in banned.items():
            if h not in active:
                raise ValueError(f"excluded host {h} not in hostfile")
            if slots is None:
                del active[h]
            else:
                active[h] = [s for s in active[h] if s not in slots]
                if not active[h]:
                    del active[h]
    return active


def encode_world_info(active: Dict[str, List[int]]) -> str:
    return base64.urlsafe_b64encode(
        json.dumps(active).encode()).decode()


def decode_world_info(blob: str) -> Dict[str, List[int]]:
    return json.loads(base64.urlsafe_b64decode(blob.encode()).decode())


def build_launch_cmd(host_idx: int, num_hosts: int, coordinator: str,
                     port: int, world_info: str, user_script: str,
                     user_args: List[str]) -> List[str]:
    """Per-host command: one process per host; jax.distributed wires chips."""
    return [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
            f"--node_rank={host_idx}",
            f"--nnodes={num_hosts}",
            f"--coordinator={coordinator}:{port}",
            f"--world_info={world_info}",
            user_script] + list(user_args)


def build_ssh_cmd(host: str, remote_cmd: List[str],
                  env_exports: Dict[str, str],
                  connect_timeout: int = 15) -> List[str]:
    """ssh argv for one rank. ``-o ConnectTimeout`` bounds the connect
    phase (a dead host fails fast instead of hanging the dispatch), and
    the remote shell prints the supervisor's started sentinel BEFORE
    exec'ing the bootstrap — the line that marks this rank non-retryable
    (see supervisor.STARTED_SENTINEL)."""
    from .supervisor import STARTED_SENTINEL
    exports = " ".join(f"export {k}={shlex.quote(v)};"
                       for k, v in env_exports.items())
    return ["ssh", "-o", "StrictHostKeyChecking=no",
            "-o", f"ConnectTimeout={int(connect_timeout)}", host,
            f"cd {shlex.quote(os.getcwd())}; {exports} "
            f"echo {STARTED_SENTINEL}; exec " +
            " ".join(shlex.quote(c) for c in remote_cmd)]


def collect_env_exports() -> Dict[str, str]:
    """Env vars forwarded to workers (reference: runner.py:508-563 exports
    NCCL_*/PYTHON* + .deepspeed_env file). The DSTPU_ prefix carries the
    launcher's own contract — coordinator overrides, DSTPU_CHAOS fault
    specs, DSTPU_INIT_TIMEOUT — which previously never reached remote
    hosts."""
    exports = {}
    for key, val in os.environ.items():
        if key.startswith(("JAX_", "XLA_", "TPU_", "LIBTPU_", "DSTPU_",
                           "PYTHONPATH")):
            exports[key] = val
    if os.path.isfile(DSTPU_ENV_FILE):
        with open(DSTPU_ENV_FILE) as f:
            for line in f:
                line = line.strip()
                if line and "=" in line and not line.startswith("#"):
                    k, v = line.split("=", 1)
                    exports[k] = v
    return exports


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="dstpu", description="deepspeed_tpu multi-host launcher")
    p.add_argument("-H", "--hostfile", default="/job/hostfile")
    p.add_argument("-i", "--include", default="")
    p.add_argument("-e", "--exclude", default="")
    p.add_argument("--num_nodes", type=int, default=-1)
    p.add_argument("--master_port", type=int, default=29500)
    p.add_argument("--master_addr", default="")
    p.add_argument("--launcher", default="ssh",
                   choices=["ssh", "local", "pdsh", "openmpi", "slurm",
                            "mvapich"])
    p.add_argument("--autotuning", default="", choices=["", "run", "tune"],
                   help="search ds_configs instead of launching directly "
                        "(reference: deepspeed --autotuning)")
    p.add_argument("--deepspeed_config", default="",
                   help="base ds_config for --autotuning mode")
    # -- run supervision (round-4; docs/RESILIENCE.md) -----------------------
    p.add_argument("--elastic", action="store_true",
                   help="supervise the run under DSElasticAgent: relaunch "
                        "on membership change, resume (uncounted) on "
                        "preemption rc 114, restart crashed/stalled runs "
                        "up to --max-restarts")
    p.add_argument("--max-restarts", "--max_restarts", type=int, default=100,
                   dest="max_restarts",
                   help="elastic: crash/stall restart budget (preemptions "
                        "are not counted)")
    p.add_argument("--min-nodes", "--min_nodes", type=int, default=1,
                   dest="min_nodes",
                   help="elastic: wait until the hostfile lists at least "
                        "this many nodes before (re)launching")
    p.add_argument("--check-interval", "--check_interval", type=float,
                   default=1.0, dest="check_interval",
                   help="elastic: hostfile/worker poll interval, seconds")
    p.add_argument("--grace-secs", "--grace_secs", type=float, default=30.0,
                   dest="grace_secs",
                   help="teardown grace: SIGTERM -> this many seconds (the "
                        "preemption handlers' checkpoint window) -> SIGKILL")
    p.add_argument("--connect-retries", "--connect_retries", type=int,
                   default=3, dest="connect_retries",
                   help="retries for ssh CONNECT-phase failures (a rank "
                        "that started user code is never retried)")
    p.add_argument("--connect-timeout", "--connect_timeout", type=int,
                   default=15, dest="connect_timeout",
                   help="ssh -o ConnectTimeout per dispatch attempt")
    p.add_argument("--log-dir", "--log_dir", default="", dest="log_dir",
                   help="persist each rank's prefixed stdout/stderr to "
                        "<log_dir>/<host>.rank<k>.log alongside the live "
                        "prefixed stream (local ranks switch to captured "
                        "pipes); truncated per run, appended across "
                        "connect retries. Scheduler backends "
                        "(pdsh/slurm) demultiplex their merged stream by "
                        "the per-rank prefix into <log_dir>/<key>.log")
    # -- heartbeat channel (round-6; docs/RESILIENCE.md) ---------------------
    p.add_argument("--heartbeat-dir", "--heartbeat_dir", default="",
                   dest="heartbeat_dir",
                   help="shared directory for per-rank liveness records "
                        "(exported to workers as DSTPU_HEARTBEAT_DIR); "
                        "enables launcher-side per-rank liveness on EVERY "
                        "backend incl. pdsh/slurm/openmpi, blacklist-"
                        "driven degraded resume under --elastic, and "
                        "`dstpu health <dir>`")
    p.add_argument("--heartbeat-timeout", "--heartbeat_timeout", type=float,
                   default=0.0, dest="heartbeat_timeout",
                   help="seconds of heartbeat silence (a rank that stops "
                        "attesting liveness) before the supervisor tears "
                        "the launch down as a stall (rc 117); 0 disables "
                        "silence detection (records still written)")
    p.add_argument("user_script")
    p.add_argument("user_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def health_main(argv) -> int:
    """``dstpu health <heartbeat-dir>`` — the operator's one-glance pod
    view: per-rank phase, step, RATE (the rolling step_ms wall-time
    gauge, round 15 — '-' for records predating it), record age, host,
    pid, pipeline STAGE (MPMD stage workers stamp it, round 13), phase
    GAUGES (SERVE stamps queue-depth / active-lane load) and
    integrity/straggler FLAGS from the
    heartbeat channel. Works on a serving fleet's per-replica channel
    (serving/fleet.py) and an MPMD pipeline's per-stage channel
    (runtime/pipe/mpmd) exactly as on a training world's per-rank one. Exit 0 when every rank is live or
    concluded cleanly, 1 when any rank's last word is STALLED, any rank
    carries an integrity flag (e.g. ``SDC`` — its host's numbers cannot
    be trusted), or the channel is empty (nothing attesting = nothing
    provably alive)."""
    import time as _time
    from ..runtime import heartbeat as hb
    from ..runtime.straggler import STRAGGLER_FLAG
    p = argparse.ArgumentParser(prog="dstpu health")
    p.add_argument("heartbeat_dir")
    p.add_argument("--stale-after", type=float, default=60.0,
                   help="flag records older than this many seconds")
    a = p.parse_args(argv)
    records = hb.read_heartbeats(a.heartbeat_dir)
    if not records:
        print(f"no heartbeat records under {a.heartbeat_dir}")
        return 1
    now = _time.time()
    rows = [("RANK", "STAGE", "HOST", "PHASE", "STEP", "RATE", "AGE", "PID",
             "GAUGES", "FLAGS", "")]
    bad = False
    for rank in sorted(records):
        rec = records[rank]
        age = hb.record_age(rec, now)
        phase = str(rec.get("phase"))
        # phase-specific load gauges (SERVE: queue depth / active lanes)
        # so a serving rank's health line answers "how loaded", not just
        # "alive" — a fleet replica pinned at queue>0 active=0 is wedged
        # admission, visible here before any timeout fires
        gauges = rec.get("gauges") or {}
        # pipeline STAGE (MPMD stage workers stamp it, round 13 —
        # mirrors the round-12 role=PREFILL/DECODE gauge): its own
        # column, because "which stage died" is the first question a
        # pipeline operator asks
        stage = gauges.get("stage")
        stage_txt = str(stage) if stage is not None else "-"
        # RATE: the rolling per-step wall-time gauge (round 15,
        # runtime/straggler.py) — the one-glance answer to "is this rank
        # DRAGGING the synchronous world" that liveness alone can never
        # give. '-' for records predating the gauge; rc semantics
        # unchanged (a slow rank is the straggler detector's verdict to
        # make, not this view's)
        step_ms = gauges.get("step_ms")
        rate_txt = f"{float(step_ms):.0f}ms" if step_ms is not None else "-"
        gtxt = ",".join(f"{k}={gauges[k]}" for k in sorted(gauges)
                        if k not in ("stage", "step_ms")) or "-"
        flags = ",".join(rec.get("flags") or ()) or "-"
        note = ""
        if phase == hb.PHASE_STALLED:
            note, bad = "wedged (rc 117)", True
        elif phase == hb.PHASE_PREEMPTED:
            note = "preempted (rc 114)"
        elif phase == hb.PHASE_EXIT:
            # a flagged EXIT is a concluded integrity ABORT, not a clean run
            note = "" if rec.get("flags") else "clean exit"
        elif age > a.stale_after:
            note, bad = f"SILENT > {a.stale_after:.0f}s", True
        rec_flags = rec.get("flags") or []
        if STRAGGLER_FLAG in rec_flags:
            # a slow host is operator news even while alive and stepping
            note = (note + "; " if note else "") + "straggler (slow host)"
            bad = True
        if any(f != STRAGGLER_FLAG for f in rec_flags):
            note = (note + "; " if note else "") + "integrity flags (rc 118)"
            bad = True
        rows.append((str(rank), stage_txt, str(rec.get("host")), phase,
                     str(rec.get("step")), rate_txt, f"{age:.1f}s",
                     str(rec.get("pid")), gtxt, flags, note))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    for r in rows:
        print("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
    return 1 if bad else 0


def main(argv=None):
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "health":
        sys.exit(health_main(argv[1:]))
    if argv and argv[0] == "comm-plan":
        # record collective sweeps / select + inspect comm plans
        # (docs/COMM.md; consumed by the comm_plan config section)
        from ..comm_plan.cli import main as comm_plan_main
        sys.exit(comm_plan_main(argv[1:]))
    args = parse_args(argv)
    if args.autotuning:
        if not args.deepspeed_config:
            sys.exit("--autotuning requires --deepspeed_config")
        from ..autotuning.cli import main as autotune_main
        results_dir = "autotuning_results"
        autotune_main(["--config", args.deepspeed_config,
                       "--results-dir", results_dir, "--",
                       sys.executable, args.user_script] + args.user_args)
        if args.autotuning == "run":
            # tune-then-train: relaunch the script with the winning config
            # (reference: --autotuning run vs tune distinction)
            best = os.path.join(results_dir, "best_config.json")
            cmd = [sys.executable, args.user_script] + args.user_args + \
                ["--deepspeed_config", best]
            os.execvpe(cmd[0], cmd, os.environ.copy())
        return
    if args.elastic:
        sys.exit(run_elastic(args))
    pool = fetch_hostfile(args.hostfile)
    if not pool:
        # single node, all local chips
        cmd = [sys.executable, args.user_script] + args.user_args
        os.execvpe(cmd[0], cmd, os.environ.copy())
        return
    active = parse_inclusion_exclusion(pool, args.include, args.exclude)
    if args.num_nodes > 0:
        active = OrderedDict(list(active.items())[:args.num_nodes])
    exports = collect_env_exports()
    _apply_heartbeat_exports(args, exports)
    if args.launcher in ("pdsh", "openmpi", "slurm", "mvapich"):
        # scheduler backends run as ONE process, but no longer as an
        # UNSUPERVISED one: BackendSupervisor adds heartbeat-driven
        # per-rank liveness, the backend's own kill path on first
        # confirmed failure, and rc 114/117 reconstruction (round 6)
        sys.exit(build_backend_supervisor(active, args, exports).run())
    # ssh/local: concurrent per-rank supervision — first failure tears the
    # world down, connect failures retry, rc 114 survives aggregation
    # (reference: launch.py terminate_process_tree, rebuilt fail-fast)
    sys.exit(build_world_supervisor(active, args, exports).run())


def _apply_heartbeat_exports(args, exports: Dict[str, str]) -> None:
    """--heartbeat-dir reaches every worker as DSTPU_HEARTBEAT_DIR (the
    DSTPU_ prefix already rides collect_env_exports to remote hosts) and
    the launcher's own environment (loopback ranks + backend schedulers
    inherit it)."""
    hb_dir = getattr(args, "heartbeat_dir", "") or ""
    if not hb_dir:
        return
    hb_dir = os.path.abspath(hb_dir)
    os.makedirs(hb_dir, exist_ok=True)
    exports["DSTPU_HEARTBEAT_DIR"] = hb_dir
    os.environ["DSTPU_HEARTBEAT_DIR"] = hb_dir


def _backend_runner_env(args, active, exports):
    """(runner, env) for a scheduler backend launch."""
    from .multinode_runner import build_runner
    hosts = list(active)
    coordinator = args.master_addr or hosts[0]
    world_info = encode_world_info(active)
    runner = build_runner(args.launcher, args, world_info)
    if not runner.backend_exists():
        sys.exit(f"launcher backend '{args.launcher}' not found in PATH")
    env = {"DSTPU_WORLD_INFO": world_info,
           "DSTPU_COORDINATOR": coordinator,
           "DSTPU_MASTER_PORT": str(args.master_port), **exports}
    return runner, env


def _backend_cmd(args, active, exports) -> List[str]:
    """ONE scheduler command — the backend fans out itself (reference:
    multinode_runner.py get_cmd per backend)."""
    runner, env = _backend_runner_env(args, active, exports)
    return runner.get_cmd(env, active)


def build_backend_supervisor(active: "OrderedDict[str, List[int]]", args,
                             exports: Dict[str, str]):
    """A not-yet-started BackendSupervisor over one scheduler command,
    wired with the backend's own kill path and output routing."""
    from .supervisor import BackendSupervisor
    runner, env = _backend_runner_env(args, active, exports)
    hosts = {h: active[h] for h in active}
    return BackendSupervisor(
        runner.get_cmd(env, hosts),
        kill_cmd=runner.get_kill_cmd(env, hosts),
        heartbeat_dir=getattr(args, "heartbeat_dir", "") or None,
        heartbeat_timeout=getattr(args, "heartbeat_timeout", 0.0),
        grace_secs=getattr(args, "grace_secs", 30.0),
        log_dir=getattr(args, "log_dir", "") or None,
        route_line=runner.route_line,
        backend=runner.name,
        rank_hosts=list(hosts))


_LOCAL_HOSTS = ("localhost", "127.0.0.1", "::1")


def build_world_supervisor(active: "OrderedDict[str, List[int]]", args,
                           exports: Dict[str, str]):
    """A started-but-not-yet-running RunSupervisor over the active world:
    one RankSpec per host (ssh dispatch unless --launcher local or the
    host is loopback)."""
    from .supervisor import RankSpec, RunSupervisor
    hosts = list(active)
    coordinator = args.master_addr or hosts[0]
    world_info = encode_world_info(active)
    specs = []
    for idx, host in enumerate(hosts):
        remote_cmd = build_launch_cmd(idx, len(hosts), coordinator,
                                      args.master_port, world_info,
                                      args.user_script, args.user_args)
        if args.launcher == "local" or host in _LOCAL_HOSTS:
            # exports (incl. .deepspeed_env entries that may not be in the
            # launcher's own environ) still reach loopback ranks, which
            # have no ssh command line to carry them
            specs.append(RankSpec(host, remote_cmd, remote=False,
                                  env=exports))
        else:
            specs.append(RankSpec(
                host,
                build_ssh_cmd(host, remote_cmd, exports,
                              connect_timeout=args.connect_timeout),
                remote=True))
    return RunSupervisor(specs,
                         grace_secs=args.grace_secs,
                         connect_retries=args.connect_retries,
                         log_dir=getattr(args, "log_dir", "") or None,
                         heartbeat_dir=getattr(args, "heartbeat_dir", "")
                         or None,
                         heartbeat_timeout=getattr(args, "heartbeat_timeout",
                                                   0.0))


def elastic_active_world(args, members: List[str]
                         ) -> "OrderedDict[str, List[int]]":
    """The world one elastic (re)launch covers: the agent's confirmed
    membership, narrowed by the same --include/--exclude/--num_nodes
    filters the non-elastic path applies (an operator excluding a flaky
    host must stay excluded across every relaunch)."""
    pool = fetch_hostfile(args.hostfile)
    if pool:
        filtered = parse_inclusion_exclusion(pool, args.include,
                                             args.exclude)
    else:
        # no/unreadable hostfile: the agent already fell back to localhost
        filtered = OrderedDict((h, [0]) for h in members)
    active = OrderedDict(
        (h, filtered[h]) for h in members if h in filtered)
    if args.num_nodes > 0:
        active = OrderedDict(list(active.items())[:args.num_nodes])
    return active


def run_elastic(args) -> int:
    """dstpu --elastic: DSElasticAgent supervising the RunSupervisor (ssh/
    local) or the BackendSupervisor (scheduler backends — same facade,
    same rc contract since round 6).

    The agent polls the hostfile and relaunches on membership change; the
    rc contract does the rest — 114 (preemption) resumes without touching
    --max-restarts, the stall rc and crashes count against it. With a
    heartbeat channel the agent also quarantines repeatedly-failing hosts
    and re-forms a SMALLER world from the survivors (degraded resume),
    publishing it to <hostfile>.active — which is also the hostfile the
    scheduler backends launch over, so a blacklisted host leaves their
    worlds too."""
    from ..elasticity.elastic_agent import DSElasticAgent

    active_hostfile = (args.hostfile + ".active"
                       if os.path.isfile(args.hostfile) else None)

    def launch(members):
        active = elastic_active_world(args, members)
        if not active:
            sys.exit("dstpu --elastic: every confirmed member is excluded "
                     "by --include/--exclude; nothing to launch")
        exports = collect_env_exports()
        _apply_heartbeat_exports(args, exports)
        if args.launcher in ("pdsh", "openmpi", "slurm", "mvapich"):
            backend_args = args
            if active_hostfile and os.path.isfile(active_hostfile):
                # the scheduler must fan out over the DEGRADED world, not
                # the operator's full hostfile
                backend_args = argparse.Namespace(**vars(args))
                backend_args.hostfile = active_hostfile
            return build_backend_supervisor(active, backend_args,
                                            exports).start()
        return build_world_supervisor(active, args, exports).start()

    agent = DSElasticAgent(launch, args.hostfile,
                           max_restarts=args.max_restarts,
                           min_nodes=args.min_nodes,
                           check_interval=args.check_interval,
                           teardown_grace=args.grace_secs,
                           heartbeat_dir=getattr(args, "heartbeat_dir", "")
                           or None,
                           heartbeat_timeout=getattr(args,
                                                     "heartbeat_timeout",
                                                     0.0),
                           active_hostfile=active_hostfile)
    return agent.run()


if __name__ == "__main__":
    main()
