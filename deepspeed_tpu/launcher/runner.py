"""Multi-node launcher CLI — `dstpu` (the reference's `deepspeed`/`ds` CLI).

Capability parity with ``deepspeed/launcher/runner.py`` (hostfile parsing,
--include/--exclude filters, single-node exec, multi-node per-host dispatch)
re-targeted at TPU pods: instead of forking one process per GPU with
RANK/LOCAL_RANK env (launch.py:129), TPU hosts run ONE process per host and
`jax.distributed.initialize` wires the multi-host runtime (the per-host device
set is what the reference calls the local world). Remote dispatch uses ssh
(the reference's PDSH runner, multinode_runner.py:45) built as an argv list.

Hostfile syntax is the reference's:
    worker-1 slots=4
    worker-2 slots=4
and --include/--exclude use `host:slot1,slot2@host2:...` filters
(runner.py:386-418).
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import shlex
import subprocess
import sys
from collections import OrderedDict
from typing import Dict, List, Optional

DSTPU_ENV_FILE = ".deepspeed_env"


def parse_hostfile(lines) -> "OrderedDict[str, int]":
    """'host slots=N' per line -> {host: N}; '#' comments allowed."""
    resource_pool: "OrderedDict[str, int]" = OrderedDict()
    for raw in lines:
        line = raw.split("#")[0].strip()
        if not line:
            continue
        try:
            host, slots_str = line.split()
            key, val = slots_str.split("=")
            if key != "slots":
                raise ValueError
            slots = int(val)
        except ValueError:
            raise ValueError(f"invalid hostfile line: {raw!r} "
                             "(expected 'hostname slots=N')")
        if host in resource_pool:
            raise ValueError(f"duplicate host {host} in hostfile")
        resource_pool[host] = slots
    return resource_pool


def fetch_hostfile(path: Optional[str]) -> "OrderedDict[str, int]":
    if not path or not os.path.isfile(path):
        return OrderedDict()
    with open(path) as f:
        return parse_hostfile(f)


def _parse_filter(s: str) -> Dict[str, Optional[List[int]]]:
    """'host1:0,2@host2' -> {host1: [0,2], host2: None (all slots)}."""
    out: Dict[str, Optional[List[int]]] = {}
    for part in s.split("@"):
        if not part:
            continue
        if ":" in part:
            host, slots = part.split(":")
            out[host] = [int(x) for x in slots.split(",") if x != ""]
        else:
            out[part] = None
    return out


def parse_inclusion_exclusion(resource_pool: Dict[str, int],
                              include_str: str = "",
                              exclude_str: str = "") -> "OrderedDict[str, List[int]]":
    """Apply --include/--exclude filters (reference: parse_resource_filter)."""
    active: "OrderedDict[str, List[int]]" = OrderedDict(
        (h, list(range(n))) for h, n in resource_pool.items())
    if include_str and exclude_str:
        raise ValueError("--include and --exclude are mutually exclusive")
    if include_str:
        wanted = _parse_filter(include_str)
        for h in wanted:
            if h not in active:
                raise ValueError(f"included host {h} not in hostfile")
        active = OrderedDict(
            (h, wanted[h] if wanted[h] is not None else list(range(resource_pool[h])))
            for h in wanted)
        for h, slots in active.items():
            bad = [s for s in slots if s >= resource_pool[h]]
            if bad:
                raise ValueError(f"host {h} has no slots {bad}")
    elif exclude_str:
        banned = _parse_filter(exclude_str)
        for h, slots in banned.items():
            if h not in active:
                raise ValueError(f"excluded host {h} not in hostfile")
            if slots is None:
                del active[h]
            else:
                active[h] = [s for s in active[h] if s not in slots]
                if not active[h]:
                    del active[h]
    return active


def encode_world_info(active: Dict[str, List[int]]) -> str:
    return base64.urlsafe_b64encode(
        json.dumps(active).encode()).decode()


def decode_world_info(blob: str) -> Dict[str, List[int]]:
    return json.loads(base64.urlsafe_b64decode(blob.encode()).decode())


def build_launch_cmd(host_idx: int, num_hosts: int, coordinator: str,
                     port: int, world_info: str, user_script: str,
                     user_args: List[str]) -> List[str]:
    """Per-host command: one process per host; jax.distributed wires chips."""
    return [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
            f"--node_rank={host_idx}",
            f"--nnodes={num_hosts}",
            f"--coordinator={coordinator}:{port}",
            f"--world_info={world_info}",
            user_script] + list(user_args)


def build_ssh_cmd(host: str, remote_cmd: List[str],
                  env_exports: Dict[str, str]) -> List[str]:
    exports = " ".join(f"export {k}={shlex.quote(v)};"
                       for k, v in env_exports.items())
    return ["ssh", "-o", "StrictHostKeyChecking=no", host,
            f"cd {shlex.quote(os.getcwd())}; {exports} " +
            " ".join(shlex.quote(c) for c in remote_cmd)]


def collect_env_exports() -> Dict[str, str]:
    """Env vars forwarded to workers (reference: runner.py:508-563 exports
    NCCL_*/PYTHON* + .deepspeed_env file)."""
    exports = {}
    for key, val in os.environ.items():
        if key.startswith(("JAX_", "XLA_", "TPU_", "LIBTPU_", "PYTHONPATH")):
            exports[key] = val
    if os.path.isfile(DSTPU_ENV_FILE):
        with open(DSTPU_ENV_FILE) as f:
            for line in f:
                line = line.strip()
                if line and "=" in line and not line.startswith("#"):
                    k, v = line.split("=", 1)
                    exports[k] = v
    return exports


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="dstpu", description="deepspeed_tpu multi-host launcher")
    p.add_argument("-H", "--hostfile", default="/job/hostfile")
    p.add_argument("-i", "--include", default="")
    p.add_argument("-e", "--exclude", default="")
    p.add_argument("--num_nodes", type=int, default=-1)
    p.add_argument("--master_port", type=int, default=29500)
    p.add_argument("--master_addr", default="")
    p.add_argument("--launcher", default="ssh",
                   choices=["ssh", "local", "pdsh", "openmpi", "slurm",
                            "mvapich"])
    p.add_argument("--autotuning", default="", choices=["", "run", "tune"],
                   help="search ds_configs instead of launching directly "
                        "(reference: deepspeed --autotuning)")
    p.add_argument("--deepspeed_config", default="",
                   help="base ds_config for --autotuning mode")
    p.add_argument("user_script")
    p.add_argument("user_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    if args.autotuning:
        if not args.deepspeed_config:
            sys.exit("--autotuning requires --deepspeed_config")
        from ..autotuning.cli import main as autotune_main
        results_dir = "autotuning_results"
        autotune_main(["--config", args.deepspeed_config,
                       "--results-dir", results_dir, "--",
                       sys.executable, args.user_script] + args.user_args)
        if args.autotuning == "run":
            # tune-then-train: relaunch the script with the winning config
            # (reference: --autotuning run vs tune distinction)
            best = os.path.join(results_dir, "best_config.json")
            cmd = [sys.executable, args.user_script] + args.user_args + \
                ["--deepspeed_config", best]
            os.execvpe(cmd[0], cmd, os.environ.copy())
        return
    pool = fetch_hostfile(args.hostfile)
    if not pool:
        # single node, all local chips
        cmd = [sys.executable, args.user_script] + args.user_args
        os.execvpe(cmd[0], cmd, os.environ.copy())
        return
    active = parse_inclusion_exclusion(pool, args.include, args.exclude)
    if args.num_nodes > 0:
        active = OrderedDict(list(active.items())[:args.num_nodes])
    hosts = list(active)
    coordinator = args.master_addr or hosts[0]
    world_info = encode_world_info(active)
    exports = collect_env_exports()
    if args.launcher in ("pdsh", "openmpi", "slurm", "mvapich"):
        # backend fans out itself — ONE scheduler command (reference:
        # multinode_runner.py get_cmd per backend)
        from .multinode_runner import build_runner
        runner = build_runner(args.launcher, args, world_info)
        if not runner.backend_exists():
            sys.exit(f"launcher backend '{args.launcher}' not found in PATH")
        env = {"DSTPU_WORLD_INFO": world_info,
               "DSTPU_COORDINATOR": coordinator,
               "DSTPU_MASTER_PORT": str(args.master_port), **exports}
        cmd = runner.get_cmd(env, active)
        sys.exit(subprocess.call(cmd))
    procs = []
    for idx, host in enumerate(hosts):
        remote = build_launch_cmd(idx, len(hosts), coordinator,
                                  args.master_port, world_info,
                                  args.user_script, args.user_args)
        cmd = (remote if args.launcher == "local"
               else build_ssh_cmd(host, remote, exports))
        procs.append(subprocess.Popen(cmd))
    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    # kill stragglers if any rank failed (reference: launch.py
    # terminate_process_tree supervision)
    if rc:
        for p in procs:
            if p.poll() is None:
                p.terminate()
    sys.exit(rc)


if __name__ == "__main__":
    main()
