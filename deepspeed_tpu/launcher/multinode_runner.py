"""Multinode runners — pluggable remote-dispatch backends for dstpu.

Capability parity with the reference's ``launcher/multinode_runner.py``
(MultiNodeRunner ABC + PDSH/OpenMPI/Slurm/MVAPICH runners building the
per-backend launch command). Each runner turns (environment exports, active
resource pool, user command) into ONE argv the scheduler executes; TPU hosts
run one process per host (jax.distributed wires ranks), so the per-GPU rank
plumbing of the reference collapses into node-level dispatch.

Round-6 supervision contract: besides ``get_cmd`` each runner now
describes its OWN teardown and observability surfaces so
``launcher.supervisor.BackendSupervisor`` can treat the scheduler like a
supervised world instead of an opaque Popen:

- ``get_kill_cmd``: the backend-native way to reach the REMOTE ranks
  (``scancel`` the allocation, ``pdsh -w ... pkill`` the bootstraps) —
  signaling the local scheduler process alone may orphan them;
- ``route_line``: demultiplex the scheduler's merged output stream into
  per-rank/host keys (``pdsh`` prefixes ``host:``, ``srun --label``
  prefixes ``taskid:``) for ``--log-dir`` persistence.
"""

from __future__ import annotations

import os
import re
import shlex
from abc import ABC, abstractmethod
from typing import Dict, List, Optional, Tuple


class MultiNodeRunner(ABC):
    name = "base"

    def __init__(self, args, world_info_base64: str = ""):
        self.args = args
        self.world_info_base64 = world_info_base64
        self.user_arguments = list(getattr(args, "user_args", []))
        self.user_script = getattr(args, "user_script", "")
        self.exports: Dict[str, str] = {}

    def add_export(self, key: str, var: str) -> None:
        self.exports[key.strip()] = str(var).strip()

    @abstractmethod
    def get_cmd(self, environment: Dict[str, str],
                active_resources: Dict[str, int]) -> List[str]:
        ...

    def backend_exists(self) -> bool:
        return True

    def get_kill_cmd(self, environment: Dict[str, str],
                     active_resources: Dict[str, int]
                     ) -> Optional[List[str]]:
        """Backend-native teardown argv reaching the REMOTE ranks, or
        None when signaling the scheduler process is already sufficient
        (mpirun propagates SIGTERM to its children)."""
        return None

    def route_line(self, line: str) -> Optional[Tuple[str, str]]:
        """(log key, payload) for one merged-output line, or None when
        this backend's stream carries no per-rank attribution."""
        return None

    def _user_cmd(self, environment: Dict[str, str],
                  active_resources: Dict[str, int]) -> List[str]:
        """Per-node bootstrap through launch.py (jax.distributed rendezvous;
        rank autodetected from the scheduler env or world_info hostname) —
        running the raw script would leave nnodes disconnected trainings."""
        import sys
        coordinator = environment.get("DSTPU_COORDINATOR", "localhost")
        port = environment.get("DSTPU_MASTER_PORT", "29500")
        return ([sys.executable, "-m", "deepspeed_tpu.launcher.launch",
                 "--node_rank=-1",
                 f"--nnodes={len(active_resources)}",
                 f"--coordinator={coordinator}:{port}",
                 f"--world_info={self.world_info_base64}",
                 self.user_script] + self.user_arguments)


#: the per-host bootstrap every backend dispatches — the pattern the
#: pdsh kill path pkills (killing the bootstrap tears down the user
#: script it exec'd into; matching the module name avoids collateral)
_BOOTSTRAP_PATTERN = "deepspeed_tpu.launcher.launch"


class PDSHRunner(MultiNodeRunner):
    """reference: multinode_runner.py:45 — pdsh fanout over the host list."""

    name = "pdsh"

    def backend_exists(self) -> bool:
        import shutil
        return shutil.which("pdsh") is not None

    def get_cmd(self, environment, active_resources):
        env_exports = "".join(
            f"export {k}={shlex.quote(v)}; "
            for k, v in {**environment, **self.exports}.items())
        hosts = ",".join(active_resources)
        return (["pdsh", "-S", "-f", "1024", "-w", hosts,
                 env_exports + "cd " + shlex.quote(os.getcwd()) + "; "]
                + self._user_cmd(environment, active_resources))

    def get_kill_cmd(self, environment, active_resources):
        hosts = ",".join(active_resources)
        return ["pdsh", "-S", "-w", hosts,
                f"pkill -TERM -f {_BOOTSTRAP_PATTERN}"]

    #: pdsh prefixes every forwarded line with "host: "
    _PREFIX = re.compile(r"^(\S+?): (.*\n?)$")

    def route_line(self, line):
        m = self._PREFIX.match(line)
        return (m.group(1), m.group(2)) if m else None


class OpenMPIRunner(MultiNodeRunner):
    """reference: multinode_runner.py:116 — mpirun with one proc per host.
    No kill_cmd: mpirun forwards SIGTERM to every remote rank itself."""

    name = "openmpi"

    def backend_exists(self) -> bool:
        import shutil
        return shutil.which("mpirun") is not None

    def get_cmd(self, environment, active_resources):
        total = len(active_resources)
        cmd = ["mpirun", "-n", str(total), "--hostfile",
               getattr(self.args, "hostfile", "/job/hostfile"),
               "--map-by", "ppr:1:node",   # ONE rank per host (TPU contract)
               "--mca", "btl", "^openib",
               "--mca", "btl_tcp_if_include", "eth0"]
        for k, v in {**environment, **self.exports}.items():
            cmd += ["-x", f"{k}={v}"]
        return cmd + self._user_cmd(environment, active_resources)


class SlurmRunner(MultiNodeRunner):
    """reference: multinode_runner.py:171 — srun over the allocation."""

    name = "slurm"

    def backend_exists(self) -> bool:
        import shutil
        return shutil.which("srun") is not None

    def get_cmd(self, environment, active_resources):
        total = len(active_resources)
        cmd = ["srun", "-n", str(total), "--ntasks-per-node=1",
               # per-rank attribution in the merged stream ("taskid: ")
               # — what route_line demultiplexes for --log-dir
               "--label",
               # the filtered pool IS the node list (the include syntax's
               # ':slot' parts are not valid slurm node names)
               "--nodelist", ",".join(active_resources)]
        exports = ",".join(f"{k}={v}" for k, v in
                           {**environment, **self.exports}.items())
        if exports:
            cmd += [f"--export=ALL,{exports}"]
        return cmd + self._user_cmd(environment, active_resources)

    def get_kill_cmd(self, environment, active_resources):
        # inside an allocation (sbatch/salloc) SLURM_JOB_ID names the job
        # scancel can reach every node of; outside one there is nothing
        # to cancel beyond the srun process itself
        job_id = environment.get("SLURM_JOB_ID",
                                 os.environ.get("SLURM_JOB_ID", ""))
        if not job_id:
            return None
        return ["scancel", "--signal=TERM", job_id]

    #: srun --label prefixes every line with "taskid: "
    _PREFIX = re.compile(r"^(\d+): (.*\n?)$")

    def route_line(self, line):
        m = self._PREFIX.match(line)
        return (f"rank{m.group(1)}", m.group(2)) if m else None


class MVAPICHRunner(OpenMPIRunner):
    """reference: multinode_runner.py:218 — mpirun_rsh with MV2 env; the
    TPU-relevant delta from OpenMPI is the launcher binary + the MV2_*
    environment the reference validates/injects (force TCP-friendly
    defaults; debug backtraces on)."""

    name = "mvapich"

    #: env the reference's runner injects when absent (mvapich needs the
    #: MV2_* family set explicitly; unlike OpenMPI there is no -x flag —
    #: mpirun_rsh takes bare K=V argv pairs)
    MV2_DEFAULTS = {"MV2_SMP_USE_CMA": "0",
                    "MV2_DEBUG_SHOW_BACKTRACE": "1"}

    def backend_exists(self) -> bool:
        import shutil
        return shutil.which("mpirun_rsh") is not None

    def get_cmd(self, environment, active_resources):
        total = len(active_resources)
        cmd = ["mpirun_rsh", "-np", str(total), "-hostfile",
               getattr(self.args, "hostfile", "/job/hostfile")]
        env = {**self.MV2_DEFAULTS, **environment, **self.exports}
        for k, v in env.items():
            cmd.append(f"{k}={v}")
        return cmd + self._user_cmd(environment, active_resources)


RUNNERS = {"pdsh": PDSHRunner, "openmpi": OpenMPIRunner, "slurm": SlurmRunner,
           "mvapich": MVAPICHRunner}


def build_runner(launcher: str, args, world_info_base64: str = ""
                 ) -> MultiNodeRunner:
    if launcher not in RUNNERS:
        raise ValueError(f"unknown launcher '{launcher}' "
                         f"(have {sorted(RUNNERS)} + ssh/local built-ins)")
    return RUNNERS[launcher](args, world_info_base64)
