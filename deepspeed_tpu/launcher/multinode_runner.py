"""Multinode runners — pluggable remote-dispatch backends for dstpu.

Capability parity with the reference's ``launcher/multinode_runner.py``
(MultiNodeRunner ABC + PDSH/OpenMPI/Slurm/MVAPICH runners building the
per-backend launch command). Each runner turns (environment exports, active
resource pool, user command) into ONE argv the scheduler executes; TPU hosts
run one process per host (jax.distributed wires ranks), so the per-GPU rank
plumbing of the reference collapses into node-level dispatch.
"""

from __future__ import annotations

import os
import shlex
from abc import ABC, abstractmethod
from typing import Dict, List


class MultiNodeRunner(ABC):
    name = "base"

    def __init__(self, args, world_info_base64: str = ""):
        self.args = args
        self.world_info_base64 = world_info_base64
        self.user_arguments = list(getattr(args, "user_args", []))
        self.user_script = getattr(args, "user_script", "")
        self.exports: Dict[str, str] = {}

    def add_export(self, key: str, var: str) -> None:
        self.exports[key.strip()] = str(var).strip()

    @abstractmethod
    def get_cmd(self, environment: Dict[str, str],
                active_resources: Dict[str, int]) -> List[str]:
        ...

    def backend_exists(self) -> bool:
        return True

    def _user_cmd(self, environment: Dict[str, str],
                  active_resources: Dict[str, int]) -> List[str]:
        """Per-node bootstrap through launch.py (jax.distributed rendezvous;
        rank autodetected from the scheduler env or world_info hostname) —
        running the raw script would leave nnodes disconnected trainings."""
        import sys
        coordinator = environment.get("DSTPU_COORDINATOR", "localhost")
        port = environment.get("DSTPU_MASTER_PORT", "29500")
        return ([sys.executable, "-m", "deepspeed_tpu.launcher.launch",
                 "--node_rank=-1",
                 f"--nnodes={len(active_resources)}",
                 f"--coordinator={coordinator}:{port}",
                 f"--world_info={self.world_info_base64}",
                 self.user_script] + self.user_arguments)


class PDSHRunner(MultiNodeRunner):
    """reference: multinode_runner.py:45 — pdsh fanout over the host list."""

    name = "pdsh"

    def backend_exists(self) -> bool:
        import shutil
        return shutil.which("pdsh") is not None

    def get_cmd(self, environment, active_resources):
        env_exports = "".join(
            f"export {k}={shlex.quote(v)}; "
            for k, v in {**environment, **self.exports}.items())
        hosts = ",".join(active_resources)
        return (["pdsh", "-S", "-f", "1024", "-w", hosts,
                 env_exports + "cd " + shlex.quote(os.getcwd()) + "; "]
                + self._user_cmd(environment, active_resources))


class OpenMPIRunner(MultiNodeRunner):
    """reference: multinode_runner.py:116 — mpirun with one proc per host."""

    name = "openmpi"

    def backend_exists(self) -> bool:
        import shutil
        return shutil.which("mpirun") is not None

    def get_cmd(self, environment, active_resources):
        total = len(active_resources)
        cmd = ["mpirun", "-n", str(total), "--hostfile",
               getattr(self.args, "hostfile", "/job/hostfile"),
               "--map-by", "ppr:1:node",   # ONE rank per host (TPU contract)
               "--mca", "btl", "^openib",
               "--mca", "btl_tcp_if_include", "eth0"]
        for k, v in {**environment, **self.exports}.items():
            cmd += ["-x", f"{k}={v}"]
        return cmd + self._user_cmd(environment, active_resources)


class SlurmRunner(MultiNodeRunner):
    """reference: multinode_runner.py:171 — srun over the allocation."""

    name = "slurm"

    def backend_exists(self) -> bool:
        import shutil
        return shutil.which("srun") is not None

    def get_cmd(self, environment, active_resources):
        total = len(active_resources)
        cmd = ["srun", "-n", str(total), "--ntasks-per-node=1",
               # the filtered pool IS the node list (the include syntax's
               # ':slot' parts are not valid slurm node names)
               "--nodelist", ",".join(active_resources)]
        exports = ",".join(f"{k}={v}" for k, v in
                           {**environment, **self.exports}.items())
        if exports:
            cmd += [f"--export=ALL,{exports}"]
        return cmd + self._user_cmd(environment, active_resources)


class MVAPICHRunner(OpenMPIRunner):
    """reference: multinode_runner.py:218 — mpirun_rsh with MV2 env; the
    TPU-relevant delta from OpenMPI is just the launcher binary + env names."""

    name = "mvapich"

    def backend_exists(self) -> bool:
        import shutil
        return shutil.which("mpirun_rsh") is not None

    def get_cmd(self, environment, active_resources):
        total = len(active_resources)
        cmd = ["mpirun_rsh", "-np", str(total), "-hostfile",
               getattr(self.args, "hostfile", "/job/hostfile")]
        for k, v in {**environment, **self.exports}.items():
            cmd.append(f"{k}={v}")
        return cmd + self._user_cmd(environment, active_resources)


RUNNERS = {"pdsh": PDSHRunner, "openmpi": OpenMPIRunner, "slurm": SlurmRunner,
           "mvapich": MVAPICHRunner}


def build_runner(launcher: str, args, world_info_base64: str = ""
                 ) -> MultiNodeRunner:
    if launcher not in RUNNERS:
        raise ValueError(f"unknown launcher '{launcher}' "
                         f"(have {sorted(RUNNERS)} + ssh/local built-ins)")
    return RUNNERS[launcher](args, world_info_base64)
