"""RunSupervisor — fail-fast supervision of a multi-host launch.

The pre-round-4 launcher waited on per-host ssh processes SERIALLY
(runner.py): a crashed host was only noticed after every EARLIER host in
the list exited, a wedged host stalled the whole pod forever (each live
rank sits in a collective waiting for the dead one), and the final
``rc = rc or p.returncode`` folded every exit code into "first nonzero" —
erasing the preemption/crash distinction ``DSElasticAgent`` depends on.

This module supervises all ranks CONCURRENTLY:

- **first failure tears the world down**: any rank exiting nonzero (or a
  preempted/stalled rank) triggers SIGTERM to every other rank, a grace
  deadline for their preemption handlers to checkpoint, then SIGKILL for
  the stragglers. No half-dead pods burning TPU hours.
- **connect-phase retries**: ssh dispatch that fails BEFORE the remote
  shell started (ssh's own rc 255 under ``-o ConnectTimeout``, or a
  ``launch.ssh`` chaos fault) retries with bounded exponential backoff.
  A rank whose remote shell already started (it printed the
  :data:`STARTED_SENTINEL` line) is NEVER retried — re-dispatching a rank
  that may have run user code would double-run the job.
- **per-host log persistence** (``log_dir``): every rank's prefixed
  output is mirrored to ``<log_dir>/<host>.rank<k>.log`` alongside the
  live prefixed stream (local ranks switch to captured pipes), so the
  post-mortem for a torn-down pod doesn't depend on terminal scrollback.
- **preemption-aware aggregation**: the overall rc is computed from the
  ranks that exited VOLUNTARILY (before teardown signaled them): a
  genuine crash rc wins, else a preemption (``PREEMPTION_EXIT_CODE``,
  114) yields 114 — so "the pod was preempted" survives the launcher and
  the elastic agent resumes without burning its restart budget. A stalled
  rank's ``STALL_EXIT_CODE`` propagates the same way and DOES count as a
  failure.

The supervisor exposes a ``Popen``-like facade (``poll``/``wait``/
``terminate``/``kill``/``returncode``) so ``DSElasticAgent.launch_fn``
can return a started supervisor and the agent's monitor loop supervises
the supervisor itself.

reference counterpart: ``deepspeed/launcher/runner.py``'s pdsh path +
``launch.py``'s terminate_process_tree sweep; concurrency and the rc
contract are the TPU-native additions (one hung rank deadlocks EVERY
collective in a multi-controller job, so liveness is global).
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from typing import Callable, List, Optional, Sequence

from ..elasticity.elastic_agent import PREEMPTION_EXIT_CODE
from ..runtime import heartbeat as hb
from ..runtime.sentinel import INTEGRITY_EXIT_CODE
from ..runtime.straggler import HOST_NAMING_FLAGS
from ..runtime.watchdog import STALL_EXIT_CODE
from ..testing import chaos
from ..utils.logging import logger

#: Line a remote shell prints once ssh has connected and the per-host
#: bootstrap is about to exec — the boundary between "connect phase"
#: (retryable) and "ran user code" (never retried).
STARTED_SENTINEL = "DSTPU-RANK-STARTED"

#: ssh reserves 255 for ITS OWN failures (connection refused/timeout,
#: auth); user commands exiting 255 are indistinguishable, which is why
#: the sentinel — not the rc — decides retryability.
SSH_CONNECT_RC = 255


class RankSpec:
    """One supervised rank: where and what to launch.

    ``remote=True`` marks ssh dispatch — connect-phase failures retry and
    stdout is scanned for :data:`STARTED_SENTINEL`. Local ranks are
    "started" by construction (Popen succeeding IS the start).

    ``env``: extra environment for LOCAL ranks (remote ranks carry their
    exports inside the ssh command line) — the .deepspeed_env /
    collect_env_exports entries a loopback host must still receive even
    though no ssh shell injects them."""

    __slots__ = ("host", "cmd", "remote", "env")

    def __init__(self, host: str, cmd: Sequence[str], remote: bool = False,
                 env: Optional[dict] = None):
        self.host = host
        self.cmd = list(cmd)
        self.remote = remote
        self.env = dict(env) if env else None


class _RankStatus:
    __slots__ = ("rc", "signaled", "started", "attempts", "finished_at")

    def __init__(self):
        self.rc: Optional[int] = None
        self.signaled = False       # torn down by the supervisor
        self.started = False        # remote shell reached user code
        self.attempts = 0
        self.finished_at: Optional[float] = None


class HeartbeatMonitor:
    """Launcher-side consumer of the rank heartbeat channel
    (runtime/heartbeat.py). Answers two questions the process/pipe view
    cannot: *which phase* is a silent remote rank actually in, and *has
    it stopped attesting liveness* (process or host dead — in-worker
    phase deadlines handle wedges and stamp terminal records).

    ``expected_ranks``: ranks that MUST eventually write — one that has
    produced no file ``timeout`` seconds after monitoring began counts
    silent too (a blackholed host never says anything at all)."""

    def __init__(self, heartbeat_dir: str, timeout: float,
                 expected_ranks: Optional[Sequence[int]] = None,
                 clock=None):
        self.heartbeat_dir = heartbeat_dir
        self.timeout = float(timeout)
        self.expected = set(int(r) for r in (expected_ranks or ()))
        self._clock = clock or time.time
        self._started = self._clock()

    @property
    def enabled(self) -> bool:
        return bool(self.heartbeat_dir) and self.timeout > 0

    def snapshot(self) -> dict:
        return hb.read_heartbeats(self.heartbeat_dir)

    def silent_ranks(self) -> List[dict]:
        """Ranks that stopped attesting: last record non-terminal and
        older than ``timeout`` (hb.stale_ranks — ONE staleness rule for
        launcher and agent), or expected but never seen."""
        now = self._clock()
        records = self.snapshot()
        out = hb.stale_ranks(self.heartbeat_dir, self.timeout, now,
                             records=records)
        if now - self._started > self.timeout:
            for rank in sorted(self.expected - set(records)):
                out.append({"rank": rank, "host": None, "phase": None,
                            "step": None, "ts": None, "missing": True})
        return out

    def terminal_records(self) -> dict:
        return hb.terminal_records(self.heartbeat_dir)


def _grace_then_kill(proc, grace_secs: float) -> None:
    """Post-SIGTERM escalation shared by both supervisors: poll until the
    grace deadline (the workers' emergency-checkpoint budget), SIGKILL
    whatever is still alive."""
    deadline = time.monotonic() + grace_secs
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            return
        time.sleep(0.05)
    if proc.poll() is None:
        try:
            proc.kill()
        except OSError:
            pass


class RunSupervisor:
    """Monitor every rank concurrently; tear the world down on first
    failure; aggregate exit codes preemption-aware."""

    def __init__(self,
                 specs: Sequence[RankSpec],
                 grace_secs: float = 30.0,
                 connect_retries: int = 3,
                 connect_backoff: float = 0.5,
                 connect_backoff_max: float = 10.0,
                 popen_fn: Optional[Callable[..., subprocess.Popen]] = None,
                 stream=None,
                 log_dir: Optional[str] = None,
                 heartbeat_dir: Optional[str] = None,
                 heartbeat_timeout: float = 0.0,
                 heartbeat_poll: float = 1.0):
        self.specs = list(specs)
        self.grace_secs = float(grace_secs)
        self.connect_retries = int(connect_retries)
        self.connect_backoff = float(connect_backoff)
        self.connect_backoff_max = float(connect_backoff_max)
        self._popen = popen_fn or subprocess.Popen
        self._stream = stream if stream is not None else sys.stdout
        # per-host log persistence: with log_dir set, every rank's output
        # (local ranks included — they switch to captured pipes) is also
        # written to <log_dir>/<host>.rank<k>.log, truncated on the first
        # dispatch attempt and appended across connect retries, so a
        # post-mortem doesn't depend on scrollback
        self.log_dir = log_dir
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
        # heartbeat-channel liveness (round 6): with a shared heartbeat
        # dir, ranks whose ssh pipe is silent still attest liveness via
        # per-rank files; a rank that stops attesting (host dead, process
        # blackholed) triggers the same fail-fast teardown as an exit —
        # reported as a STALL so the elastic agent counts it
        self.heartbeat_monitor: Optional[HeartbeatMonitor] = None
        self.heartbeat_poll = float(heartbeat_poll)
        self.heartbeat_dir = heartbeat_dir
        if heartbeat_dir and heartbeat_timeout > 0:
            self.heartbeat_monitor = HeartbeatMonitor(
                heartbeat_dir, heartbeat_timeout,
                expected_ranks=range(len(self.specs)))
        self._hb_stall: Optional[str] = None    # teardown evidence
        self._hb_silent: List[dict] = []        # snapshot AT detection
        self.status = [_RankStatus() for _ in self.specs]
        self._procs: List[Optional[subprocess.Popen]] = [None] * len(self.specs)
        self._lock = threading.Lock()
        self._teardown_started = threading.Event()
        self._done = threading.Event()
        self._threads: List[threading.Thread] = []
        self._started = False
        self.returncode: Optional[int] = None
        if not self.specs:
            self.returncode = 0
            self._done.set()

    # -------------------------------------------------------------- lifecycle

    def start(self) -> "RunSupervisor":
        if self._started or not self.specs:
            return self
        self._started = True
        if self.heartbeat_dir:
            # the channel is run-scoped: records from a previous attempt
            # in a reused dir must not trip silence at t=0 or leak a
            # prior STALLED verdict into this run's evidence
            hb.clear_channel(self.heartbeat_dir)
        for idx in range(len(self.specs)):
            t = threading.Thread(target=self._monitor_rank, args=(idx,),
                                 name=f"dstpu-rank-{idx}", daemon=True)
            self._threads.append(t)
            t.start()
        if self.heartbeat_monitor is not None:
            t = threading.Thread(target=self._monitor_heartbeats,
                                 name="dstpu-heartbeat-monitor", daemon=True)
            self._threads.append(t)
            t.start()
        return self

    def _monitor_heartbeats(self) -> None:
        while not self._done.wait(self.heartbeat_poll):
            if self._teardown_started.is_set():
                return
            # a rank whose PROCESS already finished is the rank monitor's
            # jurisdiction (its rc decides), not silence: a clean rank
            # that never called engine.close() leaves a frozen
            # non-terminal record, and treating that as a wedge would
            # tear down the still-healthy survivors as rc 117
            silent = [r for r in self.heartbeat_monitor.silent_ranks()
                      if not self._rank_exited(r.get("rank"))]
            if not silent:
                continue
            desc = ", ".join(
                f"rank {r.get('rank')}"
                + (f" ({r['host']})" if r.get("host") else "")
                + (" never wrote" if r.get("missing")
                   else f" silent in {r.get('phase')} at step "
                        f"{r.get('step')}")
                for r in silent)
            # snapshot NOW: after the teardown freezes every rank's
            # record, re-evaluating would implicate the whole world
            with self._lock:
                self._hb_silent = silent
                self._hb_stall = desc
            logger.error("supervisor: heartbeat silence — %s (timeout "
                         "%.1fs); tearing down the world", desc,
                         self.heartbeat_monitor.timeout)
            self._trigger_teardown(f"heartbeat silence: {desc}")
            return

    def _rank_exited(self, rank) -> bool:
        return (isinstance(rank, int) and 0 <= rank < len(self.status)
                and self.status[rank].rc is not None)

    def run(self) -> int:
        """start() + wait(): the non-elastic launcher entry point."""
        return self.start().wait()

    # ----------------------------------------------------- Popen-like facade

    def poll(self) -> Optional[int]:
        return self.returncode if self._done.is_set() else None

    def wait(self, timeout: Optional[float] = None) -> int:
        if not self._done.wait(timeout):
            raise subprocess.TimeoutExpired(cmd="RunSupervisor",
                                            timeout=timeout)
        return self.returncode

    def terminate(self) -> None:
        """External teardown request (elastic agent: membership change)."""
        self._trigger_teardown("terminate() requested")

    def kill(self) -> None:
        with self._lock:
            procs = [p for p in self._procs if p is not None]
            for st, p in zip(self.status, self._procs):
                if p is not None and p.poll() is None:
                    st.signaled = True
        self._teardown_started.set()    # stop pending connect retries
        for p in procs:
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass

    # ---------------------------------------------------------- rank monitor

    def rank_log_path(self, idx: int) -> Optional[str]:
        if not self.log_dir:
            return None
        return os.path.join(self.log_dir,
                            f"{self.specs[idx].host}.rank{idx}.log")

    def _open_rank_log(self, idx: int):
        path = self.rank_log_path(idx)
        if path is None:
            return None
        mode = "w" if self.status[idx].attempts <= 1 else "a"
        try:
            return open(path, mode, encoding="utf-8", errors="replace")
        except OSError as e:
            logger.warning("supervisor: cannot open rank log %s: %s",
                           path, e)
            return None

    def _forward_output(self, idx: int, proc: subprocess.Popen,
                        log=None) -> None:
        """Reader for a rank's merged stdout/stderr: recognizes the
        started sentinel, prefixes every other line with the host, and
        mirrors the prefixed lines into the rank's log file when
        persistence is on."""
        st = self.status[idx]
        host = self.specs[idx].host
        try:
            for line in proc.stdout:
                if STARTED_SENTINEL in line:
                    st.started = True
                    continue
                prefixed = f"[{host}] {line}"
                if log is not None:
                    try:
                        log.write(prefixed)
                        log.flush()
                    except (ValueError, OSError):
                        try:
                            log.close()   # ENOSPC etc: stop logging, but
                        except OSError:   # release the descriptor now
                            pass
                        log = None
                try:
                    self._stream.write(prefixed)
                    self._stream.flush()
                except (ValueError, OSError):
                    pass    # parent stream closed mid-teardown
        finally:
            if log is not None:
                try:
                    log.close()
                except OSError:
                    pass

    def _launch_once(self, idx: int) -> subprocess.Popen:
        spec = self.specs[idx]
        # keyed failpoint: a blackholed host fails EVERY dispatch to it
        # (arm with match=<host>), driving the blacklist/degraded-resume
        # path without touching the other hosts of the world
        chaos.failpoint("host.blackhole", key=spec.host)
        log = self._open_rank_log(idx)
        if spec.remote or log is not None:
            try:
                if spec.remote:
                    # the ssh dispatch failpoint: tests simulate connection
                    # failures deterministically (raise mode == ConnectTimeout)
                    chaos.failpoint("launch.ssh")
                env = {**os.environ, **spec.env} \
                    if (not spec.remote and spec.env) else None
                proc = self._popen(spec.cmd, stdout=subprocess.PIPE,
                                   stderr=subprocess.STDOUT, text=True,
                                   env=env)
            except BaseException:
                # connect retries re-open the log per attempt: releasing
                # it here keeps a failing rank from accumulating handles
                if log is not None:
                    try:
                        log.close()
                    except OSError:
                        pass
                raise
            if not spec.remote:
                self.status[idx].started = True
            reader = threading.Thread(target=self._forward_output,
                                      args=(idx, proc, log),
                                      name=f"dstpu-out-{idx}", daemon=True)
            reader.start()
            proc._dstpu_reader = reader
        else:
            env = {**os.environ, **spec.env} if spec.env else None
            proc = self._popen(spec.cmd, env=env)
            self.status[idx].started = True
        return proc

    def _monitor_rank(self, idx: int) -> None:
        spec = self.specs[idx]
        st = self.status[idx]
        attempt = 0
        rc: Optional[int] = None
        while not self._teardown_started.is_set():
            attempt += 1
            st.attempts = attempt
            try:
                proc = self._launch_once(idx)
            except (OSError, chaos.ChaosError) as e:
                rc = SSH_CONNECT_RC
                if self._retry_connect(spec, st, attempt, e):
                    continue
                break
            with self._lock:
                self._procs[idx] = proc
                late_teardown = (self._teardown_started.is_set()
                                 and proc.poll() is None)
                if late_teardown:
                    st.signaled = True
            if late_teardown:
                # this proc registered after _do_teardown's snapshot — it
                # still gets the full SIGTERM -> grace -> SIGKILL contract
                self._term_then_kill(proc)
            rc = proc.wait()
            reader = getattr(proc, "_dstpu_reader", None)
            if reader is not None:
                reader.join(timeout=5)
            with self._lock:
                connect_failed = (spec.remote and not st.started
                                  and not st.signaled
                                  and rc == SSH_CONNECT_RC)
            if connect_failed and self._retry_connect(
                    spec, st, attempt,
                    f"ssh exited {SSH_CONNECT_RC} before the remote shell "
                    "started"):
                with self._lock:
                    self._procs[idx] = None
                continue
            break
        if rc is None or (self._teardown_started.is_set() and not st.started
                          and rc == SSH_CONNECT_RC):
            # the teardown aborted this rank's connect attempts — its 255
            # is an artifact of the abort, not the failure that triggered it
            with self._lock:
                st.signaled = True
        st.rc = SSH_CONNECT_RC if rc is None else rc
        st.finished_at = time.monotonic()
        self._on_rank_exit(idx)

    def _retry_connect(self, spec: RankSpec, st: _RankStatus, attempt: int,
                       why) -> bool:
        """Bounded exponential backoff for CONNECT-phase failures only."""
        if not spec.remote or st.started or attempt > self.connect_retries:
            return False
        delay = min(self.connect_backoff * (2 ** (attempt - 1)),
                    self.connect_backoff_max)
        logger.warning(
            "supervisor: connect to %s failed (%s); retry %d/%d in %.2fs",
            spec.host, why, attempt, self.connect_retries, delay)
        # sleep in slices so a teardown mid-backoff aborts the retry
        deadline = time.monotonic() + delay
        while time.monotonic() < deadline:
            if self._teardown_started.wait(min(0.05, delay)):
                return False
        return not self._teardown_started.is_set()

    # -------------------------------------------------------------- teardown

    def _on_rank_exit(self, idx: int) -> None:
        st = self.status[idx]
        spec = self.specs[idx]
        with self._lock:
            signaled = st.signaled
        if st.rc != 0 and not signaled:
            kind = {PREEMPTION_EXIT_CODE: "preempted"}.get(st.rc, "failed")
            logger.error("supervisor: rank %d (%s) %s with rc=%d — tearing "
                         "down the world", idx, spec.host, kind, st.rc)
            self._trigger_teardown(f"rank {idx} ({spec.host}) rc={st.rc}")
        with self._lock:
            all_done = all(s.rc is not None for s in self.status)
        if all_done and not self._done.is_set():
            self.returncode = self._aggregate()
            self._done.set()

    def _term_then_kill(self, proc: subprocess.Popen) -> None:
        """SIGTERM one process now, SIGKILL it if it outlives the grace
        deadline — the per-proc form of _do_teardown's sweep, for procs
        that registered after the sweep's snapshot."""
        try:
            proc.terminate()
        except OSError:
            return
        threading.Thread(target=_grace_then_kill,
                         args=(proc, self.grace_secs),
                         name="dstpu-late-teardown", daemon=True).start()

    def _trigger_teardown(self, reason: str) -> None:
        with self._lock:
            if self._teardown_started.is_set():
                return
            self._teardown_started.set()
        t = threading.Thread(target=self._do_teardown, args=(reason,),
                             name="dstpu-teardown", daemon=True)
        t.start()

    def _do_teardown(self, reason: str) -> None:
        """SIGTERM the survivors (their preemption handlers get the grace
        window to checkpoint), then SIGKILL whatever outlives it."""
        with self._lock:
            live = []
            for st, p in zip(self.status, self._procs):
                if p is not None and p.poll() is None:
                    st.signaled = True
                    live.append(p)
        if live:
            logger.warning("supervisor: teardown (%s): SIGTERM %d ranks, "
                           "grace %.1fs", reason, len(live), self.grace_secs)
        for p in live:
            try:
                p.terminate()
            except OSError:
                pass
        deadline = time.monotonic() + self.grace_secs
        while time.monotonic() < deadline:
            if all(p.poll() is not None for p in live):
                return
            time.sleep(0.05)
        for p in live:
            if p.poll() is None:
                logger.error("supervisor: rank outlived the grace deadline "
                             "— SIGKILL")
                try:
                    p.kill()
                except OSError:
                    pass

    # ----------------------------------------------------------- aggregation

    def _aggregate(self) -> int:
        """Overall rc from the VOLUNTARY exits (ranks that finished before
        teardown signaled them): genuine crash > preemption > clean. The
        torn-down remnants' codes (-15/-9, or 114 from their own handlers)
        must not mask what actually happened first."""
        with self._lock:
            voluntary = [st for st in self.status if not st.signaled]
            hb_stall = self._hb_stall
        crashes = [st for st in voluntary
                   if st.rc not in (0, PREEMPTION_EXIT_CODE)]
        if crashes:
            first = min(crashes, key=lambda s: s.finished_at or 0.0)
            return first.rc
        if hb_stall is not None:
            # the teardown was triggered by heartbeat silence, not an
            # exit: every rank is a torn-down remnant, and the honest rc
            # is "wedged" — counted by the elastic agent, like any stall
            return STALL_EXIT_CODE
        if any(st.rc == PREEMPTION_EXIT_CODE for st in voluntary):
            return PREEMPTION_EXIT_CODE
        if all(st.rc == 0 for st in self.status):
            return 0
        # only torn-down ranks are nonzero: an external terminate() (the
        # elastic agent's restart) — surface a preemption if any handler
        # checkpointed, else the first nonzero remnant
        if any(st.rc == PREEMPTION_EXIT_CODE for st in self.status):
            return PREEMPTION_EXIT_CODE
        nonzero = [st.rc for st in self.status if st.rc != 0]
        return nonzero[0] if nonzero else 0

    @property
    def rank_hosts(self) -> List[str]:
        """World-ordered host per rank (one rank per spec) — the elastic
        agent's rank->host recovery indexes THIS, not its own hostfile
        membership, which launch-side --include/--exclude/--num_nodes
        filters may have narrowed further."""
        return [spec.host for spec in self.specs]

    def failed_hosts(self) -> List[str]:
        """Hosts this run has evidence AGAINST — the elastic agent's
        blacklist feed: voluntary nonzero exits (crash/stall rc), remote
        ranks that never got past the connect phase (a blackholed host),
        ranks the heartbeat monitor called silent, and ranks whose record
        carries an integrity flag (a cross-replica SDC audit implicated
        their chips — evidence MORE precise than the exit code, which
        every rank shares when the audit aborts the world)."""
        out = []
        for spec, st in zip(self.specs, self.status):
            # rc 118 exempt: an integrity abort exits EVERY rank with the
            # same code by construction (the audit is collective), so the
            # rc names no host — only the flagged record below does.
            # Striking on the rc would quarantine the whole innocent world
            voluntary_failure = (st.rc not in (None, 0, PREEMPTION_EXIT_CODE,
                                               INTEGRITY_EXIT_CODE)
                                 and not st.signaled)
            never_started = (spec.remote and not st.started
                             and not st.signaled
                             and st.rc == SSH_CONNECT_RC)
            if voluntary_failure or never_started:
                out.append(spec.host)
        if self._hb_stall is not None:
            # the snapshot taken when silence was DETECTED — not a fresh
            # silent_ranks() call: by attribution time the teardown has
            # frozen every survivor's record, and re-evaluating would
            # strike the whole (innocent) world
            for rec in self._hb_silent:
                host = hb.rec_host(rec, self.rank_hosts)
                if host and host not in out:
                    out.append(host)
        if self.heartbeat_dir:
            # host-NAMING flags only — SDC (a chip computing garbage) and
            # STRAGGLER (a host dragging the synchronous step): each is
            # stamped by exactly the implicated rank. The generic
            # INTEGRITY mark (launch.py stamps it on every rank of an
            # rc-118 abort for health visibility) names no host
            for flag in HOST_NAMING_FLAGS:
                for rec in hb.flagged_ranks(self.heartbeat_dir,
                                            flag=flag).values():
                    host = hb.rec_host(rec, self.rank_hosts)
                    if host and host not in out:
                        out.append(host)
        return out


class BackendSupervisor:
    """Supervision for the SCHEDULER-dispatched launchers (pdsh / slurm /
    openmpi / mvapich).

    Those backends fan the world out through ONE scheduler command; the
    launcher sees a single Popen whose pipe says nothing about per-rank
    liveness, whose teardown semantics belong to the scheduler, and whose
    exit code flattens the rc 114/117 contract (``pdsh -S`` returns the
    LARGEST rc, ``srun`` whatever its step policy picks). This class
    restores the three supervision properties the ssh path has had since
    round 4:

    - **per-rank liveness** via the heartbeat channel: a rank that stops
      attesting (host dead, process blackholed) triggers teardown after
      ``heartbeat_timeout`` — through the backend's OWN kill path
      (``kill_cmd``: ``scancel``, ``pdsh -w ... pkill``) first, because
      SIGTERM to the scheduler process alone may orphan remote ranks;
    - **fail-fast teardown** with the same SIGTERM → ``grace_secs`` →
      SIGKILL contract as RunSupervisor (the grace window is the workers'
      emergency-checkpoint budget);
    - **preemption-aware rc reconstruction**: the workers' terminal
      heartbeat records (STALLED / PREEMPTED) overrule the scheduler's
      flattened rc, so ``dstpu --elastic`` treats a preempted slurm world
      exactly like a preempted ssh world (resume, uncounted).

    ``route_line`` (from the backend's MultiNodeRunner) demultiplexes the
    scheduler's merged output — ``pdsh``'s ``host:`` / ``srun --label``'s
    ``rank:`` prefixes — into per-key files under ``log_dir``, mirroring
    the PR-5 ssh-path log persistence.

    Exposes the same Popen-like facade as RunSupervisor (``poll`` /
    ``wait`` / ``terminate`` / ``kill`` / ``returncode``) so
    DSElasticAgent supervises either interchangeably.
    """

    def __init__(self,
                 cmd: Sequence[str],
                 kill_cmd: Optional[Sequence[str]] = None,
                 heartbeat_dir: Optional[str] = None,
                 heartbeat_timeout: float = 0.0,
                 heartbeat_poll: float = 1.0,
                 grace_secs: float = 30.0,
                 popen_fn: Optional[Callable[..., subprocess.Popen]] = None,
                 run_fn: Optional[Callable[..., object]] = None,
                 stream=None,
                 log_dir: Optional[str] = None,
                 route_line: Optional[Callable[[str],
                                              Optional[tuple]]] = None,
                 backend: str = "backend",
                 rank_hosts: Optional[Sequence[str]] = None):
        self.cmd = list(cmd)
        # hostfile-ordered host per rank: lets silence/stall evidence be
        # attributed even for a rank that NEVER wrote a record (node dead
        # before launch.py ran — there is no self-reported host to read)
        self.rank_hosts = list(rank_hosts) if rank_hosts else []
        self.kill_cmd = list(kill_cmd) if kill_cmd else None
        self.grace_secs = float(grace_secs)
        self.heartbeat_poll = float(heartbeat_poll)
        self.backend = backend
        self._popen = popen_fn or subprocess.Popen
        self._run_cmd = run_fn or subprocess.run
        self._stream = stream if stream is not None else sys.stdout
        self.log_dir = log_dir
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
        self.route_line = route_line
        self.heartbeat_monitor: Optional[HeartbeatMonitor] = None
        if heartbeat_dir and heartbeat_timeout > 0:
            # expected_ranks closes the never-wrote blind spot: a host
            # dead BEFORE launch.py runs produces no record at all, and
            # without the expectation the launch would hang unsupervised
            self.heartbeat_monitor = HeartbeatMonitor(
                heartbeat_dir, heartbeat_timeout,
                expected_ranks=(range(len(self.rank_hosts))
                                if self.rank_hosts else None))
        self._heartbeat_dir = heartbeat_dir
        self._hb_stall: Optional[str] = None
        self._silent_hosts: List[str] = []
        self._proc: Optional[subprocess.Popen] = None
        self._done = threading.Event()
        self._teardown_started = threading.Event()
        self._started = False
        self.returncode: Optional[int] = None

    # -------------------------------------------------------------- lifecycle

    def start(self) -> "BackendSupervisor":
        if self._started:
            return self
        self._started = True
        if self._heartbeat_dir:
            # run-scoped channel: a prior attempt's STALLED record in a
            # reused dir must not reconstruct THIS run's clean rc as 117,
            # and its stale records must not trip silence at t=0
            hb.clear_channel(self._heartbeat_dir)
        capture = bool(self.log_dir) or self._stream is not sys.stdout
        if capture:
            self._proc = self._popen(self.cmd, stdout=subprocess.PIPE,
                                     stderr=subprocess.STDOUT, text=True)
            threading.Thread(target=self._forward_output,
                             name="dstpu-backend-out", daemon=True).start()
        else:
            self._proc = self._popen(self.cmd)
        threading.Thread(target=self._monitor, name="dstpu-backend-monitor",
                         daemon=True).start()
        return self

    def run(self) -> int:
        return self.start().wait()

    # ----------------------------------------------------- Popen-like facade

    def poll(self) -> Optional[int]:
        return self.returncode if self._done.is_set() else None

    def wait(self, timeout: Optional[float] = None) -> int:
        if not self._done.wait(timeout):
            raise subprocess.TimeoutExpired(cmd="BackendSupervisor",
                                            timeout=timeout)
        return self.returncode

    def terminate(self) -> None:
        self._trigger_teardown("terminate() requested")

    def kill(self) -> None:
        self._teardown_started.set()
        p = self._proc
        if p is not None and p.poll() is None:
            try:
                p.kill()
            except OSError:
                pass

    def _rank_host(self, rec: dict) -> Optional[str]:
        """A record's host, falling back to the hostfile-ordered mapping
        for ranks that never self-reported one (shared helper — see
        heartbeat.rec_host)."""
        return hb.rec_host(rec, self.rank_hosts)

    def failed_hosts(self) -> List[str]:
        """Blacklist feed: hosts whose ranks went heartbeat-silent,
        stamped a STALLED terminal record, or carry a host-naming flag —
        SDC (the audit's per-host attribution) or STRAGGLER (the
        relative-slowness detector's): the scheduler's flattened rc can
        name neither the bad chip nor the slow host; the flagged record
        can."""
        out = list(self._silent_hosts)
        if self._heartbeat_dir:
            for rec in hb.terminal_records(self._heartbeat_dir).values():
                if rec.get("phase") == hb.PHASE_STALLED:
                    host = self._rank_host(rec)
                    if host and host not in out:
                        out.append(host)
            for flag in HOST_NAMING_FLAGS:
                for rec in hb.flagged_ranks(self._heartbeat_dir,
                                            flag=flag).values():
                    host = self._rank_host(rec)
                    if host and host not in out:
                        out.append(host)
        return out

    # -------------------------------------------------------------- internals

    def _log_path(self, key: str) -> str:
        return os.path.join(self.log_dir, f"{key}.log")

    def _forward_output(self) -> None:
        """Mirror the scheduler's merged stream, demultiplexing per-rank
        prefixes into per-key files when log persistence is on."""
        logs = {}
        try:
            for line in self._proc.stdout:
                try:
                    self._stream.write(line)
                    self._stream.flush()
                except (ValueError, OSError):
                    pass
                if not self.log_dir:
                    continue
                key, payload = self.backend, line
                if self.route_line is not None:
                    routed = self.route_line(line)
                    if routed is not None:
                        key, payload = routed
                log = logs.get(key)
                if log is None:
                    try:
                        log = open(self._log_path(key), "w",
                                   encoding="utf-8", errors="replace")
                    except OSError as e:
                        logger.warning("backend supervisor: cannot open "
                                       "%s: %s", self._log_path(key), e)
                        log = False      # do not retry every line
                    logs[key] = log
                if log:
                    try:
                        log.write(payload)
                        log.flush()
                    except (ValueError, OSError):
                        try:
                            log.close()
                        except OSError:
                            pass
                        logs[key] = False
        finally:
            for log in logs.values():
                if log:
                    try:
                        log.close()
                    except OSError:
                        pass

    def _monitor(self) -> None:
        while True:
            rc = self._proc.poll()
            if rc is not None:
                break
            if (self.heartbeat_monitor is not None
                    and not self._teardown_started.is_set()):
                silent = self.heartbeat_monitor.silent_ranks()
                if silent:
                    desc = ", ".join(
                        f"rank {r.get('rank')}"
                        + (f" ({r['host']})" if r.get("host") else "")
                        for r in silent)
                    self._hb_stall = desc
                    self._silent_hosts = [
                        h for h in (self._rank_host(r) for r in silent)
                        if h]
                    logger.error(
                        "backend supervisor (%s): heartbeat silence — %s "
                        "(timeout %.1fs); tearing the launch down via the "
                        "scheduler kill path", self.backend, desc,
                        self.heartbeat_monitor.timeout)
                    self._trigger_teardown(f"heartbeat silence: {desc}")
            if self._done.wait(self.heartbeat_poll):
                return
        self.returncode = self._reconstruct_rc(rc)
        self._done.set()

    def _trigger_teardown(self, reason: str) -> None:
        if self._teardown_started.is_set():
            return
        self._teardown_started.set()
        threading.Thread(target=self._do_teardown, args=(reason,),
                         name="dstpu-backend-teardown", daemon=True).start()

    def _do_teardown(self, reason: str) -> None:
        """The scheduler's own kill path first (it reaches the REMOTE
        ranks; signaling the local scheduler proc alone may orphan them),
        then SIGTERM → grace → SIGKILL on the scheduler process itself."""
        logger.warning("backend supervisor (%s): teardown (%s), grace %.1fs",
                       self.backend, reason, self.grace_secs)
        if self.kill_cmd:
            try:
                # bounded SHORT of grace_secs: the kill command is a
                # scheduler CLI call that works in seconds or not at all,
                # and it runs BEFORE the grace wait — an unbounded (or
                # grace-sized) hang here would stretch total teardown to
                # ~2x grace and blow past the elastic agent's
                # teardown_grace budget, SIGKILLing mid-emergency-save
                self._run_cmd(self.kill_cmd,
                              timeout=max(1.0, min(self.grace_secs, 5.0)))
            except (OSError, subprocess.SubprocessError) as e:
                logger.warning("backend supervisor: kill command failed: %s",
                               e)
        p = self._proc
        if p is None:
            return
        try:
            p.terminate()
        except OSError:
            return
        _grace_then_kill(p, self.grace_secs)

    def _reconstruct_rc(self, scheduler_rc: int) -> int:
        """The scheduler flattened the per-rank rcs; the workers' terminal
        heartbeat records carry what actually happened. Stall evidence
        (incl. a silence-triggered teardown) wins — a wedge is a counted
        failure; then preemption; then the scheduler's own verdict."""
        terminal = (hb.terminal_records(self._heartbeat_dir)
                    if self._heartbeat_dir else {})
        phases = {rec.get("phase") for rec in terminal.values()}
        if self._hb_stall is not None or hb.PHASE_STALLED in phases:
            return STALL_EXIT_CODE
        if scheduler_rc == 0:
            return 0
        if scheduler_rc in (PREEMPTION_EXIT_CODE, STALL_EXIT_CODE):
            return scheduler_rc       # the contract survived the backend
        if hb.PHASE_PREEMPTED in phases:
            return PREEMPTION_EXIT_CODE
        return scheduler_rc
