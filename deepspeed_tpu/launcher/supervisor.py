"""RunSupervisor — fail-fast supervision of a multi-host launch.

The pre-round-4 launcher waited on per-host ssh processes SERIALLY
(runner.py): a crashed host was only noticed after every EARLIER host in
the list exited, a wedged host stalled the whole pod forever (each live
rank sits in a collective waiting for the dead one), and the final
``rc = rc or p.returncode`` folded every exit code into "first nonzero" —
erasing the preemption/crash distinction ``DSElasticAgent`` depends on.

This module supervises all ranks CONCURRENTLY:

- **first failure tears the world down**: any rank exiting nonzero (or a
  preempted/stalled rank) triggers SIGTERM to every other rank, a grace
  deadline for their preemption handlers to checkpoint, then SIGKILL for
  the stragglers. No half-dead pods burning TPU hours.
- **connect-phase retries**: ssh dispatch that fails BEFORE the remote
  shell started (ssh's own rc 255 under ``-o ConnectTimeout``, or a
  ``launch.ssh`` chaos fault) retries with bounded exponential backoff.
  A rank whose remote shell already started (it printed the
  :data:`STARTED_SENTINEL` line) is NEVER retried — re-dispatching a rank
  that may have run user code would double-run the job.
- **per-host log persistence** (``log_dir``): every rank's prefixed
  output is mirrored to ``<log_dir>/<host>.rank<k>.log`` alongside the
  live prefixed stream (local ranks switch to captured pipes), so the
  post-mortem for a torn-down pod doesn't depend on terminal scrollback.
- **preemption-aware aggregation**: the overall rc is computed from the
  ranks that exited VOLUNTARILY (before teardown signaled them): a
  genuine crash rc wins, else a preemption (``PREEMPTION_EXIT_CODE``,
  114) yields 114 — so "the pod was preempted" survives the launcher and
  the elastic agent resumes without burning its restart budget. A stalled
  rank's ``STALL_EXIT_CODE`` propagates the same way and DOES count as a
  failure.

The supervisor exposes a ``Popen``-like facade (``poll``/``wait``/
``terminate``/``kill``/``returncode``) so ``DSElasticAgent.launch_fn``
can return a started supervisor and the agent's monitor loop supervises
the supervisor itself.

reference counterpart: ``deepspeed/launcher/runner.py``'s pdsh path +
``launch.py``'s terminate_process_tree sweep; concurrency and the rc
contract are the TPU-native additions (one hung rank deadlocks EVERY
collective in a multi-controller job, so liveness is global).
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from typing import Callable, List, Optional, Sequence

from ..elasticity.elastic_agent import PREEMPTION_EXIT_CODE
from ..testing import chaos
from ..utils.logging import logger

#: Line a remote shell prints once ssh has connected and the per-host
#: bootstrap is about to exec — the boundary between "connect phase"
#: (retryable) and "ran user code" (never retried).
STARTED_SENTINEL = "DSTPU-RANK-STARTED"

#: ssh reserves 255 for ITS OWN failures (connection refused/timeout,
#: auth); user commands exiting 255 are indistinguishable, which is why
#: the sentinel — not the rc — decides retryability.
SSH_CONNECT_RC = 255


class RankSpec:
    """One supervised rank: where and what to launch.

    ``remote=True`` marks ssh dispatch — connect-phase failures retry and
    stdout is scanned for :data:`STARTED_SENTINEL`. Local ranks are
    "started" by construction (Popen succeeding IS the start).

    ``env``: extra environment for LOCAL ranks (remote ranks carry their
    exports inside the ssh command line) — the .deepspeed_env /
    collect_env_exports entries a loopback host must still receive even
    though no ssh shell injects them."""

    __slots__ = ("host", "cmd", "remote", "env")

    def __init__(self, host: str, cmd: Sequence[str], remote: bool = False,
                 env: Optional[dict] = None):
        self.host = host
        self.cmd = list(cmd)
        self.remote = remote
        self.env = dict(env) if env else None


class _RankStatus:
    __slots__ = ("rc", "signaled", "started", "attempts", "finished_at")

    def __init__(self):
        self.rc: Optional[int] = None
        self.signaled = False       # torn down by the supervisor
        self.started = False        # remote shell reached user code
        self.attempts = 0
        self.finished_at: Optional[float] = None


class RunSupervisor:
    """Monitor every rank concurrently; tear the world down on first
    failure; aggregate exit codes preemption-aware."""

    def __init__(self,
                 specs: Sequence[RankSpec],
                 grace_secs: float = 30.0,
                 connect_retries: int = 3,
                 connect_backoff: float = 0.5,
                 connect_backoff_max: float = 10.0,
                 popen_fn: Optional[Callable[..., subprocess.Popen]] = None,
                 stream=None,
                 log_dir: Optional[str] = None):
        self.specs = list(specs)
        self.grace_secs = float(grace_secs)
        self.connect_retries = int(connect_retries)
        self.connect_backoff = float(connect_backoff)
        self.connect_backoff_max = float(connect_backoff_max)
        self._popen = popen_fn or subprocess.Popen
        self._stream = stream if stream is not None else sys.stdout
        # per-host log persistence: with log_dir set, every rank's output
        # (local ranks included — they switch to captured pipes) is also
        # written to <log_dir>/<host>.rank<k>.log, truncated on the first
        # dispatch attempt and appended across connect retries, so a
        # post-mortem doesn't depend on scrollback
        self.log_dir = log_dir
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
        self.status = [_RankStatus() for _ in self.specs]
        self._procs: List[Optional[subprocess.Popen]] = [None] * len(self.specs)
        self._lock = threading.Lock()
        self._teardown_started = threading.Event()
        self._done = threading.Event()
        self._threads: List[threading.Thread] = []
        self._started = False
        self.returncode: Optional[int] = None
        if not self.specs:
            self.returncode = 0
            self._done.set()

    # -------------------------------------------------------------- lifecycle

    def start(self) -> "RunSupervisor":
        if self._started or not self.specs:
            return self
        self._started = True
        for idx in range(len(self.specs)):
            t = threading.Thread(target=self._monitor_rank, args=(idx,),
                                 name=f"dstpu-rank-{idx}", daemon=True)
            self._threads.append(t)
            t.start()
        return self

    def run(self) -> int:
        """start() + wait(): the non-elastic launcher entry point."""
        return self.start().wait()

    # ----------------------------------------------------- Popen-like facade

    def poll(self) -> Optional[int]:
        return self.returncode if self._done.is_set() else None

    def wait(self, timeout: Optional[float] = None) -> int:
        if not self._done.wait(timeout):
            raise subprocess.TimeoutExpired(cmd="RunSupervisor",
                                            timeout=timeout)
        return self.returncode

    def terminate(self) -> None:
        """External teardown request (elastic agent: membership change)."""
        self._trigger_teardown("terminate() requested")

    def kill(self) -> None:
        with self._lock:
            procs = [p for p in self._procs if p is not None]
            for st, p in zip(self.status, self._procs):
                if p is not None and p.poll() is None:
                    st.signaled = True
        self._teardown_started.set()    # stop pending connect retries
        for p in procs:
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass

    # ---------------------------------------------------------- rank monitor

    def rank_log_path(self, idx: int) -> Optional[str]:
        if not self.log_dir:
            return None
        return os.path.join(self.log_dir,
                            f"{self.specs[idx].host}.rank{idx}.log")

    def _open_rank_log(self, idx: int):
        path = self.rank_log_path(idx)
        if path is None:
            return None
        mode = "w" if self.status[idx].attempts <= 1 else "a"
        try:
            return open(path, mode, encoding="utf-8", errors="replace")
        except OSError as e:
            logger.warning("supervisor: cannot open rank log %s: %s",
                           path, e)
            return None

    def _forward_output(self, idx: int, proc: subprocess.Popen,
                        log=None) -> None:
        """Reader for a rank's merged stdout/stderr: recognizes the
        started sentinel, prefixes every other line with the host, and
        mirrors the prefixed lines into the rank's log file when
        persistence is on."""
        st = self.status[idx]
        host = self.specs[idx].host
        try:
            for line in proc.stdout:
                if STARTED_SENTINEL in line:
                    st.started = True
                    continue
                prefixed = f"[{host}] {line}"
                if log is not None:
                    try:
                        log.write(prefixed)
                        log.flush()
                    except (ValueError, OSError):
                        try:
                            log.close()   # ENOSPC etc: stop logging, but
                        except OSError:   # release the descriptor now
                            pass
                        log = None
                try:
                    self._stream.write(prefixed)
                    self._stream.flush()
                except (ValueError, OSError):
                    pass    # parent stream closed mid-teardown
        finally:
            if log is not None:
                try:
                    log.close()
                except OSError:
                    pass

    def _launch_once(self, idx: int) -> subprocess.Popen:
        spec = self.specs[idx]
        log = self._open_rank_log(idx)
        if spec.remote or log is not None:
            try:
                if spec.remote:
                    # the ssh dispatch failpoint: tests simulate connection
                    # failures deterministically (raise mode == ConnectTimeout)
                    chaos.failpoint("launch.ssh")
                env = {**os.environ, **spec.env} \
                    if (not spec.remote and spec.env) else None
                proc = self._popen(spec.cmd, stdout=subprocess.PIPE,
                                   stderr=subprocess.STDOUT, text=True,
                                   env=env)
            except BaseException:
                # connect retries re-open the log per attempt: releasing
                # it here keeps a failing rank from accumulating handles
                if log is not None:
                    try:
                        log.close()
                    except OSError:
                        pass
                raise
            if not spec.remote:
                self.status[idx].started = True
            reader = threading.Thread(target=self._forward_output,
                                      args=(idx, proc, log),
                                      name=f"dstpu-out-{idx}", daemon=True)
            reader.start()
            proc._dstpu_reader = reader
        else:
            env = {**os.environ, **spec.env} if spec.env else None
            proc = self._popen(spec.cmd, env=env)
            self.status[idx].started = True
        return proc

    def _monitor_rank(self, idx: int) -> None:
        spec = self.specs[idx]
        st = self.status[idx]
        attempt = 0
        rc: Optional[int] = None
        while not self._teardown_started.is_set():
            attempt += 1
            st.attempts = attempt
            try:
                proc = self._launch_once(idx)
            except (OSError, chaos.ChaosError) as e:
                rc = SSH_CONNECT_RC
                if self._retry_connect(spec, st, attempt, e):
                    continue
                break
            with self._lock:
                self._procs[idx] = proc
                late_teardown = (self._teardown_started.is_set()
                                 and proc.poll() is None)
                if late_teardown:
                    st.signaled = True
            if late_teardown:
                # this proc registered after _do_teardown's snapshot — it
                # still gets the full SIGTERM -> grace -> SIGKILL contract
                self._term_then_kill(proc)
            rc = proc.wait()
            reader = getattr(proc, "_dstpu_reader", None)
            if reader is not None:
                reader.join(timeout=5)
            connect_failed = (spec.remote and not st.started
                              and not st.signaled and rc == SSH_CONNECT_RC)
            if connect_failed and self._retry_connect(
                    spec, st, attempt,
                    f"ssh exited {SSH_CONNECT_RC} before the remote shell "
                    "started"):
                with self._lock:
                    self._procs[idx] = None
                continue
            break
        if rc is None or (self._teardown_started.is_set() and not st.started
                          and rc == SSH_CONNECT_RC):
            # the teardown aborted this rank's connect attempts — its 255
            # is an artifact of the abort, not the failure that triggered it
            st.signaled = True
        st.rc = SSH_CONNECT_RC if rc is None else rc
        st.finished_at = time.monotonic()
        self._on_rank_exit(idx)

    def _retry_connect(self, spec: RankSpec, st: _RankStatus, attempt: int,
                       why) -> bool:
        """Bounded exponential backoff for CONNECT-phase failures only."""
        if not spec.remote or st.started or attempt > self.connect_retries:
            return False
        delay = min(self.connect_backoff * (2 ** (attempt - 1)),
                    self.connect_backoff_max)
        logger.warning(
            "supervisor: connect to %s failed (%s); retry %d/%d in %.2fs",
            spec.host, why, attempt, self.connect_retries, delay)
        # sleep in slices so a teardown mid-backoff aborts the retry
        deadline = time.monotonic() + delay
        while time.monotonic() < deadline:
            if self._teardown_started.wait(min(0.05, delay)):
                return False
        return not self._teardown_started.is_set()

    # -------------------------------------------------------------- teardown

    def _on_rank_exit(self, idx: int) -> None:
        st = self.status[idx]
        spec = self.specs[idx]
        if st.rc != 0 and not st.signaled:
            kind = {PREEMPTION_EXIT_CODE: "preempted"}.get(st.rc, "failed")
            logger.error("supervisor: rank %d (%s) %s with rc=%d — tearing "
                         "down the world", idx, spec.host, kind, st.rc)
            self._trigger_teardown(f"rank {idx} ({spec.host}) rc={st.rc}")
        with self._lock:
            all_done = all(s.rc is not None for s in self.status)
        if all_done and not self._done.is_set():
            self.returncode = self._aggregate()
            self._done.set()

    def _term_then_kill(self, proc: subprocess.Popen) -> None:
        """SIGTERM one process now, SIGKILL it if it outlives the grace
        deadline — the per-proc form of _do_teardown's sweep, for procs
        that registered after the sweep's snapshot."""
        try:
            proc.terminate()
        except OSError:
            return

        def _escalate():
            deadline = time.monotonic() + self.grace_secs
            while time.monotonic() < deadline:
                if proc.poll() is not None:
                    return
                time.sleep(0.05)
            if proc.poll() is None:
                try:
                    proc.kill()
                except OSError:
                    pass

        threading.Thread(target=_escalate, name="dstpu-late-teardown",
                         daemon=True).start()

    def _trigger_teardown(self, reason: str) -> None:
        with self._lock:
            if self._teardown_started.is_set():
                return
            self._teardown_started.set()
        t = threading.Thread(target=self._do_teardown, args=(reason,),
                             name="dstpu-teardown", daemon=True)
        t.start()

    def _do_teardown(self, reason: str) -> None:
        """SIGTERM the survivors (their preemption handlers get the grace
        window to checkpoint), then SIGKILL whatever outlives it."""
        with self._lock:
            live = []
            for st, p in zip(self.status, self._procs):
                if p is not None and p.poll() is None:
                    st.signaled = True
                    live.append(p)
        if live:
            logger.warning("supervisor: teardown (%s): SIGTERM %d ranks, "
                           "grace %.1fs", reason, len(live), self.grace_secs)
        for p in live:
            try:
                p.terminate()
            except OSError:
                pass
        deadline = time.monotonic() + self.grace_secs
        while time.monotonic() < deadline:
            if all(p.poll() is not None for p in live):
                return
            time.sleep(0.05)
        for p in live:
            if p.poll() is None:
                logger.error("supervisor: rank outlived the grace deadline "
                             "— SIGKILL")
                try:
                    p.kill()
                except OSError:
                    pass

    # ----------------------------------------------------------- aggregation

    def _aggregate(self) -> int:
        """Overall rc from the VOLUNTARY exits (ranks that finished before
        teardown signaled them): genuine crash > preemption > clean. The
        torn-down remnants' codes (-15/-9, or 114 from their own handlers)
        must not mask what actually happened first."""
        voluntary = [st for st in self.status if not st.signaled]
        crashes = [st for st in voluntary
                   if st.rc not in (0, PREEMPTION_EXIT_CODE)]
        if crashes:
            first = min(crashes, key=lambda s: s.finished_at or 0.0)
            return first.rc
        if any(st.rc == PREEMPTION_EXIT_CODE for st in voluntary):
            return PREEMPTION_EXIT_CODE
        if all(st.rc == 0 for st in self.status):
            return 0
        # only torn-down ranks are nonzero: an external terminate() (the
        # elastic agent's restart) — surface a preemption if any handler
        # checkpointed, else the first nonzero remnant
        if any(st.rc == PREEMPTION_EXIT_CODE for st in self.status):
            return PREEMPTION_EXIT_CODE
        nonzero = [st.rc for st in self.status if st.rc != 0]
        return nonzero[0] if nonzero else 0
