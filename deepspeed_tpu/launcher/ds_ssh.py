"""ds_ssh — run a shell command on every host in the hostfile.

Capability parity with the reference's ``bin/ds_ssh`` (pdsh wrapper over the
hostfile). Usage: ``ds_ssh [-H hostfile] -- <command...>``.
"""

from __future__ import annotations

import argparse
import subprocess
import sys

from .runner import fetch_hostfile


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--" in argv:
        split = argv.index("--")
        argv, cmd = argv[:split], argv[split + 1:]
    else:
        cmd = []
    p = argparse.ArgumentParser(prog="ds_ssh")
    p.add_argument("-H", "--hostfile", default="/job/hostfile")
    args = p.parse_args(argv)
    if not cmd:
        p.error("pass the command after '--'")
    pool = fetch_hostfile(args.hostfile)
    hosts = list(pool) or ["localhost"]
    rc = 0
    for host in hosts:
        print(f"----- {host} -----")
        full = cmd if host == "localhost" else \
            ["ssh", "-o", "StrictHostKeyChecking=no", host] + cmd
        r = subprocess.run(full)
        rc = rc or r.returncode
    sys.exit(rc)


if __name__ == "__main__":
    main()
