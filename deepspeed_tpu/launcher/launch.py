"""Per-host bootstrap — `python -m deepspeed_tpu.launcher.launch`.

Capability parity with ``deepspeed/launcher/launch.py`` (the per-node spawner
that sets RANK/LOCAL_RANK/WORLD_SIZE and forks one process per GPU). On TPU
each host runs ONE process owning all local chips; this module initializes
the multi-host runtime via `jax.distributed.initialize` (coordinator
rendezvous = the reference's MASTER_ADDR/MASTER_PORT TCP store) and then runs
the user script in-process (runpy), so the user script sees the full
multi-host `jax.devices()` world.
"""

from __future__ import annotations

import argparse
import os
import runpy
import sys


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="deepspeed_tpu.launcher.launch")
    p.add_argument("--node_rank", type=int, required=True,
                   help="-1 = autodetect from the scheduler env "
                        "(OMPI/SLURM/PMI rank) or hostname-in-world_info")
    p.add_argument("--nnodes", type=int, required=True)
    p.add_argument("--coordinator", required=True,
                   help="host:port of process 0")
    p.add_argument("--world_info", default="",
                   help="base64 host->slots map (rank autodetect + info)")
    p.add_argument("--init_timeout", type=float,
                   default=float(os.environ.get("DSTPU_INIT_TIMEOUT", "0")
                                 or 0),
                   help="bound on jax.distributed.initialize, seconds "
                        "(0 = wait forever). On expiry the worker dumps "
                        "all thread stacks and exits the stall rc so the "
                        "supervisor can tear the launch down — a dead "
                        "coordinator otherwise hangs every rank silently")
    p.add_argument("user_script")
    p.add_argument("user_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def resolve_node_rank(args) -> int:
    """-1 -> scheduler env rank (mpirun/srun set these per process) or the
    host's position in world_info (the pdsh path, where every node gets the
    identical command — reference launch.py derives rank the same two ways)."""
    if args.node_rank >= 0:
        return args.node_rank
    import os
    for var in ("OMPI_COMM_WORLD_RANK", "SLURM_PROCID", "PMI_RANK",
                "PMIX_RANK", "MV2_COMM_WORLD_RANK", "MPIRUN_RANK"):
        if var in os.environ:
            return int(os.environ[var])
    if args.world_info:
        import socket
        from .runner import decode_world_info
        hosts = list(decode_world_info(args.world_info))
        name = socket.gethostname()
        short = name.split(".")[0]
        for i, h in enumerate(hosts):
            # exact or FQDN-vs-shortname match ONLY: prefix matching would
            # give worker-1 and worker-10 the same rank
            if h == name or h == short or h.split(".")[0] == name:
                return i
    raise RuntimeError(
        "cannot autodetect node_rank: no scheduler rank env var and the "
        "hostname is not in world_info")


def main(argv=None):
    args = parse_args(argv)
    import jax
    node_rank = 0
    if args.nnodes > 1:
        node_rank = resolve_node_rank(args)
    # the heartbeat channel starts attesting BEFORE the rendezvous: a
    # rank wedged inside jax.distributed.initialize is visible to
    # launcher-side monitors (and `dstpu health`) as INIT, not as a rank
    # that never existed
    from ..runtime import heartbeat as hb
    if args.world_info:
        from .runner import decode_world_info
        hosts = list(decode_world_info(args.world_info))
        if 0 <= node_rank < len(hosts):
            # records must name hosts in the OPERATOR's hostfile
            # vocabulary (blacklist/attribution compare against it), not
            # gethostname()'s FQDN/alias; env so the engine's own writer
            # (same process, after runpy) inherits the name
            import os
            os.environ[hb.HEARTBEAT_HOST_ENV] = hosts[node_rank]
    writer = hb.HeartbeatWriter.from_env(rank=node_rank)
    if writer is not None:
        writer.write(hb.PHASE_INIT, 0, force=True)
    if args.nnodes > 1:
        from ..runtime.watchdog import init_deadline
        with init_deadline(args.init_timeout):
            jax.distributed.initialize(
                coordinator_address=args.coordinator,
                num_processes=args.nnodes,
                process_id=node_rank)
    if writer is not None:
        # hand this writer (refresher included) to the engine via the
        # process registry: the engine's from_env ADOPTS it instead of
        # creating a second writer for the same file, and until an engine
        # exists the refresher keeps the INIT record fresh through the
        # user script's import/setup window — closing here would let a
        # slow setup read as launcher-side silence and tear down a
        # healthy launch
        hb.set_process_writer(writer)
    sys.argv = [args.user_script] + args.user_args
    try:
        runpy.run_path(args.user_script, run_name="__main__")
    except SystemExit as e:
        if writer is not None and e.code in (0, None):
            writer.stamp_terminal(hb.PHASE_EXIT, lock_timeout=5.0)
        raise
    except Exception as e:
        # integrity aborts (runtime/sentinel.py: TrainingIntegrityError,
        # NonFiniteError) carry their own rc contract — rc 118 tells the
        # supervisor/elastic agent "the run computes wrong numbers"
        # (counted failure, distinct from crash/stall/preemption). Any
        # heartbeat evidence (the SDC flag) was stamped before the raise.
        code = getattr(e, "exit_code", None)
        if isinstance(code, int) and 0 < code < 256:
            import traceback
            traceback.print_exc()
            if writer is not None:
                from ..runtime.sentinel import INTEGRITY_EXIT_CODE
                if code == INTEGRITY_EXIT_CODE:
                    # mark + conclude the record: the INTEGRITY flag
                    # keeps an rc-118 abort visible in `dstpu health` (a
                    # bare EXIT reads as a clean run) without striking
                    # anyone — blacklist consumers filter to the
                    # host-naming flags (SDC, STRAGGLER). Other coded
                    # exits (a StragglerAbort's rc 117) stamped their
                    # own evidence before raising
                    writer.add_flag("INTEGRITY", lock_timeout=5.0)
                # the terminal stamp keeps a slow scheduler teardown
                # past heartbeat_timeout from reading EVERY frozen STEP
                # record as silence (rc 117 against all innocent hosts);
                # a no-op when a terminal verdict (STALLED) already
                # stands
                writer.stamp_terminal(hb.PHASE_EXIT, lock_timeout=5.0)
            sys.exit(code)
        raise
    if writer is not None:
        # clean completion without engine.close() (or without any engine
        # at all): conclude the record so a frozen non-terminal phase
        # can't read as heartbeat silence after the process is gone
        writer.stamp_terminal(hb.PHASE_EXIT, lock_timeout=5.0)


if __name__ == "__main__":
    main()
