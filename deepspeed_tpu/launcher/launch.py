"""Per-host bootstrap — `python -m deepspeed_tpu.launcher.launch`.

Capability parity with ``deepspeed/launcher/launch.py`` (the per-node spawner
that sets RANK/LOCAL_RANK/WORLD_SIZE and forks one process per GPU). On TPU
each host runs ONE process owning all local chips; this module initializes
the multi-host runtime via `jax.distributed.initialize` (coordinator
rendezvous = the reference's MASTER_ADDR/MASTER_PORT TCP store) and then runs
the user script in-process (runpy), so the user script sees the full
multi-host `jax.devices()` world.
"""

from __future__ import annotations

import argparse
import runpy
import sys


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="deepspeed_tpu.launcher.launch")
    p.add_argument("--node_rank", type=int, required=True)
    p.add_argument("--nnodes", type=int, required=True)
    p.add_argument("--coordinator", required=True,
                   help="host:port of process 0")
    p.add_argument("--world_info", default="",
                   help="base64 host->slots map (informational on TPU)")
    p.add_argument("user_script")
    p.add_argument("user_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    import jax
    if args.nnodes > 1:
        jax.distributed.initialize(
            coordinator_address=args.coordinator,
            num_processes=args.nnodes,
            process_id=args.node_rank)
    sys.argv = [args.user_script] + args.user_args
    runpy.run_path(args.user_script, run_name="__main__")


if __name__ == "__main__":
    main()
