"""Op registry — implementation selection with availability probing.

Capability parity with the reference's ``op_builder/`` registry
(ALL_OPS + per-builder is_compatible() probing, deepspeed/ops/__init__.py):
each logical op registers candidate implementations with a probe and a
priority; ``get_op`` returns the best available (TPU kernel > XLA fallback),
and ``compatibility_report`` feeds ds_report's op table. Probes run lazily
and cache — the reference JIT-builds CUDA where we JIT-compile Pallas/C++.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

from ..utils.logging import logger


@dataclasses.dataclass
class OpImpl:
    name: str                       # e.g. "pallas_flash"
    loader: Callable[[], Any]       # returns the callable op (may raise)
    probe: Callable[[], bool]       # cheap availability check
    priority: int = 0               # higher wins


class OpRegistry:
    def __init__(self):
        self._impls: Dict[str, List[OpImpl]] = {}
        self._probe_cache: Dict[str, bool] = {}

    def register(self, op: str, impl: OpImpl) -> None:
        self._impls.setdefault(op, []).append(impl)
        self._impls[op].sort(key=lambda i: -i.priority)

    def available(self, op: str, impl_name: str) -> bool:
        key = f"{op}/{impl_name}"
        if key not in self._probe_cache:
            impl = self._find(op, impl_name)
            try:
                self._probe_cache[key] = bool(impl.probe())
            except Exception as e:
                logger.debug("op probe %s failed: %s", key, e)
                self._probe_cache[key] = False
        return self._probe_cache[key]

    def _find(self, op: str, impl_name: str) -> OpImpl:
        for impl in self._impls.get(op, []):
            if impl.name == impl_name:
                return impl
        raise KeyError(f"no impl '{impl_name}' for op '{op}'")

    def get_op(self, op: str, impl: Optional[str] = None) -> Any:
        """Best available implementation (or the named one)."""
        if op not in self._impls:
            raise KeyError(f"unknown op '{op}'; have {sorted(self._impls)}")
        candidates = ([self._find(op, impl)] if impl
                      else self._impls[op])
        for c in candidates:
            if self.available(op, c.name):
                return c.loader()
        raise RuntimeError(f"no available implementation for op '{op}' "
                           f"(tried {[c.name for c in candidates]})")

    def compatibility_report(self) -> Dict[str, Dict[str, bool]]:
        return {op: {i.name: self.available(op, i.name) for i in impls}
                for op, impls in sorted(self._impls.items())}


REGISTRY = OpRegistry()


def _on_tpu() -> bool:
    import jax
    return jax.default_backend() == "tpu"


def _register_builtins():
    def _flash():
        from .pallas.flash_attention import flash_attention
        return flash_attention

    def _ref_attn():
        from .attention import mha_reference
        return mha_reference

    def _bs_flash():
        from .pallas.block_sparse_attention import block_sparse_flash_attention
        return block_sparse_flash_attention

    def _cpu_adam():
        from .cpu.adam import DeepSpeedCPUAdam
        return DeepSpeedCPUAdam

    def _cpu_adam_numpy():
        import functools

        from .cpu.adam import DeepSpeedCPUAdam
        return functools.partial(DeepSpeedCPUAdam, use_native=False)

    def _aio():
        from .cpu.aio import AsyncIOHandle
        return AsyncIOHandle

    def _aio_python():
        import functools

        from .cpu.aio import AsyncIOHandle
        return functools.partial(AsyncIOHandle, use_native=False)

    REGISTRY.register("attention", OpImpl(
        "pallas_flash", _flash, _on_tpu, priority=10))
    REGISTRY.register("attention", OpImpl(
        "xla_reference", _ref_attn, lambda: True, priority=0))
    REGISTRY.register("sparse_attention", OpImpl(
        "pallas_block_sparse", _bs_flash, _on_tpu, priority=10))
    REGISTRY.register("cpu_adam", OpImpl(
        "cpp_simd", _cpu_adam,
        lambda: __import__("deepspeed_tpu.ops.cpu.build",
                           fromlist=["load_cpu_kernels"]
                           ).load_cpu_kernels() is not None, priority=10))
    REGISTRY.register("cpu_adam", OpImpl(
        "numpy", _cpu_adam_numpy, lambda: True, priority=0))
    REGISTRY.register("aio", OpImpl(
        "cpp_threadpool", _aio,
        lambda: __import__("deepspeed_tpu.ops.cpu.build",
                           fromlist=["load_aio"]).load_aio() is not None,
        priority=10))
    REGISTRY.register("aio", OpImpl("python", _aio_python, lambda: True,
                                    priority=0))

    def _native_loader():
        from ..runtime.data_pipeline.native_loader import NativeBatchAssembler
        return NativeBatchAssembler

    def _py_loader():
        import functools

        from ..runtime.data_pipeline.native_loader import NativeBatchAssembler
        return functools.partial(NativeBatchAssembler, use_native=False)

    REGISTRY.register("data_loader", OpImpl(
        "cpp_mmap", _native_loader,
        lambda: __import__("deepspeed_tpu.ops.cpu.build",
                           fromlist=["load_data_loader"]
                           ).load_data_loader() is not None, priority=10))
    REGISTRY.register("data_loader", OpImpl("python", _py_loader,
                                            lambda: True, priority=0))


_register_builtins()


def get_op(op: str, impl: Optional[str] = None) -> Any:
    return REGISTRY.get_op(op, impl)


def compatibility_report() -> Dict[str, Dict[str, bool]]:
    return REGISTRY.compatibility_report()
