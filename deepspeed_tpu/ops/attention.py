"""Attention ops — jnp reference implementation + dispatch to Pallas kernels.

Capability slot of the reference's attention kernel families:
  csrc/transformer/softmax_kernels.cu + attn_*       -> fused by XLA / Pallas flash
  deepspeed/ops/sparse_attention/* (Triton, block-sparse) -> block-sparse masks here,
       Pallas block-skipping kernel in ops/pallas/flash_attention.py

`attention(...)` is the single entry point models call; `impl=` selects
  "reference" — pure jnp (always available, used as the parity oracle in tests)
  "flash"     — Pallas TPU flash-attention kernel (ops/pallas/flash_attention.py)
  "auto"      — flash on TPU, reference elsewhere

The flash kernel handles boolean masks (padding and full tiles), ALiBi via
per-head slopes, causal sliding windows, and logit softcap IN-KERNEL (fwd and
bwd), so those regimes ride the flash path. Attention dropout and generic
additive biases are the documented fallbacks to the jnp reference.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def causal_mask(q_len: int, k_len: int) -> jnp.ndarray:
    """[q_len, k_len] bool mask, True = attend. Offset so the last q row sees all k."""
    offset = k_len - q_len
    q_pos = jnp.arange(q_len)[:, None]
    k_pos = jnp.arange(k_len)[None, :]
    return k_pos <= q_pos + offset


def apply_softcap(x, cap: float):
    """Gemma-2 logit softcapping: tanh(x / cap) * cap, computed in f32.
    Single definition — used for attention scores (here and the decode
    path) and final LM logits (transformer head, decode head)."""
    return (jnp.tanh(x.astype(jnp.float32) / cap) * cap)


def alibi_bias_from_slopes(slopes, q_len: int, k_len: int) -> jnp.ndarray:
    """[H] per-head slopes -> [1, H, q_len, k_len] additive ALiBi bias,
    last-query-aligned (q positions arange + k_len - q_len, the decode
    offset convention shared with causal_mask). The dense counterpart of
    the flash kernel's in-kernel slope * (k - q) term — only the fallback
    paths materialize it."""
    sl = jnp.asarray(slopes, jnp.float32).reshape(-1)
    q_pos = jnp.arange(q_len) + (k_len - q_len)
    k_pos = jnp.arange(k_len)
    dist = (k_pos[None, :] - q_pos[:, None]).astype(jnp.float32)
    return sl[None, :, None, None] * dist[None, None]


def window_mask(q_len: int, k_len: int, window) -> jnp.ndarray:
    """[1, 1, q_len, k_len] bool sliding-window mask (True = attend):
    q_pos - k_pos < window, q positions last-row-aligned (arange +
    k_len - q_len, the same offset convention as causal_mask). The dense
    counterpart of the flash kernel's in-kernel window — only fallback
    paths materialize it."""
    q_pos = jnp.arange(q_len)[:, None] + (k_len - q_len)
    k_pos = jnp.arange(k_len)[None, :]
    return (q_pos - k_pos < window)[None, None]


def mha_reference(q: jnp.ndarray,
                  k: jnp.ndarray,
                  v: jnp.ndarray,
                  *,
                  causal: bool = True,
                  bias: Optional[jnp.ndarray] = None,
                  mask: Optional[jnp.ndarray] = None,
                  sm_scale: Optional[float] = None,
                  dropout_rate: float = 0.0,
                  dropout_rng: Optional[jax.Array] = None,
                  softcap: float = 0.0) -> jnp.ndarray:
    """Multi-head attention, jnp reference. q,k,v: [batch, heads, seq, head_dim].

    The numerics oracle every Pallas kernel is tested against (mirrors the
    reference's in-tree HF-BERT baseline used by tests/unit/ops/cuda/*).
    softmax accumulates in fp32 regardless of input dtype (as the reference's
    kernels do for fp16).
    """
    *_, q_len, head_dim = q.shape
    k_len = k.shape[-2]
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(head_dim)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if softcap:
        # Gemma-2 attention-logit softcapping, BEFORE mask/softmax (HF
        # Gemma2Attention eager path); logits are already f32 here
        logits = apply_softcap(logits, softcap)
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    neg = jnp.asarray(-1e30, jnp.float32)
    if causal:
        logits = jnp.where(causal_mask(q_len, k_len)[None, None], logits, neg)
    if mask is not None:
        logits = jnp.where(mask, logits, neg)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(q.dtype), v)


def sliding_window_attention(q, k, v, window: int, *,
                             sm_scale: Optional[float] = None,
                             interpret: bool = False) -> jnp.ndarray:
    """Causal sliding-window attention on the block-skip kernel: the layout
    visits only blocks intersecting the window (compute AND K/V DMA scale
    with window, not seq) and the kernel applies the EXACT per-token window
    in-block — same numerics as the dense (q_pos - k_pos < window) mask.
    Raises when shapes can't tile; callers fall back to the flash kernel's
    in-kernel window (MXU skip only) and then the dense-mask path."""
    from .pallas.block_sparse_attention import block_sparse_flash_attention
    from .sparse_attention import LocalSlidingWindowSparsityConfig
    B, H, S, D = q.shape
    fine = 64 if S % 64 == 0 else 16
    w_blocks = -(-(window - 1) // fine) + 1 if window > 1 else 1
    cfg = LocalSlidingWindowSparsityConfig(
        num_heads=H, block=fine, num_sliding_window_blocks=w_blocks,
        attention="unidirectional")
    layout = cfg.make_layout(S)
    # the exact pattern is fully defined by the causal + window masks, so
    # the per-program fine-layout mask work is skipped (layout_exact=False)
    return block_sparse_flash_attention(
        q, k, v, layout, fine, causal=True, sm_scale=sm_scale,
        window=window, layout_exact=False, interpret=interpret)


def paged_attention(q, k_pool, v_pool, block_tables, context_lens, *,
                    sm_scale: Optional[float] = None,
                    alibi_slopes=None,
                    softcap: float = 0.0,
                    window=None,
                    layer_idx=None,
                    k_scale=None,
                    v_scale=None,
                    q_start=None,
                    impl: str = "auto",
                    interpret: bool = False) -> jnp.ndarray:
    """Dispatching paged-attention entry point (serving decode path).

    q [B, nh, T, hd] against a block-pool K/V ([L?, nh, num_blocks,
    block_size, hd]) through per-sequence ``block_tables`` [B, max_blocks]
    and ``context_lens`` [B]. The Pallas kernel
    (ops/pallas/paged_attention.py) serves the decode regime (T == 1, TPU
    or interpret) with ALiBi/softcap/window in-kernel; every other regime
    — prefill (T > 1, possibly with PADDED trailing queries positioned by
    ``q_start``), CPU, untileable shapes — runs the exact jnp gather
    reference. int8 pools ride both paths via ``k_scale``/``v_scale``
    (per-(layer, head, slot) f32, dequantized in-kernel / post-gather).
    ``impl="reference"`` forces the oracle.
    """
    kw = dict(sm_scale=sm_scale, alibi_slopes=alibi_slopes, softcap=softcap,
              window=window, layer_idx=layer_idx, k_scale=k_scale,
              v_scale=v_scale)
    on_tpu = jax.default_backend() == "tpu"
    if impl in ("auto", "flash") and (on_tpu or interpret) \
            and q.shape[2] == 1:
        # T == 1: the query position is ctx - 1 by the decode contract, so
        # q_start (== ctx - 1 when given) carries no extra information
        from .pallas.paged_attention import paged_attention as _kernel
        try:
            return _kernel(q, k_pool, v_pool, block_tables, context_lens,
                           interpret=interpret, **kw)
        except ValueError:
            pass                    # shapes don't tile — gather reference
    from .pallas.paged_attention import paged_attention_reference
    return paged_attention_reference(q, k_pool, v_pool, block_tables,
                                     context_lens, q_start=q_start, **kw)


def attention(q: jnp.ndarray,
              k: jnp.ndarray,
              v: jnp.ndarray,
              *,
              causal: bool = True,
              bias: Optional[jnp.ndarray] = None,
              mask: Optional[jnp.ndarray] = None,
              alibi_slopes=None,
              sm_scale: Optional[float] = None,
              dropout_rate: float = 0.0,
              dropout_rng: Optional[jax.Array] = None,
              impl: str = "auto",
              block_q: int = 1024,
              block_k: int = 1024,
              window: int = 0,
              softcap: float = 0.0,
              interpret: bool = False) -> jnp.ndarray:
    """Dispatching attention entry point. Shapes: [batch, heads, seq, head_dim].

    Kernel-capable regimes (flash path, in-kernel fwd+bwd): boolean ``mask``
    (padding or full), ``alibi_slopes`` ([H] per-head slopes — pass these
    instead of a materialized alibi ``bias``), causal ``window`` > 0, and
    ``softcap``. Attention dropout and generic additive ``bias`` fall back
    to the exact jnp reference (documented, warned under impl="flash").

    ``window`` must be a STATIC python int for the kernel routes — model
    paths that trace it (e.g. per-layer windows as scan elements) compose it
    into the dense mask instead; windows <= 0 mean global. A pure sliding
    window (no other features) prefers the block-skip layout kernel, which
    also skips the K/V DMA of out-of-window blocks.
    """
    window = 0 if window is None or window <= 0 else int(window)
    # the flash kernel covers mask/alibi/window/softcap; dropout and generic
    # additive biases have no kernel path — honor them on the reference impl
    # rather than silently dropping them
    kernel_capable = (dropout_rate == 0.0 and bias is None
                      and (window == 0 or causal))
    on_tpu = jax.default_backend() == "tpu"
    pure_window = (window and causal and mask is None and bias is None
                   and alibi_slopes is None and softcap == 0.0
                   and dropout_rate == 0.0)
    if pure_window and on_tpu and impl in ("auto", "flash"):
        try:
            return sliding_window_attention(q, k, v, window,
                                            sm_scale=sm_scale,
                                            interpret=interpret)
        except ValueError:
            pass        # shapes don't tile — flash in-kernel window below
    if impl == "auto":
        impl = "flash" if (on_tpu and kernel_capable) else "reference"
    if impl in ("ring", "ulysses"):
        if mask is not None or bias is not None or alibi_slopes is not None \
                or dropout_rate > 0.0 or window or softcap:
            from ..utils.logging import logger
            logger.warning(f"attention impl='{impl}' does not support "
                           "mask/bias/window/softcap/dropout; falling back "
                           "to reference")
            impl = "reference"
        else:
            from ..parallel.ring_attention import (ring_attention,
                                                   ulysses_attention)
            fn = ring_attention if impl == "ring" else ulysses_attention
            return fn(q, k, v, causal=causal, sm_scale=sm_scale)
    if impl == "flash":
        if not kernel_capable:
            from ..utils.logging import logger
            logger.warning("attention impl='flash' has no kernel path for "
                           "dropout / generic bias / non-causal windows; "
                           "falling back to reference")
            impl = "reference"
        else:
            from .pallas.flash_attention import flash_attention
            return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                                   mask=mask, alibi_slopes=alibi_slopes,
                                   window=window, softcap=softcap,
                                   block_q=block_q, block_k=block_k,
                                   interpret=interpret)
    # reference: materialize what the kernel computes from indices
    if alibi_slopes is not None:
        ali = alibi_bias_from_slopes(alibi_slopes, q.shape[-2], k.shape[-2])
        bias = ali if bias is None else bias + ali
    if window:
        wmask = window_mask(q.shape[-2], k.shape[-2], window)
        mask = wmask if mask is None else mask & wmask
    return mha_reference(q, k, v, causal=causal, bias=bias, mask=mask,
                         sm_scale=sm_scale, dropout_rate=dropout_rate,
                         dropout_rng=dropout_rng, softcap=softcap)
