"""Attention ops — jnp reference implementation + dispatch to Pallas kernels.

Capability slot of the reference's attention kernel families:
  csrc/transformer/softmax_kernels.cu + attn_*       -> fused by XLA / Pallas flash
  deepspeed/ops/sparse_attention/* (Triton, block-sparse) -> block-sparse masks here,
       Pallas block-skipping kernel in ops/pallas/flash_attention.py

`attention(...)` is the single entry point models call; `impl=` selects
  "reference" — pure jnp (always available, used as the parity oracle in tests)
  "flash"     — Pallas TPU flash-attention kernel (ops/pallas/flash_attention.py)
  "auto"      — flash on TPU, reference elsewhere
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def causal_mask(q_len: int, k_len: int) -> jnp.ndarray:
    """[q_len, k_len] bool mask, True = attend. Offset so the last q row sees all k."""
    offset = k_len - q_len
    q_pos = jnp.arange(q_len)[:, None]
    k_pos = jnp.arange(k_len)[None, :]
    return k_pos <= q_pos + offset


def apply_softcap(x, cap: float):
    """Gemma-2 logit softcapping: tanh(x / cap) * cap, computed in f32.
    Single definition — used for attention scores (here and the decode
    path) and final LM logits (transformer head, decode head)."""
    return (jnp.tanh(x.astype(jnp.float32) / cap) * cap)


def mha_reference(q: jnp.ndarray,
                  k: jnp.ndarray,
                  v: jnp.ndarray,
                  *,
                  causal: bool = True,
                  bias: Optional[jnp.ndarray] = None,
                  mask: Optional[jnp.ndarray] = None,
                  sm_scale: Optional[float] = None,
                  dropout_rate: float = 0.0,
                  dropout_rng: Optional[jax.Array] = None,
                  softcap: float = 0.0) -> jnp.ndarray:
    """Multi-head attention, jnp reference. q,k,v: [batch, heads, seq, head_dim].

    The numerics oracle every Pallas kernel is tested against (mirrors the
    reference's in-tree HF-BERT baseline used by tests/unit/ops/cuda/*).
    softmax accumulates in fp32 regardless of input dtype (as the reference's
    kernels do for fp16).
    """
    *_, q_len, head_dim = q.shape
    k_len = k.shape[-2]
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(head_dim)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if softcap:
        # Gemma-2 attention-logit softcapping, BEFORE mask/softmax (HF
        # Gemma2Attention eager path); logits are already f32 here
        logits = apply_softcap(logits, softcap)
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    neg = jnp.asarray(-1e30, jnp.float32)
    if causal:
        logits = jnp.where(causal_mask(q_len, k_len)[None, None], logits, neg)
    if mask is not None:
        logits = jnp.where(mask, logits, neg)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(q.dtype), v)


def sliding_window_attention(q, k, v, window: int, *,
                             sm_scale: Optional[float] = None,
                             interpret: bool = False) -> jnp.ndarray:
    """Causal sliding-window attention on the block-skip kernel: the layout
    visits only blocks intersecting the window (compute scales with window,
    not seq) and the kernel applies the EXACT per-token window in-block —
    same numerics as the dense (q_pos - k_pos < window) mask. Raises when
    shapes can't tile; callers fall back to the dense-mask path."""
    from .pallas.block_sparse_attention import block_sparse_flash_attention
    from .sparse_attention import LocalSlidingWindowSparsityConfig
    B, H, S, D = q.shape
    fine = 64 if S % 64 == 0 else 16
    w_blocks = -(-(window - 1) // fine) + 1 if window > 1 else 1
    cfg = LocalSlidingWindowSparsityConfig(
        num_heads=H, block=fine, num_sliding_window_blocks=w_blocks,
        attention="unidirectional")
    layout = cfg.make_layout(S)
    # the exact pattern is fully defined by the causal + window masks, so
    # the per-program fine-layout mask work is skipped (layout_exact=False)
    return block_sparse_flash_attention(
        q, k, v, layout, fine, causal=True, sm_scale=sm_scale,
        window=window, layout_exact=False, interpret=interpret)


def attention(q: jnp.ndarray,
              k: jnp.ndarray,
              v: jnp.ndarray,
              *,
              causal: bool = True,
              bias: Optional[jnp.ndarray] = None,
              mask: Optional[jnp.ndarray] = None,
              sm_scale: Optional[float] = None,
              dropout_rate: float = 0.0,
              dropout_rng: Optional[jax.Array] = None,
              impl: str = "auto",
              block_q: int = 1024,
              block_k: int = 1024,
              window: int = 0,
              softcap: float = 0.0) -> jnp.ndarray:
    """Dispatching attention entry point. Shapes: [batch, heads, seq, head_dim].

    ``window`` > 0 (with causal=True, no mask/bias/dropout) routes to the
    block-skip sliding-window kernel on TPU. The window must be a STATIC
    python int for the kernel route — model paths that trace it (the
    scanned-layers transformer, whose per-layer window is a scan element)
    compose it into the dense mask instead; windows <= 0 mean global."""
    # softcap has no flash/block-skip kernel path: honor it on the exact
    # reference impl rather than silently dropping it
    needs_reference = (bias is not None or mask is not None
                       or dropout_rate > 0.0 or softcap > 0.0)
    window = 0 if window is None or window <= 0 else window
    if window and causal and not needs_reference and \
            jax.default_backend() == "tpu" and impl in ("auto", "flash"):
        try:
            return sliding_window_attention(q, k, v, window,
                                            sm_scale=sm_scale)
        except ValueError:
            pass        # shapes don't tile — dense mask below
    if window:
        S = q.shape[-2]
        q_pos = jnp.arange(S)[:, None]
        k_pos = jnp.arange(S)[None, :]
        wmask = (q_pos - k_pos < window)[None, None]
        mask = wmask if mask is None else mask & wmask
        needs_reference = True
    if impl == "auto":
        on_tpu = jax.default_backend() == "tpu"
        impl = "flash" if (on_tpu and not needs_reference) else "reference"
    if impl in ("ring", "ulysses"):
        if needs_reference:
            from ..utils.logging import logger
            logger.warning(f"attention impl='{impl}' does not support "
                           "mask/bias/dropout; falling back to reference")
            impl = "reference"
        else:
            from ..parallel.ring_attention import (ring_attention,
                                                   ulysses_attention)
            fn = ring_attention if impl == "ring" else ulysses_attention
            return fn(q, k, v, causal=causal, sm_scale=sm_scale)
    if impl == "flash":
        if needs_reference:
            # the flash kernel has no mask/bias/dropout path yet — honor the
            # arguments rather than silently dropping them
            from ..utils.logging import logger
            logger.warning("attention impl='flash' does not support "
                           "mask/bias/dropout; falling back to reference")
            impl = "reference"
        else:
            from .pallas.flash_attention import flash_attention
            return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                                   block_q=block_q, block_k=block_k)
    return mha_reference(q, k, v, causal=causal, bias=bias, mask=mask,
                         sm_scale=sm_scale, dropout_rate=dropout_rate,
                         dropout_rng=dropout_rng, softcap=softcap)
