"""Block-sparse flash attention — layout-driven block skip in Pallas.

Executes the sparsity layouts from ops/sparse_attention.py (Fixed / BigBird /
BSLongformer / Variable / LocalSlidingWindow) the way the reference's Triton
sdd/dsd kernels do (deepspeed/ops/sparse_attention/matmul.py:6, softmax.py):
inactive blocks are never visited — attention cost scales with layout
density, which is the mechanism behind the reference's "10x longer sequences"
claim (docs/_pages/training.md:108).

The sparsity is realized at the GRID level, not by masking: per (head,
q-block) the host builds the list of active k-blocks, the innermost grid
dimension runs over that list (padded to the max count), and the k/v
BlockSpec index maps read the list from scalar-prefetch SMEM — so skipped
blocks cost neither MXU work NOR the K/V tile DMA (~128KB/block that
otherwise caps the win at memory bandwidth). This is the splash-attention
scheduling shape, rebuilt for the layout zoo.

Inside a visited block, the LAYOUT's fine granularity (SparsityConfig.block,
often 16) is applied element-exactly. TPU lowering constraints probed on v5e
(dynamic lane slices + dynamic VMEM scalar loads crash Mosaic; SMEM scalar
reads and BlockSpec-mapped fetches are fine) dictate the mechanics:
  * q selection rides the BlockSpec: the layout is host-expanded to exactly
    8 rows per kernel q block ([H, nq*8, nf] — tile-legal (1, 8, nf) blocks);
  * k selection is arithmetic: an iota-built selector
    W[f, c] = ((kb*block_k + c)//fine == f) turns the fine row into per-lane
    flags via one [8, nf] x [nf, block_k] matmul (~1% of block FLOPs).

Backward follows flash_attention.py's two-kernel split: dq reuses the
q->active-k lists; dk/dv uses the transposed k->active-q lists.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import NEG_INF, _causal_block_mask

__all__ = ["block_sparse_flash_attention"]


def _window_block_mask(s, iq, kb, block_q, block_k, window):
    """Exact per-token sliding window: keep logits with q_pos - k_pos <
    window (the causal side is _causal_block_mask's job)."""
    q_pos = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(q_pos - k_pos < window, s, NEG_INF)


def _layout_mask(sub8, s, kb, fine, block_q, block_k):
    """Apply the fine layout to logits s [block_q, block_k]; kb is the
    (dynamic) k-block index, sub8 the q side's [8, nf] fine rows."""
    nf = sub8.shape[1]
    f_iota = jax.lax.broadcasted_iota(jnp.int32, (nf, block_k), 0)
    c_iota = jax.lax.broadcasted_iota(jnp.int32, (nf, block_k), 1)
    sel = ((kb * block_k + c_iota) // fine == f_iota).astype(jnp.float32)
    mask8 = jax.lax.dot(sub8.astype(jnp.float32), sel,
                        preferred_element_type=jnp.float32)   # [8, block_k]
    mask = jnp.repeat(mask8 > 0.5, block_q // 8, axis=0)
    return jnp.where(mask, s, NEG_INF)


def _fwd_kernel(cnt_ref, idx_ref, lay_ref, q_ref, k_ref, v_ref, o_ref,
                lse_ref, acc, m_scr, l_scr,
                *, H, nq, maxk, sm_scale, causal, block_q, block_k, fine,
                window, layout_exact):
    b, iq, j = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    h = b % H
    row = h * nq + iq

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    kb = idx_ref[row * maxk + j]
    run = j < cnt_ref[row]

    @pl.when(run)
    def _compute():
        sub8 = lay_ref[0]                               # [8, nf] i32, static
        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if layout_exact:
            s = _layout_mask(sub8, s, kb, fine, block_q, block_k)
        if causal:
            s = _causal_block_mask(s, iq, kb, block_q, block_k, 0)
        if window:
            s = _window_block_mask(s, iq, kb, block_q, block_k, window)
        m_prev = m_scr[:, :1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # rows with nothing active so far keep m = NEG_INF; exp underflows to 0
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur)
        l_scr[:, :1] = l_scr[:, :1] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc[:] = acc[:] * alpha + jax.lax.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_scr[:, :1] = m_cur

    @pl.when(j == maxk - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_scr[:, 0] + jnp.log(jnp.maximum(l[:, 0], 1e-37)))


def _bwd_dq_kernel(cnt_ref, idx_ref, lay_ref, q_ref, k_ref, v_ref, do_ref,
                   lse_ref, delta_ref, dq_ref, dq_acc,
                   *, H, nq, maxk, sm_scale, causal, block_q, block_k, fine,
                   window, layout_exact):
    b, iq, j = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    row = (b % H) * nq + iq

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    kb = idx_ref[row * maxk + j]
    run = j < cnt_ref[row]

    @pl.when(run)
    def _compute():
        sub8 = lay_ref[0]
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if layout_exact:
            s = _layout_mask(sub8, s, kb, fine, block_q, block_k)
        if causal:
            s = _causal_block_mask(s, iq, kb, block_q, block_k, 0)
        if window:
            s = _window_block_mask(s, iq, kb, block_q, block_k, window)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dq_acc[:] += jax.lax.dot(ds.astype(k.dtype), k,
                                 preferred_element_type=jnp.float32)

    @pl.when(j == pl.num_programs(2) - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(cnt_ref, idx_ref, lay_ref, q_ref, k_ref, v_ref, do_ref,
                    lse_ref, delta_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                    *, H, nk, maxq, sm_scale, causal, block_q, block_k, fine,
                    window, layout_exact):
    b, ik, j = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    row = (b % H) * nk + ik

    @pl.when(j == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    qb = idx_ref[row * maxq + j]
    run = j < cnt_ref[row]

    @pl.when(run)
    def _compute():
        sub8 = lay_ref[0]                   # fine rows of ACTIVE q block qb
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if layout_exact:
            s = _layout_mask(sub8, s, ik, fine, block_q, block_k)
        if causal:
            s = _causal_block_mask(s, qb, ik, block_q, block_k, 0)
        if window:
            s = _window_block_mask(s, qb, ik, block_q, block_k, window)
        p = jnp.exp(s - lse)
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == pl.num_programs(2) - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# host-side schedule building
# ---------------------------------------------------------------------------

def _expand_rows8(layout: np.ndarray, block_q: int, fine: int) -> np.ndarray:
    """[H, nf, nf] fine layout -> [H, nq*8, nf]: exactly 8 rows per kernel q
    block; exact when block_q//8 divides fine (enforced by the caller)."""
    H, nfq, nf = layout.shape
    S = nfq * fine
    nq = S // block_q
    step = block_q // 8
    rows = (np.arange(nq * 8) * step) // fine
    return np.ascontiguousarray(layout[:, rows, :])


def _active_lists(layout: np.ndarray, fine: int, block_q: int, block_k: int
                  ) -> Tuple[np.ndarray, np.ndarray, int]:
    """Coarsen the fine layout to kernel blocks and build, per (head,
    q-block), the padded list of active k-block indices.
    Returns (counts [H*nq] i32, indices [H*nq*maxk] i32, maxk)."""
    H, nfq, nfk = layout.shape
    rq, rk = block_q // fine, block_k // fine
    nq, nk = nfq // rq, nfk // rk
    coarse = layout.reshape(H, nq, rq, nk, rk).any(axis=(2, 4))   # [H,nq,nk]
    counts = coarse.sum(axis=2).astype(np.int32)                  # [H, nq]
    maxk = max(int(counts.max()), 1)
    idx = np.zeros((H, nq, maxk), np.int32)
    for h in range(H):
        for i in range(nq):
            act = np.nonzero(coarse[h, i])[0]
            idx[h, i, :len(act)] = act
            if len(act):
                idx[h, i, len(act):] = act[-1]
    return counts.reshape(-1), idx.reshape(-1), maxk


def _fwd(q3, k3, v3, lay8, cnt, idx, maxk, H, causal, sm_scale, block_q,
         block_k, fine, window, layout_exact, interpret):
    BH, S, D = q3.shape
    nq = S // block_q
    nf = lay8.shape[2]
    kernel = functools.partial(
        _fwd_kernel, H=H, nq=nq, maxk=maxk, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, fine=fine, window=window,
        layout_exact=layout_exact)

    def kv_index(b, i, j, cnt_ref, idx_ref):
        return (b, idx_ref[((b % H) * nq + i) * maxk + j], 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(BH, nq, maxk),
        in_specs=[
            pl.BlockSpec((1, 8, nf), lambda b, i, j, c, x: (b % H, i, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, i, j, c, x: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), kv_index),
            pl.BlockSpec((1, block_k, D), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j, c, x: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j, c, x: (b, 0, i)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
    )
    with jax.named_scope("block_sparse_attention_fwd"):
        o, lse = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((BH, S, D), q3.dtype),
                jax.ShapeDtypeStruct((BH, 1, S), jnp.float32),
            ],
            interpret=interpret,
        )(cnt, idx, lay8, q3, k3, v3)
    return o, lse


def _bwd(q3, k3, v3, o3, do3, lse, lay8, sched, H, causal, sm_scale, block_q,
         block_k, fine, window, layout_exact, interpret):
    BH, S, D = q3.shape
    nq, nk = S // block_q, S // block_k
    nf = lay8.shape[2]
    cnt, idx, maxk, cnt_t, idx_t, maxq = sched
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1)[:, None, :]

    def kv_index(b, i, j, c, x):
        return (b, x[((b % H) * nq + i) * maxk + j], 0)

    grid_dq = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(BH, nq, maxk),
        in_specs=[
            pl.BlockSpec((1, 8, nf), lambda b, i, j, c, x: (b % H, i, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, i, j, c, x: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), kv_index),
            pl.BlockSpec((1, block_k, D), kv_index),
            pl.BlockSpec((1, block_q, D), lambda b, i, j, c, x: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j, c, x: (b, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j, c, x: (b, 0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j, c, x: (b, i, 0))],
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
    )
    with jax.named_scope("block_sparse_attention_bwd_dq"):
        dq = pl.pallas_call(
            functools.partial(_bwd_dq_kernel, H=H, nq=nq, maxk=maxk,
                              sm_scale=sm_scale, causal=causal,
                              block_q=block_q, block_k=block_k, fine=fine,
                              window=window, layout_exact=layout_exact),
            grid_spec=grid_dq,
            out_shape=[jax.ShapeDtypeStruct((BH, S, D), q3.dtype)],
            interpret=interpret,
        )(cnt, idx, lay8, q3, k3, v3, do3, lse, delta)[0]

    # dkv: grid over k blocks x active q blocks (transposed lists); every
    # q-side tensor (q, do, lse, delta) and the layout rows are fetched via
    # the active-q index
    def q_index(b, i, j, c, x):
        return (b, x[((b % H) * nk + i) * maxq + j], 0)

    def row_index(b, i, j, c, x):
        return (b, 0, x[((b % H) * nk + i) * maxq + j])

    grid_dkv = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(BH, nk, maxq),
        in_specs=[
            pl.BlockSpec((1, 8, nf),
                         lambda b, i, j, c, x:
                         (b % H, x[((b % H) * nk + i) * maxq + j], 0)),
            pl.BlockSpec((1, block_q, D), q_index),
            pl.BlockSpec((1, block_k, D), lambda b, i, j, c, x: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j, c, x: (b, i, 0)),
            pl.BlockSpec((1, block_q, D), q_index),
            pl.BlockSpec((1, 1, block_q), row_index),
            pl.BlockSpec((1, 1, block_q), row_index),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, i, j, c, x: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j, c, x: (b, i, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                        pltpu.VMEM((block_k, D), jnp.float32)],
    )
    with jax.named_scope("block_sparse_attention_bwd_dkv"):
        dk, dv = pl.pallas_call(
            functools.partial(_bwd_dkv_kernel, H=H, nk=nk, maxq=maxq,
                              sm_scale=sm_scale, causal=causal,
                              block_q=block_q, block_k=block_k, fine=fine,
                              window=window, layout_exact=layout_exact),
            grid_spec=grid_dkv,
            out_shape=[jax.ShapeDtypeStruct((BH, S, D), k3.dtype),
                       jax.ShapeDtypeStruct((BH, S, D), v3.dtype)],
            interpret=interpret,
        )(cnt_t, idx_t, lay8, q3, k3, v3, do3, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(4, 5, 6, 7, 8, 9, 10, 11, 12, 13))
def _bs_flash(q, k, v, prefetch, sched_meta, H, causal, sm_scale, block_q,
              block_k, fine, window, layout_exact, interpret):
    out, _ = _bs_fwd(q, k, v, prefetch, sched_meta, H, causal, sm_scale,
                     block_q, block_k, fine, window, layout_exact, interpret)
    return out


def _bs_fwd(q, k, v, prefetch, sched_meta, H, causal, sm_scale, block_q,
            block_k, fine, window, layout_exact, interpret):
    maxk, maxq = sched_meta
    lay8, cnt, idx, cnt_t, idx_t = prefetch
    B, Hh, S, D = q.shape
    q3 = q.reshape(B * Hh, S, D)
    k3 = k.reshape(B * Hh, S, D)
    v3 = v.reshape(B * Hh, S, D)
    o3, lse = _fwd(q3, k3, v3, lay8, cnt, idx, maxk, Hh, causal, sm_scale,
                   block_q, block_k, fine, window, layout_exact, interpret)
    return o3.reshape(B, Hh, S, D), (q3, k3, v3, o3, lse, prefetch,
                                     (B, Hh, S, D))


def _bs_bwd(sched_meta, H, causal, sm_scale, block_q, block_k, fine, window,
            layout_exact, interpret, res, g):
    q3, k3, v3, o3, lse, prefetch, (B, Hh, S, D) = res
    maxk, maxq = sched_meta
    lay8, cnt, idx, cnt_t, idx_t = prefetch
    do3 = g.reshape(B * Hh, S, D)
    sched = (cnt, idx, maxk, cnt_t, idx_t, maxq)
    dq, dk, dv = _bwd(q3, k3, v3, o3, do3, lse, lay8, sched, Hh, causal,
                      sm_scale, block_q, block_k, fine, window, layout_exact,
                      interpret)
    return (dq.reshape(B, Hh, S, D), dk.reshape(B, Hh, S, D),
            dv.reshape(B, Hh, S, D), (None,) * 5)


_bs_flash.defvjp(_bs_fwd, _bs_bwd)


def block_sparse_flash_attention(q: jnp.ndarray,
                                 k: jnp.ndarray,
                                 v: jnp.ndarray,
                                 layout: np.ndarray,
                                 fine_block: int,
                                 *,
                                 causal: bool = False,
                                 sm_scale: Optional[float] = None,
                                 block_q: int = 256,
                                 block_k: int = 256,
                                 window: int = 0,
                                 layout_exact: bool = True,
                                 interpret: bool = False) -> jnp.ndarray:
    """Layout-skipping attention. q,k,v: [B, H, S, D]; layout [H, nq, nk]
    bool at ``fine_block`` granularity (SparsityConfig.make_layout output).

    Returns exactly what the dense-mask oracle returns for the same layout
    (rows with no active keys produce zeros). Raises when shapes can't tile —
    callers fall back to the mask path (ops/sparse_attention.sparse_attention).
    """
    B, H, S, D = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(D))
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    if fine_block > block_q or fine_block > block_k:
        # a very coarse layout: the fine block IS the kernel block
        block_q = block_k = fine_block
    # the q side of the layout rides the BlockSpec at block_q//8 granularity —
    # that step must subdivide a fine block exactly
    while block_q > 8 and (block_q // 8 > fine_block
                           or fine_block % (block_q // 8)):
        block_q //= 2
    if (S % block_q or S % block_k or block_q % 8
            or block_k % fine_block or D % 8):
        raise ValueError(
            f"block_sparse_flash_attention cannot tile S={S}, D={D} with "
            f"kernel blocks ({block_q},{block_k}) and fine block {fine_block}")
    nf = S // fine_block
    if layout.shape != (H, nf, nf):
        raise ValueError(f"layout shape {layout.shape} != {(H, nf, nf)} for "
                         f"S={S}, fine_block={fine_block}")
    lay_np = np.asarray(layout).astype(np.int32)
    lay8 = jnp.asarray(_expand_rows8(lay_np, block_q, fine_block))
    cnt, idx, maxk = _active_lists(lay_np, fine_block, block_q, block_k)
    cnt_t, idx_t, maxq = _active_lists(
        lay_np.transpose(0, 2, 1), fine_block, block_k, block_q)
    prefetch = (lay8, jnp.asarray(cnt), jnp.asarray(idx),
                jnp.asarray(cnt_t), jnp.asarray(idx_t))
    return _bs_flash(q, k, v, prefetch, (maxk, maxq), H, causal, sm_scale,
                     block_q, block_k, fine_block, window, layout_exact,
                     interpret)
