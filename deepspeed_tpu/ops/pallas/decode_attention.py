"""Pallas decode attention — KV-cache attention that skips the dead tail.

Capability slot of the reference's fused decode kernels
(``csrc/transformer/inference/csrc/pt_binding.cpp:1703-1779``
``softmax_context``: attention against the preallocated KV workspace at the
CURRENT sequence length).  The jnp decode path scores the query against the
ENTIRE max_len cache every token; this kernel visits only
``ceil(cur_len / block_k)`` K/V blocks — both the compute AND the HBM DMA of
the dead tail are skipped, so per-token cost scales with the tokens generated
so far, not the preallocated maximum.

Mechanics (same machinery as block_sparse_attention's block-skip):
  * ``cur_len`` rides in as a prefetched scalar; the K/V BlockSpec index_map
    clamps dead grid steps to the last active block — Pallas's pipeline sees
    a repeated block index and elides the copy.
  * ``@pl.when(j < cnt)`` skips the FLOPs of dead steps.
  * heads are folded into each program in groups (batched MXU dots), so the
    decode loop issues B * nh/hg programs per k-block instead of B * nh.
  * causal + current-length + optional sliding-window masking is exact
    per-token, all driven by scalars so one compiled kernel serves the whole
    generation loop (no recompile as the sequence grows).
  * ALiBi (per-head slopes, bias rebuilt from indices) and the Gemma-2 tanh
    softcap run in-kernel — BLOOM/MPT and Gemma-2-class models decode on the
    kernel instead of silently falling back to the jnp path.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import NEG_INF

__all__ = ["decode_attention"]


def _kernel(scal_ref, q_ref, k_ref, v_ref, slopes_ref, o_ref, acc, m_scr,
            l_scr, *, hg, Tp, block_k, nk, sm_scale, softcap, has_alibi,
            stacked):
    j = pl.program_id(1)
    cnt, qstart, window = scal_ref[0], scal_ref[1], scal_ref[2]

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    @pl.when(j < cnt)
    def _compute():
        q = q_ref[0]                                        # [hg, Tp, hd]
        k = k_ref[0, 0] if stacked else k_ref[0]            # [hg, bk, hd]
        v = v_ref[0, 0] if stacked else v_ref[0]
        s = jax.lax.dot_general(q, k, (((2,), (2,)), ((0,), (0,))),
                                preferred_element_type=jnp.float32) * sm_scale
        if softcap:
            # Gemma-2 logit cap, BEFORE bias/masks (the decode-path order)
            s = jnp.tanh(s / softcap) * softcap
        # rows t of the (padded) q block are absolute position qstart + t;
        # cols are cache positions j*block_k + c
        q_abs = qstart + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        if has_alibi:
            # per-head slope * (k - q) distance, built from indices — the
            # same in-kernel term as the flash kernel's prefill bias
            slope = slopes_ref[0][:, :1][:, None, :]        # [hg, 1, 1]
            s = s + slope * (k_pos - q_abs).astype(jnp.float32)
        keep = k_pos <= q_abs                               # causal w/ cache
        keep &= (q_abs - k_pos < window) | (window <= 0)    # sliding window
        s = jnp.where(keep, s, NEG_INF)
        m_prev = m_scr[:, :, :1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur)
        l_scr[:, :, :1] = (l_scr[:, :, :1] * alpha
                           + jnp.sum(p, axis=2, keepdims=True))
        acc[:] = acc[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        m_scr[:, :, :1] = m_cur

    @pl.when(j == nk - 1)
    def _finalize():
        l = l_scr[:, :, :1]
        o_ref[0] = (acc[:] / jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)


def _head_group(nh: int, block_k: int, hd: int, itemsize: int) -> int:
    """Heads per program: target ~1MB K blocks, largest divisor of nh."""
    target = max(1, (1 << 20) // (block_k * hd * itemsize))
    hg = 1
    for d in range(1, nh + 1):
        if nh % d == 0 and d <= target:
            hg = d
    return hg


def decode_attention(q: jnp.ndarray,
                     k_cache: jnp.ndarray,
                     v_cache: jnp.ndarray,
                     cur_len: jnp.ndarray,
                     *,
                     window=None,
                     sm_scale: Optional[float] = None,
                     block_k: int = 512,
                     layer_idx=None,
                     alibi_slopes=None,
                     softcap: float = 0.0,
                     interpret: bool = False) -> jnp.ndarray:
    """Attention of T new tokens against a preallocated KV cache.

    q: [B, nh, T, hd] — queries for absolute positions
       [cur_len - T, cur_len).
    k_cache/v_cache: [B, nh, max_len, hd]; positions >= cur_len are dead.
       With ``layer_idx`` (traced i32 ok): [L, B, nh, max_len, hd] — the
       kernel's index_map picks layer blocks directly out of the stacked
       cache, so a scan-carried cache needs NO materialized per-layer slice.
    cur_len: i32 scalar (traced ok), total valid length INCLUDING the T new
       tokens.  window: python int or traced i32 scalar; <= 0 means global.
    alibi_slopes: [nh] per-head slopes — the bias slope * (k_pos - q_pos)
       is built from indices in-kernel (BLOOM/MPT decode stays on the
       kernel). softcap: Gemma-2 tanh logit cap, STATIC float (it changes
       the compiled math). Returns [B, nh, T, hd].

    Raises ValueError when shapes can't tile (tiny head_dim / max_len) —
    callers fall back to the jnp path.
    """
    B, nh, T, hd = q.shape
    if T > 64:
        # decode-regime kernel: per-program scratch scales with T, and a
        # large-T call is the PREFILL, which is an ordinary causal attention
        # the MXU-shaped flash/jnp paths already handle well
        raise ValueError(f"decode_attention is for small T (got {T})")
    stacked = layer_idx is not None
    max_len = k_cache.shape[3 if stacked else 2]
    if max_len % block_k != 0:
        block_k = int(np.gcd(max_len, block_k))
        if block_k < 128:
            raise ValueError(f"max_len {max_len} has no >=128 block tiling")
    if hd % 8 != 0 and not interpret:
        # Mosaic pads sub-128 lane dims (64 measured fine on v5e); truly odd
        # head dims fall back to the jnp path
        raise ValueError(f"head_dim {hd} does not tile")
    nk = max_len // block_k
    Tp = max(8, -(-T // 8) * 8)                  # sublane-pad the q rows
    hg = _head_group(nh, block_k, hd, k_cache.dtype.itemsize)
    ng = nh // hg
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(hd)

    qf = q.reshape(B * nh, T, hd)
    if Tp != T:
        qf = jnp.pad(qf, ((0, 0), (0, Tp - T), (0, 0)))
    qf = qf.reshape(B * ng, hg, Tp, hd)

    cur = jnp.asarray(cur_len, jnp.int32)
    cnt = (cur + block_k - 1) // block_k
    win = jnp.asarray(0 if window is None else window, jnp.int32)
    li = jnp.asarray(0 if layer_idx is None else layer_idx, jnp.int32)
    scal = jnp.stack([cnt, cur - T, win.reshape(()), li.reshape(())])
    softcap = float(softcap) if softcap else 0.0
    has_alibi = alibi_slopes is not None
    if has_alibi:
        # [B*ng, hg, 128]: program g reads its head group's slopes from its
        # own tile (no dynamic VMEM scalar indexing)
        sl = jnp.asarray(alibi_slopes, jnp.float32).reshape(ng, hg)
        slopes = jnp.broadcast_to(sl[None, :, :, None],
                                  (B, ng, hg, 128)).reshape(B * ng, hg, 128)
    else:
        slopes = jnp.zeros((1, 1, 128), jnp.float32)    # placeholder

    # dead grid steps clamp to the last active block: a repeated index means
    # the pipeline skips the K/V copy (the DMA half of the block skip)
    if stacked:
        L = k_cache.shape[0]
        kf = k_cache.reshape(L, B * ng, hg, max_len, hd)
        vf = v_cache.reshape(L, B * ng, hg, max_len, hd)
        kv_spec = pl.BlockSpec(
            (1, 1, hg, block_k, hd),
            lambda g, j, s: (s[3], g, 0, jnp.minimum(j, s[0] - 1), 0))
    else:
        kf = k_cache.reshape(B * ng, hg, max_len, hd)
        vf = v_cache.reshape(B * ng, hg, max_len, hd)
        kv_spec = pl.BlockSpec(
            (1, hg, block_k, hd),
            lambda g, j, s: (g, 0, jnp.minimum(j, s[0] - 1), 0))
    slopes_spec = (pl.BlockSpec((1, hg, 128), lambda g, j, s: (g, 0, 0))
                   if has_alibi else
                   pl.BlockSpec((1, 1, 128), lambda g, j, s: (0, 0, 0)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B * ng, nk),
        in_specs=[
            pl.BlockSpec((1, hg, Tp, hd), lambda g, j, s: (g, 0, 0, 0)),
            kv_spec,
            kv_spec,
            slopes_spec,
        ],
        out_specs=pl.BlockSpec((1, hg, Tp, hd), lambda g, j, s: (g, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((hg, Tp, hd), jnp.float32),
            pltpu.VMEM((hg, Tp, 128), jnp.float32),
            pltpu.VMEM((hg, Tp, 128), jnp.float32),
        ],
    )
    with jax.named_scope("decode_attention"):
        out = pl.pallas_call(
            partial(_kernel, hg=hg, Tp=Tp, block_k=block_k, nk=nk,
                    sm_scale=scale, softcap=softcap, has_alibi=has_alibi,
                    stacked=stacked),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((B * ng, hg, Tp, hd), q.dtype),
            interpret=interpret,
        )(scal, qf, kf, vf, slopes)
    return out.reshape(B, nh, Tp, hd)[:, :, :T]
