"""Pallas TPU kernels — the rebuild's equivalent of the reference's csrc/ CUDA
kernel families (transformer attention, quantization, …). Every kernel has a
jnp reference oracle in ops/ and interpreter-mode parity tests."""
