"""Flash attention (FlashAttention-2 schedule) as Pallas TPU kernels.

Fills the slot of the reference's attention kernels: the fused softmax/attention
CUDA path (csrc/transformer/softmax_kernels.cu, attn kernels) and the Triton
block-sparse attention (deepspeed/ops/sparse_attention/) — block-sparse masks
plug in via the same block-skip mechanism used for causal masking here
(see ops/sparse.py).

Layout: inputs [batch, heads, seq, head_dim] are flattened to [B*H, S, D];
grid = (B*H, q_blocks, k_blocks) with the k dimension innermost (sequential on
TPU), carrying the online-softmax running max/denominator in VMEM scratch.
Backward recomputes probabilities from the saved logsumexp (no S×S
materialization) in two kernels: dq (grid over q blocks) and dk/dv (grid over
k blocks).

In-kernel score features (the reference fuses the same set into its softmax
kernels — masking, alibi, and the inference softmax_context path):
  * boolean masks, in two forms: a key/padding mask [B, 1, 1, Sk] rides as an
    O(S) per-key row broadcast over queries; anything with a query dimension
    rides as per-(q-block, k-block) tiles. Fully-masked tiles skip the MXU
    work entirely (same ``@pl.when`` block-skip as causal).
  * ALiBi bias from per-head slopes: the bias term slope * (k_pos - q_pos) is
    rebuilt from block indices via iota — no [B, H, S, S] materialization
    anywhere, forward or backward.
  * causal sliding-window masking: KV blocks strictly outside
    (q - window, q] are skipped at block level; the boundary blocks apply the
    exact per-token window.
  * logit softcap (Gemma-2): cap * tanh(s / cap) pre-softmax; the backward
    threads the tanh derivative through dS.
Attention dropout has NO kernel path (the router falls back to the jnp
reference for it).

Numerics: logits and softmax statistics in fp32; the P·V / dP matmuls cast P to
the value dtype (bf16), matching standard flash implementations. Query rows
with zero active keys produce ZEROS (and zero grads) — the jnp reference's
softmax of an all-masked row degenerates to uniform weights instead, so parity
holds on rows that attend at least one key (any real padding layout).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _causal_block_mask(s, iq, ik, block_q, block_k, offset):
    """Apply the triangular mask inside a diagonal block. s: [block_q, block_k].

    ``offset = k_len - q_len`` matches mha_reference's causal semantics: the
    last query row attends all keys (used for decode where Sk > S)."""
    q_pos = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + offset
    k_pos = ik * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(k_pos <= q_pos, s, NEG_INF)


def _scores(s, iq, ik, *, block_q, block_k, offset, causal, window, softcap,
            slope, kvm, qkm):
    """Shared fwd/bwd score pipeline on one [block_q, block_k] tile.

    Order matches mha_reference: scaled logits -> softcap -> +alibi bias ->
    causal/window/boolean masks to NEG_INF. Returns (s, dsoft) where dsoft
    is d(capped)/d(raw) for the backward (None when softcap is off)."""
    dsoft = None
    if softcap:
        t = jnp.tanh(s / softcap)
        s = t * softcap
        dsoft = 1.0 - t * t
    if slope is None and not window and kvm is None and qkm is None:
        # pure causal: mask only the diagonal block (interior blocks are
        # either fully attended or skipped by the grid-level `run` gate)
        if causal:
            diagonal = ik * block_k + block_k > iq * block_q + offset
            s = jax.lax.cond(
                diagonal,
                lambda x: _causal_block_mask(x, iq, ik, block_q, block_k,
                                             offset),
                lambda x: x, s)
        return s, dsoft
    q_pos = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) \
        + offset
    k_pos = ik * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    if slope is not None:
        s = s + slope * (k_pos - q_pos).astype(jnp.float32)
    keep = None
    if causal:
        keep = k_pos <= q_pos
    if window:
        w = q_pos - k_pos < window
        keep = w if keep is None else keep & w
    if kvm is not None:                       # [1, block_k] broadcasts over q
        keep = kvm if keep is None else keep & kvm
    if qkm is not None:                       # [block_q, block_k]
        keep = qkm if keep is None else keep & qkm
    if keep is not None:
        s = jnp.where(keep, s, NEG_INF)
    return s, dsoft


def _unpack(refs, n_fixed, has_kvm, has_qkm, has_alibi):
    """Split a kernel's ref list into (fixed inputs, kvm, qkm, slopes, rest)."""
    fixed = refs[:n_fixed]
    i = n_fixed
    kvm_ref = qkm_ref = slopes_ref = None
    if has_kvm:
        kvm_ref = refs[i]
        i += 1
    if has_qkm:
        qkm_ref = refs[i]
        i += 1
    if has_alibi:
        slopes_ref = refs[i]
        i += 1
    return fixed, kvm_ref, qkm_ref, slopes_ref, refs[i:]


def _run_gate(causal, window, offset, block_q, block_k, iq, ik,
              kvm_ref, qkm_ref):
    """Block-level skip predicate for the (iq, ik) tile: out-of-triangle /
    out-of-window blocks and fully-masked mask tiles contribute nothing."""
    conds = []
    if causal:
        conds.append(ik * block_k <= iq * block_q + block_q - 1 + offset)
    if window:
        conds.append(ik * block_k + block_k - 1
                     >= iq * block_q + offset - (window - 1))
    if kvm_ref is not None:
        conds.append(jnp.any(kvm_ref[0] != 0))
    if qkm_ref is not None:
        conds.append(jnp.any(qkm_ref[0] != 0))
    if not conds:
        return True
    return functools.reduce(jnp.logical_and, conds)


def _mask_operands(kvm_ref, qkm_ref, slopes_ref):
    kvm = (kvm_ref[0] != 0) if kvm_ref is not None else None
    qkm = (qkm_ref[0] != 0) if qkm_ref is not None else None
    slope = slopes_ref[0][0, 0] if slopes_ref is not None else None
    return kvm, qkm, slope


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(*refs, sm_scale, causal, block_q, block_k, offset, window,
                softcap, has_kvm, has_qkm, has_alibi):
    (q_ref, k_ref, v_ref), kvm_ref, qkm_ref, slopes_ref, rest = _unpack(
        refs, 3, has_kvm, has_qkm, has_alibi)
    o_ref, lse_ref, acc, m_scr, l_scr = rest
    iq, ik = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    run = _run_gate(causal, window, offset, block_q, block_k, iq, ik,
                    kvm_ref, qkm_ref)
    guarded = causal or bool(window) or has_kvm or has_qkm

    @pl.when(run)
    def _compute():
        q = q_ref[0]  # [block_q, D]
        k = k_ref[0]  # [block_k, D]
        v = v_ref[0]
        kvm, qkm, slope = _mask_operands(kvm_ref, qkm_ref, slopes_ref)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        s, _ = _scores(s, iq, ik, block_q=block_q, block_k=block_k,
                       offset=offset, causal=causal, window=window,
                       softcap=softcap, slope=slope, kvm=kvm, qkm=qkm)
        m_prev = m_scr[:, :1]                       # [block_q, 1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_cur)
        if guarded:
            # a fully-masked ROW inside a live tile has m_cur == NEG_INF and
            # exp(s - m_cur) would degenerate to 1 per entry; zero it so
            # l stays 0, the output finalizes to zeros, and the backward's
            # identical guard makes the grads the true gradient of THIS fwd
            p = jnp.where(s > NEG_INF * 0.5, jnp.exp(s - m_cur), 0.0)
        else:
            p = jnp.exp(s - m_cur)                  # [block_q, block_k] f32
        l_scr[:, :1] = l_scr[:, :1] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc[:] = acc[:] * alpha + jax.lax.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_scr[:, :1] = m_cur

    last = (jnp.clip((iq * block_q + block_q - 1 + offset) // block_k, 0, nk - 1)
            if causal else nk - 1)

    @pl.when(ik == last)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_scr[:, 0] + jnp.log(jnp.maximum(l[:, 0], 1e-37)))


def _extra_specs(kvm, qkm, slopes, H, mask_per_head, block_q, block_k,
                 qi, ki):
    """BlockSpecs + operands for the optional mask/slope inputs. ``qi``/``ki``
    pick the q- and k-block grid indices out of (b, *grid) so the same
    builder serves the fwd (b, iq, ik) and dkv (b, ik, iq) grids."""
    specs, operands = [], []
    if kvm is not None:
        specs.append(pl.BlockSpec(
            (1, 1, block_k), lambda b, i, j: (b // H, 0, (i, j)[ki])))
        operands.append(kvm)
    if qkm is not None:
        div = 1 if mask_per_head else H
        specs.append(pl.BlockSpec(
            (1, block_q, block_k),
            lambda b, i, j: (b // div, (i, j)[qi], (i, j)[ki])))
        operands.append(qkm)
    if slopes is not None:
        specs.append(pl.BlockSpec((1, 1, 128), lambda b, i, j: (b, 0, 0)))
        operands.append(slopes)
    return specs, operands


def _fwd(q3, k3, v3, kvm, qkm, slopes, H, causal, sm_scale, block_q, block_k,
         window, softcap, mask_per_head, interpret):
    BH, S, D = q3.shape
    Sk = k3.shape[1]
    nq, nk = S // block_q, Sk // block_k
    grid = (BH, nq, nk)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, offset=Sk - S, window=window, softcap=softcap,
        has_kvm=kvm is not None, has_qkm=qkm is not None,
        has_alibi=slopes is not None)
    extra_specs, extra_ops = _extra_specs(kvm, qkm, slopes, H, mask_per_head,
                                          block_q, block_k, qi=0, ki=1)
    with jax.named_scope("flash_attention_fwd"):
        o, lse = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            ] + extra_specs,
            out_specs=[
                pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((BH, S, D), q3.dtype),
                jax.ShapeDtypeStruct((BH, 1, S), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, D), jnp.float32),
                pltpu.VMEM((block_q, 128), jnp.float32),
                pltpu.VMEM((block_q, 128), jnp.float32),
            ],
            interpret=interpret,
        )(q3, k3, v3, *extra_ops)
    return o, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_p(s, lse, guarded):
    """Recover P from the saved logsumexp. ``guarded`` zeroes masked entries
    explicitly: a fully-masked row's lse is itself NEG_INF-sized, and the
    plain exp(s - lse) would resurrect p=1 there."""
    if guarded:
        return jnp.where(s > NEG_INF * 0.5, jnp.exp(s - lse), 0.0)
    return jnp.exp(s - lse)


def _bwd_dq_kernel(*refs, sm_scale, causal, block_q, block_k, offset, window,
                   softcap, has_kvm, has_qkm, has_alibi):
    ((q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref), kvm_ref, qkm_ref,
     slopes_ref, rest) = _unpack(refs, 6, has_kvm, has_qkm, has_alibi)
    dq_ref, dq_acc = rest
    iq, ik = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = _run_gate(causal, window, offset, block_q, block_k, iq, ik,
                    kvm_ref, qkm_ref)
    guarded = causal or bool(window) or has_kvm or has_qkm

    @pl.when(run)
    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse = lse_ref[0, 0][:, None]                # [block_q, 1]
        delta = delta_ref[0, 0][:, None]
        kvm, qkm, slope = _mask_operands(kvm_ref, qkm_ref, slopes_ref)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        s, dsoft = _scores(s, iq, ik, block_q=block_q, block_k=block_k,
                           offset=offset, causal=causal, window=window,
                           softcap=softcap, slope=slope, kvm=kvm, qkm=qkm)
        p = _bwd_p(s, lse, guarded)                 # [block_q, block_k]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        if dsoft is not None:
            ds = ds * dsoft
        ds = ds * sm_scale
        dq_acc[:] += jax.lax.dot(ds.astype(k.dtype), k,
                                 preferred_element_type=jnp.float32)

    last = (jnp.clip((iq * block_q + block_q - 1 + offset) // block_k, 0, nk - 1)
            if causal else nk - 1)

    @pl.when(ik == last)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, sm_scale, causal, block_q, block_k, offset, window,
                    softcap, has_kvm, has_qkm, has_alibi):
    ((q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref), kvm_ref, qkm_ref,
     slopes_ref, rest) = _unpack(refs, 6, has_kvm, has_qkm, has_alibi)
    dk_ref, dv_ref, dk_acc, dv_acc = rest
    ik, iq = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    # same predicate as the fwd/dq grids: causal (q blocks strictly before
    # this k block never attend it) and window (q blocks entirely past the
    # window never attend it) are symmetric in (iq, ik)
    run = _run_gate(causal, window, offset, block_q, block_k, iq, ik,
                    kvm_ref, qkm_ref)
    guarded = causal or bool(window) or has_kvm or has_qkm

    @pl.when(run)
    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        kvm, qkm, slope = _mask_operands(kvm_ref, qkm_ref, slopes_ref)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        s, dsoft = _scores(s, iq, ik, block_q=block_q, block_k=block_k,
                           offset=offset, causal=causal, window=window,
                           softcap=softcap, slope=slope, kvm=kvm, qkm=qkm)
        p = _bwd_p(s, lse, guarded)                 # [block_q, block_k]
        # dV += P^T dO
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        if dsoft is not None:
            ds = ds * dsoft
        ds = ds * sm_scale                          # [block_q, block_k]
        # dK += dS^T Q
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(iq == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(q3, k3, v3, o3, do3, lse, kvm, qkm, slopes, H, causal, sm_scale,
         block_q, block_k, window, softcap, mask_per_head, interpret):
    BH, S, D = q3.shape
    Sk = k3.shape[1]
    nq, nk = S // block_q, Sk // block_k
    # delta_i = rowsum(dO * O) — small elementwise pass, XLA fuses it
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1)[:, None, :]            # [BH, 1, S]
    static = dict(sm_scale=sm_scale, causal=causal, block_q=block_q,
                  block_k=block_k, offset=Sk - S, window=window,
                  softcap=softcap, has_kvm=kvm is not None,
                  has_qkm=qkm is not None, has_alibi=slopes is not None)

    qspec = pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0))
    kspec_for_dq = pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0))
    row_q = pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i))
    extra_specs, extra_ops = _extra_specs(kvm, qkm, slopes, H, mask_per_head,
                                          block_q, block_k, qi=0, ki=1)
    with jax.named_scope("flash_attention_bwd_dq"):
        dq = pl.pallas_call(
            functools.partial(_bwd_dq_kernel, **static),
            grid=(BH, nq, nk),
            in_specs=[qspec, kspec_for_dq, kspec_for_dq, qspec, row_q, row_q]
            + extra_specs,
            out_specs=[qspec],
            out_shape=[jax.ShapeDtypeStruct((BH, S, D), q3.dtype)],
            scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
            interpret=interpret,
        )(q3, k3, v3, do3, lse, delta, *extra_ops)[0]

    # dkv: grid dim 1 = k block, dim 2 (innermost) = q block
    qspec2 = pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0))
    kspec2 = pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0))
    row_q2 = pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i))
    extra_specs2, extra_ops2 = _extra_specs(kvm, qkm, slopes, H,
                                            mask_per_head, block_q, block_k,
                                            qi=1, ki=0)
    with jax.named_scope("flash_attention_bwd_dkv"):
        dk, dv = pl.pallas_call(
            functools.partial(_bwd_dkv_kernel, **static),
            grid=(BH, nk, nq),
            in_specs=[qspec2, kspec2, kspec2, qspec2, row_q2, row_q2]
            + extra_specs2,
            out_specs=[kspec2, kspec2],
            out_shape=[jax.ShapeDtypeStruct((BH, Sk, D), k3.dtype),
                       jax.ShapeDtypeStruct((BH, Sk, D), v3.dtype)],
            scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                            pltpu.VMEM((block_k, D), jnp.float32)],
            interpret=interpret,
        )(q3, k3, v3, do3, lse, delta, *extra_ops2)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public entry with custom VJP
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14))
def _flash(q, k, v, extras, H, causal, sm_scale, block_q, block_k,
           block_q_bwd, block_k_bwd, window, softcap, mask_per_head,
           interpret):
    out, _ = _flash_fwd(q, k, v, extras, H, causal, sm_scale, block_q,
                        block_k, block_q_bwd, block_k_bwd, window, softcap,
                        mask_per_head, interpret)
    return out


def _flash_fwd(q, k, v, extras, H, causal, sm_scale, block_q, block_k,
               block_q_bwd, block_k_bwd, window, softcap, mask_per_head,
               interpret):
    kvm, qkm, slopes = extras
    B, Hq, S, D = q.shape
    Sk = k.shape[2]
    q3 = q.reshape(B * Hq, S, D)
    k3 = k.reshape(B * Hq, Sk, D)
    v3 = v.reshape(B * Hq, Sk, D)
    o3, lse = _fwd(q3, k3, v3, kvm, qkm, slopes, H, causal, sm_scale,
                   block_q, block_k, window, softcap, mask_per_head,
                   interpret)
    return o3.reshape(B, Hq, S, D), (q3, k3, v3, o3, lse, kvm, qkm, slopes,
                                     (B, Hq, S, D))


def _flash_bwd(H, causal, sm_scale, block_q, block_k, block_q_bwd,
               block_k_bwd, window, softcap, mask_per_head, interpret,
               res, g):
    q3, k3, v3, o3, lse, kvm, qkm, slopes, (B, Hq, S, D) = res
    do3 = g.reshape(B * Hq, S, D)
    dq, dk, dv = _bwd(q3, k3, v3, o3, do3, lse, kvm, qkm, slopes, H, causal,
                      sm_scale, block_q_bwd, block_k_bwd, window, softcap,
                      mask_per_head, interpret)
    Sk = k3.shape[1]
    return (dq.reshape(B, Hq, S, D), dk.reshape(B, Hq, Sk, D),
            dv.reshape(B, Hq, Sk, D), (None, None, None))


_flash.defvjp(_flash_fwd, _flash_bwd)


def _normalize_mask(mask, B, H, S, Sk):
    """Classify a boolean mask (any mha_reference-broadcastable shape) into
    the kernel's two forms: a key mask [B, 1, Sk] (no query dim — the
    padding case, O(S) memory) or query-block tiles [B(*H), S, Sk].
    Returns (kvm, qkm, mask_per_head); int32 because Mosaic tiles i32/f32
    uniformly where bool memrefs are not portable."""
    mask = jnp.asarray(mask)
    if mask.ndim > 4:
        raise ValueError(f"attention mask has rank {mask.ndim} > 4")
    mask = mask.reshape((1,) * (4 - mask.ndim) + mask.shape)
    mb, mh, mq, mk = mask.shape
    if mb not in (1, B) or mh not in (1, H) or mq not in (1, S) \
            or mk not in (1, Sk):
        raise ValueError(f"mask shape {mask.shape} does not broadcast to "
                         f"{(B, H, S, Sk)}")
    if mh == 1 and mq == 1:
        kvm = jnp.broadcast_to(mask, (B, 1, 1, Sk)).reshape(B, 1, Sk)
        return kvm.astype(jnp.int32), None, False
    per_head = mh == H and H > 1
    if per_head:
        qkm = jnp.broadcast_to(mask, (B, H, S, Sk)).reshape(B * H, S, Sk)
    else:
        qkm = jnp.broadcast_to(mask, (B, 1, S, Sk)).reshape(B, S, Sk)
    return None, qkm.astype(jnp.int32), per_head


def flash_attention(q: jnp.ndarray,
                    k: jnp.ndarray,
                    v: jnp.ndarray,
                    *,
                    causal: bool = True,
                    sm_scale: Optional[float] = None,
                    mask: Optional[jnp.ndarray] = None,
                    alibi_slopes=None,
                    window: int = 0,
                    softcap: float = 0.0,
                    block_q: int = 1024,
                    block_k: int = 1024,
                    block_q_bwd: Optional[int] = None,
                    block_k_bwd: Optional[int] = None,
                    interpret: bool = False) -> jnp.ndarray:
    """Flash attention. q,k,v: [batch, heads, seq, head_dim] -> same shape.

    ``mask``: boolean, True = attend, any shape broadcastable to
    [B, H, Sq, Sk] (padding masks [B, 1, 1, Sk] ride an O(S) kernel input).
    ``alibi_slopes``: [H] per-head slopes; the bias slope * (k - q) is built
    from block indices in-kernel. ``window`` > 0 (causal only): sliding
    window with block-level skip. ``softcap``: Gemma-2 tanh logit cap.
    All features compose and are differentiable (fwd + bwd in-kernel).

    Forward and backward take independent block sizes: measured on v5e
    (gpt2-350m, seq 1024, D=64) 1024x1024 blocks win for BOTH passes — at
    seq<=1024 the whole sequence sits in one tile (no online-softmax loop),
    and per-step MXU occupancy dominates VMEM pressure up to that size.

    Falls back to the jnp reference when shapes don't tile (short
    sequences), or for a non-causal window: kernels want seq % block == 0
    and head_dim lane-friendly.
    """
    B, H, S, D = q.shape
    Sk = k.shape[-2]
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(D))
    window = int(window) if window and window > 0 else 0
    softcap = float(softcap) if softcap else 0.0

    # classify the mask (shape work only; materialization happens after the
    # tiling check passes)
    mask4 = None
    if mask is not None:
        mask4 = jnp.asarray(mask)
        if mask4.ndim > 4:
            raise ValueError(f"attention mask has rank {mask4.ndim} > 4")
        mask4 = mask4.reshape((1,) * (4 - mask4.ndim) + mask4.shape)
    has_qk_mask = mask4 is not None and (mask4.shape[1] != 1
                                         or mask4.shape[2] != 1)
    if has_qk_mask:
        # per-(q,k) tiles live in VMEM next to the f32 score tile: cap the
        # tile footprint (1024² i32 mask + f32 scores alone would be 8MB) —
        # for the backward kernels too, which carry even more live tiles
        block_q = min(block_q, 512)
        block_k = min(block_k, 512)
        block_q_bwd = min(block_q_bwd, 512) if block_q_bwd else None
        block_k_bwd = min(block_k_bwd, 512) if block_k_bwd else None

    def snap(seq_len: int, want: int) -> int:
        """Largest 16-multiple divisor of seq_len <= want (keeps e.g.
        seq=1280 on the kernel at block 256 instead of falling back dense)."""
        b = min(want, seq_len)
        while b > 16 and (seq_len % b or b % 16):
            b -= 16
        return b

    block_q = snap(S, block_q)
    block_k = snap(Sk, block_k)
    block_q_bwd = snap(S, block_q_bwd or max(block_q, 512))
    block_k_bwd = snap(Sk, block_k_bwd or max(block_k, 512))
    # fall back unless blocks tile the sequences AND are TPU-tile aligned
    # (sublane multiple of 16 covers bf16; lane dim D padded by Mosaic)
    aligned = all(s % b == 0 and b % 16 == 0
                  for s, b in [(S, block_q), (Sk, block_k),
                               (S, block_q_bwd), (Sk, block_k_bwd)]) \
        and D % 8 == 0
    # non-TPU backends can only run the kernel interpreted — fall back to
    # the exact reference instead of crashing in pallas_call
    runnable = interpret or jax.default_backend() == "tpu"
    if not aligned or (window and not causal) or not runnable:
        return _reference_fallback(q, k, v, causal, sm_scale, mask,
                                   alibi_slopes, window, softcap)
    kvm = qkm = None
    mask_per_head = False
    if mask4 is not None:
        kvm, qkm, mask_per_head = _normalize_mask(mask4, B, H, S, Sk)
    slopes3 = None
    if alibi_slopes is not None:
        sl = jnp.asarray(alibi_slopes, jnp.float32).reshape(H)
        # [B*H, 1, 128] so each program reads its head's slope from its own
        # (1, 1, 128) tile — no dynamic VMEM scalar indexing
        slopes3 = jnp.broadcast_to(jnp.tile(sl, B)[:, None, None],
                                   (B * H, 1, 128))
    return _flash(q, k, v, (kvm, qkm, slopes3), H, causal, sm_scale, block_q,
                  block_k, block_q_bwd, block_k_bwd, window, softcap,
                  mask_per_head, interpret)


def _reference_fallback(q, k, v, causal, sm_scale, mask, alibi_slopes,
                        window, softcap):
    """Exact jnp path for untileable shapes: same feature semantics, the
    O(S²) way (bias/window materialized)."""
    from ..attention import alibi_bias_from_slopes, mha_reference, window_mask
    S, Sk = q.shape[-2], k.shape[-2]
    bias = None
    if alibi_slopes is not None:
        bias = alibi_bias_from_slopes(alibi_slopes, S, Sk)
    if window:
        wmask = window_mask(S, Sk, window)
        mask = wmask if mask is None else jnp.asarray(mask).astype(bool) & wmask
    return mha_reference(q, k, v, causal=causal, bias=bias, mask=mask,
                         sm_scale=sm_scale, softcap=softcap)
