"""Flash attention (FlashAttention-2 schedule) as Pallas TPU kernels.

Fills the slot of the reference's attention kernels: the fused softmax/attention
CUDA path (csrc/transformer/softmax_kernels.cu, attn kernels) and the Triton
block-sparse attention (deepspeed/ops/sparse_attention/) — block-sparse masks
plug in via the same block-skip mechanism used for causal masking here
(see ops/sparse.py).

Layout: inputs [batch, heads, seq, head_dim] are flattened to [B*H, S, D];
grid = (B*H, q_blocks, k_blocks) with the k dimension innermost (sequential on
TPU), carrying the online-softmax running max/denominator in VMEM scratch.
Backward recomputes probabilities from the saved logsumexp (no S×S
materialization) in two kernels: dq (grid over q blocks) and dk/dv (grid over
k blocks).

Numerics: logits and softmax statistics in fp32; the P·V / dP matmuls cast P to
the value dtype (bf16), matching standard flash implementations.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _causal_block_mask(s, iq, ik, block_q, block_k, offset):
    """Apply the triangular mask inside a diagonal block. s: [block_q, block_k].

    ``offset = k_len - q_len`` matches mha_reference's causal semantics: the
    last query row attends all keys (used for decode where Sk > S)."""
    q_pos = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + offset
    k_pos = ik * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(k_pos <= q_pos, s, NEG_INF)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_scr, l_scr,
                *, sm_scale, causal, block_q, block_k, offset):
    iq, ik = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    # causal: k blocks strictly above the diagonal contribute nothing
    run = (ik * block_k <= iq * block_q + block_q - 1 + offset) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0]  # [block_q, D]
        k = k_ref[0]  # [block_k, D]
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            diagonal = ik * block_k + block_k > iq * block_q + offset
            s = jax.lax.cond(
                diagonal,
                lambda x: _causal_block_mask(x, iq, ik, block_q, block_k, offset),
                lambda x: x, s)
        m_prev = m_scr[:, :1]                       # [block_q, 1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur)                      # [block_q, block_k] f32
        l_scr[:, :1] = l_scr[:, :1] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc[:] = acc[:] * alpha + jax.lax.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_scr[:, :1] = m_cur

    last = (jnp.clip((iq * block_q + block_q - 1 + offset) // block_k, 0, nk - 1)
            if causal else nk - 1)

    @pl.when(ik == last)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_scr[:, 0] + jnp.log(jnp.maximum(l[:, 0], 1e-37)))


def _fwd(q3, k3, v3, causal, sm_scale, block_q, block_k, interpret):
    BH, S, D = q3.shape
    Sk = k3.shape[1]
    nq, nk = S // block_q, Sk // block_k
    grid = (BH, nq, nk)
    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                               block_q=block_q, block_k=block_k, offset=Sk - S)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q3.dtype),
            jax.ShapeDtypeStruct((BH, 1, S), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3)
    return o, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc, *, sm_scale, causal, block_q, block_k, offset):
    iq, ik = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = (ik * block_k <= iq * block_q + block_q - 1 + offset) if causal else True

    @pl.when(run)
    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse = lse_ref[0, 0][:, None]                # [block_q, 1]
        delta = delta_ref[0, 0][:, None]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            diagonal = ik * block_k + block_k > iq * block_q + offset
            s = jax.lax.cond(
                diagonal,
                lambda x: _causal_block_mask(x, iq, ik, block_q, block_k, offset),
                lambda x: x, s)
        p = jnp.exp(s - lse)                        # [block_q, block_k]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dq_acc[:] += jax.lax.dot(ds.astype(k.dtype), k,
                                 preferred_element_type=jnp.float32)

    last = (jnp.clip((iq * block_q + block_q - 1 + offset) // block_k, 0, nk - 1)
            if causal else nk - 1)

    @pl.when(ik == last)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc,
                    *, sm_scale, causal, block_q, block_k, offset):
    ik, iq = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    # causal: q blocks strictly before this k block never attend it
    run = (iq * block_q + block_q - 1 + offset >= ik * block_k) if causal else True

    @pl.when(run)
    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            diagonal = ik * block_k + block_k > iq * block_q + offset
            s = jax.lax.cond(
                diagonal,
                lambda x: _causal_block_mask(x, iq, ik, block_q, block_k, offset),
                lambda x: x, s)
        p = jnp.exp(s - lse)                        # [block_q, block_k]
        # dV += P^T dO
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale            # [block_q, block_k]
        # dK += dS^T Q
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(iq == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(q3, k3, v3, o3, do3, lse, causal, sm_scale, block_q, block_k,
         interpret):
    BH, S, D = q3.shape
    Sk = k3.shape[1]
    nq, nk = S // block_q, Sk // block_k
    # delta_i = rowsum(dO * O) — small elementwise pass, XLA fuses it
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1)[:, None, :]            # [BH, 1, S]

    qspec = pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0))
    kspec_for_dq = pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0))
    row_q = pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, offset=Sk - S),
        grid=(BH, nq, nk),
        in_specs=[qspec, kspec_for_dq, kspec_for_dq, qspec, row_q, row_q],
        out_specs=[qspec],
        out_shape=[jax.ShapeDtypeStruct((BH, S, D), q3.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)[0]

    # dkv: grid dim 1 = k block, dim 2 (innermost) = q block
    qspec2 = pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0))
    kspec2 = pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0))
    row_q2 = pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, offset=Sk - S),
        grid=(BH, nk, nq),
        in_specs=[qspec2, kspec2, kspec2, qspec2, row_q2, row_q2],
        out_specs=[kspec2, kspec2],
        out_shape=[jax.ShapeDtypeStruct((BH, Sk, D), k3.dtype),
                   jax.ShapeDtypeStruct((BH, Sk, D), v3.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                        pltpu.VMEM((block_k, D), jnp.float32)],
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public entry with custom VJP
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash(q, k, v, causal, sm_scale, block_q, block_k, block_q_bwd,
           block_k_bwd, interpret):
    out, _ = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k,
                        block_q_bwd, block_k_bwd, interpret)
    return out


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, block_q_bwd,
               block_k_bwd, interpret):
    B, H, S, D = q.shape
    Sk = k.shape[2]
    q3 = q.reshape(B * H, S, D)
    k3 = k.reshape(B * H, Sk, D)
    v3 = v.reshape(B * H, Sk, D)
    o3, lse = _fwd(q3, k3, v3, causal, sm_scale, block_q, block_k, interpret)
    return o3.reshape(B, H, S, D), (q3, k3, v3, o3, lse, (B, H, S, D))


def _flash_bwd(causal, sm_scale, block_q, block_k, block_q_bwd, block_k_bwd,
               interpret, res, g):
    q3, k3, v3, o3, lse, (B, H, S, D) = res
    do3 = g.reshape(B * H, S, D)
    dq, dk, dv = _bwd(q3, k3, v3, o3, do3, lse, causal, sm_scale,
                      block_q_bwd, block_k_bwd, interpret)
    Sk = k3.shape[1]
    return (dq.reshape(B, H, S, D), dk.reshape(B, H, Sk, D),
            dv.reshape(B, H, Sk, D))


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jnp.ndarray,
                    k: jnp.ndarray,
                    v: jnp.ndarray,
                    *,
                    causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: int = 1024,
                    block_k: int = 1024,
                    block_q_bwd: Optional[int] = None,
                    block_k_bwd: Optional[int] = None,
                    interpret: bool = False) -> jnp.ndarray:
    """Flash attention. q,k,v: [batch, heads, seq, head_dim] -> same shape.

    Forward and backward take independent block sizes: measured on v5e
    (gpt2-350m, seq 1024, D=64) 1024x1024 blocks win for BOTH passes — at
    seq<=1024 the whole sequence sits in one tile (no online-softmax loop),
    and per-step MXU occupancy dominates VMEM pressure up to that size.

    Falls back to the jnp reference when shapes don't tile (short sequences):
    kernels want seq % block == 0 and head_dim lane-friendly.
    """
    *_, S, D = q.shape
    Sk = k.shape[-2]
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(D))

    def snap(seq_len: int, want: int) -> int:
        """Largest 16-multiple divisor of seq_len <= want (keeps e.g.
        seq=1280 on the kernel at block 256 instead of falling back dense)."""
        b = min(want, seq_len)
        while b > 16 and (seq_len % b or b % 16):
            b -= 16
        return b

    block_q = snap(S, block_q)
    block_k = snap(Sk, block_k)
    block_q_bwd = snap(S, block_q_bwd or max(block_q, 512))
    block_k_bwd = snap(Sk, block_k_bwd or max(block_k, 512))
    # fall back unless blocks tile the sequences AND are TPU-tile aligned
    # (sublane multiple of 16 covers bf16; lane dim D padded by Mosaic)
    aligned = all(s % b == 0 and b % 16 == 0
                  for s, b in [(S, block_q), (Sk, block_k),
                               (S, block_q_bwd), (Sk, block_k_bwd)]) \
        and D % 8 == 0
    if not aligned:
        from ..attention import mha_reference
        return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale)
    return _flash(q, k, v, causal, sm_scale, block_q, block_k,
                  block_q_bwd, block_k_bwd, interpret)
