"""Weight-only blockwise-int8 Pallas matmul for the serving decode path.

Round 17 (ROADMAP item 2): decode matmuls used to stream full bf16
kernels from HBM every token, or — on the per-channel int8 tier — to
materialize a full f32 dequantized copy OUTSIDE the dot
(``models/generation._kernel_of``). This kernel moves the dequant inside:
weights are stored int8 with one f32 scale per ``QUANT_BLOCK`` = 256
contraction elements — the SAME blockwise format
``runtime/comm/quantized.py`` puts on the wire, single-sourced in
``deepspeed_tpu/quant_format.py`` — so int8 is what crosses HBM (half
the bf16 bytes, a quarter of f32) and the int8 -> f32 convert happens on
a (256, 128) tile already resident in VMEM, fused into the MXU feed.

Blockwise-along-K is the exact identity the wire format proves out
(ZeRO++ 2306.10209 / EQuARX 2506.17615): with ``w[i, n] =
q[i, n] * s[i // 256, n]``,

    y[m, n] = sum_kb  dot(x[m, kb-block], q[kb-block, n]) * s[kb, n]

— each K-block's partial product is scaled once, accumulated f32. The
per-element weight error is bounded by ``block_absmax / 127`` (the
COMM.md model), so the logit error is bounded by the corresponding
matvec norm — pinned by tests/test_low_precision.py.

Packing (:func:`pack_kernel` / :func:`pack_decode_weights`) happens ONCE
at engine construction (``serving.weight_dtype: "int8"``); the hot path
never re-quantizes.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...quant_format import QUANT_BLOCK, block_quant

__all__ = ["quant_matmul", "quant_matmul_reference", "pack_kernel",
           "pack_decode_weights"]

#: output rows per program — decode M is the (tiny) batch, padded to the
#: sublane minimum
_BM = 8
#: output cols per program — one lane width
_BN = 128


def pack_kernel(w: jnp.ndarray, block: int = QUANT_BLOCK):
    """[..., K, N] weight -> (q int8 [..., Kp, N], scales f32 [..., Kp/block, N]).

    Blockwise symmetric int8 along the CONTRACTION dim (quant_format's
    wire math applied down columns): Kp is K padded up to a block
    multiple; the padded rows quantize to exactly 0 (zero input, scale
    1), so a zero-padded activation contributes nothing — padding is
    exact, not approximate. Leading dims (the scan-stacked layer axis)
    pack independently per slice."""
    wt = jnp.swapaxes(w, -1, -2)                    # [..., N, K]
    q, s, _pad = block_quant(wt, 8, block)          # [..., N, Kp], [..., N, nkb]
    return jnp.swapaxes(q, -1, -2), jnp.swapaxes(s, -1, -2)


def pack_decode_weights(params, block: int = QUANT_BLOCK):
    """Pack a scan-layout serving param tree's dense kernels to blockwise
    int8 (run ONCE at ``ServingEngine`` construction under
    ``serving.weight_dtype: "int8"``).

    Packs the direct matmul leaves of ``blocks`` (attn_qkv, attn_proj,
    mlp_fc/gate/proj — per-layer slices of the stacked [L, K, N] leaves)
    plus ``lm_head``. Deliberately left alone: the MoE subtree (the
    router gate's logits pick experts — a quantized argmax flips routing,
    and the 3-D expert einsums ride ``_kernel_of``'s materializing tier),
    and anything already carrying a per-channel ``kernel_scale`` pack."""
    def _pack(sub):
        if "kernel_scale" in sub or "kernel_qscale" in sub:
            return sub
        q, s = pack_kernel(sub["kernel"], block)
        out = {k: v for k, v in sub.items() if k != "kernel"}
        out["kernel"], out["kernel_qscale"] = q, s
        return out

    out = dict(params)
    blocks = dict(params["blocks"])
    for name, sub in blocks.items():
        if isinstance(sub, dict) and "kernel" in sub:
            blocks[name] = _pack(sub)
    out["blocks"] = blocks
    if isinstance(params.get("lm_head"), dict) and "kernel" in params["lm_head"]:
        out["lm_head"] = _pack(params["lm_head"])
    return out


def _kernel(x_ref, q_ref, s_ref, o_ref, acc, *, nkb):
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    # the int8 -> f32 convert happens HERE, on a (block, _BN) tile in
    # VMEM; one scale row rescales the whole partial product (blockwise
    # identity: every contraction element of this grid step shares it)
    w = q_ref[:].astype(jnp.float32)
    x = x_ref[:].astype(jnp.float32)
    acc[:] += jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * s_ref[0][None, :]

    @pl.when(kb == nkb - 1)
    def _finalize():
        o_ref[:] = acc[:].astype(o_ref.dtype)


def quant_matmul(x: jnp.ndarray,
                 q: jnp.ndarray,
                 scales: jnp.ndarray,
                 *,
                 interpret: bool = False) -> jnp.ndarray:
    """x [..., K] @ blockwise-int8 weight -> [..., N].

    q: [Kp, N] int8, scales: [Kp/block, N] f32 (:func:`pack_kernel`
    output; Kp >= K, padded rows are exact zeros). Routes to the Pallas
    kernel on TPU (or under ``interpret``) when N tiles to the lane
    width; otherwise — CPU oracle runs, ragged vocab heads — falls back
    to :func:`quant_matmul_reference`, the same per-block math in jnp
    (the paged-attention fallback idiom)."""
    Kp, N = q.shape
    nkb = scales.shape[0]
    lead, K = x.shape[:-1], x.shape[-1]
    on_tpu = jax.default_backend() == "tpu"
    if not (on_tpu or interpret) or N % _BN != 0:
        return quant_matmul_reference(x, q, scales)

    xf = x.reshape(-1, K)
    M = xf.shape[0]
    Mp = -(-M // _BM) * _BM
    xf = jnp.pad(xf, ((0, Mp - M), (0, Kp - K)))
    block = Kp // nkb
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(Mp // _BM, N // _BN, nkb),
        in_specs=[
            pl.BlockSpec((_BM, block), lambda m, n, kb: (m, kb)),
            pl.BlockSpec((block, _BN), lambda m, n, kb: (kb, n)),
            pl.BlockSpec((1, _BN), lambda m, n, kb: (kb, n)),
        ],
        out_specs=pl.BlockSpec((_BM, _BN), lambda m, n, kb: (m, n)),
        scratch_shapes=[pltpu.VMEM((_BM, _BN), jnp.float32)],
    )
    with jax.named_scope("quant_matmul"):
        out = pl.pallas_call(
            partial(_kernel, nkb=nkb),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((Mp, N), x.dtype),
            interpret=interpret,
        )(xf, q, scales)
    return out[:M].reshape(lead + (N,))


def quant_matmul_reference(x: jnp.ndarray,
                           q: jnp.ndarray,
                           scales: jnp.ndarray) -> jnp.ndarray:
    """jnp oracle: the kernel's per-block identity — each K-block's
    partial product scaled once, summed f32."""
    Kp, N = q.shape
    nkb = scales.shape[0]
    block = Kp // nkb
    lead, K = x.shape[:-1], x.shape[-1]
    xf = x.astype(jnp.float32).reshape(-1, K)
    if Kp > K:
        xf = jnp.pad(xf, ((0, 0), (0, Kp - K)))
    xb = xf.reshape(-1, nkb, block)
    qb = q.astype(jnp.float32).reshape(nkb, block, N)
    part = jnp.einsum("mkb,kbn->mkn", xb, qb) * scales[None]
    return part.sum(axis=1).reshape(lead + (N,)).astype(x.dtype)
