"""Pallas paged-attention decode kernel — KV blocks gathered via block table.

The serving subsystem (deepspeed_tpu/serving/) keeps the KV cache as a POOL
of fixed-size blocks shared by every in-flight sequence; a per-sequence
*block table* maps logical block j to a physical pool block. The decode
step then needs attention of one fresh query token per sequence against a
K/V that is physically scattered across the pool. This kernel performs the
gather INSIDE the pipeline: the K/V BlockSpec index_map reads the block
table (a prefetched scalar array) to pick the physical block for grid step
j, so the only HBM traffic is the ``ceil(ctx_len / block_size)`` live
blocks of each sequence — no materialized per-sequence contiguous copy,
and per-token cost scales with the tokens each sequence has generated, not
with the pool size.

Capability slot of the reference's fused ``softmax_context`` decode kernels
(csrc/transformer/inference/csrc/pt_binding.cpp:1703-1779) generalized to
the vLLM-style paged layout; the mechanics (clamped index_map elides dead
copies, ``@pl.when`` skips dead FLOPs, online-softmax scratch carries
m/l across blocks) are shared with ops/pallas/decode_attention.py.

In-kernel score features (parity with the flash/decode kernels): ALiBi via
per-head slopes, Gemma-2 tanh softcap, causal masking by per-sequence
context length, and a sliding window. The jnp oracle
:func:`paged_attention_reference` computes the identical math by dense
gather — the CPU fallback and the parity target for the interpret-mode
tests.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .decode_attention import _head_group
from .flash_attention import NEG_INF

__all__ = ["paged_attention", "paged_attention_reference"]

#: query rows per program — a single decode token is broadcast to the
#: sublane minimum so every operand is a legal (>=8)x128 tile
_QROWS = 8


def _kernel(bt_ref, lens_ref, misc_ref, q_ref, k_ref, v_ref, *rest, hg, bs,
            nbk, sm_scale, softcap, has_alibi, stacked, quant):
    if quant:
        ks_ref, vs_ref, slopes_ref, o_ref, acc, m_scr, l_scr = rest
    else:
        slopes_ref, o_ref, acc, m_scr, l_scr = rest
    b, j = pl.program_id(0), pl.program_id(2)
    ctx = lens_ref[b]
    window = misc_ref[0]
    cnt = (ctx + bs - 1) // bs                    # live blocks of seq b

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    @pl.when(j < cnt)
    def _compute():
        q = q_ref[0, 0]                                     # [hg, 8, hd]
        k = k_ref[0, :, 0] if stacked else k_ref[:, 0]      # [hg, bs, hd]
        v = v_ref[0, :, 0] if stacked else v_ref[:, 0]
        if quant:
            # int8 tier (round 17): the DMA moved int8 rows + one f32
            # scale per (head, slot); dequantize HERE, on the block
            # already in VMEM — only int8 crossed HBM
            ks = ks_ref[0, :, 0] if stacked else ks_ref[:, 0]   # [hg, bs]
            vs = vs_ref[0, :, 0] if stacked else vs_ref[:, 0]
            k = (k.astype(jnp.float32) * ks[..., None]).astype(q.dtype)
            v = (v.astype(jnp.float32) * vs[..., None]).astype(q.dtype)
        s = jax.lax.dot_general(q, k, (((2,), (2,)), ((0,), (0,))),
                                preferred_element_type=jnp.float32) * sm_scale
        if softcap:
            s = jnp.tanh(s / softcap) * softcap
        # one real query at absolute (logical) position ctx - 1, broadcast
        # over the 8 padded rows; keys of block j cover logical positions
        # [j*bs, (j+1)*bs) regardless of which PHYSICAL block the table
        # routed the DMA to
        q_abs = ctx - 1
        k_pos = j * bs + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        if has_alibi:
            slope = slopes_ref[0][:, :1][:, None, :]        # [hg, 1, 1]
            s = s + slope * (k_pos - q_abs).astype(jnp.float32)
        keep = k_pos <= q_abs                               # causal + dead tail
        keep &= (q_abs - k_pos < window) | (window <= 0)    # sliding window
        s = jnp.where(keep, s, NEG_INF)
        m_prev = m_scr[:, :, :1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur)
        l_scr[:, :, :1] = (l_scr[:, :, :1] * alpha
                           + jnp.sum(p, axis=2, keepdims=True))
        acc[:] = acc[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        m_scr[:, :, :1] = m_cur

    @pl.when(j == nbk - 1)
    def _finalize():
        l = l_scr[:, :, :1]
        o_ref[0, 0] = (acc[:] / jnp.where(l == 0.0, 1.0, l)).astype(
            o_ref.dtype)


def paged_attention(q: jnp.ndarray,
                    k_pool: jnp.ndarray,
                    v_pool: jnp.ndarray,
                    block_tables: jnp.ndarray,
                    context_lens: jnp.ndarray,
                    *,
                    sm_scale: Optional[float] = None,
                    alibi_slopes=None,
                    softcap: float = 0.0,
                    window=None,
                    layer_idx=None,
                    k_scale=None,
                    v_scale=None,
                    interpret: bool = False) -> jnp.ndarray:
    """One decode token per sequence against a paged KV pool.

    q: [B, nh, 1, hd] — each sequence's fresh query, at logical position
       ``context_lens[b] - 1`` (context_lens INCLUDES the new token).
    k_pool/v_pool: [nh, num_blocks, block_size, hd]; with ``layer_idx``
       (traced i32 ok) the stacked [L, nh, num_blocks, block_size, hd]
       layout — the index_map picks the layer straight out of the
       scan-carried pool, no materialized per-layer slice.
    k_scale/v_scale: the int8 tier (round 17) — pools are int8 in the
       ``quant_format.kv_quantize`` layout and these carry the f32
       per-(layer, head, slot) scales (any shape that reshapes to the
       pool's [..., num_blocks, block_size], e.g. init_pool's
       [L, nh, num_slots, 1]). The scale blocks ride the SAME block-table
       index_map as k/v and the dequant happens in-kernel, so the HBM
       read is int8 + 4 bytes/slot — no pool-slice f32 copy exists.
    block_tables: [B, max_blocks] i32 — logical block j of sequence b
       lives in physical pool block ``block_tables[b, j]``. Entries past
       the live count are never DMA'd (the index_map clamps them to the
       last live block, which the pipeline elides as a repeated index).
    context_lens: [B] i32. ``window``: python int or traced i32, <= 0
       means global. ``alibi_slopes``: [nh] per-head slopes (in-kernel
       bias slope * (k_pos - q_pos)). ``softcap``: Gemma-2 tanh cap
       (STATIC float — it changes the compiled math).

    Returns [B, nh, 1, hd]. Raises ValueError when shapes can't tile —
    callers fall back to :func:`paged_attention_reference`.
    """
    B, nh, T, hd = q.shape
    if T != 1:
        raise ValueError(f"paged_attention decodes 1 token/seq (got T={T}); "
                         "prefill rides the gather reference/flash paths")
    stacked = layer_idx is not None
    bs = k_pool.shape[3 if stacked else 2]
    nb = k_pool.shape[2 if stacked else 1]
    if bs % 8 != 0 and not interpret:
        raise ValueError(f"block_size {bs} does not tile (sublane multiple "
                         "of 8 required)")
    if hd % 8 != 0 and not interpret:
        raise ValueError(f"head_dim {hd} does not tile")
    quant = k_scale is not None
    if quant:
        if k_pool.dtype != jnp.int8:
            raise ValueError("k_scale/v_scale given but the pool dtype is "
                             f"{k_pool.dtype} — scales pair with int8 pools")
        if bs % 32 != 0 and not interpret:
            raise ValueError(f"block_size {bs} does not tile the int8 KV "
                             "tier (int8 sublane multiple of 32 required)")
        ks_pool = jnp.asarray(k_scale, jnp.float32).reshape(k_pool.shape[:-1])
        vs_pool = jnp.asarray(v_scale, jnp.float32).reshape(v_pool.shape[:-1])
    elif k_pool.dtype == jnp.int8:
        raise ValueError("int8 KV pool needs k_scale/v_scale "
                         "(quant_format.kv_quantize layout)")
    nbk = block_tables.shape[1]
    hg = _head_group(nh, bs, hd, k_pool.dtype.itemsize)
    ng = nh // hg
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(hd)
    softcap = float(softcap) if softcap else 0.0

    # broadcast the single query row to the sublane minimum (all 8 rows are
    # the real query; row 0 is read back)
    qf = jnp.broadcast_to(q.reshape(B, ng, hg, 1, hd), (B, ng, hg, _QROWS, hd))

    bt = jnp.asarray(block_tables, jnp.int32)
    lens = jnp.asarray(context_lens, jnp.int32).reshape(B)
    win = jnp.asarray(0 if window is None else window, jnp.int32).reshape(())
    li = jnp.asarray(0 if layer_idx is None else layer_idx,
                     jnp.int32).reshape(())
    misc = jnp.stack([win, li])

    # dead grid steps clamp to the sequence's last live block: a repeated
    # physical index means the pipeline skips the K/V copy
    def _phys(j, bt_s, lens_s, b):
        last = jnp.maximum((lens_s[b] + bs - 1) // bs - 1, 0)
        return bt_s[b, jnp.minimum(j, last)]

    if stacked:
        kv_spec = pl.BlockSpec(
            (1, hg, 1, bs, hd),
            lambda b, g, j, bt_s, lens_s, misc_s: (
                misc_s[1], g, _phys(j, bt_s, lens_s, b), 0, 0))
    else:
        kv_spec = pl.BlockSpec(
            (hg, 1, bs, hd),
            lambda b, g, j, bt_s, lens_s, misc_s: (
                g, _phys(j, bt_s, lens_s, b), 0, 0))
    q_spec = pl.BlockSpec((1, 1, hg, _QROWS, hd),
                          lambda b, g, j, *_: (b, g, 0, 0, 0))

    in_specs = [q_spec, kv_spec, kv_spec]
    operands = [qf, k_pool, v_pool]
    if quant:
        # scale blocks follow the K/V through the SAME clamped
        # block-table index_map (hd dim dropped: one f32 per slot row)
        if stacked:
            sc_spec = pl.BlockSpec(
                (1, hg, 1, bs),
                lambda b, g, j, bt_s, lens_s, misc_s: (
                    misc_s[1], g, _phys(j, bt_s, lens_s, b), 0))
        else:
            sc_spec = pl.BlockSpec(
                (hg, 1, bs),
                lambda b, g, j, bt_s, lens_s, misc_s: (
                    g, _phys(j, bt_s, lens_s, b), 0))
        in_specs += [sc_spec, sc_spec]
        operands += [ks_pool, vs_pool]
    has_alibi = alibi_slopes is not None
    if has_alibi:
        sl = jnp.asarray(alibi_slopes, jnp.float32).reshape(ng, hg)
        slopes = jnp.broadcast_to(sl[:, :, None], (ng, hg, 128))
        in_specs.append(pl.BlockSpec((1, hg, 128),
                                     lambda b, g, j, *_: (g, 0, 0)))
        operands.append(slopes)
    else:
        # constant placeholder so the kernel arity is static
        in_specs.append(pl.BlockSpec((1, 1, 128), lambda b, g, j, *_: (0, 0, 0)))
        operands.append(jnp.zeros((1, 1, 128), jnp.float32))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, ng, nbk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, hg, _QROWS, hd),
                               lambda b, g, j, *_: (b, g, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((hg, _QROWS, hd), jnp.float32),
            pltpu.VMEM((hg, _QROWS, 128), jnp.float32),
            pltpu.VMEM((hg, _QROWS, 128), jnp.float32),
        ],
    )
    with jax.named_scope("paged_attention"):
        out = pl.pallas_call(
            partial(_kernel, hg=hg, bs=bs, nbk=nbk, sm_scale=scale,
                    softcap=softcap, has_alibi=has_alibi, stacked=stacked,
                    quant=quant),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((B, ng, hg, _QROWS, hd), q.dtype),
            interpret=interpret,
        )(bt, lens, misc, *operands)
    return out[:, :, :, :1].reshape(B, nh, 1, hd)


def paged_attention_reference(q: jnp.ndarray,
                              k_pool: jnp.ndarray,
                              v_pool: jnp.ndarray,
                              block_tables: jnp.ndarray,
                              context_lens: jnp.ndarray,
                              *,
                              sm_scale: Optional[float] = None,
                              alibi_slopes=None,
                              softcap: float = 0.0,
                              window=None,
                              layer_idx=None,
                              k_scale=None,
                              v_scale=None,
                              q_start=None) -> jnp.ndarray:
    """jnp oracle / CPU fallback: dense gather through the block table,
    then exactly the decode-path attention math (f32 scores, softcap
    before the ALiBi bias before the -1e30 masks, f32 softmax).

    Generalizes over the kernel: q may carry T > 1 query tokens (the
    PREFILL of a paged sequence — queries at logical positions
    [ctx - T, ctx), or [q_start, q_start + T) when ``q_start`` [B] is
    given: a bucket-PADDED prefill carries trailing garbage queries past
    ctx whose outputs the caller discards), so one definition serves
    prefill and decode.

    int8 tier (``k_scale``/``v_scale``, the kernel's layout): the gather
    moves int8 rows and their scales, and the dequant happens AFTER the
    gather — O(attended tokens), not O(pool). Gather-then-dequantize is
    elementwise identical to the round-12 dequantize-then-gather, so
    greedy decodes are token-for-token unchanged.
    """
    B, nh, T, hd = q.shape
    quant = k_scale is not None
    if quant:
        k_scale = jnp.asarray(k_scale, jnp.float32).reshape(
            k_pool.shape[:-1])
        v_scale = jnp.asarray(v_scale, jnp.float32).reshape(
            v_pool.shape[:-1])
    if layer_idx is not None:
        k_pool = jax.lax.dynamic_index_in_dim(k_pool, layer_idx, 0,
                                              keepdims=False)
        v_pool = jax.lax.dynamic_index_in_dim(v_pool, layer_idx, 0,
                                              keepdims=False)
        if quant:
            k_scale = jax.lax.dynamic_index_in_dim(k_scale, layer_idx, 0,
                                                   keepdims=False)
            v_scale = jax.lax.dynamic_index_in_dim(v_scale, layer_idx, 0,
                                                   keepdims=False)
    bs = k_pool.shape[2]
    nbk = block_tables.shape[1]
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(hd)
    bt = jnp.asarray(block_tables, jnp.int32)
    lens = jnp.asarray(context_lens, jnp.int32).reshape(B)

    # gather [nh, B, nbk, bs, hd] -> [B, nh, K, hd], K = nbk * bs logical
    k = jnp.transpose(k_pool[:, bt], (1, 0, 2, 3, 4)).reshape(
        B, nh, nbk * bs, hd)
    v = jnp.transpose(v_pool[:, bt], (1, 0, 2, 3, 4)).reshape(
        B, nh, nbk * bs, hd)
    if quant:
        ks = jnp.transpose(k_scale[:, bt], (1, 0, 2, 3)).reshape(
            B, nh, nbk * bs)
        vs = jnp.transpose(v_scale[:, bt], (1, 0, 2, 3)).reshape(
            B, nh, nbk * bs)
        k = (k.astype(jnp.float32) * ks[..., None]).astype(q.dtype)
        v = (v.astype(jnp.float32) * vs[..., None]).astype(q.dtype)

    if q_start is not None:
        q_abs = (jnp.asarray(q_start, jnp.int32).reshape(B)[:, None]
                 + jnp.arange(T))                          # [B, T]
    else:
        q_abs = (lens[:, None] - T + jnp.arange(T))        # [B, T]
    k_pos = jnp.arange(nbk * bs)                           # [K]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if softcap:
        from ..attention import apply_softcap
        s = apply_softcap(s, softcap)
    if alibi_slopes is not None:
        sl = jnp.asarray(alibi_slopes, jnp.float32).reshape(nh)
        dist = (k_pos[None, None, :] - q_abs[:, :, None]).astype(jnp.float32)
        s = s + sl[None, :, None, None] * dist[:, None]
    keep = k_pos[None, None, :] <= q_abs[:, :, None]       # [B, T, K]
    if window is not None:
        win = jnp.asarray(window, jnp.int32)
        keep = keep & ((q_abs[:, :, None] - k_pos[None, None, :] < win)
                       | (win <= 0))
    s = jnp.where(keep[:, None], s, NEG_INF)
    prob = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", prob, v)
