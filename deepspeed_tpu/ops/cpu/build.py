"""Native-kernel build + ctypes load for the host-side (offload) ops.

Role of the reference's op_builder/ (CPUAdamBuilder, AsyncIOBuilder: JIT
compile on first use, cached .so). Differences: the toolchain is plain g++
invoked directly (no torch cpp_extension), bindings are ctypes over a C ABI
(no pybind11 in this image), and -march=native lets the compiler emit the
AVX2/AVX512 the reference hand-writes in csrc/includes/simd.h.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sysconfig
import threading
from typing import Optional

from ...utils.logging import logger

_CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "csrc")
_BUILD_DIR = os.environ.get(
    "DSTPU_BUILD_DIR",
    os.path.join(os.path.expanduser("~"), ".cache", "deepspeed_tpu", "build"))

_lock = threading.Lock()
_libs = {}


def _compile(name: str, sources, extra_flags=()) -> Optional[str]:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    so_path = os.path.join(_BUILD_DIR, f"{name}.so")
    srcs = [os.path.join(_CSRC, s) for s in sources]
    if os.path.exists(so_path) and all(
            os.path.getmtime(so_path) >= os.path.getmtime(s) for s in srcs):
        return so_path
    cxx = os.environ.get("CXX", "g++")
    cmd = [cxx, "-O3", "-march=native", "-fopenmp", "-shared", "-fPIC",
           "-std=c++17", *extra_flags, *srcs, "-o", so_path]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    except (OSError, subprocess.TimeoutExpired) as e:
        logger.warning("native build of %s failed to launch (%s); using the "
                       "numpy fallback path", name, e)
        return None
    if proc.returncode != 0:
        # -march=native can be unsupported in emulated environments
        cmd_portable = [c for c in cmd if c != "-march=native"]
        proc = subprocess.run(cmd_portable, capture_output=True, text=True,
                              timeout=300)
        if proc.returncode != 0:
            logger.warning("native build of %s failed:\n%s\nusing the numpy "
                           "fallback path", name, proc.stderr[-2000:])
            return None
    return so_path


def _load(name: str, sources) -> Optional[ctypes.CDLL]:
    with _lock:
        if name in _libs:
            return _libs[name]
        # compile-once cache: the lock MUST span the compile, or two
        # threads race to build the same .so; waiters blocked on a slow
        # compile are the intended serialization, not a wedge
        so = _compile(name, sources)  # graftlint: disable=TPU017
        lib = ctypes.CDLL(so) if so else None
        _libs[name] = lib
        return lib


def load_cpu_kernels() -> Optional[ctypes.CDLL]:
    """cpu_adam/adagrad/sgd + bf16 convert (csrc/cpu_adam.cpp)."""
    lib = _load("ds_cpu_kernels", ["cpu_adam.cpp"])
    if lib is not None and not getattr(lib, "_ds_typed", False):
        c = ctypes
        lib.ds_cpu_adam_step.argtypes = [
            c.c_int64, c.c_float, c.c_float, c.c_float, c.c_float, c.c_float,
            c.c_int, c.c_int, c.c_float,
            c.c_void_p, c.c_void_p, c.c_void_p, c.c_void_p, c.c_int64, c.c_void_p]
        lib.ds_cpu_adagrad_step.argtypes = [
            c.c_float, c.c_float, c.c_float, c.c_float,
            c.c_void_p, c.c_void_p, c.c_void_p, c.c_int64, c.c_void_p]
        lib.ds_cpu_sgd_step.argtypes = [
            c.c_float, c.c_float, c.c_float, c.c_int, c.c_float,
            c.c_void_p, c.c_void_p, c.c_void_p, c.c_int64, c.c_void_p]
        lib.ds_f32_to_bf16.argtypes = [c.c_void_p, c.c_void_p, c.c_int64]
        lib.ds_cpu_kernels_num_threads.restype = c.c_int
        lib._ds_typed = True
    return lib


def load_data_loader() -> Optional[ctypes.CDLL]:
    """mmap batch assembly + prefetch thread (csrc/data_loader.cpp)."""
    lib = _load("ds_data_loader", ["data_loader.cpp"])
    if lib is not None and not getattr(lib, "_ds_typed", False):
        c = ctypes
        lib.ds_dl_open.restype = c.c_void_p
        lib.ds_dl_open.argtypes = [c.c_char_p]
        lib.ds_dl_close.argtypes = [c.c_void_p]
        lib.ds_dl_gather.restype = c.c_int64
        lib.ds_dl_gather.argtypes = [
            c.c_void_p, c.c_void_p, c.c_void_p, c.c_int64, c.c_int64,
            c.c_void_p]
        lib.ds_dl_prefetch.restype = c.c_int
        lib.ds_dl_prefetch.argtypes = lib.ds_dl_gather.argtypes
        lib.ds_dl_prefetch_wait.restype = c.c_int64
        lib.ds_dl_prefetch_wait.argtypes = [c.c_void_p]
        lib._ds_typed = True
    return lib


def load_aio() -> Optional[ctypes.CDLL]:
    """thread-pool positional IO (csrc/aio.cpp)."""
    lib = _load("ds_aio", ["aio.cpp"])
    if lib is not None and not getattr(lib, "_ds_typed", False):
        c = ctypes
        lib.ds_aio_handle_new.restype = c.c_void_p
        lib.ds_aio_handle_new.argtypes = [c.c_int64, c.c_int, c.c_int]
        lib.ds_aio_handle_free.argtypes = [c.c_void_p]
        for f in (lib.ds_aio_submit_read, lib.ds_aio_submit_write):
            f.restype = c.c_int64
            f.argtypes = [c.c_void_p, c.c_void_p, c.c_int64, c.c_char_p, c.c_int64]
        lib.ds_aio_wait.restype = c.c_int64
        lib.ds_aio_wait.argtypes = [c.c_void_p]
        for f in (lib.ds_aio_pread, lib.ds_aio_pwrite):
            f.restype = c.c_int
            f.argtypes = [c.c_void_p, c.c_void_p, c.c_int64, c.c_char_p, c.c_int64]
        lib._ds_typed = True
    return lib
