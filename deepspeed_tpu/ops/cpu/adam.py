"""DeepSpeedCPUAdam — host-memory Adam/AdamW over flat numpy partitions.

Role of the reference's ``deepspeed/ops/adam/cpu_adam.py`` (DeepSpeedCPUAdam:
torch optimizer driving csrc/adam/cpu_adam.cpp Step_AVX, with an optional
fp16 device-param write-out). Here the state is plain numpy (the offloaded
fp32 master partition lives in host RAM), the step calls the C kernel in
ops/csrc/cpu_adam.cpp through ctypes, and the optional ``bf16_out`` buffer
receives the updated params as bfloat16 for the H2D copy — fused into the
same SIMD pass exactly like the reference's dev_param path.

A pure-numpy fallback keeps the API alive when no C++ toolchain exists.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from .build import load_cpu_kernels


def _as_flat_f32(a: np.ndarray) -> np.ndarray:
    assert a.dtype == np.float32 and a.flags.c_contiguous
    return a.reshape(-1)


class DeepSpeedCPUAdam:
    """Adam/AdamW stepping host-resident fp32 buffers in place."""

    def __init__(self, lr: float = 1e-3, betas: Tuple[float, float] = (0.9, 0.999),
                 eps: float = 1e-8, weight_decay: float = 0.0,
                 bias_correction: bool = True, adamw_mode: bool = True,
                 use_native: bool = True):
        self.lr = float(lr)
        self.betas = (float(betas[0]), float(betas[1]))
        self.eps = float(eps)
        self.weight_decay = float(weight_decay)
        self.bias_correction = bool(bias_correction)
        self.adamw_mode = bool(adamw_mode)
        # use_native=False forces the numpy path (op-registry impl selection
        # and C++-kernel triage both need an honest fallback switch)
        self._lib = load_cpu_kernels() if use_native else None

    @property
    def has_native(self) -> bool:
        return self._lib is not None

    def init_state(self, param: np.ndarray) -> Dict[str, np.ndarray]:
        return {"exp_avg": np.zeros_like(param, dtype=np.float32),
                "exp_avg_sq": np.zeros_like(param, dtype=np.float32)}

    def step(self, step: int, param: np.ndarray, grad: np.ndarray,
             state: Dict[str, np.ndarray], lr: Optional[float] = None,
             grad_scale: float = 1.0,
             bf16_out: Optional[np.ndarray] = None) -> None:
        """One Adam step, in place. ``step`` is 1-based. ``grad_scale`` divides
        the grads (loss-scale unscaling fused into the kernel pass)."""
        lr = self.lr if lr is None else float(lr)
        p = _as_flat_f32(param)
        g = np.ascontiguousarray(grad, dtype=np.float32).reshape(-1)
        m = _as_flat_f32(state["exp_avg"])
        v = _as_flat_f32(state["exp_avg_sq"])
        n = p.size
        out = None
        if bf16_out is not None:
            out = bf16_out.view(np.uint16).reshape(-1)
            assert out.size == n
        if self._lib is not None:
            import ctypes
            self._lib.ds_cpu_adam_step(
                step, lr, self.betas[0], self.betas[1], self.eps,
                self.weight_decay, int(self.adamw_mode),
                # graftlint: disable=TPU001 (host C++ kernel: grad_scale is a python float; all buffers are host numpy)
                int(self.bias_correction), float(grad_scale),
                p.ctypes.data_as(ctypes.c_void_p),
                g.ctypes.data_as(ctypes.c_void_p),
                m.ctypes.data_as(ctypes.c_void_p),
                v.ctypes.data_as(ctypes.c_void_p),
                n,
                out.ctypes.data_as(ctypes.c_void_p) if out is not None else None)
            return
        # numpy fallback — same numerics, no SIMD control
        b1, b2 = self.betas
        if grad_scale != 1.0 and grad_scale != 0.0:
            g = g / grad_scale
        if self.weight_decay and not self.adamw_mode:
            g = g + self.weight_decay * p
        np.multiply(m, b1, out=m)
        m += (1.0 - b1) * g
        np.multiply(v, b2, out=v)
        v += (1.0 - b2) * g * g
        bc1 = 1.0 - b1 ** step if self.bias_correction else 1.0
        bc2 = 1.0 - b2 ** step if self.bias_correction else 1.0
        upd = (m / bc1) / (np.sqrt(v) / np.sqrt(bc2) + self.eps)
        if self.weight_decay and self.adamw_mode:
            upd += self.weight_decay * p
        p -= lr * upd
        if out is not None:
            _f32_to_bf16_np(p, out)


class DeepSpeedCPUAdagrad:
    """reference: deepspeed/ops/adagrad/cpu_adagrad.py over csrc/adagrad."""

    def __init__(self, lr: float = 1e-2, eps: float = 1e-10,
                 weight_decay: float = 0.0, use_native: bool = True):
        self.lr, self.eps, self.weight_decay = float(lr), float(eps), float(weight_decay)
        self._lib = load_cpu_kernels() if use_native else None

    def init_state(self, param: np.ndarray) -> Dict[str, np.ndarray]:
        return {"sum": np.zeros_like(param, dtype=np.float32)}

    def step(self, step: int, param: np.ndarray, grad: np.ndarray,
             state: Dict[str, np.ndarray], lr: Optional[float] = None,
             grad_scale: float = 1.0,
             bf16_out: Optional[np.ndarray] = None) -> None:
        lr = self.lr if lr is None else float(lr)
        p = _as_flat_f32(param)
        g = np.ascontiguousarray(grad, dtype=np.float32).reshape(-1)
        s = _as_flat_f32(state["sum"])
        out = bf16_out.view(np.uint16).reshape(-1) if bf16_out is not None else None
        if self._lib is not None:
            import ctypes
            self._lib.ds_cpu_adagrad_step(
                # graftlint: disable=TPU001 (host C++ kernel: grad_scale is a python float; all buffers are host numpy)
                lr, self.eps, self.weight_decay, float(grad_scale),
                p.ctypes.data_as(ctypes.c_void_p),
                g.ctypes.data_as(ctypes.c_void_p),
                s.ctypes.data_as(ctypes.c_void_p), p.size,
                out.ctypes.data_as(ctypes.c_void_p) if out is not None else None)
            return
        if grad_scale != 1.0 and grad_scale != 0.0:
            g = g / grad_scale
        if self.weight_decay:
            g = g + self.weight_decay * p
        s += g * g
        p -= lr * g / (np.sqrt(s) + self.eps)
        if out is not None:
            _f32_to_bf16_np(p, out)


def _f32_to_bf16_np(src_f32: np.ndarray, dst_u16: np.ndarray) -> None:
    """round-to-nearest-even fp32 -> bf16 bit pattern (numpy fallback)."""
    bits = src_f32.view(np.uint32)
    rounding = np.uint32(0x7FFF) + ((bits >> np.uint32(16)) & np.uint32(1))
    np.copyto(dst_u16, ((bits + rounding) >> np.uint32(16)).astype(np.uint16))
