"""Host-side (CPU) native ops — the offload tier's compute kernels.

reference: csrc/adam/cpu_adam.cpp + csrc/adagrad/cpu_adagrad.cpp (SIMD host
optimizers) and csrc/aio/ (async NVMe IO), built lazily like op_builder/.
"""

from .adam import DeepSpeedCPUAdam, DeepSpeedCPUAdagrad
from .aio import AsyncIOHandle
from .build import load_aio, load_cpu_kernels

__all__ = ["DeepSpeedCPUAdam", "DeepSpeedCPUAdagrad", "AsyncIOHandle",
           "load_cpu_kernels", "load_aio"]
