"""AsyncIOHandle — numpy array <-> file async transfers for NVMe swapping.

Role of the reference's ``deepspeed/ops/aio`` (py_ds_aio.cpp aio_handle with
sync_pread/sync_pwrite/async_pread/async_pwrite + wait over a libaio thread
pool, csrc/aio/py_lib/deepspeed_aio_thread.cpp). Backed by ops/csrc/aio.cpp
(std::thread pool, positional chunked pread/pwrite) through ctypes, with a
pure-python fallback so the swapper logic stays testable without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from .build import load_aio


class AsyncIOHandle:
    """API mirror of the reference aio_handle (py_ds_aio.cpp:14-18)."""

    def __init__(self, block_size: int = 1 << 20, queue_depth: int = 8,
                 single_submit: bool = False, overlap_events: bool = True,
                 thread_count: int = 4, use_native: bool = True):
        self.block_size = int(block_size)
        self.queue_depth = int(queue_depth)
        self.thread_count = int(thread_count)
        self._lib = load_aio() if use_native else None
        self._handle = None
        self._py_pending = []        # fallback: (write, array, path, offset)
        if self._lib is not None:
            self._handle = self._lib.ds_aio_handle_new(
                self.block_size, self.queue_depth, self.thread_count)
        # keep submitted buffers alive until wait() — the C threads write into
        # them; dropping the last python ref would free the memory under IO
        self._inflight_refs = []

    @property
    def has_native(self) -> bool:
        return self._handle is not None

    def close(self):
        if self._handle is not None:
            self._lib.ds_aio_handle_free(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # -- sync ----------------------------------------------------------------

    def sync_pread(self, buffer: np.ndarray, path: str, offset: int = 0) -> int:
        buf = _check_buffer(buffer)
        if self._handle is not None:
            rc = self._lib.ds_aio_pread(
                self._handle, buf.ctypes.data_as(ctypes.c_void_p), buf.nbytes,
                path.encode(), int(offset))
            if rc != 0:
                raise IOError(f"aio pread failed: {path} @ {offset}")
            return buf.nbytes
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read(buf.nbytes)
        buf.view(np.uint8)[:len(data)] = np.frombuffer(data, np.uint8)
        return len(data)

    def sync_pwrite(self, buffer: np.ndarray, path: str, offset: int = 0) -> int:
        buf = _check_buffer(buffer)
        if self._handle is not None:
            rc = self._lib.ds_aio_pwrite(
                self._handle, buf.ctypes.data_as(ctypes.c_void_p), buf.nbytes,
                path.encode(), int(offset))
            if rc != 0:
                raise IOError(f"aio pwrite failed: {path} @ {offset}")
            return buf.nbytes
        _py_pwrite(buf, path, offset)
        return buf.nbytes

    # -- async ---------------------------------------------------------------

    def async_pread(self, buffer: np.ndarray, path: str, offset: int = 0) -> int:
        buf = _check_buffer(buffer)
        if self._handle is not None:
            self._inflight_refs.append(buf)
            return int(self._lib.ds_aio_submit_read(
                self._handle, buf.ctypes.data_as(ctypes.c_void_p), buf.nbytes,
                path.encode(), int(offset)))
        self._py_pending.append((False, buf, path, int(offset)))
        return len(self._py_pending) - 1

    def async_pwrite(self, buffer: np.ndarray, path: str, offset: int = 0) -> int:
        buf = _check_buffer(buffer)
        if self._handle is not None:
            self._inflight_refs.append(buf)
            return int(self._lib.ds_aio_submit_write(
                self._handle, buf.ctypes.data_as(ctypes.c_void_p), buf.nbytes,
                path.encode(), int(offset)))
        self._py_pending.append((True, buf, path, int(offset)))
        return len(self._py_pending) - 1

    def wait(self) -> int:
        """Block until every outstanding async op completes; returns number of
        failed ops (reference aio_handle.wait returns completed count — errors
        there raise; here the error count is the actionable signal)."""
        if self._handle is not None:
            errs = int(self._lib.ds_aio_wait(self._handle))
            self._inflight_refs.clear()
            if errs:
                raise IOError(f"{errs} async IO ops failed")
            return 0
        pending, self._py_pending = self._py_pending, []
        for write, buf, path, offset in pending:
            if write:
                _py_pwrite(buf, path, offset)
            else:
                self.sync_pread(buf, path, offset)
        return 0


def _check_buffer(buffer: np.ndarray) -> np.ndarray:
    if not isinstance(buffer, np.ndarray) or not buffer.flags.c_contiguous:
        raise ValueError("aio buffers must be C-contiguous numpy arrays")
    return buffer


def _py_pwrite(buf: np.ndarray, path: str, offset: int):
    # r+b keeps existing content (positional write into a preallocated file)
    mode = "r+b" if os.path.exists(path) else "wb"
    with open(path, mode) as f:
        f.seek(offset)
        f.write(buf.tobytes())
