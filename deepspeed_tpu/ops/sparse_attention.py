"""Block-sparse attention — sparsity layouts + sparse self-attention.

Capability parity with the reference's ``deepspeed/ops/sparse_attention/*``
(Triton block-sparse sdd/dsd matmuls + softmax, SparseSelfAttention, and the
sparsity pattern zoo in sparsity_config.py:94-686: Fixed / Variable / BigBird
/ BSLongformer / LocalSlidingWindow). This was the reference's long-context
mechanism (~10x longer sequences, docs/_pages/training.md:108).

Here a sparsity config produces a BLOCK LAYOUT [heads, q_blocks, k_blocks]
(bool: attend/skip), exactly like the reference's `make_layout`. Execution:
  * `sparse_attention(...)` applies the layout as a mask over the jnp
    reference (XLA fuses mask+softmax; correctness oracle, works everywhere)
  * the Pallas flash kernel's causal block-skip generalizes to layout-driven
    skip (same `@pl.when` mechanism) — the layout is the single source of
    truth for both paths.
Ring/Ulysses sequence parallelism (parallel/ring_attention.py) is the other
long-context axis; they compose (sparse within a rank's chunk).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class SparsityConfig:
    """Base: dense layout (reference: sparsity_config.py SparsityConfig)."""
    num_heads: int
    block: int = 16
    different_layout_per_head: bool = False

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = self._num_blocks(seq_len)
        return np.ones((self.num_heads, n, n), dtype=bool)

    def _num_blocks(self, seq_len: int) -> int:
        if seq_len % self.block != 0:
            raise ValueError(f"seq_len {seq_len} not divisible by block "
                             f"{self.block}")
        return seq_len // self.block


@dataclasses.dataclass
class DenseSparsityConfig(SparsityConfig):
    pass


@dataclasses.dataclass
class FixedSparsityConfig(SparsityConfig):
    """Fixed pattern (Sparse Transformers): local windows of
    `num_local_blocks` + global attention to the last `num_global_blocks`
    of each preceding window (reference: sparsity_config.py Fixed)."""
    num_local_blocks: int = 4
    num_global_blocks: int = 1
    attention: str = "bidirectional"   # or "unidirectional"

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = self._num_blocks(seq_len)
        L, G = self.num_local_blocks, self.num_global_blocks
        layout = np.zeros((self.num_heads, n, n), dtype=bool)
        for qi in range(n):
            win = qi // L
            # local window
            lo = win * L
            hi = min(lo + L, n)
            layout[:, qi, lo:hi] = True
            # global: last G blocks of every previous window
            for w in range(win):
                gs = (w + 1) * L - G
                layout[:, qi, max(gs, 0):(w + 1) * L] = True
        if self.attention == "unidirectional":
            tril = np.tril(np.ones((n, n), dtype=bool))
            layout &= tril[None]
        return layout


@dataclasses.dataclass
class BSLongformerSparsityConfig(SparsityConfig):
    """Longformer: symmetric sliding window + designated global blocks."""
    num_sliding_window_blocks: int = 3
    global_block_indices: tuple = (0,)

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = self._num_blocks(seq_len)
        w = self.num_sliding_window_blocks // 2
        layout = np.zeros((self.num_heads, n, n), dtype=bool)
        for qi in range(n):
            layout[:, qi, max(0, qi - w):min(n, qi + w + 1)] = True
        for g in self.global_block_indices:
            if g < n:
                layout[:, g, :] = True     # global block attends everything
                layout[:, :, g] = True     # everything attends global block
        return layout


@dataclasses.dataclass
class BigBirdSparsityConfig(SparsityConfig):
    """BigBird: random + sliding window + global blocks."""
    num_random_blocks: int = 1
    num_sliding_window_blocks: int = 3
    num_global_blocks: int = 1
    seed: int = 0

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = self._num_blocks(seq_len)
        rng = np.random.default_rng(self.seed)
        w = self.num_sliding_window_blocks // 2
        layout = np.zeros((self.num_heads, n, n), dtype=bool)
        for qi in range(n):
            layout[:, qi, max(0, qi - w):min(n, qi + w + 1)] = True
        g = self.num_global_blocks
        layout[:, :g, :] = True
        layout[:, :, :g] = True
        heads = self.num_heads if self.different_layout_per_head else 1
        for h in range(heads):
            for qi in range(n):
                picks = rng.choice(n, size=min(self.num_random_blocks, n),
                                   replace=False)
                layout[h if heads > 1 else slice(None), qi, picks] = True
        return layout


@dataclasses.dataclass
class LocalSlidingWindowSparsityConfig(SparsityConfig):
    """Plain sliding window (optionally causal)."""
    num_sliding_window_blocks: int = 3
    attention: str = "unidirectional"

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = self._num_blocks(seq_len)
        w = self.num_sliding_window_blocks
        layout = np.zeros((self.num_heads, n, n), dtype=bool)
        for qi in range(n):
            if self.attention == "unidirectional":
                layout[:, qi, max(0, qi - w + 1):qi + 1] = True
            else:
                half = w // 2
                layout[:, qi, max(0, qi - half):min(n, qi + half + 1)] = True
        return layout


@dataclasses.dataclass
class VariableSparsityConfig(SparsityConfig):
    """Variable: per-window local sizes + custom global indices."""
    num_random_blocks: int = 0
    local_window_blocks: tuple = (4,)
    global_block_indices: tuple = (0,)
    attention: str = "bidirectional"
    seed: int = 0

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = self._num_blocks(seq_len)
        layout = np.zeros((self.num_heads, n, n), dtype=bool)
        # consecutive local windows of the given sizes (last repeats)
        sizes = list(self.local_window_blocks)
        start = 0
        while start < n:
            size = sizes.pop(0) if len(sizes) > 1 else sizes[0]
            end = min(start + size, n)
            layout[:, start:end, start:end] = True
            start = end
        for g in self.global_block_indices:
            if g < n:
                layout[:, g, :] = True
                layout[:, :, g] = True
        if self.num_random_blocks:
            rng = np.random.default_rng(self.seed)
            for qi in range(n):
                picks = rng.choice(n, size=min(self.num_random_blocks, n),
                                   replace=False)
                layout[:, qi, picks] = True
        if self.attention == "unidirectional":
            layout &= np.tril(np.ones((n, n), dtype=bool))[None]
        return layout


SPARSITY_CONFIGS = {
    "dense": DenseSparsityConfig,
    "fixed": FixedSparsityConfig,
    "variable": VariableSparsityConfig,
    "bigbird": BigBirdSparsityConfig,
    "bslongformer": BSLongformerSparsityConfig,
    "local_sliding_window": LocalSlidingWindowSparsityConfig,
}


def build_sparsity_config(mode: str, num_heads: int, **kwargs) -> SparsityConfig:
    """reference: runtime/config.py:270-453 sparse_attention section parsing."""
    if mode not in SPARSITY_CONFIGS:
        raise ValueError(f"unknown sparse attention mode '{mode}'; "
                         f"have {sorted(SPARSITY_CONFIGS)}")
    return SPARSITY_CONFIGS[mode](num_heads=num_heads, **kwargs)


def layout_to_dense_mask(layout: np.ndarray, block: int) -> jnp.ndarray:
    """[H, nq, nk] block layout -> [H, S, S] element mask."""
    return jnp.asarray(np.repeat(np.repeat(layout, block, axis=1),
                                 block, axis=2))


def sparse_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                     config: SparsityConfig,
                     *,
                     causal: bool = False,
                     sm_scale: Optional[float] = None,
                     use_kernel: Optional[bool] = None,
                     interpret: bool = False) -> jnp.ndarray:
    """Block-sparse attention. q,k,v: [B, H, S, D].

    Execution: the Pallas layout-skip kernel
    (ops/pallas/block_sparse_attention.py) when on TPU and shapes tile —
    attention FLOPs scale with layout density, like the reference's Triton
    sdd/dsd path — otherwise the dense-mask oracle (XLA fuses mask+softmax;
    correct everywhere, no compute saving).
    """
    S = q.shape[-2]
    layout = config.make_layout(S)
    auto = use_kernel is None
    if auto:
        use_kernel = jax.default_backend() == "tpu"
    if use_kernel:
        from .pallas.block_sparse_attention import block_sparse_flash_attention
        try:
            return block_sparse_flash_attention(
                q, k, v, layout, config.block, causal=causal,
                sm_scale=sm_scale, interpret=interpret)
        except ValueError:
            # only the AUTO path may quietly fall back to the dense-mask
            # oracle; an explicit use_kernel=True means the caller wants the
            # FLOP-scaling contract and must hear that it can't be met
            if not auto:
                raise
    mask = layout_to_dense_mask(layout, config.block)[None]   # [1, H, S, S]
    from .attention import mha_reference
    return mha_reference(q, k, v, causal=causal, mask=mask, sm_scale=sm_scale)


class SparseSelfAttention:
    """Module-style wrapper (reference: sparse_self_attention.py:11)."""

    def __init__(self, sparsity_config: SparsityConfig, causal: bool = False):
        self.config = sparsity_config
        self.causal = causal

    def __call__(self, q, k, v):
        return sparse_attention(q, k, v, self.config, causal=self.causal)
