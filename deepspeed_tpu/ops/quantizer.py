"""Quantization ops — per-group int8/int4 quant/dequant, stochastic rounding.

Capability parity with the reference's quantization kernel family
(csrc/quantization/*: ds_quantizer sym/asym fake-quant, stochastic-rounding
variants, dequant; pt_binding.cpp:136-155). jnp implementations lower to
tight XLA elementwise+reduce fusions on TPU; the same math backs the
compressed-collective path (runtime/comm/compressed.py) and QAT
(compression/).

Layout: tensors are quantized per GROUP (a row of `x.reshape(groups, -1)`),
matching the reference's group-wise API.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _grouped(x: jnp.ndarray, groups: int) -> jnp.ndarray:
    n = x.size
    if n % groups != 0:
        raise ValueError(f"size {n} not divisible by groups {groups}")
    return x.reshape(groups, n // groups)


def quantize_symmetric(x: jnp.ndarray, bits: int = 8, groups: int = 1,
                       stochastic: bool = False,
                       rng: Optional[jax.Array] = None
                       ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """x -> (q int8, scale f32[groups]); q in [-qmax, qmax], x ~= q * scale."""
    shape = x.shape
    g = _grouped(x.astype(jnp.float32), groups)
    qmax = float(2 ** (bits - 1) - 1)
    absmax = jnp.max(jnp.abs(g), axis=1, keepdims=True)
    scale = jnp.where(absmax == 0, 1.0, absmax / qmax)
    y = g / scale
    if stochastic and rng is not None:
        noise = jax.random.uniform(rng, y.shape) - 0.5
        q = jnp.round(y + noise)
    else:
        q = jnp.round(y)
    q = jnp.clip(q, -qmax, qmax).astype(jnp.int8)
    return q.reshape(shape), scale[:, 0]


def dequantize_symmetric(q: jnp.ndarray, scale: jnp.ndarray,
                         groups: int = 1) -> jnp.ndarray:
    shape = q.shape
    g = _grouped(q.astype(jnp.float32), groups)
    return (g * scale[:, None]).reshape(shape)


def quantize_asymmetric(x: jnp.ndarray, bits: int = 8, groups: int = 1
                        ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """x -> (q uint-range stored int32, scale, zero_point) per group."""
    shape = x.shape
    g = _grouped(x.astype(jnp.float32), groups)
    qmax = float(2 ** bits - 1)
    lo = jnp.min(g, axis=1, keepdims=True)
    hi = jnp.max(g, axis=1, keepdims=True)
    scale = jnp.where(hi == lo, 1.0, (hi - lo) / qmax)
    zp = lo
    q = jnp.clip(jnp.round((g - zp) / scale), 0, qmax).astype(jnp.int32)
    return q.reshape(shape), scale[:, 0], zp[:, 0]


def dequantize_asymmetric(q: jnp.ndarray, scale: jnp.ndarray,
                          zero_point: jnp.ndarray, groups: int = 1
                          ) -> jnp.ndarray:
    shape = q.shape
    g = _grouped(q.astype(jnp.float32), groups)
    return (g * scale[:, None] + zero_point[:, None]).reshape(shape)


def fake_quantize(x: jnp.ndarray, bits: int = 8, groups: int = 1,
                  symmetric: bool = True) -> jnp.ndarray:
    """Quant-dequant round trip (QAT forward; straight-through gradient)."""

    @jax.custom_vjp
    def _fq(x):
        if symmetric:
            q, s = quantize_symmetric(x, bits, groups)
            return dequantize_symmetric(q, s, groups).astype(x.dtype)
        q, s, zp = quantize_asymmetric(x, bits, groups)
        return dequantize_asymmetric(q, s, zp, groups).astype(x.dtype)

    _fq.defvjp(lambda x: (_fq(x), None), lambda _, g: (g,))
    return _fq(x)


def onebit_compress(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """1-bit sign compression: x -> (signs int8 {-1,+1}, scale = mean|x|).
    (reference: compressed_allreduce sign+scale packing, runtime/comm/nccl.py:52)."""
    xf = x.astype(jnp.float32)
    scale = jnp.mean(jnp.abs(xf))
    signs = jnp.where(xf >= 0, 1, -1).astype(jnp.int8)
    return signs, scale


def onebit_decompress(signs: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    return signs.astype(jnp.float32) * scale


_BIT_WEIGHTS = jnp.asarray([1, 2, 4, 8, 16, 32, 64, 128], jnp.uint8)


def pack_signs(x: jnp.ndarray) -> jnp.ndarray:
    """[-1,+1] (or real-valued; sign taken) f32 [m] -> uint8 bitmap [m/8].
    m must be a multiple of 8. This is what makes 1-bit collectives carry
    1 bit/element on the wire (the reference packs via cupy packbits,
    runtime/comm/nccl.py my_igather of sign bits)."""
    bits = (x >= 0).reshape(-1, 8).astype(jnp.uint8)
    return jnp.sum(bits * _BIT_WEIGHTS[None, :], axis=1, dtype=jnp.uint8)


def unpack_signs(packed: jnp.ndarray) -> jnp.ndarray:
    """uint8 bitmap [m/8] -> f32 signs {-1,+1} [m]."""
    bits = (packed[:, None] & _BIT_WEIGHTS[None, :]) > 0
    return jnp.where(bits, 1.0, -1.0).reshape(-1).astype(jnp.float32)
