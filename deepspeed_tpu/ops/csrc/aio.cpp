// Asynchronous tensor <-> NVMe IO for ZeRO-Infinity-style swapping.
//
// TPU-native counterpart of the reference's csrc/aio/ (deepspeed_aio_thread /
// py_ds_aio.cpp aio_handle: block-chunked reads/writes on a worker-thread
// pool with submit/wait semantics). The reference binds libaio through
// pybind11; here the same capability is a plain-C ABI over a std::thread pool
// doing positional pread/pwrite — ctypes loads it, no python.h, and the
// chunking (block_size) + queue_depth + thread_count knobs keep the aio
// config section's meaning (reference: runtime/swap_tensor/constants.py).
//
// Semantics:
//   handle = ds_aio_handle_new(block_size, queue_depth, thread_count)
//   ds_aio_submit_read(handle, buf, nbytes, path, file_offset)  -> ticket >= 0
//   ds_aio_submit_write(handle, buf, nbytes, path, file_offset) -> ticket >= 0
//   ds_aio_wait(handle)      waits for ALL outstanding ops; returns #errors
//   ds_aio_pread / ds_aio_pwrite are the synchronous forms.

#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct IoOp {
    bool write;
    char* buf;
    int64_t nbytes;
    std::string path;
    int64_t offset;
};

// one positional chunked transfer; returns 0 on success
int do_io(const IoOp& op, int64_t block_size) {
    int flags = op.write ? (O_WRONLY | O_CREAT) : O_RDONLY;
    int fd = ::open(op.path.c_str(), flags, 0644);
    if (fd < 0) return -1;
    int64_t done = 0;
    int rc = 0;
    while (done < op.nbytes) {
        int64_t chunk = std::min(block_size, op.nbytes - done);
        ssize_t r = op.write
            ? ::pwrite(fd, op.buf + done, chunk, op.offset + done)
            : ::pread(fd, op.buf + done, chunk, op.offset + done);
        if (r <= 0) { rc = -1; break; }
        done += r;
    }
    ::close(fd);
    return rc;
}

struct AioHandle {
    int64_t block_size;
    int queue_depth;      // max ops a worker claims before others wake (advisory)
    std::vector<std::thread> workers;
    std::deque<IoOp> queue;
    std::mutex mu;
    std::condition_variable cv_work, cv_done;
    std::atomic<int64_t> in_flight{0};
    std::atomic<int64_t> errors{0};
    std::atomic<int64_t> next_ticket{0};
    bool stop = false;

    AioHandle(int64_t bs, int qd, int threads) : block_size(bs), queue_depth(qd) {
        if (threads < 1) threads = 1;
        for (int i = 0; i < threads; ++i)
            workers.emplace_back([this] { run(); });
    }

    ~AioHandle() {
        {
            std::lock_guard<std::mutex> lk(mu);
            stop = true;
        }
        cv_work.notify_all();
        for (auto& t : workers) t.join();
    }

    void run() {
        for (;;) {
            IoOp op;
            {
                std::unique_lock<std::mutex> lk(mu);
                cv_work.wait(lk, [this] { return stop || !queue.empty(); });
                if (stop && queue.empty()) return;
                op = std::move(queue.front());
                queue.pop_front();
            }
            if (do_io(op, block_size) != 0) errors.fetch_add(1);
            // decrement + notify under the mutex: a waiter between its
            // predicate check and sleep must not miss the wakeup
            bool last;
            {
                std::lock_guard<std::mutex> lk(mu);
                last = in_flight.fetch_sub(1) == 1;
            }
            if (last) cv_done.notify_all();
        }
    }

    int64_t submit(IoOp op) {
        in_flight.fetch_add(1);
        {
            std::lock_guard<std::mutex> lk(mu);
            queue.push_back(std::move(op));
        }
        cv_work.notify_one();
        return next_ticket.fetch_add(1);
    }

    int64_t wait() {
        std::unique_lock<std::mutex> lk(mu);
        cv_done.wait(lk, [this] { return in_flight.load() == 0; });
        return errors.exchange(0);
    }
};

}  // namespace

extern "C" {

void* ds_aio_handle_new(int64_t block_size, int queue_depth, int thread_count) {
    if (block_size <= 0) block_size = 1 << 20;
    return new AioHandle(block_size, queue_depth, thread_count);
}

void ds_aio_handle_free(void* h) { delete static_cast<AioHandle*>(h); }

int64_t ds_aio_submit_read(void* h, void* buf, int64_t nbytes, const char* path,
                           int64_t offset) {
    return static_cast<AioHandle*>(h)->submit(
        {false, static_cast<char*>(buf), nbytes, path, offset});
}

int64_t ds_aio_submit_write(void* h, void* buf, int64_t nbytes, const char* path,
                            int64_t offset) {
    return static_cast<AioHandle*>(h)->submit(
        {true, static_cast<char*>(buf), nbytes, path, offset});
}

// waits for ALL outstanding ops on the handle; returns number of failed ops
int64_t ds_aio_wait(void* h) { return static_cast<AioHandle*>(h)->wait(); }

int ds_aio_pread(void* h, void* buf, int64_t nbytes, const char* path,
                 int64_t offset) {
    return do_io({false, static_cast<char*>(buf), nbytes, path, offset},
                 static_cast<AioHandle*>(h)->block_size);
}

int ds_aio_pwrite(void* h, void* buf, int64_t nbytes, const char* path,
                  int64_t offset) {
    return do_io({true, static_cast<char*>(buf), nbytes, path, offset},
                 static_cast<AioHandle*>(h)->block_size);
}

}  // extern "C"
