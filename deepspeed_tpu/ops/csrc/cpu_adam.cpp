// SIMD CPU optimizer kernels for ZeRO-Offload — host-side Adam/AdamW/Adagrad.
//
// TPU-native counterpart of the reference's csrc/adam/cpu_adam.cpp +
// csrc/includes/cpu_adam.h (Step_AVX over the flat fp32 partition): the hot
// loop is written scalar-simple so g++ -O3 -march=native auto-vectorizes it to
// AVX2/AVX-512 (same codegen the reference's hand-written intrinsics target),
// and OpenMP splits the flat buffer across cores (the reference uses a
// #pragma omp parallel over TILEs).
//
// The kernel updates the fp32 master partition in place and (optionally)
// emits a bf16 copy of the updated params in the same pass — the reference
// writes fp16 dev_params for the H2D copy (cpu_adam.h dev_param arg); on TPU
// the transfer dtype is bfloat16.
//
// C ABI (ctypes-friendly), no torch, no python.h.

#include <cmath>
#include <cstddef>
#include <cstdint>
#include <cstring>

#if defined(_OPENMP)
#include <omp.h>
#endif

namespace {

// round-to-nearest-even fp32 -> bf16, matching XLA's convert semantics
inline uint16_t f32_to_bf16(float x) {
    uint32_t bits;
    std::memcpy(&bits, &x, sizeof(bits));
    uint32_t rounding = 0x7FFFu + ((bits >> 16) & 1u);
    return static_cast<uint16_t>((bits + rounding) >> 16);
}

}  // namespace

extern "C" {

// Adam / AdamW step over a flat fp32 buffer.
//   step       1-based optimizer step (for bias correction)
//   adamw_mode 1 = decoupled weight decay (AdamW), 0 = L2-into-grad Adam
//   grad_scale grads are multiplied by 1/grad_scale (loss-scale unscaling
//              fused into the same pass, like the reference's ds_scale)
//   bf16_out   optional (may be null): updated params as bf16 for the H2D copy
void ds_cpu_adam_step(int64_t step,
                      float lr,
                      float beta1,
                      float beta2,
                      float eps,
                      float weight_decay,
                      int adamw_mode,
                      int bias_correction,
                      float grad_scale,
                      float* params,
                      const float* grads,
                      float* exp_avg,
                      float* exp_avg_sq,
                      int64_t n,
                      uint16_t* bf16_out) {
    const float bc1 = bias_correction ? 1.0f - std::pow(beta1, (float)step) : 1.0f;
    const float bc2 = bias_correction ? 1.0f - std::pow(beta2, (float)step) : 1.0f;
    const float inv_scale = grad_scale != 0.0f ? 1.0f / grad_scale : 1.0f;
    const float bc2_sqrt = std::sqrt(bc2);

#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float g = grads[i] * inv_scale;
        float p = params[i];
        if (weight_decay != 0.0f && !adamw_mode) g += weight_decay * p;
        float m = beta1 * exp_avg[i] + (1.0f - beta1) * g;
        float v = beta2 * exp_avg_sq[i] + (1.0f - beta2) * g * g;
        float denom = std::sqrt(v) / bc2_sqrt + eps;
        float upd = (m / bc1) / denom;
        if (weight_decay != 0.0f && adamw_mode) upd += weight_decay * p;
        p -= lr * upd;
        params[i] = p;
        exp_avg[i] = m;
        exp_avg_sq[i] = v;
        if (bf16_out) bf16_out[i] = f32_to_bf16(p);
    }
}

// Adagrad step (reference: csrc/adagrad/cpu_adagrad.cpp).
void ds_cpu_adagrad_step(float lr,
                         float eps,
                         float weight_decay,
                         float grad_scale,
                         float* params,
                         const float* grads,
                         float* sum_sq,
                         int64_t n,
                         uint16_t* bf16_out) {
    const float inv_scale = grad_scale != 0.0f ? 1.0f / grad_scale : 1.0f;
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float g = grads[i] * inv_scale;
        float p = params[i];
        if (weight_decay != 0.0f) g += weight_decay * p;
        float s = sum_sq[i] + g * g;
        p -= lr * g / (std::sqrt(s) + eps);
        params[i] = p;
        sum_sq[i] = s;
        if (bf16_out) bf16_out[i] = f32_to_bf16(p);
    }
}

// Momentum-SGD step, for completeness of the host-offload optimizer family.
void ds_cpu_sgd_step(float lr,
                     float momentum,
                     float weight_decay,
                     int nesterov,
                     float grad_scale,
                     float* params,
                     const float* grads,
                     float* momentum_buf,
                     int64_t n,
                     uint16_t* bf16_out) {
    const float inv_scale = grad_scale != 0.0f ? 1.0f / grad_scale : 1.0f;
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float g = grads[i] * inv_scale;
        float p = params[i];
        if (weight_decay != 0.0f) g += weight_decay * p;
        if (momentum != 0.0f) {
            float b = momentum * momentum_buf[i] + g;
            momentum_buf[i] = b;
            g = nesterov ? g + momentum * b : b;
        }
        p -= lr * g;
        params[i] = p;
        if (bf16_out) bf16_out[i] = f32_to_bf16(p);
    }
}

// Fused fp32 -> bf16 convert (H2D staging helper).
void ds_f32_to_bf16(const float* src, uint16_t* dst, int64_t n) {
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) dst[i] = f32_to_bf16(src[i]);
}

int ds_cpu_kernels_num_threads() {
#if defined(_OPENMP)
    return omp_get_max_threads();
#else
    return 1;
#endif
}

}  // extern "C"
