// Native batch assembly for the mmap indexed dataset.
//
// TPU-VM counterpart of the reference's data-loading native layer: where the
// reference leans on torch DataLoader worker processes to hide batch-assembly
// cost, here the hot loop — gathering N variable-length token sequences from
// the mmapped .bin into one contiguous [N, seq_len] host buffer (truncate /
// pad) — is C++: mmap once, OpenMP-parallel row memcpy (saturates host
// memory bandwidth), plus a single background prefetch thread so the next
// batch assembles while the device runs the current step (the role of the
// reference's prefetching DataLoader workers, without per-batch pickling).
//
// C ABI (ctypes-friendly), no torch, no python.h. Layout knowledge (index
// pointers/sizes, dtype) stays in Python — this module only moves bytes.

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct DlHandle {
    char* base = nullptr;
    int64_t size = 0;

    // prefetch state: one outstanding batch assembled on a worker thread
    std::thread worker;
    std::mutex mu;
    std::condition_variable cv;
    bool busy = false;
    int64_t last_bad = 0;   // bad-row count of the last finished prefetch

    ~DlHandle() {
        {
            std::unique_lock<std::mutex> lk(mu);
            cv.wait(lk, [this] { return !busy; });
        }
        if (worker.joinable()) worker.join();
        if (base) ::munmap(base, size);
    }
};

// gather rows[i] = bin[pointers[i] : pointers[i] + min(lengths, row)*item]
// into out[i*row_bytes ...]; caller pre-fills `out` with the pad token.
// Returns the number of rows whose pointer/length fell outside the .bin
// (corrupt or stale index) so the caller can raise instead of training on
// silently pad-filled rows.
int64_t gather(const DlHandle* h, const int64_t* pointers,
               const int64_t* nbytes, int64_t n, int64_t row_bytes,
               char* out) {
    int64_t bad = 0;
#pragma omp parallel for schedule(static) reduction(+ : bad)
    for (int64_t i = 0; i < n; ++i) {
        int64_t take = nbytes[i] < row_bytes ? nbytes[i] : row_bytes;
        // overflow-safe form: pointers[i] + take could wrap for garbage
        // int64 values from a corrupt index
        if (pointers[i] < 0 || take < 0 || pointers[i] > h->size ||
            take > h->size - pointers[i]) {
            ++bad;
            continue;
        }
        std::memcpy(out + i * row_bytes, h->base + pointers[i], take);
    }
    return bad;
}

}  // namespace

extern "C" {

void* ds_dl_open(const char* bin_path) {
    int fd = ::open(bin_path, O_RDONLY);
    if (fd < 0) return nullptr;
    struct stat st;
    if (::fstat(fd, &st) != 0) { ::close(fd); return nullptr; }
    void* base = ::mmap(nullptr, st.st_size, PROT_READ, MAP_SHARED, fd, 0);
    ::close(fd);
    if (base == MAP_FAILED) return nullptr;
    // document sampling is random access over the corpus — tell the kernel
    // NOT to read ahead the whole file (WILLNEED here would synchronously
    // queue readahead of a multi-hundred-GB .bin and thrash the page cache)
    ::madvise(base, st.st_size, MADV_RANDOM);
    auto* h = new DlHandle();
    h->base = static_cast<char*>(base);
    h->size = st.st_size;
    return h;
}

void ds_dl_close(void* h) { delete static_cast<DlHandle*>(h); }

// synchronous assembly; caller pre-fills out with the pad token bytes.
// Returns the number of out-of-bounds rows (0 = clean).
int64_t ds_dl_gather(void* h, const int64_t* pointers, const int64_t* nbytes,
                     int64_t n, int64_t row_bytes, void* out) {
    return gather(static_cast<DlHandle*>(h), pointers, nbytes, n, row_bytes,
                  static_cast<char*>(out));
}

// asynchronous assembly into a caller-owned buffer; exactly one outstanding
// prefetch per handle (double buffering — submit batch k+1, wait, swap).
// Returns 0 on submit, -1 if a prefetch is already in flight.
int ds_dl_prefetch(void* hv, const int64_t* pointers, const int64_t* nbytes,
                   int64_t n, int64_t row_bytes, void* out) {
    auto* h = static_cast<DlHandle*>(hv);
    {
        std::lock_guard<std::mutex> lk(h->mu);
        if (h->busy) return -1;
        h->busy = true;
    }
    if (h->worker.joinable()) h->worker.join();
    // copy the index arrays: the caller may free/reuse them after submit
    std::vector<int64_t> ptrs(pointers, pointers + n);
    std::vector<int64_t> lens(nbytes, nbytes + n);
    h->worker = std::thread(
        [h, p = std::move(ptrs), l = std::move(lens), n, row_bytes, out] {
            int64_t bad = gather(h, p.data(), l.data(), n, row_bytes,
                                 static_cast<char*>(out));
            {
                std::lock_guard<std::mutex> lk(h->mu);
                h->last_bad = bad;
                h->busy = false;
            }
            h->cv.notify_all();
        });
    return 0;
}

// blocks until the outstanding prefetch (if any) completes; returns its
// bad-row count (0 = clean)
int64_t ds_dl_prefetch_wait(void* hv) {
    auto* h = static_cast<DlHandle*>(hv);
    std::unique_lock<std::mutex> lk(h->mu);
    h->cv.wait(lk, [h] { return !h->busy; });
    return h->last_bad;
}

}  // extern "C"
