"""Optimizer library — functional (init, update) pairs over parameter pytrees.

Capability parity with the reference's optimizer families:
  FusedAdam / CPUAdam     csrc/adam/* + ops/adam/*        -> `adam` / `adamw`
  FusedLamb               csrc/lamb/*                     -> `lamb`
  CPUAdagrad              csrc/adagrad/*                  -> `adagrad`
  torch SGD passthrough                                   -> `sgd`

On TPU "fused multi-tensor" is what XLA produces for free: a single jitted
update over the whole pytree fuses into large elementwise kernels (the role of
multi_tensor_apply.cuh). Master weights stay fp32 and are sharded by the ZeRO
policy; updates run on the local shard only — exactly the reference's
"optimizer steps on its partition" (stage_1_and_2.py:1750).

All state lives in a plain dict-of-pytrees so checkpointing is dtype/shape
introspectable (universal-checkpoint-friendly by construction).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


class Optimizer(NamedTuple):
    """A functional optimizer: state = init(params); params, state = update(...)."""
    init: Callable[[Any], Dict[str, Any]]
    update: Callable[..., Tuple[Any, Dict[str, Any]]]
    name: str


def _tree_zeros_like(params, dtype=None):
    """Moment buffers: fp32 masters get fp32 moments (the usual mixed-
    precision shape); pure-bf16 params get bf16 moments (6 bytes/param of
    optimizer state — see BF16Config.master_weights).  The param-dtype
    inheritance is deliberately limited to bf16: a direct caller passing
    fp16 params (outside the engine's master-weights flow) still gets fp32
    moments — fp16 moment accumulation is never a supported mode."""
    def moment_dtype(p):
        if dtype is not None:
            return dtype
        return (jnp.bfloat16 if getattr(p, "dtype", None) == jnp.bfloat16
                else jnp.float32)
    return jax.tree.map(
        lambda p: jnp.zeros(p.shape, moment_dtype(p)), params)


def adam(lr: float = 1e-3,
         betas: Tuple[float, float] = (0.9, 0.999),
         eps: float = 1e-8,
         weight_decay: float = 0.0,
         bias_correction: bool = True,
         adamw_mode: bool = False) -> Optimizer:
    """Adam/AdamW. reference: csrc/adam/multi_tensor_adam.cu + cpu_adam.h Step_AVX;
    adamw_mode matches the reference's decoupled weight-decay switch."""
    b1, b2 = betas

    def init(params):
        return {"m": _tree_zeros_like(params), "v": _tree_zeros_like(params)}

    def update(grads, state, params, step, lr_t=None):
        lr_eff = lr if lr_t is None else lr_t
        t = step.astype(jnp.float32) + 1.0
        bc1 = 1.0 - b1 ** t if bias_correction else 1.0
        bc2 = 1.0 - b2 ** t if bias_correction else 1.0

        def leaf(g, m, v, p):
            # math in f32; storage keeps each tensor's own dtype, so the
            # master-less bf16 mode (params/moments bf16) round-trips
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if weight_decay != 0.0 and not adamw_mode:
                g = g + weight_decay * p32
            m_new = b1 * m.astype(jnp.float32) + (1.0 - b1) * g
            v_new = b2 * v.astype(jnp.float32) + (1.0 - b2) * g * g
            upd = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
            if weight_decay != 0.0 and adamw_mode:
                upd = upd + weight_decay * p32
            return ((p32 - lr_eff * upd).astype(p.dtype),
                    m_new.astype(m.dtype), v_new.astype(v.dtype))

        flat_p, treedef = jax.tree.flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state["m"])
        flat_v = treedef.flatten_up_to(state["v"])
        out = [leaf(g, m, v, p) for g, m, v, p in zip(flat_g, flat_m, flat_v, flat_p)]
        new_p = treedef.unflatten([o[0] for o in out])
        new_m = treedef.unflatten([o[1] for o in out])
        new_v = treedef.unflatten([o[2] for o in out])
        return new_p, {"m": new_m, "v": new_v}

    return Optimizer(init, update, "adamw" if adamw_mode else "adam")


def adamw(lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
          weight_decay: float = 0.01, bias_correction: bool = True) -> Optimizer:
    return adam(lr, betas, eps, weight_decay, bias_correction, adamw_mode=True)


def lamb(lr: float = 1e-3,
         betas: Tuple[float, float] = (0.9, 0.999),
         eps: float = 1e-6,
         weight_decay: float = 0.0,
         min_coeff: float = 0.01,
         max_coeff: float = 10.0) -> Optimizer:
    """LAMB with per-param trust ratio. reference: csrc/lamb/fused_lamb_cuda_kernel.cu.

    The per-tensor L2 norms that the CUDA kernel computes in a two-pass reduction
    are plain jnp.norm calls here; when params are ZeRO-sharded XLA inserts the
    cross-shard psum automatically (the reference needs explicit allreduce)."""
    b1, b2 = betas

    def init(params):
        return {"m": _tree_zeros_like(params, jnp.float32),
                "v": _tree_zeros_like(params, jnp.float32)}

    def update(grads, state, params, step, lr_t=None):
        lr_eff = lr if lr_t is None else lr_t

        def leaf(g, m, v, p):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m_new = b1 * m + (1.0 - b1) * g
            v_new = b2 * v + (1.0 - b2) * g * g
            upd = m_new / (jnp.sqrt(v_new) + eps) + weight_decay * p32
            w_norm = jnp.linalg.norm(p32)
            u_norm = jnp.linalg.norm(upd)
            trust = jnp.where((w_norm > 0) & (u_norm > 0),
                              jnp.clip(w_norm / u_norm, min_coeff, max_coeff), 1.0)
            return p32 - lr_eff * trust * upd, m_new, v_new

        flat_p, treedef = jax.tree.flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state["m"])
        flat_v = treedef.flatten_up_to(state["v"])
        out = [leaf(g, m, v, p) for g, m, v, p in zip(flat_g, flat_m, flat_v, flat_p)]
        return (treedef.unflatten([o[0] for o in out]),
                {"m": treedef.unflatten([o[1] for o in out]),
                 "v": treedef.unflatten([o[2] for o in out])})

    return Optimizer(init, update, "lamb")


def sgd(lr: float = 1e-3, momentum: float = 0.0, weight_decay: float = 0.0,
        nesterov: bool = False) -> Optimizer:
    def init(params):
        if momentum == 0.0:
            return {}
        return {"momentum": _tree_zeros_like(params, jnp.float32)}

    def update(grads, state, params, step, lr_t=None):
        lr_eff = lr if lr_t is None else lr_t

        def leaf(g, p, buf):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if weight_decay != 0.0:
                g = g + weight_decay * p32
            if momentum != 0.0:
                buf_new = momentum * buf + g
                g = g + momentum * buf_new if nesterov else buf_new
                return p32 - lr_eff * g, buf_new
            return p32 - lr_eff * g, None

        if momentum == 0.0:
            new_p = jax.tree.map(lambda g, p: leaf(g, p, None)[0], grads, params)
            return new_p, {}
        flat_p, treedef = jax.tree.flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_b = treedef.flatten_up_to(state["momentum"])
        out = [leaf(g, p, b) for g, p, b in zip(flat_g, flat_p, flat_b)]
        return (treedef.unflatten([o[0] for o in out]),
                {"momentum": treedef.unflatten([o[1] for o in out])})

    return Optimizer(init, update, "sgd")


def adagrad(lr: float = 1e-2, eps: float = 1e-10, weight_decay: float = 0.0) -> Optimizer:
    """reference: csrc/adagrad/cpu_adagrad.cpp."""

    def init(params):
        return {"sum": _tree_zeros_like(params, jnp.float32)}

    def update(grads, state, params, step, lr_t=None):
        lr_eff = lr if lr_t is None else lr_t

        def leaf(g, s, p):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if weight_decay != 0.0:
                g = g + weight_decay * p32
            s_new = s + g * g
            return p32 - lr_eff * g / (jnp.sqrt(s_new) + eps), s_new

        flat_p, treedef = jax.tree.flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_s = treedef.flatten_up_to(state["sum"])
        out = [leaf(g, s, p) for g, s, p in zip(flat_g, flat_s, flat_p)]
        return (treedef.unflatten([o[0] for o in out]),
                {"sum": treedef.unflatten([o[1] for o in out])})

    return Optimizer(init, update, "adagrad")


def onebit_adam(lr: float = 1e-3,
                betas: Tuple[float, float] = (0.9, 0.999),
                eps: float = 1e-8,
                weight_decay: float = 0.0,
                freeze_step: int = 100) -> Optimizer:
    """1-bit Adam (reference: runtime/fp16/onebit/adam.py).

    Two stages: (1) warmup — exact Adam, variance v learning; (2) compression
    — v frozen, the momentum update is sign+scale compressed with persistent
    worker error feedback before being applied. In the SPMD engine the grads
    entering `update` are already globally averaged; the explicit
    bandwidth-saving collective for the momentum (sign a2a + scale allgather)
    is runtime/comm/compressed.compressed_allreduce, used when grad sync runs
    in explicit-collective mode. This optimizer reproduces the algorithm's
    numerics (compressed-momentum dynamics + error feedback) either way.
    """
    b1, b2 = betas
    from .quantizer import onebit_compress, onebit_decompress

    def init(params):
        return {"m": _tree_zeros_like(params, jnp.float32),
                "v": _tree_zeros_like(params, jnp.float32),
                "comp_err": _tree_zeros_like(params, jnp.float32)}

    def update(grads, state, params, step, lr_t=None):
        lr_eff = lr if lr_t is None else lr_t
        t = step.astype(jnp.float32) + 1.0
        warm = t <= float(freeze_step)

        def leaf(g, m, v, err, p):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m_new = b1 * m + (1.0 - b1) * g
            v_new = jnp.where(warm, b2 * v + (1.0 - b2) * g * g, v)
            # compression stage: communicate compressed momentum w/ EF; the
            # momentum STATE becomes the decompressed value (the error buffer
            # holds the residual — reference: exp_avg is overwritten by the
            # server result, onebit/adam.py)
            signs, scale = onebit_compress(m_new + err)
            m_comp = onebit_decompress(signs, scale)
            err_new = (m_new + err) - m_comp
            m_out = jnp.where(warm, m_new, m_comp)
            err_out = jnp.where(warm, err, err_new)
            upd = m_out / (jnp.sqrt(v_new) + eps)
            if weight_decay != 0.0:
                upd = upd + weight_decay * p32
            return p32 - lr_eff * upd, m_out, v_new, err_out

        flat_p, treedef = jax.tree.flatten(params)
        flat = [leaf(g, m, v, e, p) for g, m, v, e, p in zip(
            treedef.flatten_up_to(grads), treedef.flatten_up_to(state["m"]),
            treedef.flatten_up_to(state["v"]),
            treedef.flatten_up_to(state["comp_err"]), flat_p)]
        return (treedef.unflatten([o[0] for o in flat]),
                {"m": treedef.unflatten([o[1] for o in flat]),
                 "v": treedef.unflatten([o[2] for o in flat]),
                 "comp_err": treedef.unflatten([o[3] for o in flat])})

    return Optimizer(init, update, "onebitadam")


def lamb_warm_leaf(p32, m_new, v_new, cf, *, eps, weight_decay, min_coeff,
                   max_coeff, coeff_beta):
    """Per-leaf LAMB warmup step math, shared by the dp=1 functional
    onebit_lamb and the explicit-collective OneBitRunner (runtime/onebit.py).
    Returns (update, trust coeff, new coeff_freeze EMA)."""
    upd = m_new / (jnp.sqrt(v_new) + eps) + weight_decay * p32
    w_norm = jnp.linalg.norm(p32)
    u_norm = jnp.linalg.norm(upd)
    coeff = jnp.where((w_norm > 0) & (u_norm > 0),
                      jnp.clip(w_norm / u_norm, min_coeff, max_coeff), 1.0)
    cf_new = jnp.where(coeff != 1.0,
                       coeff_beta * cf + (1 - coeff_beta) * coeff, cf)
    return upd, coeff, cf_new


def lamb_frozen_leaf(p32, m_old, m_comp, v, vf, lf, *, b1, b2, eps,
                     weight_decay, factor_min, factor_max, factor_threshold):
    """Per-leaf 1-bit LAMB compression-stage math (reference:
    onebit/lamb.py:337-386): frozen-variance update scaled by the
    clipped/rate-limited denominator factor. Returns (update, factor,
    new v_fresh); the param step is p - lr * coeff_freeze * factor * update."""
    denom = jnp.sqrt(v) + eps
    upd_prelim = m_comp / denom
    upd = upd_prelim + weight_decay * p32
    g_recon = (m_comp - b1 * m_old) / (1.0 - b1)
    vf_new = b2 * vf + (1.0 - b2) * g_recon * g_recon
    denom_real = jnp.sqrt(vf_new) + eps
    factor = jnp.max(denom / denom_real)
    if weight_decay > 0.0:
        ratio = jnp.minimum(1.0, jnp.linalg.norm(upd_prelim) /
                            (jnp.linalg.norm(upd) + 1e-30))
        factor = factor * ratio + (1.0 - ratio)
    factor = jnp.clip(factor, factor_min, factor_max)
    factor = jnp.clip(factor, lf * (1.0 - factor_threshold),
                      lf * (1.0 + factor_threshold))
    return upd, factor, vf_new


def zero_one_adam(lr: float = 1e-3,
                  betas: Tuple[float, float] = (0.9, 0.999),
                  eps: float = 1e-8,
                  weight_decay: float = 0.0,
                  var_freeze_step: int = 100000,
                  var_update_scaler: int = 16,
                  local_step_scaler: int = 32678,
                  local_step_clipper: int = 16) -> Optimizer:
    """0/1 Adam (reference: runtime/fp16/onebit/zoadam.py:11-377; paper
    arXiv:2202.06009). A DIFFERENT algorithm from 1-bit Adam:

    * Variance phase (step <= var_freeze_step): v updates only at steps
      where ``step % var_interval == 0`` — the interval DOUBLES after every
      ``var_update_scaler`` v-updates (kappa in the paper), so v freezes
      gradually. v-steps use the exact gradient; in between, the gradient is
      1-bit compressed with error feedback before entering the momentum.
    * Local-step phase (step > var_freeze_step): updates are purely local;
      the parameter deltas accumulate in ``u`` and only at interval
      boundaries (``step % local_interval == 0``) is the accumulated
      momentum exchanged (compressed) and parameters resynced; the local
      interval doubles every ``local_step_scaler`` steps up to
      ``local_step_clipper`` (H in the paper).

    No bias correction anywhere (the reference's update is
    m / (sqrt(v) + eps) + wd * p). This functional form reproduces the
    multi-rank dynamics at dp=1 by compressing locally (like
    ``onebit_adam`` above); the real cross-rank exchanges live in
    runtime/zeroone.ZeroOneRunner. Interval counters ride in the state as
    scalars, exactly the reference's per-param ``var_interval`` /
    ``local_step_interval`` bookkeeping.

    Because ``step`` is traced, both phases' math (including the unused
    phase's compression) executes every step behind ``jnp.where`` — a
    bounded ~2x on the optimizer's elementwise cost, dwarfed by fwd/bwd.
    The ZeroOneRunner dispatches four separate compiled programs host-side
    and pays none of this; it is the dp>1 performance path."""
    b1, b2 = betas
    from .quantizer import onebit_compress, onebit_decompress

    def comp(x):
        signs, scale = onebit_compress(x)
        return onebit_decompress(signs, scale)

    def init(params):
        scalar = lambda v, dt: jnp.asarray(v, dt)
        return {"m": _tree_zeros_like(params, jnp.float32),
                "v": _tree_zeros_like(params, jnp.float32),
                "u": _tree_zeros_like(params, jnp.float32),
                "comp_err": _tree_zeros_like(params, jnp.float32),
                "var_interval": scalar(1, jnp.int32),
                "var_counter": scalar(0, jnp.int32),
                "local_interval": scalar(1, jnp.int32),
                "local_counter": scalar(0, jnp.int32),
                "lrs": scalar(0.0, jnp.float32)}

    def update(grads, state, params, step, lr_t=None):
        lr_eff = jnp.asarray(lr if lr_t is None else lr_t, jnp.float32)
        t = step.astype(jnp.int32) + 1
        in_local = t > var_freeze_step
        first_local = t == (var_freeze_step + 1)
        iv = state["var_interval"]
        li = state["local_interval"]
        is_v = (~in_local) & (t % iv == 0)
        is_b = in_local & (t % li == 0)
        lrs_new = jnp.where(in_local, state["lrs"] + lr_eff, state["lrs"])

        def leaf(g, m, v, u, err, p):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            # error buffers restart at the phase transition (reference:
            # reinitial_error_buffer — grad-metric residue must not leak
            # into the accumulated-momentum exchange)
            err = jnp.where(first_local, 0.0, err)
            # -- variance phase
            g_cin = g + err
            g_c = comp(g_cin)
            g_eff = jnp.where(is_v, g, g_c)
            m_var = b1 * m + (1.0 - b1) * g_eff
            v_var = jnp.where(is_v, b2 * v + (1.0 - b2) * g * g, v)
            # -- local phase (momentum from the raw local grad)
            m_new = jnp.where(in_local, b1 * m + (1.0 - b1) * g, m_var)
            v_new = jnp.where(in_local, v, v_var)
            denom = jnp.sqrt(v_new) + eps
            upd = m_new / denom + weight_decay * p32
            p_upd = p32 - lr_eff * upd
            u_upd = u - lr_eff * upd
            # -- boundary: undo the local drift, exchange it in momentum
            # units, reapply the synced drift, recover the averaged momentum
            base = p_upd - u_upd
            u_cin = u_upd * denom + err
            u_c = comp(u_cin)
            m_bnd = -u_c / lrs_new
            p_bnd = base + u_c / denom
            p_out = jnp.where(is_b, p_bnd, p_upd)
            m_out = jnp.where(is_b, m_bnd, m_new)
            u_out = jnp.where(is_b, 0.0,
                              jnp.where(in_local, u_upd, u))
            err_out = jnp.where(
                in_local,
                jnp.where(is_b, u_cin - u_c, err),
                jnp.where(is_v, err, g_cin - g_c))
            return (p_out.astype(p.dtype), m_out, v_new, u_out, err_out)

        flat_p, treedef = jax.tree.flatten(params)
        flat = [leaf(g, m, v, u, e, p) for g, m, v, u, e, p in zip(
            treedef.flatten_up_to(grads), treedef.flatten_up_to(state["m"]),
            treedef.flatten_up_to(state["v"]),
            treedef.flatten_up_to(state["u"]),
            treedef.flatten_up_to(state["comp_err"]), flat_p)]
        unf = lambda i: treedef.unflatten([o[i] for o in flat])

        # interval bookkeeping (reference zoadam.py:283-303)
        vc1 = state["var_counter"] + is_v.astype(jnp.int32)
        double = (~in_local) & (vc1 == var_update_scaler)
        lc1 = state["local_counter"] + in_local.astype(jnp.int32)
        grow = in_local & (lc1 == local_step_scaler)
        return unf(0), {
            "m": unf(1), "v": unf(2), "u": unf(3), "comp_err": unf(4),
            "var_interval": jnp.where(double, iv * 2, iv),
            "var_counter": jnp.where(double, 0, vc1),
            "local_interval": jnp.where(
                grow, jnp.minimum(local_step_clipper, li * 2), li),
            "local_counter": jnp.where(grow, 0, lc1),
            "lrs": jnp.where(is_b, 0.0, lrs_new)}

    return Optimizer(init, update, "zerooneadam")


def onebit_lamb(lr: float = 1e-3,
                betas: Tuple[float, float] = (0.9, 0.999),
                eps: float = 1e-8,
                weight_decay: float = 0.0,
                freeze_step: int = 100,
                max_coeff: float = 10.0,
                min_coeff: float = 0.01,
                coeff_beta: float = 0.9,
                factor_max: float = 4.0,
                factor_min: float = 0.5,
                factor_threshold: float = 0.1) -> Optimizer:
    """1-bit LAMB (reference: runtime/fp16/onebit/lamb.py).

    Warmup: exact LAMB, tracking an EMA of each leaf's trust ratio
    (lamb_coeff_freeze). Compression: momentum is sign-compressed with error
    feedback, v freezes, and the trust ratio becomes coeff_freeze * factor
    where factor = max(frozen_denom / fresh_denom) estimated from the
    reconstructed grads, clipped and rate-limited (lamb.py:337-386). The
    cross-rank 1-bit exchange itself lives in runtime/onebit.OneBitRunner;
    this functional form reproduces the numerics for dp=1 / tests.
    """
    b1, b2 = betas
    from .quantizer import onebit_compress, onebit_decompress

    def init(params):
        return {"m": _tree_zeros_like(params, jnp.float32),
                "v": _tree_zeros_like(params, jnp.float32),
                "v_fresh": _tree_zeros_like(params, jnp.float32),
                "comp_err": _tree_zeros_like(params, jnp.float32),
                "coeff_freeze": jax.tree.map(
                    lambda p: jnp.zeros((), jnp.float32), params),
                "last_factor": jax.tree.map(
                    lambda p: jnp.ones((), jnp.float32), params)}

    def update(grads, state, params, step, lr_t=None):
        lr_eff = lr if lr_t is None else lr_t
        t = step.astype(jnp.float32) + 1.0
        warm = t <= float(freeze_step)

        def leaf(g, m, v, vf, err, cf, lf, p):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m_new = b1 * m + (1.0 - b1) * g
            # -- warmup branch values
            v_w = b2 * v + (1.0 - b2) * g * g
            upd_w, coeff_w, cf_w = lamb_warm_leaf(
                p32, m_new, v_w, cf, eps=eps, weight_decay=weight_decay,
                min_coeff=min_coeff, max_coeff=max_coeff,
                coeff_beta=coeff_beta)
            p_w = p32 - lr_eff * coeff_w * upd_w
            # -- compression branch values
            signs, scale = onebit_compress(m_new + err)
            m_comp = onebit_decompress(signs, scale)
            err_f = (m_new + err) - m_comp
            upd_f, factor, vf_f = lamb_frozen_leaf(
                p32, m, m_comp, v, vf, lf, b1=b1, b2=b2, eps=eps,
                weight_decay=weight_decay, factor_min=factor_min,
                factor_max=factor_max, factor_threshold=factor_threshold)
            p_f = p32 - lr_eff * (cf * factor) * upd_f
            # -- select
            sel = lambda a, b: jnp.where(warm, a, b)
            return (sel(p_w, p_f), sel(m_new, m_comp), sel(v_w, v),
                    sel(v_w, vf_f), sel(err, err_f), sel(cf_w, cf),
                    sel(lf, factor))

        flat_p, treedef = jax.tree.flatten(params)
        flat = [leaf(*args) for args in zip(
            treedef.flatten_up_to(grads), treedef.flatten_up_to(state["m"]),
            treedef.flatten_up_to(state["v"]),
            treedef.flatten_up_to(state["v_fresh"]),
            treedef.flatten_up_to(state["comp_err"]),
            treedef.flatten_up_to(state["coeff_freeze"]),
            treedef.flatten_up_to(state["last_factor"]), flat_p)]
        unf = lambda i: treedef.unflatten([o[i] for o in flat])
        return unf(0), {"m": unf(1), "v": unf(2), "v_fresh": unf(3),
                        "comp_err": unf(4), "coeff_freeze": unf(5),
                        "last_factor": unf(6)}

    return Optimizer(init, update, "onebitlamb")


# Registry keyed by the optimizer `type` names the reference engine accepts
# (engine.py:1042-1054 / _configure_basic_optimizer engine.py:1315).
_REGISTRY: Dict[str, Callable[..., Optimizer]] = {
    "adam": adam,
    "adamw": adamw,
    "fusedadam": adam,
    "lamb": lamb,
    "fusedlamb": lamb,
    "sgd": sgd,
    "adagrad": adagrad,
    "onebitadam": onebit_adam,
    "zerooneadam": zero_one_adam,
    "onebitlamb": onebit_lamb,
}


def build_optimizer(opt_type: str, params: Optional[dict] = None) -> Optimizer:
    key = opt_type.lower().replace("_", "")
    if key not in _REGISTRY:
        raise ValueError(f"Unknown optimizer type '{opt_type}'. Known: {sorted(_REGISTRY)}")
    kwargs = dict(params or {})
    # the reference accepts torch-style names; normalize
    if "betas" in kwargs:
        kwargs["betas"] = tuple(kwargs["betas"])
    kwargs.pop("torch_adam", None)
    kwargs.pop("adam_w_mode", None)
    if key in ("onebitadam", "zerooneadam", "onebitlamb"):
        # transport knobs with no TPU meaning — popped so a config stays
        # portable between single-chip (this functional path) and
        # multi-chip (runner) topologies
        kwargs.pop("cuda_aware", None)
        kwargs.pop("comm_backend_name", None)
        if kwargs.pop("amsgrad", False):
            # reference parity: zoadam.py raises for amsgrad too
            raise ValueError(f"{opt_type} does not support amsgrad")
        # accepted-and-unused by the reference's own implementations
        # (their step math never reads them) — warn so a user relying on
        # them learns the truth instead of silently different numerics
        for k in ("eps_inside_sqrt", "max_grad_norm", "bias_correction"):
            if kwargs.pop(k, None):
                from ..utils.logging import warning_once
                warning_once(
                    f"{opt_type}: '{k}' is accepted for config compatibility "
                    "but has no effect (the reference's 1-bit/0-1 step math "
                    "does not apply it either)")
    return _REGISTRY[key](**kwargs)
