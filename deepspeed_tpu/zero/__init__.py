"""deepspeed_tpu.zero — user-facing ZeRO API.

The reference exports ``zero.Init`` (partition-at-construction) and
``zero.GatheredParameters`` (temporary full-weight access) from
``runtime/zero/partition_parameters.py``. In the functional jax world:

- ``Init`` is a context manager under which model *initialization* produces
  already-sharded fp32 params: it records the target sharding policy so
  ``DeepSpeedEngine`` (or the user via ``init_sharded``) materializes each
  param directly on its owner shard — no single host ever holds the full
  model, which is the reference's reason for Init's existence.
- ``GatheredParameters`` yields fully-replicated host-accessible copies of
  selected params (reference partition_parameters.py:1519).
"""

from __future__ import annotations

import contextlib
from typing import Optional

import jax

from ..runtime.zero.stages import ZeroShardingPolicy
from ..parallel.mesh import MeshManager, get_global_mesh

_ACTIVE_INIT = None


class Init(contextlib.AbstractContextManager):
    """with zero.Init(config=...): params = model.init(...) → sharded init.

    reference: runtime/zero/partition_parameters.py:539 (Init / partition at
    construction). Under jax, `jax.jit(model.init, out_shardings=...)`
    materializes every parameter directly into its 1/N shard.
    """

    def __init__(self, config=None, mesh_manager: Optional[MeshManager] = None,
                 enabled: bool = True):
        from ..config import load_config
        self.config = load_config(config)
        self.mesh_mgr = mesh_manager or get_global_mesh()
        self.enabled = enabled and self.config.zero_optimization.stage == 3

    def __enter__(self):
        global _ACTIVE_INIT
        if self.enabled:
            _ACTIVE_INIT = self
        return self

    def __exit__(self, *exc):
        global _ACTIVE_INIT
        _ACTIVE_INIT = None
        return False

    def sharded_init(self, init_fn, *args, **kwargs):
        """Run ``init_fn`` jitted with ZeRO-3 out-shardings (no full replica)."""
        if self.mesh_mgr is None:
            from ..parallel.mesh import build_mesh_from_config
            self.mesh_mgr = build_mesh_from_config(self.config)
        policy = ZeroShardingPolicy(
            3, self.mesh_mgr,
            param_persistence_threshold=(
                self.config.zero_optimization.param_persistence_threshold))
        shapes = jax.eval_shape(init_fn, *args, **kwargs)
        shardings = policy.tree_shardings(
            jax.tree.map(lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype), shapes),
            policy.param_spec)
        # graftlint: disable=TPU002 (model init API: one trace per model construction)
        return jax.jit(init_fn, out_shardings=shardings)(*args, **kwargs)


def get_active_init() -> Optional[Init]:
    return _ACTIVE_INIT


class GatheredParameters(contextlib.AbstractContextManager):
    """Temporary full (host) access to *selected* params, with write-back.

    reference: partition_parameters.py:1519 — gathers only the params you pass
    (pass a subtree, not the whole model), and when ``modifier_rank`` is set,
    mutations made inside the block are re-partitioned on exit.

    jax arrays are immutable, so the contract is: the context yields mutable
    host numpy copies (gathered leaf-by-leaf — peak host memory is one leaf
    above the subtree size, never the whole model unless you pass it); mutate
    them in place, and after exit read ``.updated`` for device arrays restored
    to each leaf's ORIGINAL sharding::

        g = GatheredParameters(params["wte"], modifier_rank=0)
        with g as host:
            host["embedding"][0] = 0.0
        params = {**params, "wte": g.updated}
    """

    def __init__(self, params, modifier_rank: Optional[int] = None,
                 fwd_module=None, enabled: bool = True):
        import numpy as np
        self._np = np
        self.params = params
        self.modifier_rank = modifier_rank
        self.enabled = enabled
        self.updated = None

    def __enter__(self):
        if not self.enabled:
            self._host = self.params
            return self._host
        self._shardings = jax.tree.map(
            lambda p: getattr(p, "sharding", None), self.params)
        # leaf-by-leaf gather: device buffers for a leaf are freed before the
        # next leaf is pulled, so host peak ~= subtree size + one leaf
        self._host = jax.tree.map(lambda p: self._np.array(p), self.params)
        return self._host

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None or self.modifier_rank is None:
            return False
        if not self.enabled:
            # disabled is a no-op: write-back target is the original tree, so
            # the documented `params = {**params, k: g.updated}` pattern holds
            self.updated = self.params
            return False
        self.updated = jax.tree.map(
            lambda h, s: (jax.device_put(h, s) if s is not None
                          else jax.numpy.asarray(h)),
            self._host, self._shardings)
        return False


class OnDevice(contextlib.AbstractContextManager):
    """Construct model params in a target dtype / on a target device.

    reference: deepspeed/utils/init_on_device.py ``OnDevice`` (patches tensor
    constructors so a huge model materializes as fp16/meta instead of fp32 on
    the default device). jax init is an explicit function call, so the
    capability is a wrapper around it:

        with zero.OnDevice(dtype=jnp.bfloat16, device="cpu") as od:
            params = od.init(model.init, rng, batch)
        with zero.OnDevice(device="meta") as od:         # shapes only
            abstract = od.init(model.init, rng, batch)
    """

    def __init__(self, dtype=None, device: Optional[str] = None,
                 enabled: bool = True):
        self.dtype = dtype
        self.device = device
        self.enabled = enabled

    def __exit__(self, *exc):
        return False

    def init(self, init_fn, *args, **kwargs):
        if not self.enabled:
            return init_fn(*args, **kwargs)
        import jax.numpy as jnp

        def casted(*a, **k):
            tree = init_fn(*a, **k)
            if self.dtype is None:
                return tree
            # cast INSIDE the traced init so XLA fuses it into each param's
            # producer — the fp32 tree never materializes (the whole point
            # of OnDevice for models whose fp32 copy would not fit)
            return jax.tree.map(
                lambda x: x.astype(self.dtype)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)

        if self.device == "meta":
            return jax.eval_shape(casted, *args, **kwargs)
        if self.device is not None:
            # move COMMITTED args onto the target device first — default
            # device only governs uncommitted inputs, and mixing a committed
            # accelerator arg with cpu out_shardings is a jit error; init
            # args (rngs, example batches) are small, so the transfer is
            # cheap next to the params the init materializes
            dev = jax.devices(self.device)[0]
            args, kwargs = jax.tree.map(
                lambda x: jax.device_put(x, dev)
                if isinstance(x, jax.Array) else x, (args, kwargs))
            shapes = jax.eval_shape(casted, *args, **kwargs)
            sharding = jax.sharding.SingleDeviceSharding(dev)
            out_sh = jax.tree.map(lambda _: sharding, shapes)
            with jax.default_device(dev):
                # graftlint: disable=TPU002 (model init API: one trace per model construction)
                return jax.jit(casted, out_shardings=out_sh)(*args, **kwargs)
        # graftlint: disable=TPU002 (model init API: one trace per model construction)
        return jax.jit(casted)(*args, **kwargs)


__all__ = ["Init", "GatheredParameters", "OnDevice", "ZeroShardingPolicy",
           "get_active_init"]
