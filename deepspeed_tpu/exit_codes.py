"""Single source of truth for the process exit-code contract.

The supervision stack communicates failure *kind* through process return
codes, and every layer (elastic agent, launcher supervisor, MPMD driver,
chaos harness, test assertions) dispatches on the same four values:

========================  =====  ====================================================
name                      value  meaning
========================  =====  ====================================================
``PREEMPTION_EXIT_CODE``  114    voluntary exit after a checkpoint-and-resume
                                 preemption; does NOT count against restart budgets
``STALL_EXIT_CODE``       117    the watchdog declared the process wedged; counts
                                 as a failure for elastic restart accounting
``INTEGRITY_EXIT_CODE``   118    the sentinel detected silent data corruption; the
                                 relaunch must resume from the last good checkpoint
``KILL_EXIT_CODE``        13     a chaos failpoint killed the process on purpose;
                                 distinct from every organic rc so tests can tell
                                 "chaos killed it" apart from a real crash
========================  =====  ====================================================

Modules that historically defined these literals (``elasticity.elastic_agent``,
``runtime.watchdog``, ``runtime.sentinel``, ``testing.chaos``, the MPMD
driver/worker) now import from here and re-export under their original names,
so existing ``from ..runtime.watchdog import STALL_EXIT_CODE`` imports keep
working.  graftlint rule TPU021 flags any raw ``114``/``117``/``118``/``13``
exit-code literal that reappears outside this module.
"""

from __future__ import annotations

#: rc for a voluntary checkpoint-then-exit preemption (resumable).
PREEMPTION_EXIT_CODE = 114

#: rc the stall watchdog uses when it declares the process wedged.
STALL_EXIT_CODE = 117

#: rc the SDC sentinel uses when training state fails an integrity check.
INTEGRITY_EXIT_CODE = 118

#: rc chaos-injected kills use so tests can distinguish them from crashes.
KILL_EXIT_CODE = 13

__all__ = [
    "PREEMPTION_EXIT_CODE",
    "STALL_EXIT_CODE",
    "INTEGRITY_EXIT_CODE",
    "KILL_EXIT_CODE",
]
