"""Activation checkpointing for ARBITRARY user models.

Reference parity: ``deepspeed/runtime/activation_checkpointing/checkpointing.py``
(``checkpoint(function, *args)`` at :748 wraps any module; ``configure`` at :830
reads the ``activation_checkpointing`` config section).  The reference
implements this with a custom ``torch.autograd.Function`` that detaches inputs,
stashes RNG states, and re-runs the forward in backward — ~400 LoC of manual
bookkeeping.  On TPU the whole mechanism is one primitive: ``jax.checkpoint``
(remat).  XLA re-runs the forward fragment during the backward pass and its
scheduler frees recomputed values as soon as they are consumed.

TPU-native mapping of the reference knobs:

  reference knob                      TPU behavior
  ----------------------------------  -------------------------------------
  partition_activations               saved residuals are mesh-sharded by
                                      construction under pjit — the partition
                                      the reference implements by hand
                                      (checkpointing.py:372) falls out of the
                                      sharding propagation; the knob therefore
                                      just enables checkpointing
  cpu_checkpointing                   remat policy offloads dot outputs to
                                      host memory when the backend supports
                                      memories (checkpointing.py:485)
  contiguous_memory_optimization      no-op: XLA arena allocation is
                                      contiguous already (checkpointing.py:438)
  number_checkpoints                  informational (JAX segments by the
                                      wrapped function, not a global count)
  synchronize_checkpoint_boundary     no-op: XLA inserts the needed
                                      dependencies; there is no stream skew
  profile                             logs remat policy at configure time

``checkpoint()`` composes: call it around any sub-function inside a traced
computation (per-layer, like the reference's Megatron usage) or let the engine
wrap the whole ``apply_fn`` when the config section is enabled.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax

from ..utils.logging import logger

# module-level state, mirroring the reference's globals
# (checkpointing.py:52-61)
_CONFIGURED = False
_PARTITION_ACTIVATIONS = False
_CPU_CHECKPOINTING = False
_CONTIGUOUS_CHECKPOINTING = False
_NUM_CHECKPOINTS: Optional[int] = None
_PROFILE = False
_POLICY_NAME = "full"


def _backend_platform() -> str:
    try:
        return jax.devices()[0].platform
    except Exception:
        return "<uninitialized>"


def _host_offload_supported() -> bool:
    """Host ("pinned_host") memory spaces exist on TPU; CPU backends reject
    the offload policy at lowering time, so probe the platform."""
    return _backend_platform() == "tpu"


def make_remat_policy(name: str) -> Optional[Callable]:
    """Map a policy name to a ``jax.checkpoint_policies`` entry.

    ``full``    — save nothing, recompute everything (reference default
                  behavior of ``checkpoint()``)
    ``dots``    — save matmul outputs only (Megatron-style "selective"
                  recompute: cheap elementwise ops are recomputed, the
                  expensive MXU results are kept)
    ``offload`` — like ``dots`` but the saved dot outputs live in host
                  memory (the reference's ``cpu_checkpointing``)
    ``none``    — save everything (checkpointing disabled)
    """
    cp = jax.checkpoint_policies
    if name == "none":
        return cp.everything_saveable
    if name == "full":
        return cp.nothing_saveable
    if name == "dots":
        return cp.dots_with_no_batch_dims_saveable
    if name == "offload":
        if _host_offload_supported():
            return cp.offload_dot_with_no_batch_dims("device", "pinned_host")
        logger.warning(
            "cpu_checkpointing: backend %s has no host memory space — "
            "falling back to selective (dots) recompute",
            _backend_platform())
        return cp.dots_with_no_batch_dims_saveable
    raise ValueError(f"unknown remat policy {name!r} "
                     "(expected none|full|dots|offload)")


def configure(mpu_=None,
              deepspeed_config=None,
              partition_activations: Optional[bool] = None,
              contiguous_checkpointing: Optional[bool] = None,
              num_checkpoints: Optional[int] = None,
              checkpoint_in_cpu: Optional[bool] = None,
              synchronize: Optional[bool] = None,
              profile: Optional[bool] = None) -> None:
    """Configure the module-level checkpointing behavior.

    Mirrors the reference signature (checkpointing.py:830).  ``deepspeed_config``
    may be a path / dict / DeepSpeedConfig; explicit kwargs override it.
    ``mpu_`` is accepted for API parity and unused: activation partitioning is
    a sharding-propagation fact on TPU, not an mpu concern.
    """
    global _CONFIGURED, _PARTITION_ACTIVATIONS, _CPU_CHECKPOINTING
    global _CONTIGUOUS_CHECKPOINTING, _NUM_CHECKPOINTS, _PROFILE, _POLICY_NAME

    if deepspeed_config is not None:
        from ..config import load_config
        sect = load_config(deepspeed_config).activation_checkpointing
        _PARTITION_ACTIVATIONS = sect.partition_activations
        _CPU_CHECKPOINTING = sect.cpu_checkpointing
        _CONTIGUOUS_CHECKPOINTING = sect.contiguous_memory_optimization
        _NUM_CHECKPOINTS = sect.number_checkpoints
        _PROFILE = sect.profile
    if partition_activations is not None:
        _PARTITION_ACTIVATIONS = partition_activations
    if contiguous_checkpointing is not None:
        _CONTIGUOUS_CHECKPOINTING = contiguous_checkpointing
    if num_checkpoints is not None:
        _NUM_CHECKPOINTS = num_checkpoints
    if checkpoint_in_cpu is not None:
        _CPU_CHECKPOINTING = checkpoint_in_cpu
    if profile is not None:
        _PROFILE = profile

    _POLICY_NAME = "offload" if _CPU_CHECKPOINTING else "full"
    _CONFIGURED = True
    if _PROFILE:
        logger.info("activation checkpointing configured: policy=%s "
                    "partition_activations=%s (sharded by construction) "
                    "num_checkpoints=%s", _POLICY_NAME,
                    _PARTITION_ACTIVATIONS, _NUM_CHECKPOINTS)


def is_configured() -> bool:
    return _CONFIGURED


def reset() -> None:
    """Reference parity (checkpointing.py:773). The reference frees its
    contiguous activation buffers here; XLA owns allocation, so this only
    resets the module state."""
    global _CONFIGURED, _PARTITION_ACTIVATIONS, _CPU_CHECKPOINTING
    global _CONTIGUOUS_CHECKPOINTING, _NUM_CHECKPOINTS, _POLICY_NAME, _PROFILE
    _CONFIGURED = False
    _PARTITION_ACTIVATIONS = False
    _CPU_CHECKPOINTING = False
    _CONTIGUOUS_CHECKPOINTING = False
    _NUM_CHECKPOINTS = None
    _POLICY_NAME = "full"
    _PROFILE = False


def partition_activations_in_checkpoint(partition_activation: bool) -> None:
    """Reference parity (checkpointing.py:760)."""
    global _PARTITION_ACTIVATIONS
    _PARTITION_ACTIVATIONS = partition_activation


def set_num_layers(nlayers: int) -> None:
    """Reference parity (checkpointing.py:768)."""
    global _NUM_CHECKPOINTS
    _NUM_CHECKPOINTS = nlayers


def checkpoint(function: Callable, *args: Any) -> Any:
    """Checkpoint a model segment: ``deepspeed.checkpointing.checkpoint``
    (reference :748).  Call inside a traced/jitted computation around any
    sub-function (a transformer layer, a block, the whole model); during the
    backward pass XLA recomputes the segment instead of keeping its residuals.

    Unlike the torch version there is no RNG stashing to do — JAX rng is
    explicit and replays identically on recompute.
    """
    return remat(function)(*args)


def remat(function: Callable, policy_name: Optional[str] = None,
          static_argnums=()) -> Callable:
    """Return a rematerialized version of ``function`` under the configured
    (or given) policy.  ``jax.checkpoint`` is idempotent-safe to apply at
    trace time and a no-op outside differentiation."""
    name = policy_name or _POLICY_NAME
    pol = make_remat_policy(name)
    if pol is jax.checkpoint_policies.everything_saveable:
        return function
    return jax.checkpoint(function, policy=pol, static_argnums=static_argnums)


def checkpointable(function: Callable) -> Callable:
    """Decorator form of :func:`checkpoint`.  The policy is resolved at call
    time, so a later :func:`configure` applies to already-decorated fns."""
    @functools.wraps(function)
    def wrapped(*args):
        return remat(function)(*args)
    return wrapped
