"""Hessian max-eigenvalue estimation by power iteration.

Capability parity with the reference's ``runtime/eigenvalue.py:7``
(Eigenvalue: per-layer power iteration on the loss curvature, used by MoQ to
pace quantization). The reference hand-rolls double-backward through torch
autograd; here the Hessian-vector product is one ``jax.jvp`` of ``jax.grad``
(forward-over-reverse — the standard jax HVP), jitted once and reused across
iterations.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from ..utils.logging import log_dist

PyTree = Any


class Eigenvalue:
    def __init__(self, verbose: bool = False, max_iter: int = 100,
                 tol: float = 1e-2, stability: float = 1e-6,
                 gas_boundary_resolution: int = 1,
                 layer_name: str = "", layer_num: int = 0):
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution
        self.layer_name = layer_name
        self.layer_num = layer_num
        self._hvp_cache: Dict[int, Callable] = {}

    @staticmethod
    def _normalize(tree):
        sq = sum(jnp.sum(jnp.square(v)) for v in jax.tree.leaves(tree))
        inv = jax.lax.rsqrt(jnp.maximum(sq, 1e-30))
        return jax.tree.map(lambda v: v * inv, tree)

    def compute_eigenvalue(self, loss_fn: Callable[..., jnp.ndarray],
                           params: PyTree,
                           rng: Optional[jax.Array] = None,
                           loss_args: tuple = ()) -> float:
        """Largest |eigenvalue| of d2 loss / d params2 (power iteration with
        the reference's stability damping and relative-tol early stop).

        ``loss_fn(params, *loss_args)``: pass per-call data (the batch)
        through loss_args with a STABLE loss_fn object — the jitted HVP step
        is cached per loss_fn identity, so a fresh closure per call would
        recompile every time and pin every captured batch."""
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        key = id(loss_fn)
        if key not in self._hvp_cache:
            grad_fn = jax.grad(loss_fn)

            @jax.jit
            def step(params, v, *extra):
                _, hv = jax.jvp(lambda p: grad_fn(p, *extra), (params,), (v,))
                hv = jax.tree.map(
                    lambda h, vv: jnp.nan_to_num(h) + self.stability * vv,
                    hv, v)
                eig = sum(jnp.sum(a * b) for a, b in zip(
                    jax.tree.leaves(v), jax.tree.leaves(hv)))
                return self._normalize(hv), eig

            self._hvp_cache.clear()          # one stable loss_fn at a time
            self._hvp_cache[key] = step
        step = self._hvp_cache[key]

        leaves, treedef = jax.tree.flatten(params)
        keys = jax.random.split(rng, len(leaves))
        v = self._normalize(jax.tree.unflatten(
            treedef, [jax.random.normal(k, l.shape, jnp.float32)
                      for k, l in zip(keys, leaves)]))
        prev = 0.0
        eig = 0.0
        for i in range(self.max_iter):
            v, eig_dev = step(params, v, *loss_args)
            eig = float(eig_dev)
            if self.verbose:
                log_dist(f"eigenvalue iter {i}: {eig:.6f}", ranks=[0])
            if abs(eig) > 0 and abs(eig - prev) / max(abs(eig), 1e-12) < self.tol:
                break
            prev = eig
        return abs(eig)
