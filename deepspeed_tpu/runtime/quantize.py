"""MoQ — Mixture of Quantization training.

Capability parity with the reference's ``runtime/quantize.py`` (Quantizer:
schedule-driven bit reduction during training, optionally paced by the
Hessian eigenvalue so sensitive layers quantize later). TPU shape: the
ds_config ``quantize_training`` section compiles into a
compression.CompressionSpec weight-quantization group (the same in-jit STE
fake-quant machinery), and ``eigenvalue_period_scale`` lengthens the bit
schedule by the measured curvature ratio.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ..compression.compress import CompressionGroup, CompressionSpec


def build_moq_spec(qt: Dict[str, Any]) -> Optional[CompressionSpec]:
    """ds_config['quantize_training'] -> CompressionSpec (None if disabled).

    Keys follow the reference (runtime/config.py get_quantize_enabled):
    quantize_bits {start_bits, target_bits}, quantize_schedule
    {quantize_period, schedule_offset}, quantize_groups, quantize_algo
    {q_type: symmetric|asymmetric}, modules (ours; default all kernels).
    """
    if not qt or not qt.get("enabled", False):
        return None
    bits = qt.get("quantize_bits", {})
    sched = qt.get("quantize_schedule", {})
    algo = qt.get("quantize_algo", {})
    group = CompressionGroup(
        kind="weight_quantization",
        name="moq",
        modules=tuple(qt.get("modules", ["kernel", "embedding"])),
        schedule_offset=int(sched.get("schedule_offset", 0)),
        start_bits=int(bits.get("start_bits", 16)),
        target_bits=int(bits.get("target_bits", 8)),
        quantization_period=int(sched.get("quantize_period", 100)),
        quantization_type=str(algo.get("q_type", "symmetric")),
        quantize_groups=int(qt.get("quantize_groups", 1)),
    )
    return CompressionSpec(groups=[group])


class MoQScheduler:
    """Eigenvalue-paced period stretching (reference: quantize.py eigenvalue
    gating — layers with larger curvature quantize more slowly)."""

    def __init__(self, spec: CompressionSpec, eigenvalue=None,
                 period_scale_max: float = 4.0):
        self.spec = spec
        self.eigenvalue = eigenvalue
        self.period_scale_max = period_scale_max
        self._baseline: Optional[float] = None

    def maybe_rescale(self, loss_fn, params, rng=None,
                      loss_args: tuple = ()) -> CompressionSpec:
        """Measure curvature and stretch quantization_period proportionally
        (capped). Returns the (possibly updated) spec."""
        if self.eigenvalue is None:
            return self.spec
        eig = self.eigenvalue.compute_eigenvalue(loss_fn, params, rng,
                                                 loss_args=loss_args)
        if self._baseline is None:
            self._baseline = max(eig, 1e-12)
            return self.spec
        scale = min(max(eig / self._baseline, 1.0), self.period_scale_max)
        import dataclasses
        self.spec = CompressionSpec(
            groups=[dataclasses.replace(
                g, quantization_period=int(g.quantization_period * scale))
                for g in self.spec.groups],
            activation_bits=self.spec.activation_bits,
            activation_offset=self.spec.activation_offset,
            layer_reduction=self.spec.layer_reduction)
        return self.spec
