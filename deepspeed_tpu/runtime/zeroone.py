"""Explicit-collective 0/1 Adam — the real ZeroOneAdam algorithm, multi-rank.

Role of the reference's ``runtime/fp16/onebit/zoadam.py:11-377`` (paper
arXiv:2202.06009). 0/1 Adam is NOT 1-bit Adam with different defaults; it has
two distinct mechanisms the OneBitRunner doesn't have:

* **Adaptive variance freezing**: in the variance phase the second moment v
  updates only every ``var_interval`` steps, and the interval doubles after
  every ``var_update_scaler`` v-updates. v-update steps pay an exact
  (uncompressed) gradient mean; the steps in between exchange the gradient
  1-bit compressed with error feedback.
* **1-bit sync with local steps**: past ``var_freeze_step`` every rank takes
  purely LOCAL steps — zero cross-rank traffic of any kind — accumulating its
  parameter drift in ``u``; only at interval boundaries
  (``step % local_interval == 0``, the interval doubling every
  ``local_step_scaler`` steps up to ``local_step_clipper``) does a compressed
  exchange of the accumulated momentum resync params and momentum.

SPMD realization: the engine's params stay the REPLICATED synced base the
whole time. The per-rank drift u and per-rank momentum live stacked [n, ...]
(dim 0 sharded over the data axis). Local steps run entirely inside a
shard_map with no collective ops — each rank differentiates at its own
effective params ``base + u_rank`` — so the compiled HLO of the local-step
program contains zero cross-replica collectives (auditable via
``collective_bytes``; tests/test_onebit.py asserts it). At a boundary the
drift is converted to momentum units, pushed through
``compressed_allreduce``, and folded back into the base params.

Composition envelope mirrors OneBitRunner: pure-DP mesh; ZeRO-1 shards m/v
during the variance phase (v is gathered once at the freeze transition — it
is read-only afterwards and every local step needs it in full); fp16 loss
scaling composes, at the documented cost of one scalar overflow psum in the
otherwise collective-free local step.  Loss/grad-norm in the local phase are
reported as the mean over this process's addressable ranks (combining them
on-device would itself be a collective).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .comm.compressed import chunk_elems, compressed_allreduce
from ..utils.jax_compat import shard_map as _shard_map
from .onebit import hlo_collective_bytes  # noqa: F401  (re-export for tests)

PyTree = Any


class _VarSchedule:
    """var_interval in effect when processing 1-indexed step ``t`` — an
    incremental replay of the reference's var_counter/var_interval
    bookkeeping (O(1) amortized per training step; a checkpoint resume just
    replays forward from 1 once)."""

    def __init__(self, kappa: int):
        self.kappa = kappa
        self._s, self._iv, self._vc = 1, 1, 0     # next step to process

    def at(self, t: int) -> int:
        if t < self._s:
            self._s, self._iv, self._vc = 1, 1, 0
        while self._s < t:
            if self._s % self._iv == 0:
                self._vc += 1
                if self._vc == self.kappa:
                    self._vc = 0
                    self._iv *= 2
            self._s += 1
        return self._iv


class _LocalSchedule:
    """local_step_interval in effect at 1-indexed step ``t`` (counting from
    the end of the variance phase)."""

    def __init__(self, freeze: int, scaler: int, clipper: int):
        self.freeze, self.scaler, self.clipper = freeze, scaler, clipper
        self._s, self._li, self._lc = freeze + 1, 1, 0

    def at(self, t: int) -> int:
        if t < self._s:
            self._s, self._li, self._lc = self.freeze + 1, 1, 0
        while self._s < t:
            self._lc += 1
            if self._lc == self.scaler:
                self._lc = 0
                self._li = min(self.clipper, self._li * 2)
            self._s += 1
        return self._li


class ZeroOneRunner:
    """Owns optimizer state + the four compiled step programs
    (vstep / cstep in the variance phase, local / boundary after it)."""

    def __init__(self,
                 hyper: Dict,
                 mesh,
                 axis: str,
                 apply_fn,
                 loss_fn,
                 gas: int,
                 compute_dtype=jnp.float32,
                 grad_clip: float = 0.0,
                 loss_scaler=None,
                 zero_stage: int = 0):
        self.mesh = mesh
        self.axis = axis
        self.n = mesh.shape[axis]
        self.gas = gas
        self.apply_fn = apply_fn
        self.loss_fn = loss_fn
        self.compute_dtype = compute_dtype
        self.grad_clip = grad_clip
        self.loss_scaler = loss_scaler
        self.zero_stage = int(zero_stage)

        h = dict(hyper or {})
        self.lr = float(h.pop("lr", 1e-3))
        b = h.pop("betas", (0.9, 0.999))
        self.betas = (float(b[0]), float(b[1]))
        self.eps = float(h.pop("eps", 1e-8))
        self.weight_decay = float(h.pop("weight_decay", 0.0))
        self.var_freeze_step = int(h.pop("var_freeze_step", 100000))
        self.var_update_scaler = int(h.pop("var_update_scaler", 16))
        self.local_step_scaler = int(h.pop("local_step_scaler", 32678))
        self.local_step_clipper = int(h.pop("local_step_clipper", 16))
        # accepted-for-compat reference knobs (transport / unused-by-the-
        # reference's-own-math); amsgrad raises there too (zoadam.py)
        if h.pop("amsgrad", False):
            raise ValueError("0/1 Adam does not support amsgrad")
        for k in ("cuda_aware", "comm_backend_name", "bias_correction",
                  "eps_inside_sqrt", "max_grad_norm"):
            h.pop(k, None)

        self._programs: Dict[str, Any] = {}
        self._transitioned = False
        self._vsched = _VarSchedule(self.var_update_scaler)
        self._lsched = _LocalSchedule(self.var_freeze_step,
                                      self.local_step_scaler,
                                      self.local_step_clipper)

    # -- state ---------------------------------------------------------------

    def _mv_sharding(self, p) -> NamedSharding:
        if self.zero_stage >= 1 and np.ndim(p) >= 1 \
                and p.shape[0] % self.n == 0:
            return NamedSharding(self.mesh, P(self.axis))
        return NamedSharding(self.mesh, P())

    def init_state(self, params_f32: PyTree) -> Dict[str, PyTree]:
        st = NamedSharding(self.mesh, P(self.axis))
        mv = lambda: jax.tree.map(
            lambda p: jax.device_put(jnp.zeros(p.shape, jnp.float32),
                                     self._mv_sharding(p)), params_f32)
        stacked = lambda: jax.tree.map(
            lambda p: jax.device_put(
                jnp.zeros((self.n,) + p.shape, jnp.float32), st), params_f32)
        state = {"m": mv(), "v": mv(),
                 # per-rank momentum + drift for the local-step phase;
                 # allocated up front so the state pytree (and therefore the
                 # engine's checkpoint layout) never changes shape
                 "m_local": stacked(), "u": stacked(),
                 "lrs": jnp.asarray(0.0, jnp.float32)}
        state["w_err"] = jax.tree.map(
            lambda p: jax.device_put(
                jnp.zeros((self.n, p.size), jnp.float32), st), params_f32)
        state["s_err"] = jax.tree.map(
            lambda p: jax.device_put(
                jnp.zeros((self.n, chunk_elems(p.size, self.n)), jnp.float32),
                st), params_f32)
        return state

    # -- per-rank grad stage ---------------------------------------------------

    def _stacked_grads(self, params, micros, rng, scale):
        """Stacked per-rank grads at the shared base params, no reduction
        (variance-phase programs) — the shared 1-bit/0-1 gradient stage."""
        from .onebit import stacked_local_grads
        return stacked_local_grads(self, params, micros, rng, scale)

    # -- variance-phase programs ----------------------------------------------

    def _build_var(self, is_v: bool):
        b1, b2 = self.betas
        scaling = self.loss_scaler is not None and self.loss_scaler.enabled

        def step(params, state, micros, rng, lr, scale_state):
            scale = (scale_state.scale if scaling
                     else jnp.asarray(1.0, jnp.float32))
            grads_st, loss_st, sq_st = self._stacked_grads(
                params, micros, rng, scale)
            loss = jnp.mean(loss_st)

            def do_update(args):
                params, state, grads_st = args
                new_s = dict(state)
                if is_v:
                    # exact-gradient step: update momentum AND variance
                    g = jax.tree.map(lambda g: jnp.mean(g, 0), grads_st)
                    norm = jnp.sqrt(sum(
                        jnp.sum(jnp.square(x)) for x in jax.tree.leaves(g)))
                    if self.grad_clip > 0:
                        coef = jnp.minimum(
                            self.grad_clip / (norm + 1e-6), 1.0)
                        g = jax.tree.map(lambda x: x * coef, g)
                    new_s["m"] = self._mv_pin(jax.tree.map(
                        lambda m, gg: b1 * m + (1 - b1) * gg,
                        state["m"], g))
                    new_s["v"] = self._mv_pin(jax.tree.map(
                        lambda v, gg: b2 * v + (1 - b2) * gg * gg,
                        state["v"], g))
                else:
                    # compressed-gradient step: v untouched (the freeze)
                    flat_g, treedef = jax.tree.flatten(grads_st)
                    we = treedef.flatten_up_to(state["w_err"])
                    se = treedef.flatten_up_to(state["s_err"])
                    g_sync, nwe, nse = [], [], []
                    for g_st, w, s in zip(flat_g, we, se):
                        gsy, w2, s2 = compressed_allreduce(
                            g_st, w, s, mesh=self.mesh, axis=self.axis)
                        g_sync.append(gsy)
                        nwe.append(w2)
                        nse.append(s2)
                    norm = jnp.sqrt(sum(
                        jnp.sum(jnp.square(x)) for x in g_sync))
                    if self.grad_clip > 0:
                        coef = jnp.minimum(
                            self.grad_clip / (norm + 1e-6), 1.0)
                        g_sync = [x * coef for x in g_sync]
                    g = treedef.unflatten(g_sync)
                    new_s["m"] = self._mv_pin(jax.tree.map(
                        lambda m, gg: b1 * m + (1 - b1) * gg,
                        state["m"], g))
                    new_s["w_err"] = treedef.unflatten(nwe)
                    new_s["s_err"] = treedef.unflatten(nse)
                new_p = jax.tree.map(
                    lambda p, m, v: p - lr * (
                        m / (jnp.sqrt(v) + self.eps)
                        + self.weight_decay * p),
                    params, new_s["m"], new_s["v"])
                rep = NamedSharding(self.mesh, P())
                new_p = jax.lax.with_sharding_constraint(new_p, rep)
                return new_p, new_s, norm

            if scaling:
                gnorm = jnp.sqrt(jnp.mean(sq_st))
                overflow = ~jnp.isfinite(gnorm)
                new_p, new_s, norm = lax.cond(
                    overflow,
                    lambda a: (a[0], a[1], gnorm), do_update,
                    (params, state, grads_st))
                new_scale_state = self.loss_scaler.update(scale_state,
                                                          overflow)
            else:
                overflow = jnp.asarray(False)
                new_p, new_s, norm = do_update((params, state, grads_st))
                new_scale_state = scale_state
            return new_p, new_s, loss, norm, overflow, new_scale_state

        return jax.jit(step, donate_argnums=(0, 1))

    def _mv_pin(self, tree):
        if self.zero_stage < 1:
            return tree
        return jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(
                x, self._mv_sharding(x)), tree)

    # -- local-step-phase programs ---------------------------------------------

    def _build_local(self, boundary: bool):
        b1, _b2 = self.betas
        scaling = self.loss_scaler is not None and self.loss_scaler.enabled

        def step(params, state, micros, rng, lr, scale_state):
            scale = (scale_state.scale if scaling
                     else jnp.asarray(1.0, jnp.float32))
            # read-only frozen variance, needed whole by every rank
            v_rep = jax.lax.with_sharding_constraint(
                state["v"], NamedSharding(self.mesh, P()))
            gas = self.gas

            def local(params, v, m_l, u_l, micros_l, rng, scale, lr):
                """One purely-local step for this rank: no collectives."""
                m_r = jax.tree.map(lambda x: x[0], m_l)
                u_r = jax.tree.map(lambda x: x[0], u_l)
                p_eff = jax.tree.map(lambda p, u: p + u, params, u_r)
                r = jax.random.fold_in(rng, lax.axis_index(self.axis))
                rngs = jax.random.split(r, gas)

                def body(acc, xs):
                    micro, rr = xs
                    cparams = jax.tree.map(
                        lambda p: p.astype(self.compute_dtype), p_eff)

                    def lossf(p):
                        out = self.apply_fn(p, micro, rr, True)
                        return (self.loss_fn(out, micro)
                                .astype(jnp.float32) * scale)

                    l, g = jax.value_and_grad(lossf)(cparams)
                    return jax.tree.map(
                        lambda a, gg: a + gg.astype(jnp.float32),
                        acc, g), l

                zero = jax.tree.map(
                    lambda p: jnp.zeros(p.shape, jnp.float32), p_eff)
                gsum, losses = lax.scan(body, zero, (micros_l, rngs))
                g = jax.tree.map(lambda x: x / (gas * scale), gsum)
                sq = sum(jnp.sum(jnp.square(x))
                         for x in jax.tree.leaves(g))
                norm_r = jnp.sqrt(sq)
                if self.grad_clip > 0:
                    # per-rank clip: a global norm would need a psum the
                    # collective-free local step must not pay
                    coef = jnp.minimum(
                        self.grad_clip / (norm_r + 1e-6), 1.0)
                    g = jax.tree.map(lambda x: x * coef, g)
                m_new = jax.tree.map(
                    lambda m, gg: b1 * m + (1 - b1) * gg, m_r, g)
                upd = jax.tree.map(
                    lambda m, vv, pe: m / (jnp.sqrt(vv) + self.eps)
                    + self.weight_decay * pe, m_new, v, p_eff)
                u_new = jax.tree.map(lambda u, up: u - lr * up, u_r, upd)
                stack = lambda t: jax.tree.map(lambda x: x[None], t)
                return (stack(m_new), stack(u_new),
                        (jnp.mean(losses) / scale)[None], norm_r[None])

            mapped = _shard_map(
                local, mesh=self.mesh,
                in_specs=(P(), P(), P(self.axis), P(self.axis),
                          P(None, self.axis), P(), P(), P()),
                out_specs=(P(self.axis), P(self.axis), P(self.axis),
                           P(self.axis)),
                axis_names={self.axis}, check_vma=False)
            m_st, u_st, loss_st, norm_st = mapped(
                params, v_rep, state["m_local"], state["u"], micros, rng,
                scale, lr)
            lrs_new = state["lrs"] + lr

            new_s = dict(state)
            new_p = params
            if boundary:
                # params are ALREADY the synced base (drift lives in u):
                # convert drift to momentum units, compressed-exchange it,
                # fold the averaged drift into the base and recover the
                # averaged momentum m = -u_sync / sum(lr)
                # (reference zoadam.py:253-276)
                flat_u, treedef = jax.tree.flatten(u_st)
                flat_v = treedef.flatten_up_to(v_rep)
                we = treedef.flatten_up_to(state["w_err"])
                se = treedef.flatten_up_to(state["s_err"])
                flat_p = treedef.flatten_up_to(params)
                nwe, nse, n_p, n_ml, n_u = [], [], [], [], []
                for u, v, w, s, p in zip(flat_u, flat_v, we, se, flat_p):
                    denom = jnp.sqrt(v) + self.eps
                    u_m = u * denom[None]
                    u_sync, w2, s2 = compressed_allreduce(
                        u_m, w, s, mesh=self.mesh, axis=self.axis)
                    nwe.append(w2)
                    nse.append(s2)
                    # the recovered average momentum (reference: exp_avg =
                    # -comm_buffer/lrs) lives only in the per-rank stack;
                    # state["m"] stays the stale variance-phase value by
                    # design — nothing reads it after the freeze
                    m_rep = -u_sync / lrs_new
                    n_ml.append(jax.lax.with_sharding_constraint(
                        jnp.broadcast_to(m_rep[None],
                                         (self.n,) + m_rep.shape),
                        NamedSharding(self.mesh, P(self.axis))))
                    n_p.append(p + u_sync / denom)
                    n_u.append(jnp.zeros_like(u))
                rep = NamedSharding(self.mesh, P())
                new_p = jax.lax.with_sharding_constraint(
                    treedef.unflatten(n_p), rep)
                new_s["m_local"] = treedef.unflatten(n_ml)
                # pin the reset drift to its stacked sharding: unconstrained
                # fresh zeros let XLA REPLICATE u — measured at 32 B/param/
                # device instead of 4 (scripts/onebit_envelope.py caught it)
                new_s["u"] = jax.tree.map(
                    lambda z: jax.lax.with_sharding_constraint(
                        z, NamedSharding(self.mesh, P(self.axis))),
                    treedef.unflatten(n_u))
                new_s["w_err"] = treedef.unflatten(nwe)
                new_s["s_err"] = treedef.unflatten(nse)
                new_s["lrs"] = jnp.asarray(0.0, jnp.float32)
            else:
                new_s["m_local"] = m_st
                new_s["u"] = u_st
                new_s["lrs"] = lrs_new

            if scaling:
                # scalar overflow psum — the one collective the fp16 local
                # step pays (bf16 runs are strictly collective-free)
                overflow = ~jnp.isfinite(jnp.sum(norm_st))
                sel = lambda old, new: jax.tree.map(
                    lambda o, nn: jnp.where(overflow, o, nn), old, new)
                new_p = sel(params, new_p)
                new_s = sel(state, new_s)
                new_scale_state = self.loss_scaler.update(scale_state,
                                                          overflow)
            else:
                overflow = jnp.asarray(False)
                new_scale_state = scale_state
            return new_p, new_s, loss_st, norm_st, overflow, new_scale_state

        return jax.jit(step, donate_argnums=(0, 1))

    # -- freeze transition -----------------------------------------------------

    def _transition(self, state):
        """One-time restructure entering the local-step phase: broadcast the
        synced momentum into the per-rank stack, gather the (now frozen)
        variance whole, reset the error buffers (reference
        reinitial_error_buffer: they switch metrics from gradients to
        accumulated momentum)."""
        rep = NamedSharding(self.mesh, P())
        st = NamedSharding(self.mesh, P(self.axis))
        out = dict(state)
        out["v"] = jax.device_put(state["v"], rep)
        bcast = jax.jit(
            lambda m: jnp.broadcast_to(m[None], (self.n,) + m.shape),
            out_shardings=st)
        out["m_local"] = jax.tree.map(bcast, jax.device_put(state["m"], rep))
        zero = lambda t: jax.tree.map(
            lambda x: jax.device_put(jnp.zeros_like(x), x.sharding), t)
        out["w_err"] = zero(state["w_err"])
        out["s_err"] = zero(state["s_err"])
        out["u"] = zero(state["u"])
        out["lrs"] = jnp.asarray(0.0, jnp.float32)
        return out

    # -- host-side schedule + dispatch ----------------------------------------

    def _program(self, key: str):
        if key not in self._programs:
            if key in ("vstep", "cstep"):
                self._programs[key] = self._build_var(key == "vstep")
            else:
                self._programs[key] = self._build_local(key == "boundary")
        return self._programs[key]

    def program_key(self, global_step: int) -> str:
        """Which compiled program step ``global_step`` (0-indexed) runs —
        pure function of the step, so checkpoint resume replays it."""
        t = global_step + 1
        if t <= self.var_freeze_step:
            iv = self._vsched.at(t)
            return "vstep" if t % iv == 0 else "cstep"
        li = self._lsched.at(t)
        return "boundary" if t % li == 0 else "local"

    def step(self, params, state, micros, rng, lr, global_step: int,
             scale_state=None) -> Tuple[PyTree, Dict, Any, Any, Any, Any]:
        from .loss_scaler import LossScaleState
        if scale_state is None:
            scale_state = (self.loss_scaler.init()
                           if self.loss_scaler is not None
                           and self.loss_scaler.enabled
                           else LossScaleState.identity())
        key = self.program_key(global_step)
        if key in ("vstep", "cstep"):
            # back in the variance phase (e.g. a pre-freeze checkpoint was
            # restored after the freeze had been crossed) — re-arm the
            # transition so re-crossing var_freeze_step re-broadcasts m and
            # resets the error buffers
            self._transitioned = False
        if key in ("local", "boundary") and not self._transitioned:
            if global_step == self.var_freeze_step:
                state = self._transition(state)
            else:
                # resumed from a post-transition checkpoint — state already
                # carries the broadcast m_local / reset errors, but the
                # engine restored v with its init-time (ZeRO-1) sharding;
                # re-gather it once here or every local step would pay the
                # all-gather the collective-free program must not contain
                state = dict(state)
                state["v"] = jax.device_put(
                    state["v"], NamedSharding(self.mesh, P()))
            self._transitioned = True
        out = self._program(key)(params, state, micros, rng,
                                 jnp.asarray(lr, jnp.float32), scale_state)
        if key in ("local", "boundary"):
            # per-rank stacked loss/norm -> host mean over addressable
            # shards (an on-device mean would be a collective in the
            # otherwise collective-free program). The host read adds no new
            # pipeline bubble: the engine blocks on the loss every step
            # anyway (tput_timer.stop(sync=loss)).
            new_p, new_s, loss_st, norm_st, overflow, nss = out
            loss = jnp.asarray(self._host_mean(loss_st), jnp.float32)
            norm = jnp.asarray(self._host_mean(norm_st), jnp.float32)
            return new_p, new_s, loss, norm, overflow, nss
        return out

    @staticmethod
    def _host_mean(arr) -> float:
        vals = [np.asarray(sh.data).reshape(-1)
                for sh in arr.addressable_shards]
        return float(np.mean(np.concatenate(vals))) if vals else float("nan")

    # -- auditability ----------------------------------------------------------

    def collective_bytes(self, params, state, micros, rng, key: str) -> int:
        """Bytes moved by cross-replica collectives in one compiled step of
        program ``key`` — parsed from optimized HLO. The headline claims:
        'local' is 0 (bf16) and 'cstep'/'boundary' are ~1/32 of the exact
        exchange."""
        from .loss_scaler import LossScaleState
        lowered = self._program(key).lower(
            params, state, micros, rng, jnp.asarray(self.lr, jnp.float32),
            LossScaleState.identity())
        return hlo_collective_bytes(lowered.compile().as_text())
