"""ZeRO stages 0-3 as sharding policies.

The reference implements ZeRO with ~5k lines of hand-scheduled buckets, hooks
and streams (``runtime/zero/stage_1_and_2.py``, ``stage3.py``). On TPU the same
partitioning is expressed as sharding specs and XLA's SPMD partitioner emits the
all-gathers / reduce-scatters that DeepSpeed schedules by hand:

  stage 0: params, grads, optimizer state replicated over the DP axes; gradient
           psum inserted by XLA (reference: buffered_allreduce_fallback engine.py:2453)
  stage 1: fp32 master params + optimizer state sharded over DP axes
           (reference: DeepSpeedZeroOptimizer partition_id slicing stage_1_and_2.py:609)
  stage 2: + gradients sharded — a sharding constraint on grads makes XLA emit
           reduce-scatter instead of all-reduce in backward
           (reference: average_tensor reduce-scatter stage_1_and_2.py:942)
  stage 3: + compute params sharded — XLA all-gathers weights on demand per layer,
           the latency-hiding scheduler prefetches ahead of use, replacing the
           trace-and-prefetch PartitionedParameterCoordinator (stage3.py:239-458)

A param is sharded by inserting the ZeRO axes on its largest dimension that is
divisible by the ZeRO world size and not already taken by a tensor-parallel
axis; otherwise it stays replicated (cheap: such params are small).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...parallel.mesh import MeshManager, ZERO_AXES, EXPERT_ZERO_AXES


def _axes_size(mesh_shape: dict, axes: Tuple[str, ...]) -> int:
    size = 1
    for a in axes:
        size *= mesh_shape.get(a, 1)
    return size


def canonicalize_spec(spec: P, mesh_shape: dict) -> P:
    """Normalize a PartitionSpec to the compiler's canonical output form:
    drop mesh axes of size 1, unwrap single-name tuples, strip trailing
    Nones. A spec naming a size-1 axis denotes the SAME sharding but is a
    DIFFERENT jit cache key than what XLA emits for the step's outputs —
    the mismatch cost one spurious retrace of the whole train program on
    the second step (caught by test_train_step_compiles_once_across_steps)."""
    out = []
    for entry in spec:
        if entry is None:
            out.append(None)
            continue
        names = (entry,) if isinstance(entry, str) else tuple(entry)
        # drop only KNOWN size-1 axes; an unknown (typo'd) axis must stay
        # so NamedSharding still raises instead of silently replicating
        names = tuple(n for n in names if mesh_shape.get(n, 0) != 1)
        if not names:
            out.append(None)
        elif len(names) == 1:
            out.append(names[0])
        else:
            out.append(names)
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def insert_zero_axes(shape: Tuple[int, ...],
                     tp_spec: Optional[P],
                     zero_axes: Tuple[str, ...],
                     zero_size: int,
                     avoid_last: bool = False) -> P:
    """Compose a TP PartitionSpec with ZeRO sharding on one additional dim.

    ``avoid_last`` (compute-param specs only): sharding the LAST (feature)
    dim of a >=2-D param propagates that sharding into every activation that
    reads it — an embedding gather emits H-sharded activations and the SPMD
    partitioner falls back to involuntary full rematerialization restoring
    the batch layout.  Such params stay whole on the compute side (their
    fp32 master / grad / optimizer shards, which have no activation
    coupling, keep the last-dim sharding and carry the memory win)."""
    ndim = len(shape)
    base = list(tp_spec) if tp_spec is not None else []
    base = base[:ndim] + [None] * (ndim - len(base))
    if zero_size <= 1:
        return P(*base)

    free = [i for i in range(ndim)
            if base[i] is None and shape[i] > 0 and shape[i] % zero_size == 0]
    if avoid_last and ndim > 1:
        free = [i for i in free if i != ndim - 1]
    if not free:
        return P(*base)
    dim = max(free, key=lambda i: shape[i])
    base[dim] = tuple(zero_axes) if len(zero_axes) > 1 else zero_axes[0]
    return P(*base)


class ZeroShardingPolicy:
    """Maps (param path, shape, TP rule) -> shardings for each train-state element."""

    def __init__(self, stage: int, mesh_mgr: MeshManager,
                 param_persistence_threshold: int = 0):
        if stage not in (0, 1, 2, 3):
            raise ValueError(f"invalid ZeRO stage {stage}")
        self.stage = stage
        self.mm = mesh_mgr
        self.mesh = mesh_mgr.mesh
        self._zero_size = _axes_size(mesh_mgr.shape, ZERO_AXES)
        self._expert_zero_size = _axes_size(mesh_mgr.shape, EXPERT_ZERO_AXES)
        # reference: stage3_param_persistence_threshold (stage3.py persistent
        # params) — compute-dtype params smaller than this stay whole; the
        # fp32 master/grad/optimizer shards are unaffected
        self.param_persistence_threshold = int(param_persistence_threshold)

    def _zero_axes_for(self, is_expert: bool) -> Tuple[Tuple[str, ...], int]:
        if is_expert:
            return EXPERT_ZERO_AXES, self._expert_zero_size
        return ZERO_AXES, self._zero_size

    def _compose_tp_dim(self, shape, tp_spec: Optional[P],
                        axes: Tuple[str, ...], size: int,
                        path: str = "") -> Optional[P]:
        """Extend an already-TP-sharded dim with the ZeRO axes, if divisible.

        Preferred over opening a fresh dim: sharding a fresh dim of a
        transformer kernel (or its grad) lands on the residual-stream H dim,
        and the backward contraction producing dW then wants the activation
        COTANGENT H-sharded — clashing with the batch/seq activation layout
        at the backward scan boundary (involuntary-remat reshards, round-3
        Weak #2). Composing onto the TP dim shards an INTERNAL tensor's dim
        (dqkv / attn_out), which has no carry coupling, and gives the same
        or better per-device memory."""
        if tp_spec is None or size <= 1:
            return None
        if "embedding" in path:
            # embedding tables are consumed by gather/scatter on their TP
            # (vocab) dim, not by a dot contraction — widening that dim
            # 8-way makes the embedding-grad scatter unpartitionable and
            # trades one coupling for another; their fresh-dim sharding (H)
            # couples nothing that loops
            return None
        ndim = len(shape)
        base = list(tp_spec)[:ndim]
        base += [None] * (ndim - len(base))
        for i, b in enumerate(base):
            if b is None:
                continue
            ab = (b,) if isinstance(b, str) else tuple(b)
            if any(a in ab for a in axes):
                continue
            tp_sz = _axes_size(self.mm.shape, ab)
            if shape[i] > 0 and shape[i] % (tp_sz * size) == 0:
                base[i] = ab + tuple(axes)
                return P(*base)
        return None

    # -- specs ---------------------------------------------------------------

    def param_spec(self, shape, tp_spec: Optional[P] = None, is_expert: bool = False,
                   path: str = "") -> P:
        """Compute-dtype params: sharded only at stage 3; params under the
        persistence threshold stay whole (reference:
        stage3_param_persistence_threshold, stage3.py)."""
        if self.stage < 3:
            return tp_spec if tp_spec is not None else P()
        if int(np.prod(shape) if shape else 1) < self.param_persistence_threshold:
            return tp_spec if tp_spec is not None else P()
        axes, size = self._zero_axes_for(is_expert)
        composed = self._compose_tp_dim(tuple(shape), tp_spec, axes, size, path)
        if composed is not None:
            return composed
        return insert_zero_axes(tuple(shape), tp_spec, axes, size,
                                avoid_last=True)

    def master_spec(self, shape, tp_spec: Optional[P] = None, is_expert: bool = False,
                    path: str = "") -> P:
        """fp32 master params + optimizer state: sharded from stage 1 up."""
        if self.stage < 1:
            return tp_spec if tp_spec is not None else P()
        axes, size = self._zero_axes_for(is_expert)
        composed = self._compose_tp_dim(tuple(shape), tp_spec, axes, size, path)
        if composed is not None:
            return composed
        return insert_zero_axes(tuple(shape), tp_spec, axes, size)

    # grads smaller than this stay whole: sharding a 64-element layernorm
    # grad saves nothing and couples an H-sharded reduction into the backward
    # activations (the reference's analogue is reduce-scatter bucket
    # granularity — tiny tensors ride whole in a bucket)
    GRAD_SHARD_MIN_ELEMS = 8192

    def grad_spec(self, shape, tp_spec: Optional[P] = None, is_expert: bool = False,
                  path: str = "") -> P:
        """Gradients: sharded from stage 2 up (constraint → XLA reduce-scatter)."""
        if self.stage < 2:
            return tp_spec if tp_spec is not None else P()
        if int(np.prod(shape) if shape else 1) < self.GRAD_SHARD_MIN_ELEMS:
            return tp_spec if tp_spec is not None else P()
        axes, size = self._zero_axes_for(is_expert)
        if "embedding" in path and tp_spec is not None and \
                _axes_size(self.mm.shape, tuple(
                    a for d in tp_spec if d is not None
                    for a in ((d,) if isinstance(d, str) else d))) > 1:
            # vocab-parallel embedding grads stay TP-only: widening the
            # vocab dim with ZeRO axes breaks the grad scatter's
            # partitioning, and a fresh H-dim sharding couples the backward
            # scan carry into an H layout (involuntary remat). The grad is
            # already 1/tp per rank; the master/optimizer shards keep the
            # full ZeRO saving.
            return tp_spec
        composed = self._compose_tp_dim(tuple(shape), tp_spec, axes, size, path)
        if composed is not None:
            return composed
        return insert_zero_axes(tuple(shape), tp_spec, axes, size)

    # -- comm-plan grad sync (docs/COMM.md) ----------------------------------

    def grad_sync_axes(self) -> Tuple[str, ...]:
        """Mesh axes the PLANNED (explicit) gradient sync reduces over —
        the batch axes, whose implicit XLA emission this policy's
        ``grad_spec`` constraints otherwise drive. The engine's
        stacked-grads step shard_maps over these and routes each leaf
        through ``comm.planned_grad_sync`` when the comm plan picks a
        quantized wire format for the stage-2 reduce-scatter."""
        return ("data", "expert")

    def grad_sync_viable(self) -> Tuple[bool, str]:
        """Sharding-side envelope for the explicit sync: the stacked
        per-rank layout needs whole-per-DP-rank compute params (stage
        <= 2; TP sharding composes — the model axis stays auto in the
        stacked region and each leaf syncs over its own stacked layout,
        round 14) and a single-member expert axis (expert params' grads
        must not be averaged over 'expert'). The engine adds its
        runtime-side checks (offload/1-bit/compression, the
        native-shard_map gate for the TP composition) on top."""
        if self.stage > 2:
            return False, ("ZeRO-3 shards compute params; the stacked "
                           "local-grad layout needs them whole per rank")
        if self.mm.shape["expert"] != 1:
            return False, ("mesh axis 'expert' has size "
                           f"{self.mm.shape['expert']}: expert-param "
                           "grads must not be mean-reduced over it")
        return True, ""

    def zero_gather_site(self, spec: P):
        """(dim, zero_axes) of the single ZeRO-sharded dim of a
        compute-param spec, or None — the per-leaf envelope of the
        EXPLICIT stage-3 param gather (comm-plan ``overlap`` family,
        docs/COMM.md): a leaf qualifies only when exactly one dim is
        sharded and only over ZeRO axes. TP-composed leaves stay on the
        implicit gather (the manual region would have to name an auto
        axis), replicated leaves (persistence threshold) have nothing
        to gather."""
        site = None
        for dim, entry in enumerate(spec):
            if entry is None:
                continue
            names = (entry,) if isinstance(entry, str) else tuple(entry)
            if any(a not in ZERO_AXES for a in names):
                return None           # TP-composed: implicit path
            if site is not None:
                return None           # sharded on two dims: implicit path
            site = (dim, names)
        return site

    # -- pytree-level helpers -------------------------------------------------

    def tree_shardings(self, tree, spec_fn, tp_specs=None, expert_fn=None):
        """NamedSharding pytree for ``tree``; ``tp_specs`` is a matching pytree of
        PartitionSpecs (or None), ``expert_fn(path)`` marks expert params."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
        tp_flat = None
        if tp_specs is not None:
            tp_flat = jax.tree_util.tree_flatten(
                tp_specs, is_leaf=lambda x: isinstance(x, P) or x is None)[0]
        out = []
        for i, (path, leaf) in enumerate(flat):
            tp = tp_flat[i] if tp_flat is not None else None
            is_expert = bool(expert_fn and expert_fn(path))
            shape = np.shape(leaf)
            pstr = "/".join(str(getattr(k, "key", k)) for k in path)
            spec = canonicalize_spec(spec_fn(shape, tp, is_expert, pstr),
                                     self.mm.shape)
            out.append(NamedSharding(self.mesh, spec))
        return jax.tree_util.tree_unflatten(treedef, out)

    def param_shardings(self, params, tp_specs=None, expert_fn=None):
        return self.tree_shardings(params, self.param_spec, tp_specs, expert_fn)

    def master_shardings(self, params, tp_specs=None, expert_fn=None):
        return self.tree_shardings(params, self.master_spec, tp_specs, expert_fn)

    def grad_shardings(self, params, tp_specs=None, expert_fn=None):
        return self.tree_shardings(params, self.grad_spec, tp_specs, expert_fn)
