"""ZeRO-Offload — optimizer states + master weights in host memory or NVMe.

Role of the reference's offload pillar: stage_1_and_2.py cpu_offload
(grads D2H, CPUAdam on pinned fp32 partitions, updated fp16 partitions H2D;
 stage_1_and_2.py:1074-1225) and the ZeRO-Infinity NVMe tier
(partitioned_optimizer_swapper). The TPU shape of the idea:

  device (HBM)                          host (RAM / NVMe)
  ------------------------------------  --------------------------------------
  bf16 compute params, activations      fp32 master params
  grads (one jitted fwd+bwd, psum'd)    Adam moments (RAM, or NVMe-swapped)
        |                                        |
        |  grads D2H (async, leaf-pipelined)     |
        +--------------------------------------->|
                                                 |  ops/cpu C++ SIMD Adam,
                                                 |  bf16 emitted in-pass
        |<---------------------------------------+
        |  params H2D (async)

HBM never holds optimizer state or fp32 masters: for Adam that removes
12 bytes/param of the 16 the reference attributes to optimizer+master state
(ZeRO-Offload paper's 4x model-scale-per-device claim), at the cost of a
2+4 bytes/param PCIe-equivalent transfer per step, hidden behind compute via
async D2H/H2D exactly like the reference's overlapping swap streams.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

from ...ops.cpu.adam import DeepSpeedCPUAdam, DeepSpeedCPUAdagrad
from ...ops.cpu.aio import AsyncIOHandle
from ...utils.logging import log_dist
from ..swap_tensor import OptimizerStateSwapper, pipeline_pools

# live NVMe roots in this process: a second engine pointed at the same
# nvme_path must not silently clobber the first one's swap files.  Claims are
# released when the owner is garbage-collected, so engine re-initialization
# loops (sweeps, notebooks) reuse slot 0 instead of growing -1, -2, ... dirs.
_CLAIMED_ROOTS: Dict[str, set] = {}


def _claim_root(root: str, owner: Any) -> str:
    import weakref
    key = os.path.realpath(root)
    used = _CLAIMED_ROOTS.setdefault(key, set())
    n = next(i for i in range(len(used) + 1) if i not in used)
    used.add(n)
    weakref.finalize(owner, used.discard, n)
    return root if n == 0 else f"{root}-{n}"

try:
    import ml_dtypes
    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # ml_dtypes ships with jax; belt and braces
    _BF16 = None

PyTree = Any


def _build_cpu_optimizer(opt_type: str, params: Dict) -> Any:
    key = opt_type.lower().replace("_", "")
    kwargs = dict(params or {})
    kwargs.pop("torch_adam", None)
    adamw = bool(kwargs.pop("adam_w_mode", key == "adamw"))
    if "betas" in kwargs:
        kwargs["betas"] = tuple(kwargs["betas"])
    if key in ("adam", "adamw", "fusedadam"):
        return DeepSpeedCPUAdam(adamw_mode=adamw or key == "adamw", **kwargs)
    if key == "adagrad":
        return DeepSpeedCPUAdagrad(**kwargs)
    raise ValueError(
        f"offload_optimizer supports Adam/AdamW/Adagrad; got '{opt_type}' "
        "(reference: cpu_offload asserts CPUAdam, stage_1_and_2.py:589)")


class HostOffloadOptimizer:
    """Owns the fp32 master copy + optimizer state off-device and applies the
    step there; the device round-trips only grads (D2H) and compute-dtype
    params (H2D)."""

    def __init__(self,
                 opt_type: str,
                 opt_params: Dict,
                 params_f32: PyTree,
                 param_shardings: PyTree,
                 compute_dtype,
                 device: str = "cpu",
                 nvme_path: Optional[str] = None,
                 buffer_count: int = 4,
                 aio_config: Optional[Dict] = None,
                 param_device: str = "ram",
                 param_nvme_path: Optional[str] = None,
                 param_buffer_count: int = 5):
        self.cpu_opt = _build_cpu_optimizer(opt_type, opt_params)
        self.compute_dtype = compute_dtype
        self.device = device
        leaves, self.treedef = jax.tree.flatten(params_f32)
        self.shard_leaves = self.treedef.flatten_up_to(param_shardings)
        self.shapes = [tuple(np.shape(p)) for p in leaves]
        self.n_leaves = len(leaves)

        aio_config = aio_config or {}

        def _make_aio():
            return AsyncIOHandle(
                block_size=aio_config.get("block_size", 1 << 20),
                queue_depth=aio_config.get("queue_depth", 8),
                thread_count=aio_config.get("thread_count", 4))

        # fp32 master copy: host RAM (reference:
        # single_partition_of_fp32_groups pinned host tensors,
        # stage_1_and_2.py:507), or the ZeRO-Infinity NVMe param tier
        # (partitioned_param_swapper.py:35) — masters live one-file-per-leaf
        # and stream through the step's double-buffered pipeline, so
        # steady-state host RAM is O(buffers), not O(model).
        from ..swap_tensor import SwappedTensorPool
        self.param_pool: Optional[SwappedTensorPool] = None
        self.master: Optional[List[np.ndarray]] = None
        if param_device == "nvme":
            root = param_nvme_path or nvme_path
            if not root:
                raise ValueError("offload_param device=nvme needs nvme_path")
            self.param_pool = SwappedTensorPool(
                _claim_root(os.path.join(root, "zero_offload_params"), self),
                [f"leaf{j}" for j in range(self.n_leaves)],
                self.shapes, np.float32, aio=_make_aio(),
                buffer_count=max(param_buffer_count, 3),
                initialize_zero=False)
            # chunked seeding: the aio handle holds a ref to each staged
            # copy until wait(), so an unbounded burst would pin ~model-size
            # host RAM — the thing this tier exists to avoid
            for j, p in enumerate(leaves):
                self.param_pool.write_async(
                    j, np.ascontiguousarray(np.asarray(p, np.float32)))
                if (j + 1) % 8 == 0:
                    self.param_pool.wait()
            self.param_pool.wait()
            # no RAM mirror of any kind in the NVMe-param tier
            self._bf16_staging = [None] * self.n_leaves
            log_dist(f"ZeRO-Infinity: fp32 master params on NVMe at {root} "
                     f"({self.n_leaves} partitions)", ranks=[0])
        else:
            self.master = [np.ascontiguousarray(np.asarray(p, np.float32))
                           for p in leaves]
            # staging holds a bf16 mirror of master at all times (the step
            # kernel overwrites it in-pass), so current_params_device is
            # valid pre-step
            self._bf16_staging = [
                m.astype(_BF16) if _BF16 is not None else None
                for m in self.master]

        self.swapper: Optional[OptimizerStateSwapper] = None
        self.state: Optional[List[Dict[str, np.ndarray]]] = None
        slot_names = list(self.cpu_opt.init_state(np.zeros(1, np.float32)))
        self.slot_names = slot_names
        if device == "nvme":
            if not nvme_path:
                raise ValueError("offload_optimizer device=nvme needs nvme_path")
            self.swapper = OptimizerStateSwapper(
                _claim_root(os.path.join(nvme_path, "zero_offload_opt"), self),
                slot_names,
                self.shapes, aio=_make_aio(), buffer_count=buffer_count)
            log_dist(f"ZeRO-Offload: optimizer state on NVMe at {nvme_path} "
                     f"({self.n_leaves} partitions x {slot_names})", ranks=[0])
        else:
            self.state = [self.cpu_opt.init_state(
                np.zeros(int(np.prod(s)), np.float32).reshape(s))
                for s in self.shapes]
            log_dist(f"ZeRO-Offload: optimizer state in host RAM "
                     f"({self.n_leaves} partitions x {slot_names})", ranks=[0])

    # -- helpers ---------------------------------------------------------------

    def _put_param(self, j: int) -> jax.Array:
        """RAM master -> device, in compute dtype, on the param sharding.
        (NVMe-master materialization goes through the pipelined
        current_params_device/apply paths, never through here.)"""
        assert self.param_pool is None
        sharding = self.shard_leaves[j]
        if self.compute_dtype == jax.numpy.bfloat16 and self._bf16_staging[j] is not None:
            return jax.device_put(self._bf16_staging[j], sharding)
        dt = np.dtype(self.compute_dtype)
        host = self.master[j] if dt == np.float32 else self.master[j].astype(dt)
        return jax.device_put(host, sharding)

    def _put_from_host(self, j: int, host: np.ndarray) -> jax.Array:
        """device_put a master leaf from a (possibly reused) host buffer:
        always hand device_put an owning copy — on CPU backends device_put
        can alias numpy memory, and the pool buffer is about to be reused."""
        arr = np.asarray(host).reshape(self.shapes[j])
        if self.compute_dtype == jax.numpy.bfloat16 and _BF16 is not None:
            arr = arr.astype(_BF16)          # astype copies
        elif np.dtype(self.compute_dtype) != arr.dtype:
            arr = arr.astype(np.dtype(self.compute_dtype))
        else:
            arr = arr.copy()
        return jax.device_put(arr, self.shard_leaves[j])

    def _bf16_out(self, j: int) -> Optional[np.ndarray]:
        if self.compute_dtype == jax.numpy.bfloat16:
            return self._bf16_staging[j]
        return None

    def _master_host(self, j: int) -> np.ndarray:
        """The fp32 master leaf as a host array (owning copy for pool reads)."""
        if self.param_pool is not None:
            return self.param_pool.read_sync(j).reshape(self.shapes[j])
        return self.master[j]

    # -- the step ----------------------------------------------------------------

    def apply(self, grads: PyTree, step_1based: int, lr: float,
              grad_scale: float = 1.0, materialize: bool = True) -> PyTree:
        """Host optimizer step. ``grads`` is the device grad pytree (summed
        over microbatches, NOT yet unscaled); ``grad_scale`` is the total
        divisor (n_micro * loss_scale / clip_coef) folded into the kernel.
        Returns the new compute-dtype device param pytree —
        ``materialize=False`` (offload_param transient mode) skips the H2D
        entirely and returns None; the caller re-materializes at the next
        step via current_params_device."""
        grad_leaves = self.treedef.flatten_up_to(grads)
        # start all D2H copies before touching any (overlaps transfers with
        # the per-leaf CPU compute below — the role of the reference's
        # separate D2H stream, stage_1_and_2.py async_accumulate)
        for g in grad_leaves:
            if hasattr(g, "copy_to_host_async"):
                g.copy_to_host_async()

        new_leaves: List[Optional[jax.Array]] = [None] * self.n_leaves

        if self.swapper is None and self.param_pool is None:
            for j in range(self.n_leaves):
                g = np.asarray(grad_leaves[j])
                self.cpu_opt.step(step_1based, self.master[j], g,
                                  self.state[j], lr=lr, grad_scale=grad_scale,
                                  bf16_out=self._bf16_out(j))
                # async H2D: returns immediately, transfer overlaps next leaf
                if materialize:
                    new_leaves[j] = self._put_param(j)
            if not materialize:
                return None
            return self.treedef.unflatten(new_leaves)

        # NVMe pipeline: per leaf j, read master and/or state (read of j+1
        # prefetched before compute of j), step in-place in the buffers,
        # write back behind compute (reference:
        # pipelined_optimizer_swapper.py:279 read-ahead/write-behind).
        pools = {}
        if self.swapper is not None:
            pools.update(self.swapper.pools)
        if self.param_pool is not None:
            assert "master" not in pools
            pools["master"] = self.param_pool

        def compute(j, views):
            master = (views["master"] if self.param_pool is not None
                      else self.master[j])
            state = ({s: views[s].reshape(-1) for s in self.slot_names}
                     if self.swapper is not None else self.state[j])
            g = np.asarray(grad_leaves[j])
            self.cpu_opt.step(step_1based, master, g, state,
                              lr=lr, grad_scale=grad_scale,
                              bf16_out=self._bf16_out(j))
            if materialize:
                # _put_from_host copies out of the pool buffer, so the
                # in-flight write-back and later buffer reuse are safe
                new_leaves[j] = (self._put_from_host(j, master)
                                 if self.param_pool is not None
                                 else self._put_param(j))

        pipeline_pools(pools, self.n_leaves, compute)

        if not materialize:
            return None
        return self.treedef.unflatten(new_leaves)

    # -- checkpoint plumbing ------------------------------------------------------

    def state_dict(self, lazy: bool = False) -> Dict[str, Any]:
        """``lazy=True`` returns per-leaf THUNKS instead of arrays, so the
        streaming checkpoint writer holds one leaf at a time — the NVMe
        tier's O(buffers) host-RAM premise holds through saves too."""
        # thunks return OWNING COPIES: an async checkpoint writer serializes
        # them while the next step's host optimizer mutates the originals
        # in place (pool reads already copy via read_sync)
        def master_leaf(j):
            if self.param_pool is not None:
                return lambda: self._master_host(j)   # pool read copies
            return lambda: np.array(self.master[j])

        def state_leaf(s, j):
            if self.swapper is not None:
                # read only this slot's pool (read_leaf would read ALL slots
                # from NVMe per thunk — len(slots)x amplification)
                return lambda: self.swapper.pools[s].read_sync(j).reshape(
                    self.shapes[j])
            return lambda: np.array(self.state[j][s]).reshape(self.shapes[j])

        master = [master_leaf(j) for j in range(self.n_leaves)]
        slots = {s: [state_leaf(s, j) for j in range(self.n_leaves)]
                 for s in self.slot_names}
        if not lazy:
            master = [m() for m in master]
            slots = {s: [t() for t in ts] for s, ts in slots.items()}
        return {"master": self.treedef.unflatten(master),
                "state": {s: self.treedef.unflatten(ts)
                          for s, ts in slots.items()}}

    def _write_master(self, master: List[np.ndarray]) -> None:
        """Install a new fp32 master list (NVMe pool writes with bounded
        in-flight staging, or RAM mirror + bf16 staging rebuild)."""
        if self.param_pool is not None:
            for j, m in enumerate(master):
                self.param_pool.write_async(j, m)
                if (j + 1) % 8 == 0:
                    self.param_pool.wait()
            self.param_pool.wait()
        else:
            self.master = master
            self._bf16_staging = [
                m.astype(_BF16) if _BF16 is not None else None
                for m in self.master]

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self._write_master([np.ascontiguousarray(np.asarray(m, np.float32))
                            for m in self.treedef.flatten_up_to(sd["master"])])
        per_slot = {s: self.treedef.flatten_up_to(sd["state"][s])
                    for s in self.slot_names}
        state = [{s: np.asarray(per_slot[s][j], np.float32)
                  for s in self.slot_names} for j in range(self.n_leaves)]
        if self.swapper is not None:
            for j, st in enumerate(state):
                for s in self.slot_names:
                    self.swapper.pools[s].write_async(j, st[s])
            for s in self.slot_names:
                self.swapper.pools[s].wait()
        else:
            self.state = state

    def update_master_leaves(self, updates: Dict[int, np.ndarray]) -> None:
        """Overwrite SELECTED fp32 master leaves (by flatten index) — the
        weights-only load path (engine.load_module_state_dict). Leaves not
        in ``updates`` are never read or rewritten (no NVMe round trip for
        a partial load); optimizer state slots are untouched."""
        for j, m in sorted(updates.items()):
            m = np.ascontiguousarray(np.asarray(jax.device_get(m),
                                                np.float32))
            if self.param_pool is not None:
                self.param_pool.write_async(j, m)
            else:
                self.master[j] = m.reshape(self.shapes[j])
                if _BF16 is not None:
                    self._bf16_staging[j] = self.master[j].astype(_BF16)
        if self.param_pool is not None:
            self.param_pool.wait()

    def current_params_device(self) -> PyTree:
        if self.param_pool is not None:
            # transient re-materialization runs every step: pipeline the
            # NVMe reads (prefetch j+1 while device_put'ing j)
            leaves: List[Optional[jax.Array]] = [None] * self.n_leaves

            def compute(j, views):
                leaves[j] = self._put_from_host(j, views["master"])

            pipeline_pools({"master": self.param_pool}, self.n_leaves,
                           compute, write_back=False)
            return self.treedef.unflatten(leaves)
        return self.treedef.unflatten(
            [self._put_param(j) for j in range(self.n_leaves)])

    def host_params(self, lazy: bool = False) -> PyTree:
        """Compute-dtype params as HOST arrays (checkpoint/export paths in
        transient mode — no device round trip; the bf16 mirror is already
        maintained by the step kernel).  ``lazy=True``: per-leaf thunks."""
        def leaf(j):
            def get():
                # owning copies throughout: async writers must not see the
                # step kernel's in-place updates (astype copies; the two
                # passthrough cases copy explicitly)
                if self.param_pool is not None:
                    m = self._master_host(j)
                    return (m.astype(_BF16)
                            if (self.compute_dtype == jax.numpy.bfloat16
                                and _BF16 is not None)
                            else m.astype(np.dtype(self.compute_dtype)))
                if (self.compute_dtype == jax.numpy.bfloat16
                        and self._bf16_staging[j] is not None):
                    return np.array(self._bf16_staging[j])
                dt = np.dtype(self.compute_dtype)
                return (np.array(self.master[j]) if dt == np.float32
                        else self.master[j].astype(dt))
            return get

        thunks = [leaf(j) for j in range(self.n_leaves)]
        if not lazy:
            return self.treedef.unflatten([t() for t in thunks])
        return self.treedef.unflatten(thunks)
