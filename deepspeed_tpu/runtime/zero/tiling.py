"""TiledLinear — split a large linear into independently-sharded tiles.

Capability parity with the reference's ``deepspeed/runtime/zero/tiling.py``
(TiledLinear: splits in/out features so ZeRO-3 gathers one tile at a time,
capping the transient full-weight footprint of huge projections). On TPU
each tile is a separate flax param leaf: ZeRO-3's per-leaf NamedSharding
(and XLA's per-leaf all-gather scheduling) bounds live memory to one tile's
gather instead of the whole [in, out] matrix — the same peak-memory contract
without the reference's module surgery and bias-splitting bookkeeping.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class TiledLinear(nn.Module):
    """Drop-in Dense whose kernel is [in_splits x out_splits] tile params.

    y = concat_j( sum_i x_i @ K_{ij} ) + b — numerically identical to Dense
    with the assembled kernel (tests assert this).
    """
    features: int
    in_splits: int = 1
    out_splits: int = 1
    use_bias: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        in_features = x.shape[-1]
        if in_features % self.in_splits or self.features % self.out_splits:
            raise ValueError(
                f"TiledLinear: {in_features}x{self.features} not divisible "
                f"into {self.in_splits}x{self.out_splits} tiles")
        din = in_features // self.in_splits
        dout = self.features // self.out_splits
        init = nn.initializers.lecun_normal()
        outs = []
        for j in range(self.out_splits):
            acc = None
            for i in range(self.in_splits):
                k = self.param(f"kernel_{i}_{j}", init, (din, dout),
                               jnp.float32)
                xi = x[..., i * din:(i + 1) * din]
                part = xi @ k.astype(self.dtype)
                acc = part if acc is None else acc + part
            outs.append(acc)
        y = jnp.concatenate(outs, axis=-1)
        if self.use_bias:
            b = self.param("bias", nn.initializers.zeros, (self.features,),
                           jnp.float32)
            y = y + b.astype(self.dtype)
        return y
