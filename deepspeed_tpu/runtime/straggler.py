"""Straggler defense — relative-slowness detection over the heartbeat channel.

The resilience stack defends against *dead* (rc 114/117: phase watchdogs,
heartbeat silence) and *wrong* (rc 118: the integrity sentinel and SDC
audit). A slow-but-alive host — thermal throttling, a degraded NIC, a
noisy neighbor — passes every one of those checks while the synchronous
step drags the whole world down to its pace: at MPMD scale one slow
stage stalls every downstream clock tick, and in a serving fleet one
throttled replica holds the shared queue's p99 hostage. This module is
the third leg of the threat model: *slow*.

Evidence rides the EXISTING heartbeat channel (the ROADMAP guardrail —
no new liveness plumbing): every worker stamps a rolling per-step
wall-time gauge (``step_ms``, a :class:`StepClock` median over the last
few steps) into its heartbeat records — the engine step loop, MPMD stage
workers (STAGE-tagged) and fleet replica workers (SERVE) all stamp it,
and ``dstpu health`` renders it as the RATE column.

Detection is *relative*: a :class:`StragglerDetector` consumes a channel
snapshot per observation window and compares each rank's gauge against
the WORLD's — the sentinel's :class:`~.sentinel.RollingRobust`
median/MAD machinery applied cross-rank instead of cross-step, with the
judged rank LEFT OUT of its own baseline (self-inclusion makes a 2-rank
world undetectable past ``rel_threshold >= 2`` and drags every median
toward the straggler). A rank is *slow* in a window when its step time
sits ``zmax`` robust sigmas above the other ranks' median AND above
``rel_threshold`` x that median (the relative floor is what makes a
uniformly-slow world — everyone throttled by the same rack — produce
ZERO verdicts: the baseline scales with the world). Worlds too small
for a meaningful MAD (< 4 other gauges) fall back to the relative floor
alone. Records in COMPILE/RESTORE/SAVE phases, terminal records, and
records predating the gauge are never compared — a compile is not a
straggle.

Verdicts are warmup-gated (the first ``warmup`` complete windows only
feed the baseline), require ``strike_window`` CONSECUTIVE slow windows,
and are cooldown-debounced (one verdict per ``cooldown`` windows per
rank). The escalation ladder mirrors the sentinel's:

1. **flag** — the slow rank stamps a sticky ``STRAGGLER`` heartbeat flag
   on itself (every rank runs the same detector over the same shared
   snapshot, so self-verdicts need no coordination — the SDC pattern).
   Visible in ``dstpu health``; evidence-only by default.
2. **blacklist evidence** — RunSupervisor / BackendSupervisor /
   DSElasticAgent consume the flag exactly like the SDC flag (it names a
   HOST; the rc names nobody), so a struck host is quarantined by
   ``--blacklist-after`` and the next world re-forms without it (parole
   under ``min_nodes`` unchanged).
3. **abort** — with ``straggler.abort_after > 0``, a rank still slow
   ``abort_after`` windows past its verdict stamps a STALLED terminal
   record and raises :class:`StragglerAbort` (rc 117, the existing
   stall path): the supervisor tears the world down, the elastic agent
   counts the stall and relaunches without the slow host. 0 (the
   default) never tears anything down — detection is evidence-only.

Fleet-side the ladder is a DRAIN instead of a teardown: FleetSupervisor
runs the same detector over the replicas' SERVE gauges and hands a slow
replica to the existing replica-death path — admission stops, its lanes
requeue through the exactly-once token-exact path, the replica restarts
warmed and the strike counts toward ``blacklist_after``.

Chaos: ``run.slow`` (train-batch boundary) and the keyed
``serve.replica_slow`` (fleet worker loop) inject *degraded, not dead*
hosts via the ``sleep`` mode's ``every=``/``p=`` jitter semantics
(docs/RESILIENCE.md catalog).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, Optional

from .heartbeat import PHASE_SERVE, PHASE_STEP, TERMINAL_PHASES
from .sentinel import SDC_FLAG, RollingRobust, _median
from .watchdog import STALL_EXIT_CODE

#: sticky heartbeat flag naming a slow HOST — consumed as blacklist
#: evidence by both supervisors and the elastic agent, exactly like the
#: SDC flag (and unlike the generic INTEGRITY mark, which names nobody)
STRAGGLER_FLAG = "STRAGGLER"

#: the heartbeat flags that NAME A HOST — each is stamped by exactly the
#: implicated rank, so the record is per-host blacklist evidence. The
#: one vocabulary all three consumers (RunSupervisor, BackendSupervisor,
#: DSElasticAgent) sweep; the generic INTEGRITY mark is deliberately
#: absent (launch.py stamps it on EVERY rank of an rc-118 abort for
#: health visibility — it names nobody)
HOST_NAMING_FLAGS = (SDC_FLAG, STRAGGLER_FLAG)

#: the heartbeat gauge key workers stamp and the detector reads
STEP_MS_GAUGE = "step_ms"

#: verdict vocabulary returned by :meth:`StragglerDetector.observe`
SLOW = "SLOW"
ABORT = "ABORT"


class StragglerAbort(RuntimeError):
    """Rung 3: this rank has been persistently slow past
    ``straggler.abort_after`` windows and is tearing the world down so
    the elastic agent can relaunch without it. Carries the STALL exit
    code (117) — launch.py maps any exception with ``exit_code`` onto
    ``sys.exit``, and the supervisors/agent already treat 117 as a
    counted, blacklist-attributable failure."""

    exit_code = STALL_EXIT_CODE


class StepClock:
    """Worker-side rolling step-wall-time gauge.

    ``mark()`` at each step boundary records the gap since the previous
    boundary and returns the rolling MEDIAN of the last ``window`` gaps
    in milliseconds (robust: one checkpoint save or GC pause cannot spike
    the gauge). ``reset()`` drops the pending boundary so a gap spanning
    a non-step phase (RESTORE/SAVE/COMPILE, a pipeline park) is never
    charged as a step. ``push_ms()`` feeds an explicitly-measured
    duration instead (the fleet worker times its own iteration)."""

    def __init__(self, window: int = 8, clock=None):
        self.buf: deque = deque(maxlen=max(2, int(window)))
        self._clock = clock or time.monotonic
        self._last: Optional[float] = None

    def mark(self) -> Optional[float]:
        now = self._clock()
        if self._last is not None:
            self.buf.append((now - self._last) * 1000.0)
        self._last = now
        return self.gauge()

    def push_ms(self, ms: float) -> Optional[float]:
        self.buf.append(float(ms))
        return self.gauge()

    def reset(self) -> None:
        self._last = None

    def gauge(self) -> Optional[float]:
        """Rolling median step time in ms, or None before the first
        completed gap (records predating the gauge render as ``-`` in
        ``dstpu health``)."""
        if not self.buf:
            return None
        return round(_median(self.buf), 2)


def record_step_ms(rec: dict) -> Optional[float]:
    """A record's comparable step gauge, or None when the record must
    not participate in a window: terminal phases are conclusions, and
    non-STEP/SERVE phases (COMPILE, RESTORE, SAVE, INIT) measure
    something other than steady-state step cadence — a rank mid-compile
    or mid-restore must never read as a straggler."""
    phase = rec.get("phase")
    if phase in TERMINAL_PHASES or phase not in (PHASE_STEP, PHASE_SERVE):
        return None
    val = (rec.get("gauges") or {}).get(STEP_MS_GAUGE)
    try:
        return float(val) if val is not None else None
    except (TypeError, ValueError):
        return None


class StragglerDetector:
    """Cross-rank relative-slowness detector (module docstring has the
    criteria and the ladder). One instance per consumer:

    - each ENGINE holds one and acts only on verdicts against its own
      rank (flag -> abort);
    - the FleetSupervisor holds one and drains any verdicted replica;
    - tests drive :meth:`observe` directly with synthetic snapshots.

    ``observe(records)`` consumes one window — the latest heartbeat
    snapshot — and returns ``{rank: SLOW | ABORT}`` for the verdicts
    ISSUED this window (an empty dict is the healthy steady state).
    ``slow_now`` holds the ranks the current window measured slow
    (pre-strike/cooldown gating), for introspection."""

    def __init__(self, cfg):
        self.cfg = cfg
        self.zmax = float(cfg.zmax)
        self.rel_threshold = float(cfg.rel_threshold)
        self.warmup = int(cfg.warmup)
        self.strike_window = max(1, int(cfg.strike_window))
        self.cooldown = int(cfg.cooldown)
        self.abort_after = int(cfg.abort_after)
        self.windows = 0                      # complete windows consumed
        self.strikes: Dict[int, int] = {}     # consecutive slow windows
        self.verdicts: Dict[int, int] = {}    # rank -> window of last verdict
        self.persist: Dict[int, int] = {}     # slow windows since verdict
        self.slow_now: set = set()

    def _slow(self, value: float, others: list) -> bool:
        """Is ``value`` slow relative to the OTHER ranks' gauges?

        Leave-one-out: the judged rank's own gauge must not sit in the
        baseline — with it included, a 2-rank world can NEVER cross a
        ``rel_threshold >= 2`` (x > t*(x+f)/2 has no solution), and even
        in larger worlds the straggler drags the median toward itself.
        The others' median IS the world's pace without the suspect."""
        med = _median(others)
        if med <= 0.0:
            return False
        if value <= self.rel_threshold * med:
            # the relative floor: a uniformly-slow world raises the
            # others' median with it, so nobody crosses — the
            # false-positive guard the acceptance tests pin
            return False
        if len(others) < 4:
            return True                       # small world: ratio only
        rr = RollingRobust(window=len(others))
        for v in others:
            rr.push(v)
        o_med, sigma = rr.stats()             # never None at >= 4
        return (value - o_med) / sigma > self.zmax

    def observe(self, records: Dict[int, dict]) -> Dict[int, str]:
        gauges: Dict[int, float] = {}
        for rank, rec in records.items():
            ms = record_step_ms(rec)
            if ms is not None:
                gauges[int(rank)] = ms
        if len(gauges) < 2:
            # one gauge is not a distribution: never a verdict (and not a
            # window — warmup must count only comparable windows)
            self.slow_now = set()
            return {}
        self.windows += 1
        self.slow_now = {
            rank for rank, v in gauges.items()
            if self._slow(v, [g for r2, g in gauges.items() if r2 != rank])}
        out: Dict[int, str] = {}
        for rank in gauges:
            if rank in self.slow_now:
                self.strikes[rank] = self.strikes.get(rank, 0) + 1
            else:
                # a clean window retires the whole arm for this rank:
                # strikes, the post-verdict persistence count, and (once
                # the cooldown lapses) eligibility for a fresh verdict
                self.strikes[rank] = 0
                self.persist.pop(rank, None)
                continue
            if self.windows <= self.warmup:
                continue                      # warmup feeds the baseline
            if rank in self.persist:
                # already verdicted: count persistence toward the abort
                self.persist[rank] += 1
                if 0 < self.abort_after <= self.persist[rank]:
                    out[rank] = ABORT
                continue
            if self.strikes[rank] < self.strike_window:
                continue
            last = self.verdicts.get(rank)
            if last is not None and self.windows - last <= self.cooldown:
                continue                      # debounced: one event, one strike
            self.verdicts[rank] = self.windows
            self.persist[rank] = 0
            out[rank] = SLOW
        return out

    def forget(self, rank: int) -> None:
        """Drop all per-rank state — the fleet calls this after draining
        a replica so its warmed replacement starts from a clean slate
        (the cooldown stamp stays, debouncing an immediate re-verdict)."""
        self.strikes.pop(rank, None)
        self.persist.pop(rank, None)
