"""Progressive Layer Drop — compressed training via stochastic depth.

Capability parity with the reference's ``runtime/progressive_layer_drop.py:5``
(ProgressiveLayerDrop: theta(t) = (1-theta)*exp(-gamma*t) + theta schedule,
handed to the model as pld_theta; the model keeps layer l with probability
1 - (l/L)(1-theta), arXiv:2010.13369). The schedule object is identical
math; the model side lives in models/transformer.py (cfg.pld + the
"pld_theta" batch key, so theta changes per step without recompiling).
"""

from __future__ import annotations

import math
from typing import Dict

from ..utils.logging import log_dist


class ProgressiveLayerDrop:
    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = float(theta)
        self.gamma = float(gamma)
        self.current_theta = 1.0
        log_dist(f"Enabled progressive layer dropping (theta = {self.theta})",
                 ranks=[0])

    def get_theta(self) -> float:
        return self.current_theta

    def update_state(self, global_step: int) -> float:
        self.current_theta = ((1.0 - self.theta)
                              * math.exp(-self.gamma * global_step)
                              + self.theta)
        return self.current_theta

    def get_state(self) -> Dict:
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}
