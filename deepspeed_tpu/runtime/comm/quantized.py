"""Blockwise-scaled int8 collectives — quantized reduce-scatter and
all-to-all (ZeRO++ qgZ / EQuARX style, the wire-hot counterparts of
``compressed.py``'s allreduce family).

Two sites run exact-only before the comm-plan subsystem and dominate
cross-node bytes at scale:

* the ZeRO-2 gradient sync — logically a reduce-scatter of every grad
  leaf over the DP axes (the constraint-driven XLA emission moves
  f32/bf16);
* the MoE expert dispatch/combine — an all-to-all of the token queues
  over the expert axis at ep > 1.

Both get an int8 wire format here: values are quantized in fixed-size
BLOCKS with one f32 scale per block (qwZ-style per-shard scales,
generalized to per-block so one outlier poisons 256 elements, not a
whole shard), the int8 payload plus the small scale tensor ride the
collective, and receivers dequantize — ~4x fewer bytes than f32, ~2x
fewer than bf16 (see docs/COMM.md for the exact accounting and the
error model). Unlike ``compressed_allreduce`` these are STATELESS (no
error feedback): the grad sync is used under the comm-plan accuracy
guard, and the dispatch quantization error is bounded per block.

Every region is built through :func:`...utils.jax_compat.shard_map`, so
the same call sites run on jaxlibs with or without native
``jax.shard_map`` (the shapes used here are verified to compile on the
0.4.x line, unlike the qwZ+TP composition jax_compat warns about).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...quant_format import QUANT_BLOCK, block_dequant, block_quant
from ...utils.jax_compat import shard_map

#: re-export: the wire format's block granularity is THE shared format's
#: (deepspeed_tpu/quant_format.py — single-sourced round 17; the
#: blockwise quant/dequant imported above live there too)
DEFAULT_BLOCK = QUANT_BLOCK

__all__ = ["DEFAULT_BLOCK", "block_quant", "block_dequant",
           "rs_quantized_local", "rs_exact_local", "ag_quantized_local",
           "a2a_quantized_local", "quantized_reduce_scatter", "grad_sync",
           "quantized_all_to_all", "make_queue_exchange"]


def _axes_tuple(axis) -> Tuple[str, ...]:
    return (axis,) if isinstance(axis, str) else tuple(axis)


def _axes_size(mesh, axis) -> int:
    n = 1
    for a in _axes_tuple(axis):
        n *= mesh.shape[a]
    return n


# ---------------------------------------------------------------------------
# shard-local building blocks (call INSIDE a shard_map region)
# ---------------------------------------------------------------------------

def rs_quantized_local(x_flat: jnp.ndarray, axis, n: int, *,
                       bits: int = 8, block: int = DEFAULT_BLOCK,
                       mean: bool = False) -> Tuple[jnp.ndarray, int]:
    """One reduce-scatter hop: this rank's full flat buffer in, this
    rank's REDUCED chunk out. Wire: int8 all-to-all of the payload + f32
    all-to-all of the per-block scales (~1/block overhead).

    Returns (served [c] f32, pad) with c = padded chunk length."""
    c = -(-x_flat.size // n)
    c = -(-c // block) * block
    pad = n * c - x_flat.size
    chunks = jnp.pad(x_flat.astype(jnp.float32), (0, pad)).reshape(n, c)
    q, scales, _ = block_quant(chunks, bits, block)
    q_recv = jax.lax.all_to_all(q, axis, split_axis=0, concat_axis=0,
                                tiled=True)
    s_recv = jax.lax.all_to_all(scales, axis, split_axis=0, concat_axis=0,
                                tiled=True)
    deq = block_dequant(q_recv, s_recv, 0)                 # [n, c]
    served = jnp.mean(deq, axis=0) if mean else jnp.sum(deq, axis=0)
    return served, pad


def rs_exact_local(x_flat: jnp.ndarray, axis, n: int, *,
                   mean: bool = False) -> Tuple[jnp.ndarray, int]:
    """:func:`rs_quantized_local`'s contract with an EXACT f32 wire —
    one reduce-scatter hop via the same dim-0 all-to-all + local
    reduce. Shared by ``grad_sync(algo="exact")`` and every per-segment
    hop of the overlap executors (``overlap.py``), so the pad/reduce
    semantics live in exactly one place."""
    c = -(-x_flat.size // n)
    pad = n * c - x_flat.size
    chunks = jnp.pad(x_flat.astype(jnp.float32), (0, pad)).reshape(n, c)
    recv = jax.lax.all_to_all(chunks, axis, split_axis=0, concat_axis=0,
                              tiled=True)
    served = jnp.mean(recv, axis=0) if mean else jnp.sum(recv, axis=0)
    return served, pad


def ag_quantized_local(x_flat: jnp.ndarray, axis, *, bits: int = 8,
                       block: int = DEFAULT_BLOCK) -> jnp.ndarray:
    """Quantized all-gather hop: each rank contributes its flat chunk,
    every rank receives the int8-roundtripped concatenation [n * len]."""
    q, scales, pad = block_quant(x_flat, bits, block)
    out_q = jax.lax.all_gather(q, axis)                    # [n, cp]
    out_s = jax.lax.all_gather(scales, axis)               # [n, cp/block]
    deq = block_dequant(out_q, out_s, pad)                 # [n, len]
    return deq.reshape(-1)


def a2a_quantized_local(x: jnp.ndarray, axis, *, bits: int = 8,
                        block: int = DEFAULT_BLOCK) -> jnp.ndarray:
    """Drop-in for ``lax.all_to_all(..., split_axis=0, concat_axis=0,
    tiled=True)`` with an int8 wire format: dim-0 chunks are
    blockwise-quantized on their flattened payload (blocks never
    straddle a chunk boundary — rows move intact with their own scale
    rows), payload + scales ride two all-to-alls, receivers dequantize
    back to ``x.dtype``. Asymmetric split/concat layouts are built from
    this involution + local reshapes (see :func:`make_queue_exchange`)."""
    lead, rest = x.shape[0], x.shape[1:]
    flat = x.reshape(lead, -1)
    q, scales, pad = block_quant(flat, bits, block)
    q_recv = jax.lax.all_to_all(q, axis, split_axis=0, concat_axis=0,
                                tiled=True)
    s_recv = jax.lax.all_to_all(scales, axis, split_axis=0, concat_axis=0,
                                tiled=True)
    deq = block_dequant(q_recv, s_recv, pad).astype(x.dtype)
    return deq.reshape((lead,) + rest)


# ---------------------------------------------------------------------------
# public collectives (build their own shard_map; stacked per-rank layout)
# ---------------------------------------------------------------------------

def quantized_reduce_scatter(x: jnp.ndarray, *, mesh, axis="data",
                             bits: int = 8, block: int = DEFAULT_BLOCK,
                             mean: bool = False) -> jnp.ndarray:
    """Blockwise-scaled int8 reduce-scatter.

    x: stacked per-rank values [n, ...] with dim 0 sharded over ``axis``
    (rank r contributes x[r] — the layout of ``compressed_allreduce``).
    Returns the reduced flat chunks [n, c] with dim 0 sharded over
    ``axis``: row r is materialized only on rank r and holds its reduced
    (sum or mean) chunk of the flattened input. Wire bytes per rank:
    ~(n-1)/n * numel int8 + scales, vs 4x that for an f32 exchange."""
    n = _axes_size(mesh, axis)
    axes = _axes_tuple(axis)

    def inner(xs):
        served, _ = rs_quantized_local(xs[0].reshape(-1), axes, n,
                                       bits=bits, block=block, mean=mean)
        return served[None]

    mapped = shard_map(inner, mesh=mesh, in_specs=P(axes),
                       out_specs=P(axes), axis_names=set(axes),
                       check_vma=False)
    # graftlint: disable=TPU002 (called under the caller's outer jit: one construction per outer trace)
    return jax.jit(mapped)(x)


def grad_sync(x: jnp.ndarray, *, mesh, axis="data", algo: str = "int8",
              bits: int = 8, block: int = DEFAULT_BLOCK,
              mean: bool = True) -> jnp.ndarray:
    """ZeRO-2 gradient sync: reduce-scatter + all-gather with the chosen
    wire format — the plan-routed replacement for the implicit XLA grad
    reduction.

    x: stacked per-rank grads [n, ...] dim 0 sharded over ``axis``.
    Returns the reduced (mean by default) value in the ORIGINAL leaf
    shape, replicated — callers re-apply their ZeRO grad sharding
    constraint, which lowers to a local slice.

    ``algo``:
      * ``"int8"`` — qgZ's two quantized hops: blockwise-int8 a2a
        (reduce-scatter), dequant+reduce, re-quantize the served chunk,
        int8 all-gather. ~25% of the f32 wire bytes.
      * ``"exact"`` — the same two hops at f32. Exists so wire-byte
        audits and benchmarks compare identical op structures; the
        engine's exact path stays the implicit XLA emission.
    """
    if algo not in ("exact", "int8"):
        raise ValueError(f"grad_sync algo {algo!r}: expected exact|int8")
    n = _axes_size(mesh, axis)
    axes = _axes_tuple(axis)

    def inner(xs):
        x0 = xs[0]
        flat = x0.reshape(-1).astype(jnp.float32)
        if algo == "int8":
            served, pad = rs_quantized_local(flat, axes, n, bits=bits,
                                             block=block, mean=mean)
            full = ag_quantized_local(served, axes, bits=bits, block=block)
        else:
            served, pad = rs_exact_local(flat, axes, n, mean=mean)
            full = jax.lax.all_gather(served, axes).reshape(-1)
        out = full[:flat.size].reshape(x0.shape).astype(x0.dtype)
        return out

    mapped = shard_map(inner, mesh=mesh, in_specs=P(axes), out_specs=P(),
                       axis_names=set(axes), check_vma=False)
    # graftlint: disable=TPU002 (called under the caller's outer jit: one construction per outer trace)
    return jax.jit(mapped)(x)


def quantized_all_to_all(x: jnp.ndarray, *, mesh, axis="expert",
                         bits: int = 8,
                         block: int = DEFAULT_BLOCK) -> jnp.ndarray:
    """int8 all-to-all over ``axis`` (dim-0 split/concat, the facade's
    ``comm.all_to_all`` default layout): the standalone benchmark/test
    wrapper around :func:`a2a_quantized_local`. ``x`` is sharded on dim 0
    over ``axis``; the result mirrors the exact all-to-all's value within
    blockwise-int8 tolerance."""
    axes = _axes_tuple(axis)

    def inner(xl):
        return a2a_quantized_local(xl, axes, bits=bits, block=block)

    spec = [axis] + [None] * (x.ndim - 1)
    mapped = shard_map(inner, mesh=mesh, in_specs=P(*spec),
                       out_specs=P(*spec), axis_names=set(axes),
                       check_vma=False)
    # graftlint: disable=TPU002 (called under the caller's outer jit: one construction per outer trace)
    return jax.jit(mapped)(x)


# ---------------------------------------------------------------------------
# MoE queue exchange (the GShard a2a pair as an explicit, plan-routable seam)
# ---------------------------------------------------------------------------

def make_queue_exchange(mesh, *, algo: str = "int8", bits: int = 8,
                        block: int = DEFAULT_BLOCK):
    """(dispatch, combine) exchange pair for the grouped MoE layout.

    dispatch: [G, E, Cg, H] (G = data*expert*seq product, dim 0 sharded
    over those axes) -> [E, G*Cg, H] queues (E over 'expert', queue dim
    over ('data','seq')) — the reference ``_AllToAll`` exchange, made
    explicit so the wire format is ours to choose. combine is the exact
    inverse. Both are ``custom_vjp``: the backward of each direction is
    the other direction's exchange of the cotangent (straight-through
    past the quantizer), so the BACKWARD a2a is quantized too.

    The row order of the queue dim is a fixed permutation of the
    implicit-path layout; it is self-consistent between the pair (and
    per-expert compute is row-independent), which is the only property
    the MoE math needs.
    """
    if algo not in ("exact", "int8"):
        raise ValueError(f"queue exchange algo {algo!r}: expected "
                         "exact|int8")
    manual = ("data", "expert", "seq")
    ep = mesh.shape["expert"]

    if algo == "int8":
        # The custom_vjp (straight-through past the quantizer; backward
        # cotangents ride the SAME int8 wire format) sits INSIDE the
        # shard_map body, around the shard-local exchange: an outer
        # custom_vjp wrapping the whole shard_map leaks tracers under
        # flax's nn.scan lifting on the 0.4.x jax line. The dim-0 peer
        # exchange is an involution and its own transpose, so one
        # function serves both directions and both passes.
        @jax.custom_vjp
        def _exchange(x):
            return a2a_quantized_local(x, "expert", bits=bits, block=block)

        _exchange.defvjp(
            lambda x: (_exchange(x), None),
            lambda _, g: (a2a_quantized_local(g, "expert", bits=bits,
                                              block=block),))
    else:
        def _exchange(x):
            return jax.lax.all_to_all(x, "expert", split_axis=0,
                                      concat_axis=0, tiled=True)

    def to_queues_local(xl):          # [1, E, Cg, H] per-device group
        assert xl.shape[0] == 1, (
            f"queue exchange needs the fully-grouped layout (one group "
            f"per device); got {xl.shape[0]} local groups")
        y = _exchange(xl[0])          # block r = peer r's slice of MY experts
        E, Cg, H = y.shape
        return (y.reshape(ep, E // ep, Cg, H).transpose(1, 0, 2, 3)
                .reshape(E // ep, ep * Cg, H))

    def to_groups_local(ql):          # [E/ep, ep*Cg, H]
        El, Q, H = ql.shape
        y = (ql.reshape(El, ep, Q // ep, H).transpose(1, 0, 2, 3)
             .reshape(ep * El, Q // ep, H))
        return _exchange(y)[None]     # [1, E, Cg, H]

    group_spec = P(manual, None, None, None)
    queue_spec = P("expert", ("data", "seq"), None)
    dispatch = shard_map(to_queues_local, mesh=mesh, in_specs=group_spec,
                         out_specs=queue_spec, axis_names=set(manual),
                         check_vma=False)
    combine = shard_map(to_groups_local, mesh=mesh, in_specs=queue_spec,
                        out_specs=group_spec, axis_names=set(manual),
                        check_vma=False)
    return dispatch, combine
