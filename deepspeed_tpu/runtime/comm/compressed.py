"""Compressed collectives — 1-bit and int8 allreduce with error feedback.

Capability parity with the reference's cupy compressed-comm backends
(``runtime/comm/nccl.py:52-204`` NcclBackend.compressed_allreduce and the MPI
variant): sign+scale compression, chunked exchange so every rank "serves" one
chunk (average + re-compress with server error feedback), then allgather of
the served chunks. TPU-native: the exchange is `lax.all_to_all` /
`lax.all_gather` over a mesh axis inside partial-auto shard_map — the wire
carries int8 signs + f32 scales, an ~4x (int8) to ~32x (1-bit, byte-packed
sign) reduction vs f32. Pays off over DCN; over fast ICI prefer plain psum
(the reference gates 1-bit the same way: worth it on Ethernet, engine docs).

Error-feedback state (worker_error, server_error) is carried by the caller
(the 1-bit optimizers keep it in their state pytree, reference:
onebit/adam.py worker_error/server_error buffers).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...utils.jax_compat import shard_map as _shard_map


def _chunk(x: jnp.ndarray, n: int, multiple: int = 1) -> jnp.ndarray:
    """Pad + reshape flat x to [n, c] with c a multiple of ``multiple``."""
    c = -(-x.size // n)
    c = -(-c // multiple) * multiple
    pad = n * c - x.size
    xp = jnp.pad(x.reshape(-1), (0, pad))
    return xp.reshape(n, -1), pad


def chunk_elems(numel: int, n: int, multiple: int = 8) -> int:
    """Per-rank chunk length the 1-bit path uses for ``numel`` elements."""
    c = -(-numel // n)
    return -(-c // multiple) * multiple


def compressed_allreduce(x: jnp.ndarray,
                         worker_error: jnp.ndarray,
                         server_error: jnp.ndarray,
                         *,
                         mesh,
                         axis: str = "data"
                         ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """1-bit allreduce of per-rank values with two-level error feedback.

    x: stacked per-rank values [n, ...] (dim 0 sharded over `axis` — rank r
    contributes x[r]). worker_error [n, numel] / server_error [n, ceil(numel/n)]
    are the running compensation buffers, same sharding.
    Returns (averaged value [...], new_worker_error, new_server_error).
    """
    n = mesh.shape[axis]

    from ...ops.quantizer import pack_signs, unpack_signs

    def inner(x, w_err, s_err):
        x, w_err, s_err = x[0], w_err[0], s_err[0]
        flat = x.reshape(-1).astype(jnp.float32)
        corrected = flat + w_err
        chunks, pad = _chunk(corrected, n, multiple=8)        # [n, c], c%8==0
        scale = jnp.mean(jnp.abs(chunks), axis=1, keepdims=True)  # [n, 1]
        signs = jnp.where(chunks >= 0, 1.0, -1.0)
        new_w_err = corrected - (signs * scale).reshape(-1)[:corrected.size]

        # exchange: rank r serves chunk r — a2a the PACKED sign bits (1 bit per
        # element on the wire; reference packs via cupy packbits), allgather
        # the tiny per-chunk scales
        c = chunks.shape[1]
        packed = jax.vmap(pack_signs)(signs)                   # [n, c/8] u8
        packed_recv = jax.lax.all_to_all(packed, axis,
                                         split_axis=0, concat_axis=0,
                                         tiled=True)           # [n, c/8]
        signs_recv = jax.vmap(unpack_signs)(packed_recv)       # [n, c]
        scales_all = jax.lax.all_gather(scale[:, 0], axis)     # [n, n]
        my = jax.lax.axis_index(axis)
        my_scales = scales_all[:, my]                          # senders' scales
        served = jnp.mean(signs_recv * my_scales[:, None], axis=0)  # [c]

        # server-side re-compress with server error feedback
        served_c = served + s_err
        s_scale = jnp.mean(jnp.abs(served_c))
        s_signs = jnp.where(served_c >= 0, 1.0, -1.0)
        new_s_err = served_c - s_signs * s_scale

        out_packed = jax.lax.all_gather(pack_signs(s_signs), axis,
                                        tiled=True)            # [n*c/8]
        out_scales = jax.lax.all_gather(s_scale, axis)         # [n]
        out = (unpack_signs(out_packed).reshape(n, c) *
               out_scales[:, None]).reshape(-1)
        out = out[:flat.size].reshape(x.shape).astype(x.dtype)
        return out, new_w_err[None], new_s_err[None]

    mapped = _shard_map(inner, mesh=mesh,
                           in_specs=(P(axis), P(axis), P(axis)),
                           out_specs=(P(), P(axis), P(axis)),
                           axis_names={axis}, check_vma=False)
    # graftlint: disable=TPU002 (called from the runner's outer jitted step: one construction per outer trace)
    return jax.jit(mapped)(x, worker_error, server_error)


def quantized_allreduce(x: jnp.ndarray,
                        error: jnp.ndarray,
                        *,
                        mesh,
                        axis: str = "data",
                        bits: int = 8
                        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """int8 allreduce with error feedback: reduce-scatter int8 chunks,
    average, allgather int8 results (EQuARX-style; ~4x wire reduction).

    x: stacked per-rank values [n, ...], dim 0 sharded over `axis`;
    error [n, numel]. Returns (averaged [...], new_error [n, numel])."""
    n = mesh.shape[axis]
    qmax = float(2 ** (bits - 1) - 1)

    def inner(x, err):
        x, err = x[0], err[0]
        flat = x.reshape(-1).astype(jnp.float32) + err
        chunks, pad = _chunk(flat, n)
        q, scale = _sym_quant(chunks, qmax, axis=1)
        deq = (q * scale).reshape(-1)[:flat.size]
        new_err = flat - deq

        q_recv = jax.lax.all_to_all(q.astype(jnp.int8), axis, split_axis=0,
                                    concat_axis=0, tiled=True)
        scales_all = jax.lax.all_gather(scale[:, 0], axis)
        my = jax.lax.axis_index(axis)
        served = jnp.mean(q_recv.astype(jnp.float32) *
                          scales_all[:, my][:, None], axis=0)
        s_q, s_scale = _sym_quant(served, qmax)

        out_q = jax.lax.all_gather(s_q.astype(jnp.int8), axis, tiled=True)
        out_scales = jax.lax.all_gather(s_scale, axis)
        c = served.shape[0]
        out = (out_q.astype(jnp.float32).reshape(n, c) *
               out_scales[:, None]).reshape(-1)[:flat.size]
        return out.reshape(x.shape).astype(x.dtype), new_err[None]

    mapped = _shard_map(inner, mesh=mesh, in_specs=(P(axis), P(axis)),
                           out_specs=(P(), P(axis)),
                           axis_names={axis}, check_vma=False)
    # graftlint: disable=TPU002 (called from the runner's outer jitted step: one construction per outer trace)
    return jax.jit(mapped)(x, error)


# ---------------------------------------------------------------------------
# ZeRO++-style quantized weight gather (qwZ) / gradient reduce-scatter (qgZ)
# ---------------------------------------------------------------------------

def _sym_quant(x: jnp.ndarray, qmax: float, axis=None):
    """Symmetric quant: (clipped-rounded f32 values, f32 scale). axis=None
    scales per-tensor; an int axis scales per-slice (keepdims)."""
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=axis, keepdims=axis is not None)
    scale = jnp.where(absmax == 0, 1.0, absmax / qmax)
    q = jnp.clip(jnp.round(xf / scale), -qmax, qmax)
    return q, scale


def make_quantized_gather(mesh, axis, dim: int, bits: int = 8,
                          spec: "P" = None):
    """ZeRO++-style quantized weight gather (qwZ).

    Returns f(x) where x is sharded on ``dim`` over mesh axis ``axis`` (a
    name or tuple of names, e.g. the composed ZeRO axes): forward
    all-gathers int8 shards + per-shard scales and dequantizes — the wire
    carries 1/4 the bf16 gather bytes (ZeRO++'s quantized weight
    communication). Backward is the exact zero-communication slice back to
    the shard: under SPMD the cotangent reaching this seam is already
    globally reduced, so the gradient-side quantization (qgZ) lives in the
    explicit grad-sync collectives above (``quantized_allreduce``), not
    here. Intended for DCN-bound meshes where gather bandwidth dominates;
    over fast ICI prefer the implicit XLA gathers.

    ``spec``: the leaf's full PartitionSpec (to preserve TP axes on other
    dims); defaults to sharding only ``dim``.
    """
    if not 2 <= bits <= 8:
        raise ValueError(f"bits={bits}: the wire dtype is int8, so only "
                         "2..8-bit quantization is supported")
    qmax = float(2 ** (bits - 1) - 1)
    axes = (axis,) if isinstance(axis, str) else tuple(axis)

    def _specs(ndim):
        base = list(spec) if spec is not None else [None] * ndim
        base = base[:ndim] + [None] * (ndim - len(base))
        in_spec = list(base)
        in_spec[dim] = axis if isinstance(axis, str) else tuple(axis)
        out_spec = list(base)
        out_spec[dim] = None
        # every axis the specs mention must be manual in the shard_map —
        # including TP axes on other dims, over which the inner fn simply
        # operates shard-locally (no collective touches them)
        manual = set(axes)
        for entry in base:
            if entry is None:
                continue
            manual |= {entry} if isinstance(entry, str) else set(entry)
        return P(*in_spec), P(*out_spec), manual

    @jax.custom_vjp
    def qgather(x):
        return _fwd(x)[0]

    def _fwd(x):
        def inner(xs):
            q, scale = _sym_quant(xs, qmax)
            q = q.astype(jnp.int8)
            qg = jax.lax.all_gather(q, axes)              # [k, ...shard]
            sg = jax.lax.all_gather(scale, axes)          # [k]
            deq = qg.astype(jnp.float32) * \
                sg.reshape((-1,) + (1,) * xs.ndim)
            full = jnp.concatenate(list(deq), axis=dim)
            return full.astype(xs.dtype)

        in_spec, out_spec, manual = _specs(x.ndim)
        # deliberately jax.shard_map, NOT the jax_compat wrapper: the
        # qwZ+TP composition ABORTS inside XLA on the 0.4.x jaxlib (see
        # utils/jax_compat docstring) — a clean AttributeError is safer
        mapped = jax.shard_map(inner, mesh=mesh, in_specs=in_spec,
                               out_specs=out_spec, axis_names=manual,
                               check_vma=False)
        return mapped(x), None

    def _bwd(_, g):
        def inner(gs):
            # the cotangent is already globally reduced at this seam: the
            # shard's gradient is exactly its slice of it
            k = 1
            for a in axes:
                k *= jax.lax.axis_size(a)
            size = gs.shape[dim] // k
            # axis_index over the tuple = row-major flat rank, matching the
            # all_gather concat order
            idx = jax.lax.axis_index(axes)
            return jax.lax.dynamic_slice_in_dim(gs, idx * size, size,
                                                axis=dim)

        in_spec, out_spec, manual = _specs(g.ndim)
        mapped = jax.shard_map(inner, mesh=mesh, in_specs=out_spec,
                               out_specs=in_spec, axis_names=manual,
                               check_vma=False)
        return (mapped(g),)

    qgather.defvjp(_fwd, _bwd)
    return qgather


def hierarchical_quantized_allreduce(x: jnp.ndarray,
                                     error: jnp.ndarray,
                                     *,
                                     mesh,
                                     intra_axis: str,
                                     inter_axis: str,
                                     bits: int = 8
                                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Two-level int8 allreduce: exact psum over the fast axis, quantized
    exchange over the slow one (ZeRO++ qgZ's hierarchical scheme; SURVEY §5's
    "data over DCN, model/pipe over ICI" layout).

    Level 1 reduces over ``intra_axis`` (ICI within a slice) at full
    precision — ICI bandwidth makes quantization a loss there. Level 2 runs
    the error-feedback int8 chunk exchange of ``quantized_allreduce`` over
    ``inter_axis`` (DCN across slices), where the 4x byte saving pays.

    x: per-rank values [n_intra * n_inter, ...] stacked on dim 0, sharded
    over (inter, intra); error: [n_inter, numel] per-slice error feedback.
    Returns (averaged [...], new_error).
    """
    n_inter = mesh.shape[inter_axis]
    qmax = float(2 ** (bits - 1) - 1)

    def inner(x, err):
        x, err = x[0], err[0]
        # level 1: exact average within the slice (rides ICI)
        local = jax.lax.pmean(x, intra_axis)
        # level 2: error-feedback int8 chunk exchange across slices
        flat = local.reshape(-1).astype(jnp.float32) + err
        chunks, _ = _chunk(flat, n_inter)
        q, scale = _sym_quant(chunks, qmax, axis=1)
        new_err = flat - (q * scale).reshape(-1)[:flat.size]
        q_recv = jax.lax.all_to_all(q.astype(jnp.int8), inter_axis,
                                    split_axis=0, concat_axis=0, tiled=True)
        scales_all = jax.lax.all_gather(scale[:, 0], inter_axis)
        my = jax.lax.axis_index(inter_axis)
        served = jnp.mean(q_recv.astype(jnp.float32) *
                          scales_all[:, my][:, None], axis=0)
        s_q, s_scale = _sym_quant(served, qmax)
        out_q = jax.lax.all_gather(s_q.astype(jnp.int8), inter_axis,
                                   tiled=True)
        out_scales = jax.lax.all_gather(s_scale, inter_axis)
        c = served.shape[0]
        out = (out_q.astype(jnp.float32).reshape(n_inter, c) *
               out_scales[:, None]).reshape(-1)[:flat.size]
        return out.reshape(x.shape).astype(x.dtype), new_err[None]

    mapped = _shard_map(inner, mesh=mesh,
                           in_specs=(P((inter_axis, intra_axis)),
                                     P(inter_axis)),
                           out_specs=(P(), P(inter_axis)),
                           axis_names={intra_axis, inter_axis},
                           check_vma=False)
    # graftlint: disable=TPU002 (called from the runner's outer jitted step: one construction per outer trace)
    return jax.jit(mapped)(x, error)
