"""Specialized runtime communication: compressed (1-bit/int8)
collectives, the blockwise-int8 reduce-scatter / all-to-all family, and
the chunked ``overlap`` schedules (hand-pipelined allgather->matmul and
grad reduce-scatter)."""

from .compressed import compressed_allreduce, quantized_allreduce
from .overlap import (
    make_overlap_gather,
    overlap_grad_sync,
)
from .quantized import (
    grad_sync,
    make_queue_exchange,
    quantized_all_to_all,
    quantized_reduce_scatter,
)

__all__ = ["compressed_allreduce", "quantized_allreduce", "grad_sync",
           "make_queue_exchange", "make_overlap_gather",
           "overlap_grad_sync", "quantized_all_to_all",
           "quantized_reduce_scatter"]
