"""Specialized runtime communication: compressed (1-bit/int8) collectives
and the blockwise-int8 reduce-scatter / all-to-all family."""

from .compressed import compressed_allreduce, quantized_allreduce
from .quantized import (
    grad_sync,
    make_queue_exchange,
    quantized_all_to_all,
    quantized_reduce_scatter,
)

__all__ = ["compressed_allreduce", "quantized_allreduce", "grad_sync",
           "make_queue_exchange", "quantized_all_to_all",
           "quantized_reduce_scatter"]
