"""Specialized runtime communication: compressed (1-bit/int8) collectives."""

from .compressed import compressed_allreduce, quantized_allreduce

__all__ = ["compressed_allreduce", "quantized_allreduce"]
