"""Hand-pipelined overlap schedules — chunked allgather→matmul and
chunked grad reduce-scatter (T3 / ZeRO++ style), the comm-plan
``overlap``/``overlap_int8`` algorithm family.

The two seams these executors replace are the last places COVERAGE.md
said "trust XLA's latency-hiding scheduler":

* the ZeRO-3 param fetch — the per-leaf all-gather of a sharded weight
  ahead of its consuming matmul (:func:`make_overlap_gather`);
* the ZeRO-2/3 grad sync — the reduce-scatter of every grad leaf over
  the DP axes (:func:`overlap_grad_sync`).

Instead of ONE whole-tensor collective per leaf (whose wire time the
scheduler may or may not hide), each executor splits the payload into
``chunks`` pieces and issues one chunk-sized collective per piece. The
chunks are data-independent, so the async collective-start/done pairs
XLA emits can interleave chunk k+1's wire time under chunk k's compute
(and under neighboring layers' matmuls) — hand-pipelined fine-grained
overlap rather than scheduler-discovered, which is exactly the regime
T3 (arXiv 2401.16677) and ZeRO++ (arXiv 2306.10209) measure wins in.
A naive auto-SPMD chunking (slice + sharding constraint per chunk) does
NOT survive compilation — the partitioner CSEs the chunk gathers back
into one full-tensor collective (measured on this host) — so every
executor builds the chunks INSIDE a shard_map body where the manual
collectives are final.

``overlap`` moves exact f32 chunks; ``overlap_int8`` composes with the
blockwise-int8 wire format of ``quantized.py`` — each chunk is
quantized independently and its per-block scales ride WITH the chunk,
so a chunk is self-contained on the wire and dequant of chunk k can
start (and overlap) before chunk k+1 lands.

Autodiff: inside a manual shard_map region the transpose of
``lax.all_gather`` is ``lax.psum_scatter`` — differentiating through a
chunked gather therefore yields chunk-sized reduce-scatters in the
backward for free, which is how the overlapped ZeRO-3 step gets BOTH
tentpole structures (chunked allgather forward, chunked grad
reduce-scatter backward) from one executor. The int8 gather carries a
``custom_vjp`` (straight-through past the quantizer, defined INSIDE the
shard_map body — the MoE queue-exchange lesson) whose backward is the
same exact chunk-sized psum_scatter.

Every region is built through ``utils.jax_compat.shard_map`` and is
fully-manual over the ZeRO/DP axes only (TP axes stay auto) — the
shape class verified to compile on the 0.4.x jaxlib, unlike the
qwZ+TP composition ``jax_compat`` warns about.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ...utils.jax_compat import shard_map
from .quantized import (DEFAULT_BLOCK, _axes_size, _axes_tuple,
                        ag_quantized_local, rs_exact_local,
                        rs_quantized_local)

#: default pieces per overlapped collective; one more chunk = one more
#: opportunity to hide wire time, at one more collective's latency floor
DEFAULT_CHUNKS = 4

OVERLAP_ALGOS = ("overlap", "overlap_int8")


def effective_chunks(length: int, chunks: int) -> int:
    """Largest c <= chunks that divides ``length`` (>= 1): chunk edges
    must be static and equal-sized so every chunk compiles to the same
    collective shape (one program, not per-chunk variants)."""
    c = max(1, min(int(chunks), int(length)))
    while length % c:
        c -= 1
    return c


def _rs_hop(seg, axes, n, *, algo, bits, block, mean):
    """One per-segment reduce-scatter hop (shard-local): the int8 or
    exact variant of the ``rs_*_local`` contract, served chunk out —
    the single definition every chunked executor below shares."""
    if algo == "overlap_int8":
        served, _ = rs_quantized_local(seg, axes, n, bits=bits,
                                       block=block, mean=mean)
    else:
        served, _ = rs_exact_local(seg, axes, n, mean=mean)
    return served


def _segment_bounds(length: int, chunks: int):
    """Static [lo, hi) bounds cutting ``length`` into ``chunks`` nearly
    equal contiguous segments (flat-buffer chunking: segments need not
    be equal — each hop pads itself)."""
    ch = max(1, min(int(chunks), int(length)))
    base, rem = divmod(length, ch)
    bounds, lo = [], 0
    for k in range(ch):
        hi = lo + base + (1 if k < rem else 0)
        bounds.append((lo, hi))
        lo = hi
    return bounds


# ---------------------------------------------------------------------------
# chunked grad sync (ZeRO-2 seam; the overlap counterpart of grad_sync)
# ---------------------------------------------------------------------------

def overlap_grad_sync(x: jnp.ndarray, *, mesh, axis="data",
                      chunks: int = DEFAULT_CHUNKS,
                      algo: str = "overlap", bits: int = 8,
                      block: int = DEFAULT_BLOCK,
                      mean: bool = True) -> jnp.ndarray:
    """Chunked ZeRO-2 gradient sync: ``grad_sync``'s contract (stacked
    per-rank grads [n, ...] in, reduced value in the original leaf shape
    out) with the flat buffer cut into ``chunks`` segments, each riding
    its OWN reduce-scatter + all-gather hop — no tail-end whole-tensor
    collective for the scheduler to (maybe) hide.

    ``algo``:
      * ``"overlap"`` — exact f32 chunks (same math as the implicit
        sync, only the wire schedule changes);
      * ``"overlap_int8"`` — each chunk blockwise-int8 quantized, its
        per-block scales riding with it (self-contained chunks).
    """
    if algo not in OVERLAP_ALGOS:
        raise ValueError(f"overlap_grad_sync algo {algo!r}: expected "
                         f"{'|'.join(OVERLAP_ALGOS)}")
    n = _axes_size(mesh, axis)
    axes = _axes_tuple(axis)

    def inner(xs):
        x0 = xs[0]
        flat = x0.reshape(-1).astype(jnp.float32)
        outs = []
        for lo, hi in _segment_bounds(flat.size, chunks):
            seg = jax.lax.slice(flat, (lo,), (hi,))
            served = _rs_hop(seg, axes, n, algo=algo, bits=bits,
                             block=block, mean=mean)
            if algo == "overlap_int8":
                full = ag_quantized_local(served, axes, bits=bits,
                                          block=block)
            else:
                full = jax.lax.all_gather(served, axes).reshape(-1)
            outs.append(full[:seg.size])
        out = jnp.concatenate(outs) if len(outs) > 1 else outs[0]
        return out.reshape(x0.shape).astype(x0.dtype)

    mapped = shard_map(inner, mesh=mesh, in_specs=P(axes), out_specs=P(),
                       axis_names=set(axes), check_vma=False)
    # graftlint: disable=TPU002 (called under the caller's outer jit: one construction per outer trace)
    return jax.jit(mapped)(x)


# ---------------------------------------------------------------------------
# chunked param gather (ZeRO-3 seam; replaces the implicit stage-3 allgather)
# ---------------------------------------------------------------------------

def make_overlap_gather(mesh, axis, dim: int, *,
                        chunks: int = DEFAULT_CHUNKS,
                        algo: str = "overlap", bits: int = 8,
                        block: int = DEFAULT_BLOCK):
    """Chunked explicit all-gather for one ZeRO-3 param leaf sharded on
    ``dim`` over mesh axis ``axis`` (a name or the composed ZeRO axis
    tuple). Returns f(x) -> the whole leaf, assembled from
    ``effective_chunks`` chunk-sized all-gathers of shard sub-slices —
    the fetch-coordinator's prefetch granularity, made explicit.

    Forward: chunk k of every rank's shard rides its own
    ``lax.all_gather`` (chunk-shaped wire op, [n, step, ...] out), then
    a local transpose/reshape restores the rank-major row order.
    Backward: the transpose of each chunk gather is a chunk-sized
    ``psum_scatter`` — the overlapped ZeRO-3 backward gets its grads
    reduce-scattered in the same chunks, no full-tensor collective in
    either direction. The leaf spec must name ONLY the gather axes (on
    ``dim``); TP-composed leaves stay on the implicit path (engine
    envelope).

    ``algo="overlap_int8"`` quantizes each chunk blockwise before the
    gather (scales ride with their chunk; ~25% of the f32 chunk bytes)
    with a straight-through ``custom_vjp`` whose backward is the exact
    chunk psum_scatter.
    """
    if algo not in OVERLAP_ALGOS:
        raise ValueError(f"make_overlap_gather algo {algo!r}: expected "
                         f"{'|'.join(OVERLAP_ALGOS)}")
    axes = _axes_tuple(axis)
    n = _axes_size(mesh, axes)

    if algo == "overlap_int8":
        # custom_vjp around the shard-LOCAL chunk exchange (defined at
        # make time, called inside the shard_map body — an outer
        # custom_vjp wrapping the whole shard_map leaks tracers under
        # nn.scan lifting on the 0.4.x jax line)
        @jax.custom_vjp
        def _chunk_gather(c):
            deq = ag_quantized_local(c.reshape(-1), axes, bits=bits,
                                     block=block)       # [n * L]
            return deq.reshape((n,) + c.shape).astype(c.dtype)

        _chunk_gather.defvjp(
            lambda c: (_chunk_gather(c), None),
            # straight-through past the quantizer: the exact chunk-sized
            # reduce-scatter (all_gather's transpose), reduced in f32
            # and cast back — the bwd cotangent must match the primal
            # dtype on jax lines that enforce custom_vjp avals
            lambda _, g: (jax.lax.psum_scatter(
                g.astype(jnp.float32), axes, scatter_dimension=0,
                tiled=False).astype(g.dtype),))
    else:
        def _chunk_gather(c):
            return jax.lax.all_gather(c, axes)          # [n, *c.shape]

    def inner(wl):
        local = wl.shape[dim]
        ch = effective_chunks(local, chunks)
        step = local // ch
        parts = []
        for k in range(ch):
            c = jax.lax.slice_in_dim(wl, k * step, (k + 1) * step,
                                     axis=dim)
            parts.append(_chunk_gather(c))              # [n, ..step..]
        g = jnp.concatenate(parts, axis=1 + dim) if ch > 1 else parts[0]
        g = jnp.moveaxis(g, 0, dim)                     # [..., n, local, ...]
        if algo == "overlap_int8":
            g = g.astype(wl.dtype)
        return g.reshape(wl.shape[:dim] + (n * local,)
                         + wl.shape[dim + 1:])

    spec_in = [None] * max(dim + 1, 1)
    spec_in[dim] = axes if len(axes) > 1 else axes[0]
    mapped = shard_map(inner, mesh=mesh, in_specs=P(*spec_in),
                       out_specs=P(), axis_names=set(axes),
                       check_vma=False)

    def gather(x):
        # graftlint: disable=TPU002 (called under the caller's outer jit: one construction per outer trace)
        return mapped(x)

    return gather


# ---------------------------------------------------------------------------
# benchmark pipelines (ds_bench overlap cells; also the HLO-audit fixtures)
# ---------------------------------------------------------------------------

def chunked_ag_matmul(x: jnp.ndarray, w: jnp.ndarray, *, mesh, axis,
                      chunks: int = DEFAULT_CHUNKS, algo: str = "overlap",
                      bits: int = 8, block: int = DEFAULT_BLOCK
                      ) -> jnp.ndarray:
    """The T3 allgather→matmul pipeline as a self-contained benchmark
    payload: ``w`` [R, C] sharded on dim 0 over ``axis``, ``x`` [B, R]
    replicated; returns ``x @ w`` computed as
    ``sum_k x[:, rows_k] @ all_gather(w_chunk_k)`` so chunk k+1's gather
    has chunk k's matmul to hide under. Row selection per chunk is a
    static index map (rank-major shard layout), precomputed on host."""
    axes = _axes_tuple(axis)
    n = _axes_size(mesh, axes)
    R = w.shape[0]
    S = R // n                      # rows per rank
    ch = effective_chunks(S, chunks)
    step = S // ch
    cols = [np.concatenate([np.arange(r * S + k * step,
                                      r * S + (k + 1) * step)
                            for r in range(n)]) for k in range(ch)]

    def inner(xl, wl):
        acc = jnp.zeros((xl.shape[0], wl.shape[1]), jnp.float32)
        for k in range(ch):
            c = jax.lax.slice_in_dim(wl, k * step, (k + 1) * step, axis=0)
            if algo == "overlap_int8":
                wk = ag_quantized_local(c.reshape(-1), axes, bits=bits,
                                        block=block).reshape(
                                            (-1, wl.shape[1]))
            else:
                wk = jax.lax.all_gather(c, axes, tiled=True)  # [n*step, C]
            xk = jnp.take(xl, jnp.asarray(cols[k]), axis=1)
            acc = acc + xk.astype(jnp.float32) @ wk.astype(jnp.float32)
        return acc.astype(x.dtype)

    mapped = shard_map(inner, mesh=mesh,
                       in_specs=(P(), P(axes if len(axes) > 1 else axes[0])),
                       out_specs=P(), axis_names=set(axes), check_vma=False)
    # graftlint: disable=TPU002 (called under the caller's outer jit: one construction per outer trace)
    return jax.jit(mapped)(x, w)


def chunked_rs(g: jnp.ndarray, *, mesh, axis,
               chunks: int = DEFAULT_CHUNKS, algo: str = "overlap",
               bits: int = 8, block: int = DEFAULT_BLOCK,
               mean: bool = True) -> jnp.ndarray:
    """Chunked reduce-scatter of a PRECOMPUTED stacked buffer [n, L]
    (dim 0 over ``axis``): the comm-only half of
    :func:`chunked_matmul_rs` — ds_bench times it to split an overlap
    cell's wall time into its comm and compute parts
    (``overlap_ratio``). Returns this rank's served chunk-concat
    [1, ~L/n] (per-chunk scattered layout, dim 0 over ``axis``)."""
    axes = _axes_tuple(axis)
    n = _axes_size(mesh, axes)

    def inner(gl):
        outs = []
        for lo, hi in _segment_bounds(gl.shape[-1], chunks):
            seg = jax.lax.slice(gl[0], (lo,), (hi,)).astype(jnp.float32)
            outs.append(_rs_hop(seg, axes, n, algo=algo, bits=bits,
                                block=block, mean=mean))
        out = jnp.concatenate(outs) if len(outs) > 1 else outs[0]
        return out[None]

    mapped = shard_map(inner, mesh=mesh, in_specs=P(axes),
                       out_specs=P(axes), axis_names=set(axes),
                       check_vma=False)
    # graftlint: disable=TPU002 (called under the caller's outer jit: one construction per outer trace)
    return jax.jit(mapped)(g)


def chunked_matmul_rs(u: jnp.ndarray, v: jnp.ndarray, *, mesh, axis,
                      chunks: int = DEFAULT_CHUNKS, algo: str = "overlap",
                      bits: int = 8, block: int = DEFAULT_BLOCK,
                      mean: bool = True) -> jnp.ndarray:
    """The grad-production side of the overlap story as a benchmark
    payload: per chunk, a matmul PRODUCES the grad segment
    (``u_local @ v[:, seg_k]``) and that segment immediately rides its
    own reduce-scatter hop — grads are reduce-scattered as they are
    produced, not as one tail-end collective. ``u`` [n, B] stacked over
    ``axis``; ``v`` [B, L] replicated; returns this rank's reduced
    chunk-concat [1, ~L/n] (per-chunk scattered layout — each chunk's
    served piece in chunk order, padded per hop — dim 0 over
    ``axis``)."""
    axes = _axes_tuple(axis)
    n = _axes_size(mesh, axes)
    L = v.shape[1]

    def inner(ul, vl):
        outs = []
        for lo, hi in _segment_bounds(L, chunks):
            gk = (ul[0].astype(jnp.float32)
                  @ jax.lax.slice(vl, (0, lo),
                                  (vl.shape[0], hi)).astype(jnp.float32))
            outs.append(_rs_hop(gk, axes, n, algo=algo, bits=bits,
                                block=block, mean=mean))
        out = jnp.concatenate(outs) if len(outs) > 1 else outs[0]
        return out[None]

    mapped = shard_map(inner, mesh=mesh, in_specs=(P(axes), P()),
                       out_specs=P(axes), axis_names=set(axes),
                       check_vma=False)
    # graftlint: disable=TPU002 (called under the caller's outer jit: one construction per outer trace)
    return jax.jit(mapped)(u, v)
