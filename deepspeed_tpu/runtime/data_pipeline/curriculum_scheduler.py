"""Curriculum difficulty schedules.

Capability parity with the reference's
``runtime/data_pipeline/curriculum_scheduler.py`` (CurriculumScheduler:
fixed_linear / fixed_root / fixed_discrete / custom difficulty as a function
of global step, quantized to difficulty_step). Pure step->difficulty math —
no torch state; get_state/set_state keep the reference's checkpoint surface.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Optional


class CurriculumScheduler:
    def __init__(self, config: Dict):
        self.min_difficulty = int(config["min_difficulty"])
        self.max_difficulty = int(config["max_difficulty"])
        self.schedule_type = config.get("schedule_type", "fixed_linear")
        sc = dict(config.get("schedule_config", {}))
        if self.schedule_type in ("fixed_linear", "fixed_root"):
            if "total_curriculum_step" not in sc:
                raise ValueError(f"{self.schedule_type} needs "
                                 "schedule_config.total_curriculum_step")
            sc.setdefault("difficulty_step", 8)
            if self.schedule_type == "fixed_root" and "root_degree" not in sc:
                raise ValueError("fixed_root needs schedule_config.root_degree")
        elif self.schedule_type == "fixed_discrete":
            if "difficulty" not in sc or "max_step" not in sc:
                raise ValueError("fixed_discrete needs schedule_config."
                                 "difficulty + max_step lists")
        elif self.schedule_type != "custom":
            raise ValueError(f"unknown schedule_type '{self.schedule_type}'")
        self.schedule_config = sc
        self.current_difficulty = self.min_difficulty
        self.custom_get_difficulty: Optional[Callable[[int], int]] = None

    # -- schedules (reference curriculum_scheduler.py:136-175) ----------------

    def _fixed_root(self, global_steps: int, root_degree: int) -> int:
        sc = self.schedule_config
        frac = (float(global_steps) / sc["total_curriculum_step"]) ** \
            (1.0 / root_degree)
        d = math.floor(frac * (self.max_difficulty - self.min_difficulty)
                       + self.min_difficulty)
        d -= d % sc["difficulty_step"]
        return min(max(d, self.min_difficulty), self.max_difficulty)

    def _fixed_discrete(self, global_steps: int) -> int:
        sc = self.schedule_config
        for diff, max_step in zip(sc["difficulty"], sc["max_step"]):
            if global_steps <= max_step:
                return int(diff)
        return int(sc["difficulty"][-1])

    def get_difficulty(self, global_steps: int) -> int:
        if self.schedule_type == "fixed_discrete":
            return self._fixed_discrete(global_steps)
        if self.schedule_type == "fixed_linear":
            return self._fixed_root(global_steps, 1)
        if self.schedule_type == "fixed_root":
            return self._fixed_root(global_steps,
                                    self.schedule_config["root_degree"])
        if self.custom_get_difficulty is None:
            raise RuntimeError("custom schedule needs "
                               "set_custom_get_difficulty")
        return self.custom_get_difficulty(global_steps)

    def update_difficulty(self, global_steps: int) -> int:
        if self.current_difficulty < self.max_difficulty:
            self.current_difficulty = self.get_difficulty(global_steps)
        return self.current_difficulty

    def set_custom_get_difficulty(self, fn: Callable[[int], int]) -> None:
        self.custom_get_difficulty = fn

    def get_state(self) -> Dict:
        return {"current_difficulty": self.current_difficulty}

    def set_state(self, state: Dict) -> None:
        self.current_difficulty = state["current_difficulty"]
