"""Memory-mapped indexed dataset — zero-copy token storage.

Capability parity with the reference's
``data_pipeline/data_sampling/indexed_dataset.py`` MMapIndexedDataset
(Megatron format: a .bin of contiguous token arrays + a .idx of dtypes/
sizes/pointers, read through np.memmap so the OS page cache is the only
copy). Same two-file layout and builder/reader API; the header magic
differs (this is not a byte-compatible Megatron reader — it is the same
mechanism rebuilt).
"""

from __future__ import annotations

import os
import struct
from typing import List, Sequence

import numpy as np

_MAGIC = b"DSTPUIDX"
_VERSION = 1

_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32,
           5: np.int64, 6: np.float32, 7: np.float64, 8: np.uint16}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


class MMapIndexedDatasetBuilder:
    """reference: MMapIndexedDatasetBuilder (indexed_dataset.py:602)."""

    def __init__(self, out_prefix: str, dtype=np.int32):
        self._prefix = out_prefix
        self._dtype = np.dtype(dtype)
        self._data = open(data_file_path(out_prefix), "wb")
        self._sizes: List[int] = []

    def add_item(self, tokens: Sequence[int]) -> None:
        arr = np.asarray(tokens, dtype=self._dtype)
        self._data.write(arr.tobytes(order="C"))
        self._sizes.append(arr.size)

    def finalize(self) -> None:
        self._data.close()
        with open(index_file_path(self._prefix), "wb") as idx:
            idx.write(_MAGIC)
            idx.write(struct.pack("<QBQ", _VERSION,
                                  _DTYPE_CODES[self._dtype],
                                  len(self._sizes)))
            sizes = np.asarray(self._sizes, np.int64)
            pointers = np.zeros_like(sizes)
            np.cumsum(sizes[:-1] * self._dtype.itemsize, out=pointers[1:])
            idx.write(sizes.tobytes(order="C"))
            idx.write(pointers.tobytes(order="C"))


class MMapIndexedDataset:
    """reference: MMapIndexedDataset (indexed_dataset.py:381)."""

    def __init__(self, prefix: str):
        self._prefix = prefix
        with open(index_file_path(prefix), "rb") as f:
            magic = f.read(len(_MAGIC))
            if magic != _MAGIC:
                raise ValueError(f"{index_file_path(prefix)} is not a "
                                 "deepspeed_tpu indexed dataset")
            version, dtype_code, count = struct.unpack("<QBQ", f.read(17))
            if version != _VERSION:
                raise ValueError(f"unsupported index version {version}")
            self._dtype = np.dtype(_DTYPES[dtype_code])
            self._len = count
            self._sizes = np.frombuffer(f.read(8 * count), np.int64)
            self._pointers = np.frombuffer(f.read(8 * count), np.int64)
        self._bin = np.memmap(data_file_path(prefix), mode="r", dtype=np.uint8)

    def __len__(self) -> int:
        return self._len

    @property
    def sizes(self) -> np.ndarray:
        return self._sizes

    def __getitem__(self, i: int) -> np.ndarray:
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(self._len))]
        ptr = self._pointers[i]
        nbytes = self._sizes[i] * self._dtype.itemsize
        return self._bin[ptr:ptr + nbytes].view(self._dtype)

    def get(self, i: int, offset: int = 0, length: int = None) -> np.ndarray:
        item = self[i]
        return item[offset:offset + length if length is not None else None]
