"""deepspeed_tpu.runtime.data_pipeline — data efficiency suite.

reference: deepspeed/runtime/data_pipeline/ (curriculum scheduler, curriculum
data sampler, mmap indexed dataset, random-LTD routing).
"""

from .curriculum_scheduler import CurriculumScheduler
from .data_sampler import (CurriculumBatchTransform, DeepSpeedDataSampler,
                           apply_seqlen_curriculum)
from .indexed_dataset import (MMapIndexedDataset, MMapIndexedDatasetBuilder)
from .native_loader import NativeBatchAssembler

__all__ = ["CurriculumScheduler", "CurriculumBatchTransform",
           "DeepSpeedDataSampler", "apply_seqlen_curriculum",
           "MMapIndexedDataset", "MMapIndexedDatasetBuilder",
           "NativeBatchAssembler"]
