"""Native batch assembly over the mmap indexed dataset.

Role of the reference's prefetching DataLoader workers for pretraining-scale
token streams: the per-batch hot loop (gather N variable-length documents
into one contiguous [N, seq_len] array with truncate/pad) runs in C++
(ops/csrc/data_loader.cpp — mmap + OpenMP row memcpy) with one background
prefetch thread double-buffering the next batch while the device steps.
Falls back to a numpy loop when no toolchain is available, so behavior is
identical everywhere.

Usage::

    ds = MMapIndexedDataset("corpus")
    nb = NativeBatchAssembler(ds, seq_len=1024, pad_token=0)
    for idx_batch in sampler:                  # list[int] document ids
        batch = nb.gather(idx_batch)           # np [n, seq_len]
    # or double-buffered:
    nb.prefetch(ids0)
    for next_ids in ...:
        arr = nb.wait()                        # batch k
        nb.prefetch(next_ids)                  # overlaps with the step
"""

from __future__ import annotations

import ctypes
from typing import Optional, Sequence

import numpy as np

from .indexed_dataset import MMapIndexedDataset, data_file_path


class NativeBatchAssembler:
    def __init__(self, dataset: MMapIndexedDataset, seq_len: int,
                 pad_token: int = 0, use_native: bool = True):
        self._ds = dataset
        self.seq_len = int(seq_len)
        self.pad_token = pad_token
        self._dtype = dataset._dtype
        self._row_bytes = self.seq_len * self._dtype.itemsize
        self._lib = None
        self._handle = None
        self._pending: Optional[np.ndarray] = None
        if use_native:
            from ...ops.cpu.build import load_data_loader
            self._lib = load_data_loader()
        if self._lib is not None:
            self._handle = self._lib.ds_dl_open(
                data_file_path(dataset._prefix).encode())
            if not self._handle:
                self._lib = None

    @property
    def has_native(self) -> bool:
        return self._handle is not None

    def close(self):
        if self._handle:
            self._lib.ds_dl_prefetch_wait(self._handle)
            self._lib.ds_dl_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # -- internals -----------------------------------------------------------

    def _index_arrays(self, ids: Sequence[int]):
        ids = np.asarray(ids, np.int64)
        ptrs = self._ds._pointers[ids]
        nbytes = self._ds._sizes[ids] * self._dtype.itemsize
        return np.ascontiguousarray(ptrs), np.ascontiguousarray(nbytes)

    def _alloc(self, n: int) -> np.ndarray:
        out = np.full((n, self.seq_len), self.pad_token, dtype=self._dtype)
        return out

    def _gather_py(self, ids, out):
        for r, i in enumerate(ids):
            item = self._ds[int(i)][:self.seq_len]
            out[r, :len(item)] = item
        return out

    # -- API -----------------------------------------------------------------

    def gather(self, ids: Sequence[int]) -> np.ndarray:
        """Synchronous [n, seq_len] batch (truncate/pad to seq_len)."""
        out = self._alloc(len(ids))
        if self._handle is None:
            return self._gather_py(ids, out)
        ptrs, nbytes = self._index_arrays(ids)
        bad = self._lib.ds_dl_gather(
            self._handle,
            ptrs.ctypes.data_as(ctypes.c_void_p),
            nbytes.ctypes.data_as(ctypes.c_void_p),
            len(ids), self._row_bytes, out.ctypes.data_as(ctypes.c_void_p))
        if bad:
            raise IndexError(
                f"{bad} of {len(ids)} rows fell outside the .bin (corrupt or "
                "stale index?) — refusing to return pad-filled rows")
        return out

    def prefetch(self, ids: Sequence[int]) -> None:
        """Assemble the batch on the background thread; wait() returns it.
        One outstanding prefetch (double buffering)."""
        if self._pending is not None:
            raise RuntimeError("prefetch already in flight; call wait() first")
        out = self._alloc(len(ids))
        if self._handle is None:
            # keep the overlap contract in the fallback too: assemble on a
            # python thread so prefetch() stays non-blocking; exceptions are
            # captured and re-raised from wait() (native-path parity)
            import threading
            self._py_exc = None

            def work(ids=list(ids), out=out):
                try:
                    self._gather_py(ids, out)
                except BaseException as e:      # re-raised in wait()
                    self._py_exc = e

            t = threading.Thread(target=work)
            t.start()
            self._py_thread = t
            self._pending = out
            return
        ptrs, nbytes = self._index_arrays(ids)
        rc = self._lib.ds_dl_prefetch(
            self._handle,
            ptrs.ctypes.data_as(ctypes.c_void_p),
            nbytes.ctypes.data_as(ctypes.c_void_p),
            len(ids), self._row_bytes, out.ctypes.data_as(ctypes.c_void_p))
        if rc != 0:
            raise RuntimeError("prefetch already in flight in native handle")
        self._pending = out

    def wait(self) -> np.ndarray:
        """Block until the prefetched batch is ready and return it."""
        if self._pending is None:
            raise RuntimeError("no prefetch in flight")
        if self._handle is not None:
            bad = self._lib.ds_dl_prefetch_wait(self._handle)
            if bad:
                self._pending = None
                raise IndexError(
                    f"{bad} prefetched rows fell outside the .bin (corrupt "
                    "or stale index?) — refusing to return pad-filled rows")
        elif getattr(self, "_py_thread", None) is not None:
            self._py_thread.join()
            self._py_thread = None
            if self._py_exc is not None:
                self._pending = None
                exc, self._py_exc = self._py_exc, None
                raise exc
        out, self._pending = self._pending, None
        return out

    def __iter__(self):
        raise TypeError("NativeBatchAssembler is not an iterator; drive it "
                        "with a sampler via gather()/prefetch()")
