"""Offline data analyzer — per-sample difficulty metrics for curriculum
sampling.

Capability parity with the reference's
``data_pipeline/data_sampling/data_analyzer.py:527`` (DataAnalyzer: map a
metric function over the dataset with worker sharding, persist per-sample
values + a sample-index-sorted-by-metric file consumed by
DeepSpeedDataSampler). Metrics ship for the reference's two canonical
curricula — sequence length and vocabulary rarity — plus any user callable.
Output is npz (values + argsort), loadable by DeepSpeedDataSampler.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Iterable, Optional, Sequence

import numpy as np


def seqlen_metric(sample) -> float:
    """Token count (reference: seqlen curriculum metric)."""
    ids = sample["input_ids"] if isinstance(sample, dict) else sample
    arr = np.asarray(ids)
    return float(arr.shape[-1] if arr.ndim else 1)


def vocab_rarity_metric(vocab_freq: np.ndarray) -> Callable:
    """-mean log frequency of the sample's tokens (reference:
    voc curriculum — rarer vocabulary = harder)."""
    logf = np.log(np.maximum(vocab_freq, 1e-12))

    def metric(sample) -> float:
        ids = np.asarray(sample["input_ids"] if isinstance(sample, dict)
                         else sample).reshape(-1)
        return float(-logf[ids].mean())

    return metric


METRICS: Dict[str, Callable] = {"seqlen": seqlen_metric}


class DataAnalyzer:
    def __init__(self, dataset: Sequence, metric: Callable | str = "seqlen",
                 num_workers: int = 1, worker_id: int = 0,
                 save_path: Optional[str] = None):
        """dataset: indexable samples; metric: callable(sample)->float or a
        METRICS name. num_workers/worker_id shard the scan like the
        reference's distributed analyzer."""
        self.dataset = dataset
        self.metric = METRICS[metric] if isinstance(metric, str) else metric
        self.num_workers = num_workers
        self.worker_id = worker_id
        self.save_path = save_path

    def run(self) -> Dict[str, np.ndarray]:
        n = len(self.dataset)
        idx = np.arange(self.worker_id, n, self.num_workers)
        values = np.empty(len(idx), np.float32)
        for j, i in enumerate(idx):
            values[j] = self.metric(self.dataset[int(i)])
        out = {"index": idx.astype(np.int64), "values": values}
        if self.save_path:
            os.makedirs(os.path.dirname(self.save_path) or ".", exist_ok=True)
            np.savez(self._worker_file(), **out)
        return out

    def _worker_file(self) -> str:
        return f"{self.save_path}.worker{self.worker_id}.npz"

    @staticmethod
    def merge(save_path: str, num_workers: int) -> str:
        """Combine worker shards into the final metric file: values ordered
        by sample index + the metric-sorted sample order (the file
        DeepSpeedDataSampler consumes)."""
        idx_parts, val_parts = [], []
        for w in range(num_workers):
            with np.load(f"{save_path}.worker{w}.npz") as d:
                idx_parts.append(d["index"])
                val_parts.append(d["values"])
        index = np.concatenate(idx_parts)
        values = np.concatenate(val_parts)
        order = np.argsort(index)
        dense = values[order]                       # values by sample id
        np.savez(save_path, values=dense,
                 sorted_indices=np.argsort(dense, kind="stable"))
        return save_path

    @staticmethod
    def load(save_path: str) -> Dict[str, np.ndarray]:
        with np.load(save_path if save_path.endswith(".npz")
                     else save_path + ".npz") as d:
            return {k: d[k] for k in d.files}
