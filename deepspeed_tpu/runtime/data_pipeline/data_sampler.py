"""Curriculum data sampling — difficulty-ordered batches + seqlen truncation.

Capability parity with the reference's
``data_pipeline/data_sampling/data_sampler.py:33`` (DeepSpeedDataSampler:
difficulty-bucketed index sampling driven by a CurriculumScheduler) and the
legacy seqlen curriculum the engine applies to each batch
(runtime/engine.py curriculum hooks). Two pieces:

  * DeepSpeedDataSampler — index-level: samples only examples whose
    difficulty metric is within the current threshold (metric values
    supplied as an array, the role of the reference's analyzer output).
  * apply_seqlen_curriculum — batch-level: truncate [B, S] token batches to
    the scheduled sequence length (the Megatron-style seqlen curriculum;
    note each new difficulty value compiles a fresh step, so schedules
    should move in coarse difficulty_step increments on TPU).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, Optional

import numpy as np

from .curriculum_scheduler import CurriculumScheduler

PyTree = Any


def apply_seqlen_curriculum(batch: PyTree, seqlen: int) -> PyTree:
    """Truncate every rank>=2 leaf's axis 1 (sequence) to ``seqlen``."""
    import jax

    def trunc(x):
        arr = np.asarray(x) if not hasattr(x, "ndim") else x
        if arr.ndim >= 2 and arr.shape[1] > seqlen:
            return arr[:, :seqlen]
        return arr

    return jax.tree.map(trunc, batch)


class CurriculumBatchTransform:
    """Engine-side seqlen curriculum: call on each global batch."""

    def __init__(self, config: Dict):
        self.scheduler = CurriculumScheduler(config)
        self.curriculum_type = config.get("curriculum_type", "seqlen")
        if self.curriculum_type != "seqlen":
            raise ValueError("batch-level curriculum supports "
                             f"curriculum_type='seqlen', got "
                             f"'{self.curriculum_type}' (use "
                             "DeepSpeedDataSampler for metric-based types)")

    def __call__(self, batch: PyTree, global_steps: int) -> PyTree:
        seqlen = self.scheduler.update_difficulty(global_steps)
        return apply_seqlen_curriculum(batch, seqlen)


class DeepSpeedDataSampler:
    """Difficulty-gated index sampler.

    ``difficulties``: per-example metric values (the reference reads these
    from the offline data analyzer's indexed store; any array-like works).
    Yields batches of indices drawn uniformly from examples whose difficulty
    <= the scheduler's current threshold — ramping the pool open exactly like
    the reference's curriculum sampling.
    """

    def __init__(self, difficulties, batch_size: int,
                 curriculum_config: Dict, seed: int = 1234,
                 drop_last: bool = True):
        self.difficulties = np.asarray(difficulties)
        self.order = np.argsort(self.difficulties)
        self.sorted_vals = self.difficulties[self.order]
        self.batch_size = batch_size
        self.scheduler = CurriculumScheduler(curriculum_config)
        self.rng = np.random.default_rng(seed)
        self.global_steps = 0

    def set_step(self, global_steps: int) -> None:
        self.global_steps = global_steps

    def _eligible(self) -> np.ndarray:
        thresh = self.scheduler.update_difficulty(self.global_steps)
        n = int(np.searchsorted(self.sorted_vals, thresh, side="right"))
        return self.order[:max(n, self.batch_size)]

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            pool = self._eligible()
            yield self.rng.choice(pool, size=self.batch_size,
                                  replace=len(pool) < self.batch_size)
            self.global_steps += 1
