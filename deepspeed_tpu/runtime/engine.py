"""DeepSpeedEngine — config-driven training engine, TPU-native.

Capability parity with the reference's ``deepspeed/runtime/engine.py``
(DeepSpeedEngine: forward/backward/step, train_batch, checkpoint save/load,
monitor/timer integration, ZeRO dispatch) — rebuilt around one jitted,
donated, sharded train step instead of module hooks + streams + buckets:

  reference mechanism                          TPU-native replacement
  -------------------------------------------  --------------------------------
  per-param grad hooks + bucketed allreduce    grads are scan-carried; a sharding
    (stage_1_and_2.py:836,942)                 constraint makes XLA emit fused
                                               reduce-scatter/all-reduce, overlapped
                                               by the latency-hiding scheduler
  ZeRO-3 submodule hooks + prefetch trace      params sharded by NamedSharding;
    (parameter_offload.py, coordinator)        XLA all-gathers per layer and
                                               prefetches automatically
  fp16 flat master buffers (fused_optimizer)   fp32 master pytree, ZeRO-sharded
  DynamicLossScaler python branch              lax.cond inside the compiled step
  CPU optimizer offload (CPUAdam + pinned)     host-memory donation (future: C++
                                               AVX path in ops/cpu)

The public surface keeps the reference's names: ``forward``/``backward``/
``step`` (micro-batch API), ``train_batch``/``eval_batch`` (fused API),
``save_checkpoint``/``load_checkpoint``, ``save_16bit_model``, plus the config
accessor properties user code relies on (engine.py:498-879).
"""

from __future__ import annotations

import inspect
import os
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..config import DeepSpeedConfig, load_config
from ..monitor.monitor import MonitorMaster
from ..ops.optimizers import Optimizer, build_optimizer
from ..parallel.mesh import MeshManager, build_mesh_from_config
from ..utils.logging import log_dist, logger
from ..utils.partitioning import build_tp_specs
from ..utils.timer import SynchronizedWallClockTimer, ThroughputTimer
from ..testing import chaos
from . import checkpointing as ckpt_lib
from . import heartbeat as hb
from . import sentinel as sentinel_lib
from . import straggler as straggler_lib
from .loss_scaler import LossScaler
from .lr_schedules import LRScheduler, build_schedule
# NonFiniteError moved into the sentinel ladder (round 7) — re-exported
# here because user code and tests import it from the engine module
from .sentinel import (NonFiniteError, TrainingIntegrityError,  # noqa: F401
                       TrainingSentinel)
from .state import TrainState
from .zero.stages import ZeroShardingPolicy

PyTree = Any


def _default_loss_fn(outputs, batch):
    """By default the model is assumed to return the scalar loss (the usual
    DeepSpeed contract: loss = engine(batch))."""
    return outputs


def wire_attention_config(model, config: DeepSpeedConfig):
    """Map the ``sparse_attention`` and ``sequence_parallel.mode`` config
    sections onto the model's ``attention_impl`` (reference: the
    sparse-attention section configures SparseSelfAttention modules at init,
    runtime/config.py:270-453; sequence parallelism is a TPU-native section).

    Returns the (possibly rebuilt) model. Contract: unknown sparse modes and
    unknown sequence-parallel modes RAISE — a parsed-but-ignored section
    silently running dense attention is a wrong answer, not a default.
    Models that hand-set a conflicting ``attention_impl`` also fail loudly.
    """
    sp = config.sequence_parallel
    if sp.mode not in ("ring", "ulysses"):
        raise ValueError(
            f"sequence_parallel.mode '{sp.mode}' is not supported; "
            "expected 'ring' or 'ulysses'")
    sa = config.sparse_attention
    if sa is not None:
        from ..ops.sparse_attention import SPARSITY_CONFIGS
        if sa.mode not in SPARSITY_CONFIGS:
            raise ValueError(
                f"unknown sparse attention mode '{sa.mode}'; "
                f"have {sorted(SPARSITY_CONFIGS)}")
    wants_sp = sp.sp_size > 1
    if sa is None and not wants_sp:
        return model
    from ..models.transformer import TransformerConfig
    mcfg = getattr(model, "cfg", None)
    if not isinstance(mcfg, TransformerConfig):
        if sa is not None:
            raise ValueError(
                "the sparse_attention config section requires the in-tree "
                "transformer family (models.build_model); this model has no "
                "TransformerConfig to wire the layout into")
        # sequence parallelism over a custom apply_fn: the mesh still carries
        # the seq axis; the model is responsible for its own SP attention
        logger.warning("sequence_parallel.sp_size > 1 with a non-in-tree "
                       "model: attention_impl cannot be auto-selected")
        return model
    import dataclasses as _dc
    updates = {}
    if sa is not None:
        if wants_sp:
            raise ValueError(
                "sparse_attention and sequence_parallel.sp_size > 1 cannot "
                "be combined (the layout-skip kernel is not sequence-"
                "parallel); drop one of the two sections")
        if mcfg.attention_impl not in ("auto", "sparse"):
            raise ValueError(
                f"sparse_attention config conflicts with the model's "
                f"hand-set attention_impl='{mcfg.attention_impl}'")
        items = tuple(sorted(
            (k, tuple(v) if isinstance(v, list) else v)
            for k, v in sa.model_dump().items()))
        updates = {"attention_impl": "sparse", "sparse_attention": items}
    elif wants_sp:
        if mcfg.attention_impl == "auto":
            updates = {"attention_impl": sp.mode}
        elif mcfg.attention_impl in ("ring", "ulysses") \
                and mcfg.attention_impl != sp.mode:
            raise ValueError(
                f"sequence_parallel.mode='{sp.mode}' conflicts with the "
                f"model's hand-set attention_impl='{mcfg.attention_impl}'")
        elif mcfg.attention_impl not in ("ring", "ulysses"):
            # an explicit flash/reference/sparse impl wins, but the user
            # asked for sequence parallelism — don't leave the section
            # silently dead
            logger.warning(
                "sequence_parallel.sp_size=%d with hand-set attention_impl="
                "'%s': no %s attention will run; set attention_impl='auto' "
                "to let the config section select it",
                sp.sp_size, mcfg.attention_impl, sp.mode)
    if not updates:
        return model
    new_cfg = _dc.replace(mcfg, **updates)
    if hasattr(model, "clone"):                 # flax module (Transformer)
        model = model.clone(cfg=new_cfg)
    elif hasattr(model, "pp"):                  # PipelinedTransformer
        model = type(model)(new_cfg, pp=model.pp, n_micro=model.n_micro,
                            mesh=model.mesh, backward=model.backward)
    else:
        raise ValueError(
            f"cannot rebuild model {type(model).__name__} with "
            f"attention_impl='{updates['attention_impl']}'")
    log_dist(f"attention config wired: attention_impl="
             f"'{updates['attention_impl']}'", ranks=[0])
    return model


def wire_low_precision(model, config: DeepSpeedConfig):
    """Consume ``compression_training.activation_quantization`` by rewiring
    the model's ``activation_quant`` (round 17 — the section parsed into
    ``CompressionSpec.activation_bits`` since round 6 but nothing read it:
    a parsed-but-dead section, the wire_attention_config contract).

    The low-precision step is an EXPERIMENT, not a default: it requires
    the integrity sentinel (``config.integrity.enabled``) so a quantized
    step that degrades loss rides the skip -> rollback -> abort ladder
    instead of silently training through it. Unknown bit widths and
    activation schedule offsets RAISE — silently running full precision
    would be a wrong answer.
    """
    act = (config.compression_training.model_dump()
           .get("activation_quantization") or {})
    shared = act.get("shared_parameters") or {}
    from ..models.transformer import TransformerConfig
    mcfg = getattr(model, "cfg", None)
    if not shared.get("enabled", False):
        # model-knob route (build_model(activation_quant=...)) still gets
        # the sentinel gate below when training through the engine
        if isinstance(mcfg, TransformerConfig) and mcfg.activation_quant \
                and not config.integrity.enabled:
            raise ValueError(
                "activation_quant is a gated experiment: enable the "
                "integrity sentinel (config.integrity.enabled) so bad "
                "quantized steps hit the skip/rollback ladder")
        return model
    if int(shared.get("schedule_offset", 0)):
        raise NotImplementedError(
            "activation_quantization.schedule_offset is not supported for "
            "the low-precision step (the quant lives inside the model, "
            "which does not see the step counter)")
    bits_list = [int((g.get("params") or {}).get("bits", 8))
                 for g in (act.get("different_groups") or {}).values()]
    bits = min(bits_list) if bits_list else 8
    if bits != 8:
        raise ValueError(
            f"activation_quantization bits={bits}: only 8 (blockwise int8 "
            "fake-quant; fp8 emulation rides the model knob "
            "activation_quant='fp8')")
    if not isinstance(mcfg, TransformerConfig):
        raise ValueError(
            "activation_quantization requires the in-tree transformer "
            "family (models.build_model); this model has no "
            "TransformerConfig to wire activation_quant into")
    if not config.integrity.enabled:
        raise ValueError(
            "activation_quantization is a gated experiment: enable the "
            "integrity sentinel (config.integrity.enabled) so bad "
            "quantized steps hit the skip/rollback ladder")
    if mcfg.activation_quant not in (None, "int8"):
        raise ValueError(
            f"activation_quantization conflicts with the model's hand-set "
            f"activation_quant={mcfg.activation_quant!r}")
    import dataclasses as _dc
    model = model.clone(cfg=_dc.replace(mcfg, activation_quant="int8")) \
        if hasattr(model, "clone") else model
    if getattr(getattr(model, "cfg", None), "activation_quant", None) \
            != "int8":
        raise ValueError(
            f"cannot rebuild model {type(model).__name__} with "
            "activation_quant='int8'")
    log_dist("low-precision experiment wired: activation_quant='int8' "
             "(sentinel-gated)", ranks=[0])
    return model


class DeepSpeedEngine:
    def __init__(self,
                 model,
                 config: Optional[DeepSpeedConfig | dict | str] = None,
                 model_parameters: Optional[PyTree] = None,
                 loss_fn: Optional[Callable] = None,
                 apply_fn: Optional[Callable] = None,
                 example_batch: Optional[PyTree] = None,
                 rng: Optional[jax.Array] = None,
                 sharding_rules: Optional[Dict[str, P]] = None,
                 mesh_manager: Optional[MeshManager] = None,
                 optimizer: Optional[Optimizer] = None,
                 lr_scheduler=None,
                 mpu=None):
        self.config = load_config(config)
        # sparse_attention / sequence_parallel.mode consume their config
        # sections by rewiring the model's attention_impl (VERDICT: the two
        # parsed-but-dead sections). Must happen before apply_fn is built.
        model = wire_attention_config(model, self.config)
        # compression_training.activation_quantization -> the round-17
        # low-precision step (sentinel-gated; also before apply_fn)
        model = wire_low_precision(model, self.config)
        self.module = model
        self.mesh_mgr = mesh_manager or build_mesh_from_config(self.config)
        self.mesh = self.mesh_mgr.mesh
        # ranks that receive distinct batch slices (the reference's DP world size)
        dp = self.mesh_mgr.shape["data"] * self.mesh_mgr.shape["expert"]
        self.config.resolve_batch_sizes(dp_world_size=dp)
        self.dp_world_size = dp

        # precision ----------------------------------------------------------
        self.compute_dtype = {"float16": jnp.float16, "bfloat16": jnp.bfloat16,
                              "float32": jnp.float32}[self.config.precision_dtype]
        self.keep_master = self.compute_dtype != jnp.float32
        self._pure_bf16 = (self.config.bf16.enabled
                           and not self.config.bf16.master_weights)
        if self._pure_bf16:
            # pure-bf16: params are the master, moments bf16 (config.py
            # BF16Config.master_weights) — no fp32 state anywhere.
            # (validated against the RESOLVED optimizer below)
            self.keep_master = False
        # reference: data_types.grad_accum_dtype (config.py:907) — the dtype
        # microbatch grads accumulate in; fp32 default, bf16 halves the
        # accumulator footprint (update math stays f32 in _finalize_step)
        gad = (self.config.data_types.grad_accum_dtype or "fp32").lower()
        _gad_map = {"fp32": jnp.float32, "float32": jnp.float32,
                    "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
                    "fp16": jnp.float16, "float16": jnp.float16}
        if gad not in _gad_map:
            raise ValueError(
                f"data_types.grad_accum_dtype '{gad}' is not supported; "
                f"expected one of {sorted(_gad_map)}")
        self.grad_accum_dtype = _gad_map[gad]
        fp16 = self.config.fp16
        self.loss_scaler = LossScaler(
            static_scale=fp16.loss_scale,
            initial_scale_power=fp16.initial_scale_power,
            scale_window=fp16.loss_scale_window,
            min_scale=fp16.min_loss_scale,
            hysteresis=fp16.hysteresis,
            enabled=fp16.enabled)

        # model fns ----------------------------------------------------------
        self.loss_fn = loss_fn or _default_loss_fn
        self._rng = rng if rng is not None else jax.random.PRNGKey(self.config.seed)
        self.apply_fn = apply_fn or self._build_apply_fn(model)

        # activation checkpointing section (reference:
        # runtime/activation_checkpointing/checkpointing.py:748,830): for the
        # in-house model family remat is a per-layer model knob (better
        # segmentation); for ARBITRARY user models the engine wraps the whole
        # apply_fn in jax.checkpoint under a selective policy, so the config
        # section is behavior, not a warning.
        act = self.config.activation_checkpointing
        mcfg = getattr(model, "cfg", None)
        act_on = bool(act.partition_activations or act.cpu_checkpointing
                      or act.number_checkpoints)
        if act_on and mcfg is not None and getattr(mcfg, "remat", False):
            # in-house family already segments remat per layer — honor the
            # cpu_checkpointing knob by checking the model's policy matches
            if act.cpu_checkpointing and \
                    getattr(mcfg, "remat_policy", None) != "offload":
                logger.warning(
                    "activation_checkpointing.cpu_checkpointing is set but the "
                    "model's remat_policy is %r — build the model with "
                    "remat=True, remat_policy='offload' to host-offload saved "
                    "activations", getattr(mcfg, "remat_policy", None))
        elif act_on:
            from .act_checkpoint import configure as act_configure, remat as act_remat
            act_configure(
                partition_activations=act.partition_activations,
                contiguous_checkpointing=act.contiguous_memory_optimization,
                num_checkpoints=act.number_checkpoints,
                checkpoint_in_cpu=act.cpu_checkpointing,
                profile=act.profile)
            # whole-fn remat under "full" saves nothing (backward would
            # re-materialize every residual anyway); selective "dots" /
            # host-"offload" policies are where an unsegmented wrap wins
            policy = "offload" if act.cpu_checkpointing else "dots"
            # train (argnum 3) is a python bool the apply_fn branches on
            self.apply_fn = act_remat(self.apply_fn, policy_name=policy,
                                      static_argnums=(3,))
            log_dist(f"activation checkpointing: engine-level remat of the "
                     f"user apply_fn (policy={policy})", ranks=[0])

        # compression training (QAT / pruning) --------------------------------
        # the spec transforms params INSIDE the jitted step; grads flow
        # straight-through to the raw master weights (reference: compress.py
        # init_compression wraps linears; engine.py:1395 scheduler hook)
        from ..compression import init_compression
        spec = init_compression({"compression_training":
                                 self.config.compression_training.model_dump()})
        # MoQ (reference: runtime/quantize.py) compiles into the same
        # weight-quantization machinery
        from .quantize import build_moq_spec
        moq = build_moq_spec(self.config.quantize_training)
        if moq is not None:
            spec.groups.extend(moq.groups)
        self.compression_spec = spec if spec.enabled else None
        self._moq_enabled = moq is not None
        if self.compression_spec is not None:
            log_dist(f"compression training: "
                     f"{[g.kind + ':' + g.name for g in spec.groups]}",
                     ranks=[0])

        # params -------------------------------------------------------------
        if model_parameters is None:
            if example_batch is None:
                raise ValueError("need model_parameters or example_batch to initialize")
            model_parameters = self._init_params(example_batch)
        params_f32 = jax.tree.map(lambda p: jnp.asarray(p, jnp.float32), model_parameters)

        # sharding policy ----------------------------------------------------
        stage = self.config.zero_optimization.stage
        self.zero_policy = ZeroShardingPolicy(
            stage, self.mesh_mgr,
            param_persistence_threshold=(
                self.config.zero_optimization.param_persistence_threshold))
        self.tp_specs = build_tp_specs(params_f32, sharding_rules)
        # expert params (path under an "experts" module, reference: MoE expert
        # groups carved from DP, utils/groups.py) shard ZeRO state over the
        # non-expert DP axes only
        from ..utils.partitioning import path_str
        expert_fn = lambda path: "experts" in path_str(path)
        self.param_shardings = self.zero_policy.param_shardings(
            params_f32, self.tp_specs, expert_fn)
        self.master_shardings = self.zero_policy.master_shardings(
            params_f32, self.tp_specs, expert_fn)
        self.grad_shardings = self.zero_policy.grad_shardings(
            params_f32, self.tp_specs, expert_fn)
        self.batch_sharding = self.mesh_mgr.batch_sharding()
        self._qw_gathers = None
        if self.config.zero_optimization.zero_quantized_weights:
            if stage != 3:
                raise ValueError("zero_quantized_weights needs ZeRO stage 3 "
                                 "(it quantizes the stage-3 param gathers)")
            self._qw_gathers = self._build_qw_gathers()

        # optimizer ----------------------------------------------------------
        # client-passed functional optimizer wins over the config section
        # (reference: deepspeed.initialize honors the client optimizer object)
        opt_cfg = self.config.optimizer
        if optimizer is not None:
            if not isinstance(optimizer, Optimizer):
                raise TypeError(
                    "optimizer must be a deepspeed_tpu.ops.optimizers.Optimizer "
                    "(build one with e.g. ops.optimizers.adamw(lr=...)); torch "
                    f"optimizers are not usable on TPU. Got {type(optimizer)}")
            self.optimizer: Optional[Optimizer] = optimizer
            self.base_lr = float(opt_cfg.params.get("lr", 1e-3)) if opt_cfg else 1e-3
        elif opt_cfg is not None:
            self.optimizer = build_optimizer(opt_cfg.type, opt_cfg.params)
            self.base_lr = float(opt_cfg.params.get("lr", 1e-3))
        else:
            self.optimizer = None
            self.base_lr = 0.0
        if self._pure_bf16 and (self.optimizer is None or
                                self.optimizer.name not in ("adam", "adamw")):
            # only Adam/AdamW implement the dtype round-trip; other
            # optimizers keep fp32 state, which would silently triple the
            # 6-bytes/param budget this mode exists for
            raise ValueError(
                "bf16.master_weights=false (pure-bf16 state) supports "
                "Adam/AdamW only; got optimizer "
                f"'{self.optimizer.name if self.optimizer else None}'")

        # lr schedule --------------------------------------------------------
        # lr_fn (step->lr, evaluated in-jit) when we own the schedule; an
        # external scheduler object instead feeds its lr into the step as an arg.
        self.lr_fn = None
        if lr_scheduler is not None:
            self.lr_scheduler = lr_scheduler
            if isinstance(lr_scheduler, LRScheduler):
                self.lr_fn = lr_scheduler.fn
        elif self.config.scheduler is not None and self.config.scheduler.type:
            self.lr_fn = build_schedule(self.config.scheduler.type,
                                        self.config.scheduler.params)
            self.lr_scheduler = LRScheduler(self.lr_fn)
        else:
            self.lr_scheduler = None

        # ZeRO-Offload --------------------------------------------------------
        # optimizer state + fp32 master live off-device (host RAM or NVMe);
        # the device round-trips grads out / compute-dtype params in.
        off = self.config.zero_optimization.offload_optimizer
        # offload_param: TRANSIENT device params (reference: ZeRO-3 param
        # offload keeps weights host-side and pages them in per use,
        # partition_parameters.py) — HBM holds the weights only while a
        # compiled step runs; they re-materialize from the host (cpu) or
        # NVMe (ZeRO-Infinity param tier, partitioned_param_swapper.py:35)
        # master maintained by the host optimizer.
        off_p = self.config.zero_optimization.offload_param
        self.offload = None
        if off_p is not None and off_p.device in ("cpu", "nvme") \
                and (off is None or off.device not in ("cpu", "nvme")):
            raise ValueError(
                "offload_param needs offload_optimizer (the host-resident "
                "master the transient params re-materialize from)")
        if off is not None and off.device in ("cpu", "nvme"):
            if optimizer is not None:
                raise ValueError(
                    "offload_optimizer needs the optimizer declared in the "
                    "config (type + params) so the host kernel can be built; "
                    "a client optimizer object cannot be offloaded")
            if opt_cfg is None:
                raise ValueError("offload_optimizer requires an 'optimizer' "
                                 "config section")
            from .zero.offload import HostOffloadOptimizer
            self.offload = HostOffloadOptimizer(
                opt_cfg.type, opt_cfg.params, params_f32,
                self.param_shardings, self.compute_dtype,
                device=off.device, nvme_path=off.nvme_path,
                buffer_count=off.buffer_count,
                aio_config=self.config.aio.model_dump(),
                param_device=("nvme" if off_p is not None
                              and off_p.device == "nvme" else "ram"),
                param_nvme_path=(off_p.nvme_path if off_p is not None
                                 else None),
                param_buffer_count=(off_p.buffer_count if off_p is not None
                                    else 5))
        self._transient_params = bool(
            self.offload is not None and off_p is not None
            and off_p.device in ("cpu", "nvme"))

        # 1-bit explicit-collective mode --------------------------------------
        # onebit optimizers only save wire bytes if the grad sync is explicit:
        # the OneBitRunner owns the whole train step (per-rank grads out of
        # shard_map, compressed momentum exchange after freeze_step).
        self.onebit = None
        opt_key = (opt_cfg.type.lower().replace("_", "")
                   if opt_cfg is not None else "")
        if (self.offload is None and optimizer is None
                and self.optimizer is not None
                and opt_key in ("onebitadam", "zerooneadam", "onebitlamb")
                and self.mesh_mgr.shape["data"] > 1):
            for ax in ("model", "seq", "pipe", "expert"):
                if self.mesh_mgr.shape[ax] != 1:
                    raise ValueError(
                        f"1-bit optimizers support pure data parallelism; "
                        f"mesh axis '{ax}' has size {self.mesh_mgr.shape[ax]}")
            if stage > 1:
                raise ValueError(
                    "1-bit optimizers compose with ZeRO stage 0 or 1 "
                    "(optimizer-state sharding); stages >= 2 shard GRADS, "
                    "which defeats the stacked per-rank layout the "
                    "compressed momentum exchange is built on")
            if self.compression_spec is not None:
                raise ValueError(
                    "compression_training is not threaded through the 1-bit "
                    "explicit-collective step yet — disable one of the two")
            if opt_key == "zerooneadam":
                # 0/1 Adam is a DIFFERENT algorithm from 1-bit Adam
                # (adaptive variance freezing + 1-bit sync with local
                # steps, reference onebit/zoadam.py) — own runner
                from .zeroone import ZeroOneRunner
                runner_cls, head = ZeroOneRunner, ()
            else:
                from .onebit import OneBitRunner
                runner_cls = OneBitRunner
                head = ("lamb" if "lamb" in opt_key else "adam",)
            self.onebit = runner_cls(
                *head, opt_cfg.params, self.mesh, "data",
                self.apply_fn, self.loss_fn,
                self.config.gradient_accumulation_steps,
                compute_dtype=self.compute_dtype,
                grad_clip=self.config.gradient_clipping,
                loss_scaler=self.loss_scaler,
                zero_stage=stage)

        # comm-plan: per-collective algorithm selection (round 10;
        # docs/COMM.md) ------------------------------------------------------
        # Policy resolves HERE (programs are static); execution routes
        # through comm.planned -> runtime/comm/quantized.py. The MoE
        # dispatch reads the same context at trace time via the apply_fn
        # wrap, so one plan steers both wire-hot seams.
        self.comm_plan_ctx = None
        self._cp_guard = None
        self._train_step_q = None
        self._overlap_gathers = None
        cp = self.config.comm_plan
        if cp.enabled:
            from ..comm_plan import CommPlan
            from ..comm_plan.runtime import AccuracyGuard, PlanContext
            plan = CommPlan.load(cp.plan_path) if cp.plan_path else None
            self.comm_plan_ctx = PlanContext(
                plan=plan, overrides=dict(cp.overrides or {}),
                bits=cp.quant_bits, block=cp.quant_block,
                size_threshold=int(cp.size_threshold_mb * 2 ** 20),
                overlap_chunks=cp.overlap_chunks)
            self.apply_fn = self._wrap_apply_comm_plan(self.apply_fn)
            self._resolve_grad_sync_algo(params_f32)
            self._resolve_param_gather(params_f32)
            if cp.guard_min_grad_norm > 0:
                self._cp_guard = AccuracyGuard(cp.guard_min_grad_norm)
            log_dist(
                "comm plan: "
                f"plan={'recorded:' + cp.plan_path if cp.plan_path else 'heuristic'} "
                f"grad_sync={self.comm_plan_ctx.resolved.get('grad_reduce_scatter')} "
                f"param_gather={self.comm_plan_ctx.resolved.get('param_all_gather')} "
                f"overlap_chunks={cp.overlap_chunks} "
                f"overrides={dict(cp.overrides or {})} "
                f"guard={cp.guard_min_grad_norm}", ranks=[0])

        # device placement of state -----------------------------------------
        # fp32 training: params ARE the master copy — TrainState.master is kept
        # empty so the same buffers aren't donated twice through the pytree.
        if self.onebit is not None:
            # fp32 params, replicated (pure DP); runner casts for compute
            params = jax.device_put(params_f32,
                                    NamedSharding(self.mesh, P()))
            master = ()
        elif self.offload is not None:
            params = (() if self._transient_params
                      else self.offload.current_params_device())
            master = ()
        elif self.keep_master:
            master = jax.device_put(params_f32, self.master_shardings)
            params = jax.jit(  # graftlint: disable=TPU002 (engine init: one trace per engine)
                lambda m: jax.tree.map(lambda x: x.astype(self.compute_dtype), m),
                out_shardings=self.param_shardings)(master)
        else:
            # fp32 (params are f32 already — no transient host copy) or
            # pure-bf16 (cast down; no master)
            cast = (params_f32 if self.compute_dtype == jnp.float32
                    else jax.tree.map(
                        lambda x: x.astype(self.compute_dtype), params_f32))
            params = jax.device_put(cast, self.param_shardings)
            master = ()
        opt_state = {}
        if self.onebit is not None:
            opt_state = {"onebit": self.onebit.init_state(params_f32)}
            self.opt_shardings = jax.tree.map(lambda x: x.sharding, opt_state)
        elif self.offload is not None:
            self.opt_shardings = {}
        else:
            self.opt_shardings = self._opt_state_shardings(params_f32)
            if self.optimizer is not None:
                opt_state = jax.jit(self.optimizer.init,  # graftlint: disable=TPU002 (engine init: one trace per engine)
                                    out_shardings=self.opt_shardings)(
                                        master if self.keep_master else params)
        # scalars placed REPLICATED ON THE MESH, matching the canonical
        # sharding the compiled step emits for its outputs — a
        # SingleDeviceSharding here is a different jit cache key and cost a
        # spurious retrace of the whole program on the second step
        rep = NamedSharding(self.mesh, P())
        self.state = TrainState(
            step=jax.device_put(jnp.asarray(0, jnp.int32), rep),
            params=params,
            master=master,
            opt_state=opt_state,
            scale=jax.tree.map(lambda x: jax.device_put(x, rep),
                               self.loss_scaler.init()),
            skipped_steps=jax.device_put(jnp.asarray(0, jnp.int32), rep),
            nonfinite_streak=jax.device_put(jnp.asarray(0, jnp.int32), rep))
        # offload mode applies updates on host — its consecutive
        # non-finite count lives host-side too (no extra device traffic)
        self._host_nonfinite_streak = 0

        # compiled fns -------------------------------------------------------
        if self.offload is not None:
            self._grads_step = self._make_grads_step()
            self._train_step = None
        elif self.onebit is not None:
            self._grads_step = None
            self._train_step = None           # the runner owns the step
        else:
            self._grads_step = None
            self._train_step = self._make_train_step()
        self._micro_grad = self._make_micro_grad()
        self._fwd_loss = self._make_fwd_loss(train=True)
        self._fwd_loss_eval = None          # built lazily on first eval use
        self._apply_update = self._make_apply_update()
        self._eval_step = self._make_eval_step()

        # fwd/bwd/step emulation buffers -------------------------------------
        self._accum_grads = None
        self._accum_losses = []
        self._micro_count = 0
        self._last_metrics: Dict[str, Any] = {}

        # observability ------------------------------------------------------
        self.monitor = MonitorMaster(self.config)
        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(batch_size=self.config.train_batch_size)
        self.global_steps = 0
        self.micro_steps = 0

        # training-integrity sentinel (round 7; docs/RESILIENCE.md): host
        # detector over the in-jit step statistics, remediation ladder
        # (skip -> rollback -> abort), cross-replica SDC audit. The PR-3
        # nonfinite_guard streak/abort lives inside observe() — one code
        # path for every "wrong numbers" verdict.
        self.sentinel = TrainingSentinel(self.config.integrity)
        self._audit_fn = None
        # the checkpoint dir the audit marker lands in and the rollback
        # default — tracks the last save/load; an explicit
        # integrity.load_dir always wins at rollback time (a pinned
        # known-good archive must not be clobbered by a routine save)
        self._ckpt_dir: Optional[str] = self.config.integrity.load_dir
        # global batches consumed since data start: checkpointed, NOT
        # rolled back by a sentinel rollback (the poisoned span is
        # fast-forwarded past, never replayed); feeds
        # fast_forward_dataloader at resume
        self.data_position = 0
        if self.sentinel.enabled or self.config.integrity.audit_interval > 0:
            log_dist(
                f"integrity sentinel: metrics={self.config.integrity.metrics} "
                f"zmax={self.config.integrity.zmax} "
                f"skip={self.config.integrity.skip} "
                f"rollback_after={self.config.integrity.rollback_after} "
                f"audit_interval={self.config.integrity.audit_interval}",
                ranks=[0])

        # phase-aware watchdog + rank heartbeat channel (rounds 4+6;
        # docs/RESILIENCE.md): the engine reports lifecycle phases
        # (RESTORE -> COMPILE -> STEP -> SAVE), each with its own deadline;
        # a gap beyond the current phase's deadline dumps all stacks and
        # exits STALL_EXIT_CODE so the supervisor can tear the world down.
        # The heartbeat writer (opt-in via DSTPU_HEARTBEAT_DIR, exported by
        # dstpu --heartbeat-dir) mirrors every phase/step transition to a
        # per-rank file so LAUNCHER-side monitors get liveness even for
        # ranks whose ssh pipe (or scheduler) is silent.
        self.heartbeat = hb.HeartbeatWriter.from_env(
            rank=jax.process_index())
        self._step_phase_reached = False
        self.watchdog = None
        wd = self.config.watchdog
        pre_step = {hb.PHASE_COMPILE: wd.compile_timeout,
                    hb.PHASE_RESTORE: wd.restore_timeout,
                    hb.PHASE_SAVE: wd.save_timeout}
        if wd.stall_timeout > 0 or any(t > 0 for t in pre_step.values()):
            from .watchdog import StallWatchdog
            self.watchdog = StallWatchdog(
                wd.stall_timeout or 0.0,
                poll_interval=wd.poll_interval or None,
                phase_timeouts=pre_step,
                heartbeat=self.heartbeat,
                phase=hb.PHASE_INIT)
            if any(t > 0 for t in pre_step.values()):
                # pre-step deadlines need the monitor BEFORE the first
                # completed step — the round-4 blind spot (a compile or
                # restore hang) is exactly what they bound. The INIT
                # phase itself stays unbounded here (init_deadline's
                # jurisdiction); the clock starts mattering at the first
                # phase transition.
                self.watchdog.start()
            log_dist(f"watchdog configured: stall={wd.stall_timeout}s "
                     f"compile={wd.compile_timeout}s "
                     f"restore={wd.restore_timeout}s "
                     f"save={wd.save_timeout}s", ranks=[0])
        if self.heartbeat is not None:
            self.heartbeat.write(hb.PHASE_INIT, 0, force=True)

        # straggler defense (round 15; runtime/straggler.py,
        # docs/RESILIENCE.md): the rolling step_ms gauge is stamped into
        # every STEP heartbeat unconditionally (it is just timekeeping —
        # `dstpu health` renders it as RATE); the cross-rank detector is
        # opt-in. Each rank runs the SAME detector over the SAME shared
        # channel snapshot and acts only on verdicts against ITSELF (the
        # SDC self-flagging pattern): rung 1 stamps the sticky STRAGGLER
        # flag, rung 3 (straggler.abort_after > 0) exits rc 117 so the
        # elastic agent relaunches the world without this host.
        self._step_clock = straggler_lib.StepClock(
            window=self.config.straggler.window)
        self.straggler: Optional[straggler_lib.StragglerDetector] = None
        self._straggler_next_check = 0.0
        self._straggler_flagged = False
        if self.config.straggler.enabled and self.heartbeat is not None:
            self.straggler = straggler_lib.StragglerDetector(
                self.config.straggler)
            log_dist(
                f"straggler detector: zmax={self.config.straggler.zmax} "
                f"rel_threshold={self.config.straggler.rel_threshold} "
                f"strike_window={self.config.straggler.strike_window} "
                f"abort_after={self.config.straggler.abort_after}"
                + (" (evidence-only)"
                   if self.config.straggler.abort_after <= 0 else ""),
                ranks=[0])

        # progressive layer drop + eigenvalue (reference: engine hooks for
        # runtime/progressive_layer_drop.py + runtime/eigenvalue.py) ---------
        self.progressive_layer_drop = None
        if self.config.progressive_layer_drop.enabled:
            from .progressive_layer_drop import ProgressiveLayerDrop
            pld_cfg = self.config.progressive_layer_drop
            self.progressive_layer_drop = ProgressiveLayerDrop(
                theta=pld_cfg.theta, gamma=pld_cfg.gamma)
        self.eigenvalue = None
        if self.config.eigenvalue.enabled:
            from .eigenvalue import Eigenvalue
            ev = self.config.eigenvalue
            self.eigenvalue = Eigenvalue(
                verbose=ev.verbose, max_iter=ev.max_iter, tol=ev.tol,
                stability=ev.stability,
                gas_boundary_resolution=ev.gas_boundary_resolution,
                layer_name=ev.layer_name, layer_num=ev.layer_num)

        # data efficiency: seqlen curriculum (reference: engine curriculum
        # hooks + data_pipeline/data_sampling) -------------------------------
        self.curriculum = None
        cl_cfg = None
        if self.config.curriculum_learning.enabled:
            cl_cfg = self.config.curriculum_learning.model_dump()
        elif self.config.data_efficiency.enabled:
            clc = (self.config.data_efficiency.data_sampling or {}).get(
                "curriculum_learning") or {}
            if clc.get("enabled"):
                cl_cfg = clc
        if cl_cfg is not None:
            from .data_pipeline import CurriculumBatchTransform
            self.curriculum = CurriculumBatchTransform(cl_cfg)
            log_dist(f"curriculum learning: {cl_cfg.get('curriculum_type', 'seqlen')} "
                     f"{cl_cfg['min_difficulty']}->{cl_cfg['max_difficulty']} "
                     f"({cl_cfg.get('schedule_type', 'fixed_linear')})",
                     ranks=[0])

        from ..config.config import warn_unconsumed
        warn_unconsumed(self.config)
        log_dist(f"DeepSpeedEngine initialized: ZeRO stage {stage}, "
                 f"dtype {self.config.precision_dtype}, mesh {self.mesh_mgr.describe()}, "
                 f"batch {self.config.train_batch_size} "
                 f"(micro {self.config.train_micro_batch_size_per_gpu} x gas "
                 f"{self.config.gradient_accumulation_steps} x dp {dp})", ranks=[0])

    # ------------------------------------------------------------------ setup

    def _build_apply_fn(self, model) -> Callable:
        """Adapt a flax module (or raw callable) to (params, batch, rng, train)."""
        if model is None:
            raise ValueError("model must be a flax module or apply_fn given")
        if not hasattr(model, "apply"):
            # raw callable(params, batch) -> outputs
            return lambda params, batch, rng, train: model(params, batch)
        sig = None
        try:
            sig = inspect.signature(model.__call__)
        except (TypeError, ValueError):
            pass
        takes_train = sig is not None and "train" in sig.parameters

        # probe once whether .apply accepts rngs (flax does; plain objects with
        # an .apply attribute may not) — a runtime try/except would swallow
        # genuine TypeErrors raised inside the model
        takes_rngs = True
        try:
            apply_sig = inspect.signature(model.apply)
            takes_rngs = ("rngs" in apply_sig.parameters or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in apply_sig.parameters.values()))
        except (TypeError, ValueError):
            pass

        # the "pld" stream is threaded only when the engine actually runs
        # progressive layer drop — unused extra rng streams through nn.scan
        # disturb the remat policy (measured bench regression)
        wants_pld = self.config.progressive_layer_drop.enabled

        def apply_fn(params, batch, rng, train):
            kwargs = {"train": train} if takes_train else {}
            if takes_rngs:
                if train:
                    r_drop, r_gate, r_pld = jax.random.split(rng, 3)
                    kwargs["rngs"] = {"dropout": r_drop, "gating": r_gate}
                    if wants_pld:
                        kwargs["rngs"]["pld"] = r_pld
                else:
                    kwargs["rngs"] = None
            return model.apply({"params": params}, batch, **kwargs)

        return apply_fn

    def _init_params(self, example_batch) -> PyTree:
        init_rng, self._rng = jax.random.split(self._rng)
        sig = None
        try:
            sig = inspect.signature(self.module.__call__)
        except (TypeError, ValueError):
            pass
        kwargs = {"train": False} if sig is not None and "train" in sig.parameters else {}
        # jit: abstract init is faster and partial-auto shard_map regions in
        # the model (ring attention, explicit-a2a MoE) require a jit context
        example_batch = jax.tree.map(jnp.asarray, example_batch)
        variables = jax.jit(  # graftlint: disable=TPU002 (param init: one trace per engine)
            lambda rng, batch: self.module.init(rng, batch, **kwargs)
        )(init_rng, example_batch)
        return variables["params"]

    def _opt_state_shardings(self, params_f32):
        """Optimizer-state slots are param-shaped trees (m/v/momentum/...);
        shard each exactly like the fp32 master so updates stay local."""
        if self.optimizer is None:
            return {}
        shape_state = jax.eval_shape(self.optimizer.init, params_f32)
        treedef = jax.tree.structure(params_f32)
        master_flat = jax.tree.leaves(self.master_shardings)

        def per_slot(sub):
            try:
                treedef.flatten_up_to(sub)
                return jax.tree.unflatten(treedef, master_flat)
            except (ValueError, TypeError):
                return jax.tree.map(
                    lambda ls: NamedSharding(
                        self.mesh, self.zero_policy.master_spec(ls.shape, None)),
                    sub)

        return {k: per_slot(v) for k, v in shape_state.items()}

    # ----------------------------------------------------------- compiled fns

    def _build_qw_gathers(self):
        """ZeRO++ qwZ: one quantized-gather fn per ZeRO-sharded param leaf
        (reference: ZeRO++'s quantized weight communication; the int8 gather
        replaces the implicit bf16 stage-3 all-gather)."""
        from .comm.compressed import make_quantized_gather

        def per_leaf(sharding):
            spec = sharding.spec
            for dim, entry in enumerate(spec):
                if entry is None:
                    continue
                names = (entry,) if isinstance(entry, str) else tuple(entry)
                zero_names = [n for n in names if n in
                              ("data", "expert", "seq")]
                if zero_names and any(
                        self.mesh_mgr.shape.get(n, 1) > 1
                        for n in zero_names):
                    return make_quantized_gather(
                        self.mesh, tuple(names), dim, spec=spec)
            return None

        return jax.tree.map(per_leaf, self.param_shardings)

    def _qw_gather_params(self, params):
        if self._qw_gathers is None:
            return params
        return jax.tree.map(
            lambda fn, p: p if fn is None else fn(p),
            self._qw_gathers, params,
            is_leaf=lambda x: x is None or callable(x))

    def _grads_of_micro(self, params, scale_state, micro, rng, step=None):
        """Scaled-loss grads for one microbatch; returns (grads, unscaled loss)."""

        def scaled_loss(p):
            # qwZ: int8 gather inside the differentiated closure so the
            # custom-vjp slice maps grads back to the shards; overlap:
            # chunked gather inside it so its transpose reduce-scatters
            # the grads in the same chunks
            p = self._qw_gather_params(p)
            p = self._overlap_gather_params(p)
            if self.compression_spec is not None:
                from ..compression import apply_compression
                p = apply_compression(
                    p, self.compression_spec,
                    step if step is not None else jnp.asarray(0, jnp.int32))
            out = self.apply_fn(p, micro, rng, True)
            loss = self.loss_fn(out, micro)
            return (loss * scale_state.scale).astype(jnp.float32), loss

        grads, loss = jax.grad(scaled_loss, has_aux=True)(params)
        grads = jax.tree.map(lambda g, s: lax.with_sharding_constraint(
            g.astype(self.grad_accum_dtype), s), grads, self.grad_shardings)
        return grads, loss

    def _finalize_step(self, state: TrainState, grads_sum, n_micro, lr_arg,
                       spike_limit=None):
        """Shared tail: unscale, clip, optimize, loss-scale bookkeeping.

        ``lr_arg``: host-computed lr (external scheduler objects); ignored when
        the schedule is an in-jit lr_fn.

        ``spike_limit``: the sentinel's grad-norm ceiling (remediation
        ladder rung 1; +inf during warmup). A step whose raw global norm
        exceeds it is skipped through the SAME keep-old-state path the
        fp16 overflow skip uses — one skip semantics for scaler overflow,
        non-finite grads, and detected spikes. ``None`` (integrity off)
        compiles the check away entirely."""
        master = state.master if self.keep_master else state.params
        denom = n_micro * state.scale.scale
        grads = jax.tree.map(lambda g: g / denom, grads_sum)
        overflow = LossScaler.has_overflow(grads)

        # global grad norm: at jit level grads are logically global, so this IS
        # the global norm; XLA inserts cross-shard reductions (reference:
        # get_global_norm + clip_grad_norm_ w/ allreduce, runtime/utils.py)
        sq = sum(jnp.sum(jnp.square(g)) for g in jax.tree.leaves(grads))
        global_norm = jnp.sqrt(sq)
        spiked = None
        skip = overflow
        if spike_limit is not None:
            spiked = global_norm > spike_limit
            skip = overflow | spiked
        clip = self.config.gradient_clipping
        if clip > 0:
            coef = jnp.minimum(clip / (global_norm + 1e-6), 1.0)
            grads = jax.tree.map(lambda g: g * coef, grads)

        lr = self.lr_fn(state.step) if self.lr_fn is not None else lr_arg

        new_master, new_opt = self.optimizer.update(
            grads, state.opt_state, master, state.step, lr_t=lr)
        master_sh = self.master_shardings if self.keep_master else self.param_shardings
        new_master = jax.tree.map(lambda x, s: lax.with_sharding_constraint(x, s),
                                  new_master, master_sh)

        # skip → keep old state, count a skipped step (reference: engine.step
        # overflow path engine.py:2105-2112; sentinel spikes ride the same arm)
        keep = lambda old, new: jax.tree.map(
            lambda a, b: jnp.where(skip, a, b), old, new)
        new_master = keep(master, new_master)
        new_opt = keep(state.opt_state, new_opt)

        if self.keep_master:
            new_params = jax.tree.map(
                lambda m, s: lax.with_sharding_constraint(
                    m.astype(self.compute_dtype), s),
                new_master, self.param_shardings)
        else:
            new_params = new_master

        # skip streak: consecutive skipped steps of ANY kind, counted
        # in-jit (a bf16 run has no loss scaler to notice divergence; fp16
        # counts too — a scale already at min_scale that still overflows
        # is the same signal; a sentinel spike skip is the same verdict).
        # The host only reads this in _after_step's batched pull.
        prev_streak = (state.nonfinite_streak
                       if state.nonfinite_streak is not None
                       else jnp.asarray(0, jnp.int32))
        new_streak = jnp.where(skip, prev_streak + 1, 0).astype(jnp.int32)

        # a skip does not advance the optimizer step (Adam bias correction /
        # in-jit lr schedules stay put), matching the reference's skip path;
        # the loss scale reacts to GENUINE overflow only — a finite spike
        # must not shrink a healthy fp16 scale
        new_state = TrainState(
            step=state.step + 1 - skip.astype(jnp.int32),
            params=new_params,
            master=new_master if self.keep_master else (),
            opt_state=new_opt,
            scale=self.loss_scaler.update(state.scale, overflow),
            skipped_steps=state.skipped_steps + skip.astype(jnp.int32),
            nonfinite_streak=new_streak)
        metrics = {"grad_norm": global_norm, "lr": lr, "overflow": skip,
                   "loss_scale": state.scale.scale,
                   "nonfinite_streak": new_streak}
        if spiked is not None:
            metrics["anomaly_skip"] = spiked
        integ = self.config.integrity
        if integ.enabled:
            # sentinel statistics, computed in-jit so they ride the one
            # batched host pull: the update norm (0 on a skipped step) and
            # the param norm — divergence signals a grad norm alone misses
            if "update_norm" in integ.metrics:
                usq = sum(jnp.sum(jnp.square((a - b).astype(jnp.float32)))
                          for a, b in zip(jax.tree.leaves(new_master),
                                          jax.tree.leaves(master)))
                metrics["update_norm"] = jnp.sqrt(usq)
            if "param_norm" in integ.metrics:
                psq = sum(jnp.sum(jnp.square(p.astype(jnp.float32)))
                          for p in jax.tree.leaves(new_master))
                metrics["param_norm"] = jnp.sqrt(psq)
        return new_state, metrics

    def _make_train_step(self):
        gas = self.config.gradient_accumulation_steps

        def train_step(state: TrainState, micros, rng, lr_arg,
                       spike_limit=None):
            # micros: [gas, global_micro, ...], dim 1 sharded over the DP axes
            rngs = jax.random.split(rng, gas)
            zero_grads = jax.tree.map(
                lambda p, s: lax.with_sharding_constraint(
                    jnp.zeros(p.shape, self.grad_accum_dtype), s),
                state.params, self.grad_shardings)

            def micro_step(acc, xs):
                micro, r = xs
                grads, loss = self._grads_of_micro(state.params, state.scale,
                                                   micro, r, state.step)
                acc = jax.tree.map(lambda a, g, s: lax.with_sharding_constraint(a + g, s),
                                   acc, grads, self.grad_shardings)
                return acc, loss

            grads_sum, losses = lax.scan(micro_step, zero_grads, (micros, rngs))
            new_state, metrics = self._finalize_step(
                state, grads_sum, float(gas), lr_arg, spike_limit=spike_limit)
            metrics["loss"] = jnp.mean(losses)
            return new_state, metrics

        return jax.jit(train_step, donate_argnums=(0,))

    # ------------------------------------------------- comm-plan grad sync

    def _wrap_apply_comm_plan(self, apply_fn):
        """Install the engine's plan context around every model trace so
        trace-time seams (the MoE dispatch) read THIS engine's plan —
        thread-local and scoped, so a second engine in the same process
        never inherits it."""
        from ..comm_plan.runtime import use_context
        ctx = self.comm_plan_ctx

        def wrapped(params, batch, rng, train):
            with use_context(ctx):
                return apply_fn(params, batch, rng, train)

        return wrapped

    def _grad_sync_envelope(self) -> Tuple[bool, str]:
        """Can the explicit stacked-grads sync replace the implicit XLA
        grad reduction here? Mirrors the 1-bit runner's envelope: the
        stacked per-rank layout needs a fused step the engine owns, and
        data parallelism optionally COMPOSED with TP (round 14): the
        model axis stays auto in the partial-auto stacked region, each
        leaf syncing over its own stacked layout — but only where native
        ``jax.shard_map`` exists (the 0.4.x legacy adapter aborts inside
        XLA on auto-TP operands; see utils/jax_compat)."""
        if self.onebit is not None:
            return False, "the 1-bit runner owns the train step"
        if self.offload is not None:
            return False, "offload mode splits the step across host/device"
        if self.compression_spec is not None:
            return False, ("compression_training is not threaded through "
                           "the stacked-grads step")
        ok, why = self.zero_policy.grad_sync_viable()
        if not ok:
            return False, why
        for ax in ("seq", "pipe"):
            if self.mesh_mgr.shape[ax] != 1:
                return False, (f"mesh axis '{ax}' has size "
                               f"{self.mesh_mgr.shape[ax]} (data "
                               "parallelism, optionally with TP, "
                               "required)")
        if self.mesh_mgr.shape["model"] != 1 and \
                not hasattr(jax, "shard_map"):
            return False, (f"mesh axis 'model' has size "
                           f"{self.mesh_mgr.shape['model']}: the "
                           "TP-composed explicit sync needs native "
                           "jax.shard_map (this jaxlib's legacy "
                           "shard_map aborts inside XLA on auto-TP "
                           "operands)")
        if self.mesh_mgr.shape["data"] <= 1:
            return False, "a single DP rank has nothing to sync"
        return True, ""

    def _resolve_grad_sync_algo(self, params_f32) -> None:
        """Init-time resolution of the ZeRO-2 grad-sync wire format
        (programs are static, so the verdict is per-engine, modulo the
        accuracy guard's host-side exact fallback). A verdict outside
        the envelope — forced or selected — DEGRADES to exact with a
        warning (round 14: selection and overrides must never brick a
        launch; the envelope test pins which configs degrade)."""
        from ..comm_plan.runtime import resolve_algo
        ctx = self.comm_plan_ctx
        itemsize = jnp.dtype(self.grad_accum_dtype).itemsize
        grad_bytes = sum(
            int(np.prod(np.shape(p)) if np.shape(p) else 1)
            for p in jax.tree.leaves(params_f32)) * itemsize
        n = self.mesh_mgr.shape["data"] * self.mesh_mgr.shape["expert"]
        algo = resolve_algo(ctx, "grad_reduce_scatter", "data", grad_bytes,
                            axis_size=n)
        if algo != "exact":
            ok, why = self._grad_sync_envelope()
            if not ok:
                forced = any((ctx.overrides or {}).get(k)
                             for k in ("grad_reduce_scatter",
                                       "reduce_scatter"))
                logger.warning(
                    "comm_plan: grad sync %s %r but %s — running exact",
                    "forced" if forced else "selected", algo, why)
                algo = "exact"
                ctx.resolved["grad_reduce_scatter"] = "exact"
        self._grad_sync_algo = algo

    # --------------------------------------------- comm-plan param gather

    def _param_gather_viable(self) -> Tuple[bool, str]:
        """Engine-side envelope for the explicit chunked ZeRO-3 param
        fetch (per-leaf checks live in ``_resolve_param_gather``)."""
        if self.zero_policy.stage < 3:
            return False, ("ZeRO stage < 3 keeps compute params whole — "
                           "there is no param gather to overlap")
        if self._qw_gathers is not None:
            return False, ("zero_quantized_weights already owns the "
                           "explicit param gather (qwZ)")
        if self.offload is not None:
            return False, "offload mode splits the step across host/device"
        if self.onebit is not None:
            return False, "the 1-bit runner owns the train step"
        return True, ""

    def _resolve_param_gather(self, params_f32) -> None:
        """Per-LEAF init-time resolution of the ZeRO-3 param-fetch wire
        schedule: each ZeRO-sharded leaf queries the plan in its own
        size bucket (site ``param_all_gather`` -> kind ``all_gather``),
        and leaves the overlap family covers get an explicit chunked
        gather replacing the implicit whole-tensor stage-3 allgather.
        Leaves outside the per-leaf envelope (TP-composed specs, tiny
        leaves under ``overlap_min_leaf_elems``) stay implicit —
        downgrade, never raise."""
        from ..comm.planned import planned_param_gather
        from ..comm_plan.runtime import resolve_algo
        ctx = self.comm_plan_ctx
        cp = self.config.comm_plan
        ctx.resolved.setdefault("param_all_gather", "exact")
        ok, why = self._param_gather_viable()
        if not ok:
            forced = any((ctx.overrides or {}).get(k)
                         for k in ("param_all_gather", "all_gather"))
            if forced and self.zero_policy.stage >= 3:
                logger.warning(
                    "comm_plan: param gather forced but %s — running the "
                    "implicit gather", why)
            return
        itemsize = jnp.dtype(self.compute_dtype).itemsize
        n_overlap = 0

        def per_leaf(sharding, leaf):
            nonlocal n_overlap
            site = self.zero_policy.zero_gather_site(sharding.spec)
            numel = int(np.prod(np.shape(leaf)) if np.shape(leaf) else 1)
            if site is None or numel < cp.overlap_min_leaf_elems:
                return None
            zero_dim, zero_names = site
            algo = resolve_algo(ctx, "param_all_gather", "data",
                                numel * itemsize,
                                axis_size=int(np.prod(
                                    [self.mesh_mgr.shape[a]
                                     for a in zero_names])))
            if algo not in ("overlap", "overlap_int8"):
                return None
            n_overlap += 1
            return planned_param_gather(
                self.mesh, zero_names, zero_dim, algo=algo,
                chunks=cp.overlap_chunks, bits=cp.quant_bits,
                block=cp.quant_block)

        gathers = jax.tree.map(per_leaf, self.param_shardings, params_f32)
        if n_overlap:
            self._overlap_gathers = gathers
        # the aggregate audit tag: overlap iff ANY leaf left the
        # implicit path (per-leaf verdicts differ across size buckets)
        ctx.resolved["param_all_gather"] = (
            "overlap" if n_overlap else "exact")

    def _overlap_gather_params(self, params):
        if self._overlap_gathers is None:
            return params
        return jax.tree.map(
            lambda fn, p: p if fn is None else fn(p),
            self._overlap_gathers, params,
            is_leaf=lambda x: x is None or callable(x))

    def _make_train_step_quantized(self):
        """The comm-plan train step: per-rank grads come out of a
        shard_map UNREDUCED (the 1-bit runner's stacked layout), the sync
        is the explicit reduce-scatter + all-gather in the resolved wire
        format — blockwise-int8, or the chunked ``overlap`` schedule
        (``comm.planned_grad_sync``) — and everything from the synced
        grads on — clip, optimizer, skip arms, sentinel — is the shared
        ``_finalize_step`` tail, so the two programs differ ONLY in how
        grad bytes cross the wire. With TP composed (round 14, native
        jax.shard_map only) the model axis stays AUTO: params ride in
        TP-sharded, the model trace keeps its TP constraints (the
        local region strips only the manual DP axes), and each grad
        leaf syncs over its own stacked layout."""
        gas = self.config.gradient_accumulation_steps
        axes = self.zero_policy.grad_sync_axes()
        cp = self.config.comm_plan
        algo = self._grad_sync_algo
        mesh = self.mesh
        tp_composed = self.mesh_mgr.shape["model"] > 1
        from ..comm.planned import planned_grad_sync
        from ..comm_plan.runtime import local_region
        from ..utils.jax_compat import shard_map

        def local(params, micros_all, rng, scale):
            r = jax.random.fold_in(rng, lax.axis_index(axes))
            rngs = jax.random.split(r, gas)

            def body(acc, xs):
                micro, rr = xs

                def scaled_loss(p):
                    # shard-local model trace: manual-axis mesh
                    # constraints don't apply here (local_region makes
                    # _spec_constraint a no-op / strips the manual axes
                    # when TP rides along as an auto axis)
                    with local_region(manual_axes=set(axes)
                                      if tp_composed else None):
                        out = self.apply_fn(p, micro, rr, True)
                        loss = self.loss_fn(out, micro)
                    return (loss * scale).astype(jnp.float32), loss

                grads, loss = jax.grad(scaled_loss, has_aux=True)(params)
                acc = jax.tree.map(
                    lambda a, g: a + g.astype(self.grad_accum_dtype),
                    acc, grads)
                return acc, loss

            zero = jax.tree.map(
                lambda p: jnp.zeros(p.shape, self.grad_accum_dtype), params)
            gsum, losses = lax.scan(body, zero, (micros_all, rngs))
            return (jax.tree.map(lambda g: g[None], gsum), losses[None])

        mapped = shard_map(local, mesh=mesh,
                           in_specs=(P(), P(None, axes), P(), P()),
                           out_specs=(P(axes), P(axes)),
                           axis_names=set(axes), check_vma=False)

        def train_step(state, micros, rng, lr_arg, spike_limit=None):
            grads_st, losses_st = mapped(state.params, micros, rng,
                                         state.scale.scale)
            synced = jax.tree.map(
                lambda g: planned_grad_sync(
                    g, mesh=mesh, axis=axes, algo=algo,
                    bits=cp.quant_bits, block=cp.quant_block, mean=True,
                    chunks=cp.overlap_chunks),
                grads_st)
            grads_sum = jax.tree.map(
                lambda g, s: lax.with_sharding_constraint(
                    g.astype(self.grad_accum_dtype), s),
                synced, self.grad_shardings)
            new_state, metrics = self._finalize_step(
                state, grads_sum, float(gas), lr_arg,
                spike_limit=spike_limit)
            metrics["loss"] = jnp.mean(losses_st)
            return new_state, metrics

        return jax.jit(train_step, donate_argnums=(0,))

    def _active_train_step(self):
        """Pick the per-step program: the explicit-sync step when the
        plan routed it, unless the accuracy guard latched exact (both
        stay compiled — switching is free after the first use of each).
        The guard applies to LOSSY wire formats only: ``overlap`` moves
        exact values, so forcing it back to the whole-tensor schedule
        would change nothing numerically."""
        from ..comm_plan.plan import QUANTIZED_ALGOS
        algo = getattr(self, "_grad_sync_algo", "exact")
        guard_latched = (self._cp_guard is not None
                         and self._cp_guard.use_exact
                         and algo in QUANTIZED_ALGOS)
        if (self.comm_plan_ctx is not None and algo != "exact"
                and not guard_latched):
            if self._train_step_q is None:
                self._train_step_q = self._make_train_step_quantized()
            return self._train_step_q, algo
        return self._train_step, "exact"

    def _make_grads_step(self):
        """Offload mode: the compiled step ends at the summed grads — the
        optimizer runs on the host (reference: cpu_offload grads land in CPU
        buffers and CPUAdam consumes them, stage_1_and_2.py:1074)."""
        gas = self.config.gradient_accumulation_steps

        def grads_step(params, scale_state, micros, rng, step):
            rngs = jax.random.split(rng, gas)
            zero_grads = jax.tree.map(
                lambda p, s: lax.with_sharding_constraint(
                    jnp.zeros(p.shape, self.grad_accum_dtype), s),
                params, self.grad_shardings)

            def micro_step(acc, xs):
                micro, r = xs
                grads, loss = self._grads_of_micro(params, scale_state, micro,
                                                   r, step)
                acc = jax.tree.map(
                    lambda a, g, s: lax.with_sharding_constraint(a + g, s),
                    acc, grads, self.grad_shardings)
                return acc, loss

            grads_sum, losses = lax.scan(micro_step, zero_grads, (micros, rngs))
            overflow = LossScaler.has_overflow(grads_sum)
            sq = sum(jnp.sum(jnp.square(g)) for g in jax.tree.leaves(grads_sum))
            return grads_sum, jnp.mean(losses), jnp.sqrt(sq), overflow

        return jax.jit(grads_step)

    def _apply_offload_update(self, grads_sum, n_micro: float, loss,
                              raw_norm, overflow) -> Dict[str, Any]:
        """Host tail of the offload step: unscale/clip folded into the C++
        kernel's grad_scale, loss-scale bookkeeping on host."""
        state = self.state
        overflow_h = bool(jax.device_get(overflow))
        scale = float(jax.device_get(state.scale.scale))
        denom = n_micro * scale
        gnorm = float(jax.device_get(raw_norm)) / denom
        # sentinel rung 1 on the host tail (the offload optimizer runs
        # host-side, so the skip decision can too — same semantics as the
        # in-jit arm, same keep-old-state outcome)
        limit = self.sentinel.spike_limit()
        spiked = bool(limit is not None and gnorm > limit)
        skip = overflow_h or spiked
        new_scale = self.loss_scaler.update(state.scale,
                                            jnp.asarray(overflow_h))
        clip = self.config.gradient_clipping
        coef = min(clip / (gnorm + 1e-6), 1.0) if clip > 0 else 1.0
        if self.lr_fn is not None:
            lr = float(jax.device_get(self.lr_fn(state.step)))
        else:
            lr = float(jax.device_get(self._current_lr()))
        self._host_nonfinite_streak = (
            self._host_nonfinite_streak + 1 if skip else 0)
        if skip:
            self.state = state.replace(
                scale=new_scale,
                skipped_steps=state.skipped_steps + 1,
                nonfinite_streak=jnp.asarray(self._host_nonfinite_streak,
                                             jnp.int32))
        else:
            step_1based = int(jax.device_get(state.step)) + 1
            new_params = self.offload.apply(
                grads_sum, step_1based, lr, grad_scale=denom / coef,
                materialize=not self._transient_params)
            self.state = state.replace(
                step=state.step + 1,
                params=() if self._transient_params else new_params,
                scale=new_scale,
                nonfinite_streak=jnp.asarray(0, jnp.int32))
        out = {"loss": loss, "lr": lr, "grad_norm": gnorm,
               "overflow": skip, "loss_scale": scale,
               "nonfinite_streak": self._host_nonfinite_streak}
        if limit is not None:
            out["anomaly_skip"] = spiked
        return out

    def _make_micro_grad(self):
        def micro_grad(params, scale_state, batch, rng, step):
            grads, loss = self._grads_of_micro(params, scale_state, batch, rng,
                                               step)
            return grads, loss

        return jax.jit(micro_grad)

    def _make_fwd_loss(self, train: bool = True):
        """Forward-only loss for one microbatch — no backward pass compiled
        in. ``train`` feeds the model's mode flag: the eval-mode program
        runs deterministically (dropout off), the reference's eval/no_grad
        forward."""
        def fwd_loss(params, batch, rng, step):
            params = self._qw_gather_params(params)
            params = self._overlap_gather_params(params)
            if self.compression_spec is not None:
                from ..compression import apply_compression
                params = apply_compression(params, self.compression_spec, step)
            out = self.apply_fn(params, batch, rng, train)
            return self.loss_fn(out, batch)

        return jax.jit(fwd_loss)

    def _make_apply_update(self):
        def apply_update(state, grads_sum, n_micro, lr_arg, spike_limit=None):
            return self._finalize_step(state, grads_sum, n_micro, lr_arg,
                                       spike_limit=spike_limit)

        return jax.jit(apply_update, donate_argnums=(0,))

    def _make_eval_step(self):
        def eval_step(params, batch, rng, step):
            params = self._qw_gather_params(params)
            params = self._overlap_gather_params(params)
            if self.compression_spec is not None:
                from ..compression import apply_compression
                params = apply_compression(params, self.compression_spec, step)
            out = self.apply_fn(params, batch, rng, False)
            return out

        return jax.jit(eval_step)

    # -------------------------------------------------------------- public API

    def _current_lr(self):
        """Host-side lr for the next step (used when no in-jit lr_fn owns it)."""
        if self.lr_fn is None and self.lr_scheduler is not None and \
                hasattr(self.lr_scheduler, "get_lr"):
            return jnp.asarray(float(self.lr_scheduler.get_lr()[0]), jnp.float32)
        return jnp.asarray(self.base_lr, jnp.float32)

    def _params_device(self):
        """Device params for a compute call — in offload_param transient mode
        the weights live host-side and materialize here (freed when the
        returned pytree is dropped after the step)."""
        if self._transient_params:
            return self.offload.current_params_device()
        return self.state.params

    def shard_batch(self, batch):
        """Place a host batch onto the mesh, split over the DP axes."""
        return jax.tree.map(
            lambda x: jax.device_put(jnp.asarray(x), self.batch_sharding), batch)

    def next_rng(self):
        self._rng, out = jax.random.split(self._rng)
        return out

    # --- lifecycle phase reporting (watchdog deadlines + heartbeat file) ----

    def _report_phase(self, phase: str) -> None:
        """Move the watchdog clock into ``phase`` and mirror the
        transition to the per-rank heartbeat file (phase transitions
        always write; only same-phase repeats are throttled)."""
        if self.watchdog is not None:
            self.watchdog.start().enter_phase(phase, step=self.global_steps)
        if self.heartbeat is not None:
            self.heartbeat.write(phase, self.global_steps, force=True)
        if phase != hb.PHASE_STEP:
            # the gap spanning a non-step phase (COMPILE, RESTORE) must
            # not be charged to the step_ms gauge as a step
            self._step_clock.reset()

    def _phase_scope(self, phase: str):
        """Bracket a bounded lifecycle section (RESTORE/SAVE): the phase's
        own deadline applies inside, and the previous phase resumes with a
        fresh clock on exit."""
        import contextlib
        if self.heartbeat is not None:
            self.heartbeat.write(phase, self.global_steps, force=True)
        # the section's duration must not pollute the step_ms gauge (a
        # checkpoint save is not a slow step); the next step boundary
        # re-baselines the clock
        self._step_clock.reset()
        if self.watchdog is not None:
            self.watchdog.start()
            return self.watchdog.phase_scope(phase)
        return contextlib.nullcontext()

    def train_batch(self, batch) -> Dict[str, Any]:
        """Run one full global batch (all gas microbatches) in one compiled step.

        The fused fast path — equivalent to gas x (forward+backward) + step of
        the reference, with comm/compute overlap handled by XLA. The batch's
        leading dim is the global batch size; it is split [gas, micro] on the
        host so each microbatch stays contiguous per DP shard."""
        if self.optimizer is None:
            raise RuntimeError(
                "engine has no optimizer: add an 'optimizer' section to the "
                "config or pass optimizer= to initialize()")
        # run-phase failpoints (testing/chaos.py; armed via DSTPU_CHAOS in
        # subprocess chaos tests, no-ops otherwise): a crashing, preempted
        # or wedged rank at a step boundary
        chaos.failpoint("run.kill")
        chaos.failpoint("run.preempt")
        chaos.failpoint("run.hang")
        # degraded-not-dead: sleep mode (with every=/p= jitter) makes THIS
        # rank slow while it keeps stepping — the straggler-defense shape
        # no dead/wrong check can see (spec e.g.
        # "run.slow:sleep:ms=300:times=0")
        chaos.failpoint("run.slow")
        # sentinel chaos: a poisoned batch — float features scaled by
        # `factor`, producing the finite-but-huge grad spike the integrity
        # ladder exists to remediate (spec e.g.
        # "sentinel.spike:flag:skip=10:times=3:factor=1000")
        spike = chaos.flag("sentinel.spike")
        if spike is not None:
            batch = jax.tree.map(
                lambda x: (np.asarray(x) * spike
                           if np.issubdtype(np.asarray(x).dtype, np.floating)
                           else x), batch)
        if not self._step_phase_reached:
            # the window from the FIRST train_batch entry to the first
            # completed step is COMPILE (XLA compile + sharded-restore
            # materialization) — bounded by watchdog.compile_timeout, a
            # hang the round-4 step-armed clock could never see
            self._report_phase(hb.PHASE_COMPILE)
            chaos.failpoint("run.compile_hang")
        from ..parallel.mesh import BATCH_AXES
        if self.curriculum is not None:
            batch = self.curriculum(batch, self.global_steps)
        if self.progressive_layer_drop is not None and isinstance(batch, dict):
            theta = self.progressive_layer_drop.update_state(self.global_steps)
            bsz = len(next(iter(batch.values())))
            batch = dict(batch, pld_theta=np.full((bsz,), theta, np.float32))
        gas = self.config.gradient_accumulation_steps
        micro_sharding = NamedSharding(self.mesh, P(None, BATCH_AXES))
        micros = jax.tree.map(
            lambda x: jax.device_put(
                jnp.asarray(x).reshape((gas, x.shape[0] // gas) + x.shape[1:]),
                micro_sharding),
            batch)
        self.tput_timer.start()
        if self.config.wall_clock_breakdown:
            self.timers("train_batch").start()
        if self.onebit is not None:
            if self.lr_fn is not None:
                lr = float(jax.device_get(self.lr_fn(self.state.step)))
            else:
                lr = float(jax.device_get(self._current_lr()))
            # the runner's program schedule (warmup freeze / v-update and
            # local-step intervals) must count only EFFECTIVE steps: an
            # fp16 overflow reverts the optimizer state in-jit, and the
            # reference's zoadam/onebit counters do not advance on a
            # skipped torch step. state.step is exactly that count (step +
            # 1 - overflow) and survives checkpoint resume; reading it
            # costs one scalar D2H only when a scaler can actually skip
            scaler = getattr(self.onebit, "loss_scaler", None)
            sched_step = (int(jax.device_get(self.state.step))
                          if scaler is not None and scaler.enabled
                          else self.global_steps)
            new_p, new_s, loss, norm, overflow, new_scale = self.onebit.step(
                self.state.params, self.state.opt_state["onebit"], micros,
                self.next_rng(), lr, sched_step,
                scale_state=self.state.scale)
            # bookkeeping stays on device (no host sync mid-dispatch), the
            # fused path's step + 1 - overflow convention: overflow does not
            # advance the optimizer step
            ovf_i32 = overflow.astype(jnp.int32)
            prev_streak = (self.state.nonfinite_streak
                           if self.state.nonfinite_streak is not None
                           else jnp.asarray(0, jnp.int32))
            new_streak = jnp.where(overflow, prev_streak + 1,
                                   0).astype(jnp.int32)
            self.state = self.state.replace(
                step=self.state.step + 1 - ovf_i32, params=new_p,
                opt_state={"onebit": new_s}, scale=new_scale,
                skipped_steps=self.state.skipped_steps + ovf_i32,
                nonfinite_streak=new_streak)
            metrics = {"loss": loss, "lr": lr, "grad_norm": norm,
                       "overflow": overflow,
                       "loss_scale": new_scale.scale,
                       "nonfinite_streak": new_streak}
        elif self.offload is not None:
            grads_sum, loss, raw_norm, overflow = self._grads_step(
                self._params_device(), self.state.scale, micros,
                self.next_rng(), self.state.step)
            metrics = self._apply_offload_update(grads_sum, float(gas), loss,
                                                 raw_norm, overflow)
        else:
            step_fn, sync_algo = self._active_train_step()
            limit = self._spike_limit_arg()
            if limit is None:
                self.state, metrics = step_fn(
                    self.state, micros, self.next_rng(), self._current_lr())
            else:
                self.state, metrics = step_fn(
                    self.state, micros, self.next_rng(), self._current_lr(),
                    limit)
            if self.comm_plan_ctx is not None:
                # host-side audit tags: which wire format this step's grad
                # sync actually ran (tests + the guard's visibility), and
                # whether the ZeRO-3 param fetch left the implicit path
                metrics["grad_sync_algo"] = sync_algo
                metrics["param_gather_algo"] = \
                    self.comm_plan_ctx.resolved.get("param_all_gather",
                                                    "exact")
        self.tput_timer.stop(sync=metrics["loss"])
        if self.config.wall_clock_breakdown:
            # the jitted step is one program: the breakdown the reference
            # logs per phase (fwd/bwd/step) collapses into step wall time +
            # sustained throughput (reference: engine wall_clock_breakdown
            # timer logs, engine.py:2240). timers.log logs internally; the
            # normalizer turns the accumulated window into a PER-STEP time
            self.timers("train_batch").stop(sync=metrics["loss"])
            if (self.global_steps + 1) % self.config.steps_per_print == 0:
                self.timers.log(["train_batch"],
                                normalizer=float(self.config.steps_per_print))
                log_dist(f"throughput: "
                         f"{self.tput_timer.avg_samples_per_sec:.1f} "
                         "samples/sec", ranks=[0])
        self._after_step(metrics)
        # counted AFTER remediation: a sentinel rollback preserves the
        # pipeline position (the poisoned span is never replayed), and
        # this batch was consumed regardless of its verdict
        self.data_position += 1
        return metrics

    def eval_batch(self, batch):
        batch = self.shard_batch(batch)
        out = self._eval_step(self._params_device(), batch, self.next_rng(),
                              self.state.step)
        if self.watchdog is not None:
            # evaluation progress is liveness too: a long validation pass
            # between optimizer steps must not read as a training stall
            self.watchdog.beat()
        if self.heartbeat is not None:
            self.heartbeat.write(hb.PHASE_STEP, self.global_steps)
        return out

    # --- micro-batch API (reference forward/backward/step contract) ----------

    def train(self, mode: bool = True):
        """Switch the micro-batch API to training mode (reference: the
        engine is an nn.Module — users call engine.train()/engine.eval()).
        In training mode forward() runs the fused value-and-grad program and
        caches the grads for backward() — the XLA analogue of torch autograd
        'building the graph' during a training forward — so a
        forward/backward pair costs exactly one fwd+bwd, the same FLOPs as
        the fused train_batch path (round-3 Weak #4: the recompute made it
        ~1.5x)."""
        self._train_mode = bool(mode)
        return self

    def eval(self):
        """Inference mode: forward() compiles only the forward pass (no
        gradient residuals — the cost model of the reference's eval/no_grad
        forward)."""
        return self.train(False)

    def forward(self, batch):
        """Loss for one microbatch.

        Training mode (default, reference parity: torch modules start in
        train mode): fused value_and_grad — the loss comes back immediately
        and the microbatch's grads are cached for backward(). Eval mode:
        deterministic forward-only program (dropout off), no backward
        compiled in — scoring loops should call engine.eval() first.
        """
        batch = self.shard_batch(batch)
        rng = self.next_rng()
        params_dev = self._params_device()
        train_mode = getattr(self, "_train_mode", True)
        if train_mode and self.onebit is None:
            grads, loss = self._micro_grad(params_dev, self.state.scale,
                                           batch, rng, self.state.step)
        elif train_mode:
            # 1-bit mode: training goes through the runner's train_batch;
            # a bare forward is still the train-mode (stochastic) forward
            grads = None
            loss = self._fwd_loss(params_dev, batch, rng, self.state.step)
        else:
            grads = None
            if self._fwd_loss_eval is None:
                self._fwd_loss_eval = self._make_fwd_loss(train=False)
            loss = self._fwd_loss_eval(params_dev, batch, rng,
                                       self.state.step)
        # transient (offload_param) mode: the grads were computed from this
        # materialization already; dropping params_dev here frees the
        # full-model device copy between forward and backward
        del params_dev
        prev = getattr(self, "_pending", None)
        if grads is not None and prev is not None and prev[3] is not None:
            # a fused-gradient forward whose predecessor's grads were never
            # consumed: scoring loops that never call backward() are paying
            # the fused fwd+bwd program (FLOPs + a full gradient pytree)
            # per call — make the train-mode default diagnosable instead of
            # silent. (The 1-bit branch runs a forward-only program, so
            # it never counts; backward() resets the streak.)
            self._fwd_no_bwd = getattr(self, "_fwd_no_bwd", 0) + 1
            if self._fwd_no_bwd >= 3:
                from ..utils.logging import warning_once
                warning_once(
                    "3+ train-mode forward() calls without backward(): "
                    "each one runs the fused forward+backward program and "
                    "materializes gradients. For scoring/inference call "
                    "engine.eval() first (forward-only program, no "
                    "gradient residuals).")
        self._pending = (batch, rng, loss, grads)
        if self.watchdog is not None:
            # micro-API liveness: scoring loops (eval-mode forward, no
            # step()) must not read as a training stall
            self.watchdog.beat()
        if self.heartbeat is not None:
            self.heartbeat.write(hb.PHASE_STEP, self.global_steps)
        return loss

    __call__ = forward

    def backward(self, loss=None):
        """Accumulate grads for the last forward's microbatch (reference:
        engine.backward scales by 1/gas and fires reduction hooks). The
        grads were already produced by the training forward's fused program
        — this call only accumulates them into the gas window."""
        if self.onebit is not None:
            # inference-style forward() is fine in 1-bit mode; the TRAINING
            # micro API is not — the compressed momentum exchange needs
            # per-rank grads, which only the fused train_batch step produces
            raise NotImplementedError(
                "backward()/step() are not supported with 1-bit optimizers "
                "on a multi-rank mesh — use train_batch()")
        if not hasattr(self, "_pending") or self._pending is None:
            raise RuntimeError("backward() called before forward()")
        batch, rng, loss_val, grads = self._pending
        self._pending = None
        self._fwd_no_bwd = 0          # the pair completed: not a scoring loop
        if grads is None:
            # eval-mode forward has no gradient residuals (that is its cost
            # model); silently differentiating a DIFFERENT computation
            # (train-mode dropout) here would be wrong numerics
            raise RuntimeError(
                "backward() after an eval-mode forward — call "
                "engine.train() before training forwards (grads are "
                "computed by the training forward and cached)")
        if self._accum_grads is None:
            self._accum_grads = grads
        else:
            self._accum_grads = jax.tree.map(jnp.add, self._accum_grads, grads)
        self._accum_losses.append(loss_val)
        self._micro_count += 1
        self.micro_steps += 1
        return loss_val

    def is_gradient_accumulation_boundary(self) -> bool:
        return self._micro_count >= self.config.gradient_accumulation_steps

    def step(self):
        """Apply the optimizer at the gas boundary; no-op otherwise."""
        if not self.is_gradient_accumulation_boundary():
            return
        if self.offload is not None:
            grads = self._accum_grads
            overflow = LossScaler.has_overflow(grads)
            # norm stays on device: float() per leaf was one blocking D2H
            # transfer per param tensor per step (graftlint TPU001); the
            # single sync happens in _apply_offload_update's device_get
            sq = sum(jnp.sum(jnp.square(g))
                     for g in jax.tree.leaves(grads))
            metrics = self._apply_offload_update(
                grads, float(self._micro_count),
                jnp.mean(jnp.stack(self._accum_losses)),
                jnp.sqrt(sq), overflow)
        else:
            n = jnp.asarray(float(self._micro_count), jnp.float32)
            limit = self._spike_limit_arg()
            if limit is None:
                self.state, metrics = self._apply_update(
                    self.state, self._accum_grads, n, self._current_lr())
            else:
                self.state, metrics = self._apply_update(
                    self.state, self._accum_grads, n, self._current_lr(),
                    limit)
            metrics["loss"] = jnp.mean(jnp.stack(self._accum_losses))
        # one shared tail: _after_step (and the SDC audit's collective
        # inside it) runs on every arm — a per-arm tail would put a
        # conditional return between paired collectives (TPU013)
        self._accum_grads = None
        self._accum_losses = []
        self._micro_count = 0
        self._after_step(metrics)
        self.data_position += 1
        return metrics

    def _after_step(self, metrics):  # graftlint: hotpath
        self.global_steps += 1
        self._step_phase_reached = True
        if self.watchdog is not None:
            # step progress IS the liveness signal (dispatch completed; a
            # wedged collective never reaches this line). start() is
            # idempotent — the first completed step arms the clock, and
            # entering STEP retires the COMPILE deadline.
            self.watchdog.start().enter_phase(hb.PHASE_STEP,
                                              step=self.global_steps)
        if self.heartbeat is not None:
            # throttled: same-phase records within min_interval are dropped.
            # The rolling step_ms gauge rides along (None before the first
            # completed step gap — `dstpu health` shows '-' until then)
            gauge = self._step_clock.mark()
            self.heartbeat.write(
                hb.PHASE_STEP, self.global_steps,
                extra=({straggler_lib.STEP_MS_GAUGE: gauge}
                       if gauge is not None else None))
            self._maybe_check_straggler()
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
        self._last_metrics = metrics
        print_step = self.global_steps % self.config.steps_per_print == 0
        if print_step or self.sentinel.wants_every_step \
                or self._cp_guard is not None:
            # one batched D2H pull for every scalar the logging tier AND
            # the integrity sentinel read (graftlint TPU001: per-scalar
            # float() here was 3-4 separate blocking transfers per print
            # step). The skip streak and the sentinel statistics ride the
            # SAME pull — enabling the detector costs per-step cadence on
            # this one transfer, never an extra sync. The comm-plan
            # accuracy guard reads grad_norm off the same pull (its
            # documented cost: per-step cadence when enabled).
            keys = set(self.sentinel.metric_keys)
            if self._cp_guard is not None:
                keys.add("grad_norm")
            host = jax.device_get({k: metrics[k]
                                   for k in keys if k in metrics})
            if self._cp_guard is not None and "grad_norm" in host:
                self._cp_guard.observe(float(host["grad_norm"]))
            # one code path for every "wrong numbers" verdict: the folded
            # nonfinite_guard streak abort (NonFiniteError), anomaly
            # strikes, and the post-rollback abort all live in observe()
            verdict = self.sentinel.observe(self.global_steps, host)
            if print_step:
                if self.monitor.enabled:
                    events = [("Train/Samples/train_loss",
                               float(host["loss"]), self.global_steps),
                              ("Train/Samples/lr", float(host["lr"]),
                               self.global_steps)]
                    if self.loss_scaler.enabled:
                        events.append(("Train/Samples/loss_scale",
                                       float(host["loss_scale"]),
                                       self.global_steps))
                    self.monitor.write_events(events)
                log_dist(f"step={self.global_steps} "
                         f"loss={float(host['loss']):.4f} "
                         f"lr={float(host['lr']):.3e} "
                         f"grad_norm={float(host['grad_norm']):.3f}",
                         ranks=[0])
            if verdict == sentinel_lib.ROLLBACK:
                self._sentinel_rollback()
        self._maybe_sdc_audit()
        self._autotuning_hook()

    def _autotuning_hook(self):
        """Script-mode autotuning (reference: engine autotuning exit after
        end_profile_step): when the autotuner launched this run, write the
        measured throughput and stop."""
        import os
        at = self.config.autotuning
        metric_file = os.environ.get("DS_AUTOTUNING_METRIC_FILE")
        if not (at.enabled and metric_file):
            return
        if self.global_steps < at.end_profile_step:
            return
        import json
        import sys
        tput = self.tput_timer.avg_samples_per_sec
        metrics = {"throughput": float(tput) if tput else 0.0,
                   "train_batch_size": self.config.train_batch_size,
                   "steps": self.global_steps}
        with open(metric_file, "w") as f:
            json.dump(metrics, f)
        log_dist(f"autotuning: wrote {metric_file}, exiting", ranks=[0])
        sys.exit(0)

    # --------------------------------------------- training-integrity sentinel

    def _spike_limit_arg(self):
        """The sentinel's grad-norm ceiling as a device scalar for the
        compiled step, or None when rung 1 is off. Always a float (+inf
        during warmup) once the rung is on, so the compiled program's arg
        structure — and its cache entry — never changes mid-run."""
        thr = self.sentinel.spike_limit()
        if thr is None:
            return None
        return jnp.asarray(thr, jnp.float32)

    def _maybe_check_straggler(self):
        """Worker-side straggler ladder (runtime/straggler.py), run at
        ``straggler.check_interval`` cadence off the step path: read the
        shared heartbeat channel, run the cross-rank detector, and act on
        verdicts against THIS rank — rung 1 stamps the sticky STRAGGLER
        flag (blacklist evidence, health-visible), rung 3 exits rc 117
        so the degraded world relaunches without this host. Every rank
        sees the same snapshot, so self-verdicts need no coordination."""
        det = self.straggler
        if det is None:
            return
        now = time.monotonic()
        if now < self._straggler_next_check:
            return
        self._straggler_next_check = now + \
            self.config.straggler.check_interval
        records = hb.read_heartbeats(self.heartbeat.directory)
        mine = det.observe(records).get(self.heartbeat.rank)
        if mine is None:
            return
        if not self._straggler_flagged:
            self._straggler_flagged = True
            logger.error(
                "straggler: this rank's step time is %s MADs above the "
                "world median for %d consecutive windows — stamping the "
                "STRAGGLER heartbeat flag (host %s)",
                self.config.straggler.zmax,
                self.config.straggler.strike_window, self.heartbeat.host)
            self.heartbeat.add_flag(straggler_lib.STRAGGLER_FLAG,
                                    lock_timeout=5.0)
        if mine == straggler_lib.ABORT:
            # the rc-117 path: the terminal STALLED record lets
            # scheduler-flattening backends reconstruct the rc, and the
            # voluntary 117 exit + the flag are the agent's strike
            self.heartbeat.stamp_terminal(hb.PHASE_STALLED,
                                          lock_timeout=5.0)
            raise straggler_lib.StragglerAbort(
                f"rank {self.heartbeat.rank} ({self.heartbeat.host}) "
                f"persistently slow past straggler.abort_after="
                f"{self.config.straggler.abort_after} windows — exiting "
                f"rc {straggler_lib.STALL_EXIT_CODE} so the elastic agent "
                "relaunches the world without this host")

    def _sentinel_rollback(self):
        """Remediation rung 2: restore the newest intact checkpoint via
        the PR-3 verified loader; the data pipeline is NOT rewound — its
        position survives the restore, so the poisoned span is
        deterministically fast-forwarded past rather than replayed."""
        load_dir = self.config.integrity.load_dir or self._ckpt_dir
        if not load_dir:
            raise TrainingIntegrityError(
                "sentinel rollback requested (strikes: "
                f"{self.sentinel.last_anomaly}) but no checkpoint directory "
                "is known — set integrity.load_dir or save a checkpoint "
                "before enabling the rollback rung")
        from_step = self.global_steps
        position = self.data_position
        logger.error(
            "integrity sentinel: rolling back from step %d (%s) to the "
            "newest intact checkpoint under %s", from_step,
            self.sentinel.last_anomaly, load_dir)
        try:
            # an explicit resolve (newest intact) rather than tag=None: the
            # post-SDC audited-clean preference must not apply to an
            # in-run anomaly rollback, where latest-intact is the target
            tag = ckpt_lib.resolve_load_tag(
                load_dir, check_digests=self.config.checkpoint.verify_load)
            self.load_checkpoint(load_dir, tag=tag)
        except (FileNotFoundError, OSError,
                ckpt_lib.CheckpointIntegrityError) as e:
            raise TrainingIntegrityError(
                f"sentinel rollback from step {from_step} failed: no intact "
                f"checkpoint under {load_dir} ({e}); aborting with rc "
                f"{sentinel_lib.INTEGRITY_EXIT_CODE}") from e
        self.data_position = position
        self.sentinel.note_rollback(self.global_steps)
        log_dist(
            f"integrity sentinel: rolled back to step {self.global_steps} "
            f"(tag {tag}); data pipeline continues at batch {position} — "
            "the poisoned span is skipped, not replayed", ranks=[0])

    def fast_forward_dataloader(self, loader, batches_per_step: int = 1):
        """Deterministically position ``loader`` past the data this
        engine's (restored) state already consumed: ``data_position``
        global batches, checkpointed in client state. The resume path
        after a rollback-abort or an SDC relaunch — re-feeding the
        poisoned span would re-trigger the very anomaly the restart is
        recovering from. ``batches_per_step`` scales for loaders yielding
        microbatches. Returns the number of batches skipped."""
        ff = getattr(loader, "fast_forward", None)
        if ff is None:
            raise TypeError(
                f"{type(loader).__name__} has no fast_forward(n); wrap it "
                "in deepspeed_tpu.runtime.dataloader.RepeatingLoader or use "
                "DeepSpeedDataLoader")
        n = self.data_position * int(batches_per_step)
        ff(n)
        return n

    # -- cross-replica SDC audit ---------------------------------------------

    def _maybe_sdc_audit(self):
        iv = self.config.integrity.audit_interval
        if iv <= 0 or self.global_steps % iv != 0:
            return
        self._run_sdc_audit()

    def _audit_state_leaves(self):
        """(path, leaf) for every FULLY-REPLICATED leaf of params + master
        + optimizer state. Only replicated leaves are auditable: each
        device holds its own complete copy, so a checksum program with no
        collectives yields per-device values that MUST agree — a sharded
        leaf's per-device bytes differ legitimately, and a global
        reduction would mix a corrupted replica's bytes into every
        device's answer, hiding the minority."""
        tree = {"params": self.state.params, "master": self.state.master,
                "opt_state": self.state.opt_state}
        out = []
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            sharding = getattr(leaf, "sharding", None)
            if sharding is None or not getattr(
                    sharding, "is_fully_replicated", False):
                continue
            if getattr(leaf, "dtype", None) is None or \
                    leaf.dtype.itemsize not in (1, 2, 4) or leaf.ndim == 0:
                # scalars (step counters, scale) churn every step and are
                # cheap to recompute; the audit exists for the big state
                continue
            out.append((ckpt_lib.path_str(path), leaf))
        return out

    def _make_audit_fn(self):
        """Bit-exact checksum program over the auditable leaves: bitcast
        to unsigned words, position-weight (so two swapped elements can't
        cancel), wraparound-sum to one uint32. No collectives — each
        device audits its own replica's bytes."""
        def checksum(leaves):
            total = jnp.zeros((), jnp.uint32)
            words = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32}
            for x in leaves:
                if x.dtype == jnp.bool_:
                    x = x.astype(jnp.uint8)
                u = lax.bitcast_convert_type(x, words[x.dtype.itemsize])
                u = u.astype(jnp.uint32).reshape(-1)
                # idx+1: every position gets a DISTINCT nonzero weight —
                # an |1-style weight would give neighbors 2k/2k+1 the same
                # one, letting a swapped or compensating pair cancel
                idx = jnp.arange(u.size, dtype=jnp.uint32)
                total = total + jnp.sum(u * (idx + jnp.uint32(1)))
            return total

        return jax.jit(checksum)

    def _run_sdc_audit(self):
        """One cross-replica audit: per-device checksums, a host-side
        majority vote (cross-process via one small allgather), SDC flag +
        abort on a minority replica. The audit's device_get happens every
        ``audit_interval`` steps, never on the step hot path."""
        # chaos: silent per-process bit corruption, keyed by process index
        # ("sentinel.sdc:flag:match=1" flips a bit on rank 1 only)
        if chaos.flag("sentinel.sdc",
                      key=str(jax.process_index())) is not None:
            self._inject_sdc_bitflip()
        named = self._audit_state_leaves()
        if not named:
            from ..utils.logging import warning_once
            warning_once(
                "integrity.audit_interval is set but no state leaf is "
                "fully replicated (ZeRO-3 shards everything): the "
                "cross-replica SDC audit has nothing to compare")
            return
        if self._audit_fn is None:
            self._audit_fn = self._make_audit_fn()
        out = self._audit_fn(tuple(leaf for _, leaf in named))
        local = np.asarray(
            [[jax.process_index(), sh.device.id, int(sh.data)]
             for sh in out.addressable_shards], np.uint32)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            world = np.asarray(multihost_utils.process_allgather(local))
            rows = world.reshape(-1, 3)
        else:
            rows = local
        pairs = [(f"proc{int(p)}/dev{int(d)}", int(v)) for p, d, v in rows]
        bad = sentinel_lib.compare_replica_checksums(pairs)
        if not bad:
            self.sentinel.note_clean_audit(self.global_steps)
            if self._ckpt_dir:
                tag = ckpt_lib.get_latest_tag(self._ckpt_dir)
                if tag:
                    # the newest tag existed under a clean audit: the safe
                    # resume point for a post-SDC relaunch
                    sentinel_lib.write_last_audited_clean(self._ckpt_dir,
                                                          tag)
            return
        mine = f"proc{jax.process_index()}/"
        logger.error(
            "integrity audit: cross-replica checksum MISMATCH at step %d — "
            "implicated replicas: %s (checksums: %s)", self.global_steps,
            bad, pairs)
        if self.heartbeat is not None and any(k.startswith(mine)
                                              for k in bad):
            # blacklist evidence: the elastic agent strikes this host via
            # the PR-6 quarantine path; bounded lock — the abort below
            # must not wait on a wedged refresher
            self.heartbeat.add_flag(sentinel_lib.SDC_FLAG,
                                    step=self.global_steps,
                                    lock_timeout=2.0)
        raise TrainingIntegrityError(
            f"cross-replica SDC detected at step {self.global_steps}: "
            f"replica checksums diverged (implicated: {bad}). The live "
            "state is not trustworthy; relaunch resumes from the last "
            "audited-clean checkpoint")

    def _inject_sdc_bitflip(self):
        """Chaos-only: flip one bit in the LAST local device's copy of the
        first auditable leaf — the userspace approximation of a chip
        silently corrupting memory (every other replica keeps the true
        bytes, which is exactly what the majority vote needs)."""
        named = self._audit_state_leaves()
        if not named:
            return
        path, leaf = next(((p, l) for p, l in named
                           if p.startswith("params/")), named[0])
        shards = list(leaf.addressable_shards)
        bufs = [np.array(np.asarray(s.data)) for s in shards]
        flat = bufs[-1].view(np.uint8).reshape(-1)
        flat[0] ^= 1
        arrs = [jax.device_put(b, s.device) for b, s in zip(bufs, shards)]
        flipped = jax.make_array_from_single_device_arrays(
            leaf.shape, leaf.sharding, arrs)
        tree = {"params": self.state.params, "master": self.state.master,
                "opt_state": self.state.opt_state}
        flat_tree, treedef = jax.tree_util.tree_flatten_with_path(tree)
        leaves = [flipped if ckpt_lib.path_str(p) == path else l
                  for p, l in flat_tree]
        new_tree = jax.tree_util.tree_unflatten(treedef, leaves)
        self.state = self.state.replace(params=new_tree["params"],
                                        master=new_tree["master"],
                                        opt_state=new_tree["opt_state"])
        logger.warning("chaos sentinel.sdc: flipped one bit of %s on "
                       "device %s", path, shards[-1].device)

    # ------------------------------------------------------------- accessors

    def profile_trace(self, log_dir: str, batches, warmup: int = 1):
        """Capture a jax profiler trace (xplane, TensorBoard-loadable) over
        the given train batches — the TPU face of the reference's tracing
        aux (SURVEY §5: torch profiler ranges -> jax.profiler.trace).

        ``batches``: iterable of global batches; the first ``warmup`` steps
        run OUTSIDE the trace so compile time doesn't drown the timeline.
        Returns log_dir."""
        batches = list(batches)
        if len(batches) <= warmup:
            raise ValueError(
                f"profile_trace needs more than warmup={warmup} batches "
                f"(got {len(batches)}) — the traced region would be empty")
        for batch in batches[:warmup]:
            self.train_batch(batch)
        with jax.profiler.trace(log_dir):
            for batch in batches[warmup:]:
                m = self.train_batch(batch)
            jax.block_until_ready(m["loss"])
        log_dist(f"profiler trace written to {log_dir}", ranks=[0])
        return log_dir

    def compute_eigenvalue(self, batch):
        """Max Hessian eigenvalue of the loss on ``batch`` (reference:
        engine eigenvalue hook at gas boundaries, feeding MoQ)."""
        if self.eigenvalue is None:
            raise RuntimeError("enable the 'eigenvalue' config section")
        batch = self.shard_batch(batch)
        return self.eigenvalue.compute_eigenvalue(
            self._ensure_eig_loss(), self._params_device(), self.next_rng(),
            loss_args=(batch, self.next_rng()))

    def _ensure_eig_loss(self):
        """STABLE loss closure (batch/rng flow through loss_args) so the
        eigenvalue's jitted HVP step caches across calls."""
        if not hasattr(self, "_eig_loss"):
            def _eig_loss(p, batch, rng):
                out = self.apply_fn(p, batch, rng, True)
                return self.loss_fn(out, batch)
            self._eig_loss = _eig_loss
        return self._eig_loss

    def moq_rescale(self, batch):
        """Curvature-paced MoQ (reference: quantize.py eigenvalue gating):
        measure the Hessian eigenvalue on ``batch`` and stretch the MoQ bit
        schedule's period proportionally. Recompiles the train step with the
        updated spec."""
        if not getattr(self, "_moq_enabled", False) or self.eigenvalue is None:
            raise RuntimeError("moq_rescale needs both quantize_training and "
                               "eigenvalue enabled")
        if not hasattr(self, "_moq_scheduler"):
            from .quantize import MoQScheduler
            self._moq_scheduler = MoQScheduler(self.compression_spec,
                                               self.eigenvalue)
        sharded = self.shard_batch(batch)
        new_spec = self._moq_scheduler.maybe_rescale(
            self._ensure_eig_loss(), self._params_device(), self.next_rng(),
            loss_args=(sharded, self.next_rng()))
        if new_spec is not self.compression_spec:
            self.compression_spec = new_spec
            if self._train_step is not None:
                self._train_step = self._make_train_step()
        return self.compression_spec

    def get_lr(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler.get_lr()
        return [self.base_lr]

    def get_global_grad_norm(self) -> float:
        m = self._last_metrics.get("grad_norm")
        return float(m) if m is not None else 0.0

    def get_loss_scale(self) -> float:
        return float(jax.device_get(self.state.scale.scale))

    @property
    def skipped_steps(self) -> int:
        """Reference-parity overflow-skip counter; the truth lives on device
        in TrainState (no per-step host sync)."""
        return int(jax.device_get(self.state.skipped_steps))

    @property
    def train_batch_size(self):
        return self.config.train_batch_size

    def train_micro_batch_size_per_gpu(self):
        return self.config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self):
        return self.config.gradient_accumulation_steps

    def zero_optimization_stage(self):
        return self.config.zero_optimization.stage

    def set_train_batch_size(self, train_batch_size: int):
        """reference: engine.set_train_batch_size (engine.py:440) — adjusts gas."""
        if train_batch_size % (self.config.train_micro_batch_size_per_gpu *
                               self.dp_world_size) != 0:
            raise ValueError(f"train_batch_size {train_batch_size} incompatible")
        self.config.gradient_accumulation_steps = train_batch_size // (
            self.config.train_micro_batch_size_per_gpu * self.dp_world_size)
        self.config.train_batch_size = train_batch_size
        if self.offload is not None:
            self._grads_step = self._make_grads_step()
        else:
            self._train_step = self._make_train_step()

    def module_state_dict(self) -> Dict[str, np.ndarray]:
        return ckpt_lib._tree_to_flat_dict(self._params_device())

    def load_module_state_dict(self, state_dict: Dict[str, np.ndarray],
                               strict: bool = True):
        """Load weights only (reference: engine.load_module_state_dict,
        engine.py:2582) — the inverse of ``module_state_dict``. Leaves are
        re-placed with the engine's param shardings, and EVERY weight
        representation follows: the fp32 master (else the next step would
        recompute params from the stale master, silently discarding the
        load) and the offloaded host master. Optimizer state, loss scale,
        and counters are untouched (use load_checkpoint for full resume).
        ``strict=False`` keeps current values for missing keys and ignores
        unexpected ones."""
        from jax.tree_util import tree_flatten_with_path
        if self.offload is not None:
            # reference the host masters LAZILY (thunk leaves): no device
            # materialization (transient mode exists because the model
            # doesn't fit), no eager copy of the optimizer slots — only
            # the leaves MISSING from the state_dict are ever read
            ref_tree = self.offload.state_dict(lazy=True)["master"]
        else:
            ref_tree = self.state.params
        keys = [ckpt_lib.path_str(p)
                for p, _ in tree_flatten_with_path(ref_tree)[0]]
        if strict:
            missing = sorted(set(keys) - set(state_dict))
            unexpected = sorted(set(state_dict) - set(keys))
            if missing or unexpected:
                raise KeyError(
                    f"state_dict mismatch: missing={missing[:5]} "
                    f"unexpected={unexpected[:5]} (strict=True)")

        if self.offload is not None:
            # fp32 masters take loaded values ONLY for keys present in the
            # state_dict (merging absent keys from the bf16 device params
            # would round them — the lossy-master failure this method
            # exists to prevent); absent leaves are never even read —
            # a partial load costs I/O proportional to what it loads
            updates = {j: state_dict[k]
                       for j, k in enumerate(keys) if k in state_dict}
            if updates:
                self.offload.update_master_leaves(updates)
            if self._transient_params:
                return                      # nothing device-resident to touch

        def place_present(tree):
            # present keys re-place onto the leaf's sharding; ABSENT keys
            # keep the live device leaf — no host gather, no re-upload
            clp, ctd = tree_flatten_with_path(tree)
            return jax.tree.unflatten(ctd, [
                jax.device_put(jnp.asarray(state_dict[k], dtype=leaf.dtype),
                               leaf.sharding)
                if (k := ckpt_lib.path_str(p)) in state_dict else leaf
                for p, leaf in clp])

        params = place_present(self.state.params)
        master = self.state.master
        if self.keep_master and master != ():
            master = place_present(master)
        self.state = self.state.replace(params=params, master=master)

    # ----------------------------------------------------------- checkpointing

    def _ckpt_view(self, lazy: bool = False):
        """State as checkpointed: fp32 mode aliases params into the master slot;
        offload mode surfaces the host-resident master/opt-state pytrees.

        ``lazy=True`` (sync saves only): offload leaves become thunks so the
        streaming writer never holds more than one leaf — with an async
        engine the copies must be eager or the writer thread would race the
        next step's in-place master updates."""
        if self.offload is not None:
            sd = self.offload.state_dict(lazy=lazy)
            params = (self.offload.host_params(lazy=lazy)
                      if self._transient_params else self.state.params)
            return self.state.replace(params=params,
                                      master=sd["master"],
                                      opt_state={"offload": sd["state"]})
        return self.state if self.keep_master else self.state.replace(
            master=self.state.params)

    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None,
                        client_state: Optional[dict] = None):
        if not hasattr(self, "checkpoint_engine"):
            from ..checkpoint.engine import build_checkpoint_engine
            self.checkpoint_engine = build_checkpoint_engine(self.config)
        return self._save_checkpoint_with(self.checkpoint_engine, save_dir,
                                          tag, client_state)

    def _save_checkpoint_with(self, ckpt_engine, save_dir: str,
                              tag: Optional[str],
                              client_state: Optional[dict] = None):
        """Shared body of the periodic save and the preemption-time
        emergency save (which forces a synchronous engine). Runs in the
        SAVE phase: save time is IO-bound and legitimately unbounded by
        step time (save_timeout=0, the default, keeps it unbounded; a
        positive save_timeout bounds a save wedged on dead storage)."""
        with self._phase_scope(hb.PHASE_SAVE):
            self._ckpt_dir = save_dir      # the sentinel's rollback source
            tag = tag or f"global_step{self.global_steps}"
            client_state = dict(client_state or {})
            client_state["global_steps"] = self.global_steps
            client_state["data_position"] = self.data_position
            if self.lr_scheduler is not None and hasattr(self.lr_scheduler, "state_dict"):
                client_state["lr_scheduler"] = self.lr_scheduler.state_dict()
            lazy = getattr(ckpt_engine, "wants_lazy", True)
            ckpt = self.config.checkpoint
            return ckpt_lib.save_checkpoint(
                save_dir, tag, self._ckpt_view(lazy=lazy), client_state,
                master_aliases_params=(not self.keep_master
                                       and self.offload is None),
                ckpt_engine=ckpt_engine,
                keep_last=ckpt.keep_last,
                keep_every=ckpt.keep_every)

    def wait_for_checkpoints(self):
        """Durability barrier for async checkpointing (reference: Nebula
        commit semantics); no-op with the sync engine. Returns a truthy
        CommitResult on success; on failure it names the failed paths."""
        if hasattr(self, "checkpoint_engine"):
            return self.checkpoint_engine.commit("all")
        return True

    def close(self):
        """Explicit resource shutdown: drain + stop the async checkpoint
        writer (previously only ``__del__`` did, losing pending writes at
        interpreter teardown) and stop the stall watchdog."""
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.heartbeat is not None:
            # terminal record: launcher-side monitors must read a closed
            # engine as "concluded", not "went silent". Bounded lock: a
            # refresher wedged on dead storage must not hang the clean
            # shutdown it is merely annotating
            self.heartbeat.write(hb.PHASE_EXIT, self.global_steps,
                                 force=True, lock_timeout=2.0)
        if hasattr(self, "checkpoint_engine"):
            return self.checkpoint_engine.close()
        return True

    def _emergency_save(self, save_dir: str,
                        client_state: Optional[dict] = None) -> str:
        """Preemption-time save: drain any pending async writes (their tag
        must not interleave with ours on the FIFO worker), then write
        synchronously — the grace window is no place for a fire-and-forget
        thread.

        Overlap contract (round-4): if the drain itself just published an
        intact checkpoint of THIS step — an async save was in flight when
        the signal landed — the emergency save must NOT rewrite the same
        tag. The rewrite would burn grace-window seconds re-serializing
        the whole model, and dying mid-rewrite would leave `latest` on a
        tag whose staging debris shadows the drained publish."""
        from ..checkpoint.engine import NpzCheckpointEngine
        drained_ok = True
        if hasattr(self, "checkpoint_engine"):
            try:
                drained_ok = bool(self.checkpoint_engine.commit(
                    "preempt-drain"))
            except Exception as e:       # a failed past save must not
                drained_ok = False
                logger.error("preempt: drain of pending checkpoint "
                             "writes failed: %s", e)   # block THIS save
        tag = f"global_step{self.global_steps}"
        if drained_ok and ckpt_lib.get_latest_tag(save_dir) == tag:
            path = os.path.join(save_dir, tag)
            if ckpt_lib.verify_tag(path) is None:
                log_dist(f"preempt: drained in-flight save already "
                         f"published intact {tag}; skipping the duplicate "
                         "emergency write", ranks=[0])
                return path
        client_state = dict(client_state or {})
        client_state["preempted"] = True
        return self._save_checkpoint_with(NpzCheckpointEngine(), save_dir,
                                          None, client_state)

    def install_preemption_handler(self, save_dir: str,
                                   grace_secs: float = 30.0,
                                   client_state: Optional[dict] = None,
                                   exit_fn=None):
        """SIGTERM/SIGINT -> emergency synchronous checkpoint -> exit with
        ``PREEMPTION_EXIT_CODE`` (the rc ``DSElasticAgent`` treats as
        "resume, don't count against max_restarts").

        ``grace_secs`` is a hard deadline: if the save outruns it (TPU
        preemption notices give finite warning), a watchdog still exits
        with the preemption rc — the previous intact checkpoint carries
        the restart, which the rollback-verified loader guarantees exists.
        A second signal during the save also exits immediately.
        Returns the installed handler (tests invoke it directly)."""
        import signal
        import threading
        from ..elasticity.elastic_agent import PREEMPTION_EXIT_CODE
        exit_fn = exit_fn or os._exit
        state = {"fired": False}

        def _handler(signum=None, frame=None):
            if state["fired"]:
                exit_fn(PREEMPTION_EXIT_CODE)
                return
            state["fired"] = True
            if self.watchdog is not None:
                # the grace window is save time, not step time — the stall
                # watchdog must not shoot us mid-emergency-save (never
                # resumed: this process only leaves via exit_fn)
                self.watchdog.suspend()
            # the grace timer arms BEFORE any other work: everything past
            # this point (the heartbeat stamp, the save itself) can block
            # on dead storage, and only the timer guarantees the rc-114
            # exit still happens
            watchdog = threading.Timer(
                max(grace_secs, 0.1),
                lambda: exit_fn(PREEMPTION_EXIT_CODE))
            watchdog.daemon = True
            watchdog.start()
            if self.heartbeat is not None:
                # terminal evidence: scheduler backends flatten rc 114, so
                # the PREEMPTED record is how BackendSupervisor restores it.
                # Bounded lock: the signal may have landed INSIDE a
                # step-path heartbeat.write on this same thread — a
                # blocking re-acquire of that non-reentrant lock would
                # deadlock the handler
                self.heartbeat.write(hb.PHASE_PREEMPTED, self.global_steps,
                                     force=True, lock_timeout=2.0)
            log_dist(f"preemption (signal {signum}): emergency checkpoint "
                     f"to {save_dir} within {grace_secs}s", ranks=[0])
            try:
                self._emergency_save(save_dir, client_state)
            except Exception as e:
                logger.error("emergency save failed: %s — exiting with the "
                             "resume rc anyway (previous checkpoint stands)",
                             e)
            finally:
                watchdog.cancel()
                exit_fn(PREEMPTION_EXIT_CODE)

        signal.signal(signal.SIGTERM, _handler)
        signal.signal(signal.SIGINT, _handler)
        return _handler

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None,
                        load_module_only: bool = False):
        # RESTORE phase: a restore wedged on dead storage or a hung
        # sharded materialization is bounded by watchdog.restore_timeout
        # (and visible as RESTORE in the heartbeat channel) instead of
        # hanging the rank silently before its first step
        with self._phase_scope(hb.PHASE_RESTORE):
            return self._load_checkpoint_impl(load_dir, tag,
                                              load_module_only)

    def _load_checkpoint_impl(self, load_dir: str, tag: Optional[str],
                              load_module_only: bool):
        self._ckpt_dir = load_dir          # the sentinel's rollback source
        tag = self._prefer_audited_clean(load_dir, tag)
        if self.offload is not None:
            return self._load_checkpoint_offload(load_dir, tag, load_module_only)
        loaded, client_state = ckpt_lib.load_checkpoint(
            load_dir, tag, self._ckpt_view(),
            param_shardings=self.param_shardings,
            master_shardings=(self.master_shardings if self.keep_master
                              else self.param_shardings),
            opt_shardings=self.opt_shardings,
            verify=self.config.checkpoint.verify_load)
        if self.keep_master:
            self.state = loaded
        else:
            self.state = loaded.replace(params=loaded.master, master=())
        if not load_module_only:
            self.global_steps = client_state.get("global_steps", 0)
            # data-pipeline position: 1 global batch per step unless the
            # checkpoint recorded better (fast_forward_dataloader consumes)
            self.data_position = client_state.get("data_position",
                                                  self.global_steps)
            if self.lr_scheduler is not None and "lr_scheduler" in client_state:
                self.lr_scheduler.load_state_dict(client_state["lr_scheduler"])
        return load_dir, client_state

    def _prefer_audited_clean(self, load_dir: str,
                              tag: Optional[str]) -> Optional[str]:
        """With the SDC audit on, a ``tag=None`` resume prefers the
        ``last_audited_clean`` marker over ``latest``: tags written AFTER
        the last clean cross-replica audit may carry the corruption the
        audit later caught. An explicit tag (user intent, or the
        sentinel's own rollback resolve) is never overridden, and a
        marker naming a missing/corrupt tag falls back to the normal
        newest-intact resolution."""
        if tag is not None or self.config.integrity.audit_interval <= 0:
            return tag
        clean = sentinel_lib.read_last_audited_clean(load_dir)
        if not clean:
            return None
        reason = ckpt_lib.verify_tag(
            os.path.join(load_dir, clean),
            check_digests=self.config.checkpoint.verify_load)
        if reason is not None:
            logger.warning(
                "integrity: last_audited_clean names %r but it fails "
                "verification (%s); resuming from newest intact instead",
                clean, reason)
            return None
        log_dist(f"integrity: resuming from last audited-clean checkpoint "
                 f"'{clean}'", ranks=[0])
        return clean

    def _load_checkpoint_offload(self, load_dir, tag, load_module_only):
        """Offload mode: optimizer state stays host-side numpy — no device
        shardings are applied to masters/moments."""
        import os
        verify = self.config.checkpoint.verify_load
        if tag is None:
            tag = ckpt_lib.resolve_load_tag(load_dir, check_digests=verify)
        elif verify:
            reason = ckpt_lib.verify_tag(os.path.join(load_dir, tag))
            if reason is not None:
                raise ckpt_lib.CheckpointIntegrityError(
                    f"checkpoint {os.path.join(load_dir, tag)} failed "
                    f"verification: {reason}")
        ckpt_dir = os.path.join(load_dir, tag)
        import json
        with open(os.path.join(ckpt_dir, "meta.json")) as f:
            meta = json.load(f)
        sd_like = self.offload.state_dict()
        flat = ckpt_lib.read_flat_npz(
            os.path.join(ckpt_dir, "optim_states.npz"))
        optim = ckpt_lib._flat_dict_to_tree(
            flat, {"master": sd_like["master"],
                   "opt_state": {"offload": sd_like["state"]}})
        self.offload.load_state_dict({"master": optim["master"],
                                      "state": optim["opt_state"]["offload"]})
        from .loss_scaler import LossScaleState
        self._host_nonfinite_streak = int(meta.get("nonfinite_streak", 0))
        self.state = self.state.replace(
            step=jnp.asarray(meta["step"], jnp.int32),
            skipped_steps=jnp.asarray(meta["skipped_steps"], jnp.int32),
            nonfinite_streak=jnp.asarray(self._host_nonfinite_streak,
                                         jnp.int32),
            params=(() if self._transient_params
                    else self.offload.current_params_device()),
            scale=LossScaleState(
                scale=jnp.asarray(meta["loss_scale"], jnp.float32),
                good_steps=jnp.asarray(meta["scale_good_steps"], jnp.int32),
                hysteresis=jnp.asarray(meta["scale_hysteresis"], jnp.int32)))
        client_state = meta.get("client_state", {})
        if not load_module_only:
            self.global_steps = client_state.get("global_steps", 0)
            self.data_position = client_state.get("data_position",
                                                  self.global_steps)
            if self.lr_scheduler is not None and "lr_scheduler" in client_state:
                self.lr_scheduler.load_state_dict(client_state["lr_scheduler"])
        return load_dir, client_state

    def save_16bit_model(self, save_dir: str, save_filename: str = "pytorch_model.npz"):
        import os
        os.makedirs(save_dir, exist_ok=True)
        state = self.state
        if self._transient_params:
            state = state.replace(params=self.offload.host_params())
        ckpt_lib.save_16bit_model(state, os.path.join(save_dir, save_filename))
