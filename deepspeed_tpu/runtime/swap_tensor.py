"""Tensor swapping between host RAM and NVMe — ZeRO-Infinity's storage tier.

Role of the reference's ``deepspeed/runtime/swap_tensor/`` package
(partitioned_optimizer_swapper.py:35 OptimizerSwapper,
partitioned_param_swapper.py:35 AsyncPartitionedParameterSwapper,
async_swapper.py AsyncTensorSwapper): optimizer state / parameter partitions
live in files under the nvme_path and stream through reusable host buffers
with async reads ahead of compute and async write-back behind it.

TPU-native simplifications: partitions are numpy leaves of a pytree (not
flat torch buffers), and the double-buffered pipeline below is the whole
scheduling story — no swap-out-and-release hooks, because jax params are
immutable and the engine swaps only between steps.
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..ops.cpu.aio import AsyncIOHandle


class SwappedTensorPool:
    """A set of same-dtype tensors persisted one-file-per-tensor under a
    directory, accessed through a ring of reusable pinned-size buffers."""

    def __init__(self, root: str, names: Sequence[str],
                 shapes: Sequence[Tuple[int, ...]], dtype=np.float32,
                 aio: Optional[AsyncIOHandle] = None, buffer_count: int = 4,
                 initialize_zero: bool = True):
        self.root = root
        self.names = list(names)
        self.shapes = [tuple(s) for s in shapes]
        self.dtype = np.dtype(dtype)
        self.aio = aio or AsyncIOHandle()
        os.makedirs(root, exist_ok=True)
        self._paths = [os.path.join(root, f"{n}.swp") for n in self.names]
        max_elems = max((int(np.prod(s)) for s in self.shapes), default=1)
        self._buffers = [np.zeros(max_elems, self.dtype)
                         for _ in range(max(buffer_count, 2))]
        self._buf_i = 0
        if initialize_zero:
            zero = np.zeros(max_elems, self.dtype)
            for p, s in zip(self._paths, self.shapes):
                n = int(np.prod(s))
                self.aio.async_pwrite(zero[:n], p)
            self.aio.wait()

    def _next_buffer(self, nelems: int) -> np.ndarray:
        buf = self._buffers[self._buf_i % len(self._buffers)]
        self._buf_i += 1
        return buf[:nelems]

    def read_async(self, i: int) -> np.ndarray:
        """Submit an async read of tensor i; the view is valid after wait()."""
        n = int(np.prod(self.shapes[i]))
        view = self._next_buffer(n)
        self.aio.async_pread(view, self._paths[i])
        return view

    def write_async(self, i: int, data: np.ndarray) -> None:
        self.aio.async_pwrite(np.ascontiguousarray(data.reshape(-1)),
                              self._paths[i])

    def wait(self) -> None:
        self.aio.wait()

    def read_sync(self, i: int) -> np.ndarray:
        view = self.read_async(i)
        self.wait()
        return view.reshape(self.shapes[i]).copy()


def pipeline_pools(pools: Dict[str, "SwappedTensorPool"], n_leaves: int,
                   compute_fn, write_back: bool = True) -> None:
    """Shared read-ahead / compute / write-behind pipeline over named pools.

    For each leaf j: views = {name: read(j)}; ``compute_fn(j, views)`` mutates
    the buffer views in place; write-back of j overlaps compute of j+1, and
    the read of j+1 is submitted before compute of j (reference:
    pipelined_optimizer_swapper.py:279).
    """
    if n_leaves == 0:
        return

    def read(j):
        return {k: p.read_async(j) for k, p in pools.items()}

    views = read(0)
    for j in range(n_leaves):
        for p in pools.values():
            p.wait()               # reads for j (and writes for j-1) done
        cur = views
        if j + 1 < n_leaves:
            views = read(j + 1)
        compute_fn(j, cur)
        if write_back:
            for k, p in pools.items():
                p.write_async(j, cur[k])
    for p in pools.values():
        p.wait()


class OptimizerStateSwapper:
    """NVMe-resident optimizer state, streamed leaf-by-leaf through a
    double-buffered read -> compute -> write-back pipeline.

    reference: partitioned_optimizer_swapper.py (swap_in_optimizer_state /
    swap_out_optimizer_state around the partition's Adam step) +
    pipelined_optimizer_swapper.py (overlap of reads/writes with compute).
    """

    def __init__(self, nvme_path: str, slot_names: Sequence[str],
                 leaf_shapes: Sequence[Tuple[int, ...]],
                 aio: Optional[AsyncIOHandle] = None, buffer_count: int = 4):
        self.slot_names = list(slot_names)
        self.n_leaves = len(leaf_shapes)
        self.pools = {
            slot: SwappedTensorPool(
                os.path.join(nvme_path, slot),
                [f"leaf{j}" for j in range(self.n_leaves)],
                leaf_shapes, np.float32, aio=aio, buffer_count=buffer_count)
            for slot in self.slot_names}

    def pipeline(self, compute_fn) -> None:
        """For each leaf j: state = read(j); compute_fn(j, state) mutates the
        buffers in place; write-back(j). Reads of leaf j+1 and write-backs of
        leaf j overlap compute of leaf j via the shared aio thread pool."""
        pipeline_pools(self.pools, self.n_leaves, compute_fn)

    def read_leaf(self, j: int) -> Dict[str, np.ndarray]:
        return {s: self.pools[s].read_sync(j) for s in self.slot_names}


class PartitionedParamSwapper:
    """fp32 parameter partitions on NVMe — whole-set swap facade.

    reference: partitioned_param_swapper.py:35 AsyncPartitionedParameterSwapper
    — a thin facade over SwappedTensorPool keyed by leaf index for paging a
    full param set out/in at once.  The engine's offload_param=nvme tier
    streams leaves through ``HostOffloadOptimizer``'s per-leaf pipeline
    instead (zero/offload.py); this facade currently has no engine consumer
    and is kept as the public whole-set API (+ its tests).
    """

    def __init__(self, nvme_path: str, leaf_shapes: Sequence[Tuple[int, ...]],
                 aio: Optional[AsyncIOHandle] = None, buffer_count: int = 5):
        self.pool = SwappedTensorPool(
            os.path.join(nvme_path, "params"),
            [f"leaf{j}" for j in range(len(leaf_shapes))],
            leaf_shapes, np.float32, aio=aio, buffer_count=buffer_count,
            initialize_zero=False)
        self.shapes = [tuple(s) for s in leaf_shapes]

    def swap_out(self, leaves: Sequence[np.ndarray]) -> None:
        for j, leaf in enumerate(leaves):
            self.pool.write_async(j, np.asarray(leaf, np.float32))
        self.pool.wait()

    def swap_in(self) -> List[np.ndarray]:
        out = []
        for j in range(len(self.shapes)):
            out.append(self.pool.read_sync(j).reshape(self.shapes[j]))
        return out
