"""Pipeline parallelism: layer specs, schedules, SPMD executor, engine."""

from .module import (LayerSpec, TiedLayerSpec, PipelineModule,
                     partition_uniform, partition_balanced)
from .schedule import (PipeSchedule, TrainSchedule, InferenceSchedule,
                       DataParallelSchedule, bubble_fraction,
                       build_1f1b_tables, build_gpipe_tables, build_tables,
                       stage_instruction_stream)
from .spmd import pipeline_apply, stack_stage_params, unstack_stage_params
from .engine import PipelineEngine

__all__ = [
    "LayerSpec", "TiedLayerSpec", "PipelineModule", "partition_uniform",
    "partition_balanced", "PipeSchedule", "TrainSchedule", "InferenceSchedule",
    "DataParallelSchedule", "bubble_fraction", "build_1f1b_tables",
    "build_gpipe_tables", "build_tables", "stage_instruction_stream",
    "pipeline_apply", "stack_stage_params", "unstack_stage_params",
    "PipelineEngine",
]
