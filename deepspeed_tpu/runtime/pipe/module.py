"""PipelineModule — express a model as a layer list and partition it into stages.

Capability parity with the reference's ``runtime/pipe/module.py``:
``LayerSpec``/``TiedLayerSpec`` lazy layer construction, layer→stage
partitioning by ``uniform | parameters | type:regex`` (reference
``_partition_layers`` module.py:365), and the partition-boundary math
(``ds_utils.partition_balanced``-equivalent prefix-sum search).

TPU-native difference: execution is SPMD (spmd.py), which pipelines a
*stack* of identical stage bodies with a collective-permute loop. A
heterogeneous layer list still works for stage *assignment* math and for
single-program sequential execution; pipelined execution requires the
pipelined span to be homogeneous (same spec type/kwargs), which is how
transformer stacks are in practice — embed/head run outside the loop
(models/pipeline.py builds that shape from a TransformerConfig).
"""

from __future__ import annotations

import re
from typing import Any, Callable, List, Optional, Sequence

import numpy as np


class LayerSpec:
    """Lazy layer description: class + ctor args, built at partition time
    (reference: module.py:24-71)."""

    def __init__(self, typename, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs

    def build(self):
        return self.typename(*self.module_args, **self.module_kwargs)

    def __repr__(self):
        return f"LayerSpec({getattr(self.typename, '__name__', self.typename)})"


class TiedLayerSpec(LayerSpec):
    """Layer whose parameters are shared across stages under a tie key
    (reference: module.py:72-85; e.g. embedding tied with the LM head)."""

    def __init__(self, key, typename, *module_args, forward_fn=None,
                 tied_weight_attr="embedding", **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn
        self.tied_weight_attr = tied_weight_attr


def partition_uniform(num_items: int, num_parts: int) -> List[int]:
    """Boundaries [p0..pP] splitting items as evenly as possible."""
    parts = [0] * (num_parts + 1)
    chunk = num_items // num_parts
    residual = num_items % num_parts
    for p in range(1, num_parts + 1):
        parts[p] = parts[p - 1] + chunk + (1 if p <= residual else 0)
    return parts


def partition_balanced(weights: Sequence[float], num_parts: int) -> List[int]:
    """Boundaries minimizing the max per-part weight sum (binary search over
    the bottleneck + greedy check — the reference uses the same idea)."""
    weights = list(weights)
    n = len(weights)
    if num_parts >= n:
        return list(range(n + 1)) + [n] * (num_parts - n)
    prefix = np.concatenate([[0.0], np.cumsum(weights)])

    def feasible(cap: float) -> Optional[List[int]]:
        bounds = [0]
        for _ in range(num_parts):
            lo = bounds[-1]
            # furthest j with sum(weights[lo:j]) <= cap
            j = int(np.searchsorted(prefix, prefix[lo] + cap, side="right")) - 1
            if j <= lo:
                return None
            bounds.append(min(j, n))
            if bounds[-1] == n:
                break
        if bounds[-1] != n:
            return None
        while len(bounds) < num_parts + 1:
            bounds.append(n)
        return bounds

    lo, hi = max(weights), sum(weights)
    best = feasible(hi)
    for _ in range(60):
        mid = (lo + hi) / 2
        b = feasible(mid)
        if b is not None:
            best, hi = b, mid
        else:
            lo = mid
    return best


class PipelineModule:
    """Holds the layer list + stage assignment.

    ``partition_method``: "uniform" | "parameters" | "type:<regex>"
    (reference: module.py:365-420). ``param_counts`` supplies per-layer
    parameter counts for the "parameters" method (the reference builds each
    layer and counts; here models pass counts so partitioning stays lazy).
    """

    def __init__(self,
                 layers: Sequence[LayerSpec],
                 num_stages: int,
                 partition_method: str = "parameters",
                 param_counts: Optional[Sequence[float]] = None,
                 loss_fn: Optional[Callable] = None,
                 activation_checkpoint_interval: int = 0):
        self.layer_specs = list(layers)
        self.num_stages = num_stages
        self.partition_method = partition_method
        self.loss_fn = loss_fn
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self.parts = self._partition(param_counts)

    def _partition(self, param_counts) -> List[int]:
        n = len(self.layer_specs)
        method = self.partition_method.lower()
        if method == "uniform":
            return partition_uniform(n, self.num_stages)
        if method == "parameters":
            if param_counts is None:
                param_counts = [1.0] * n
            if len(param_counts) != n:
                raise ValueError("param_counts length != number of layers")
            return partition_balanced(param_counts, self.num_stages)
        if method.startswith("type:"):
            pat = re.compile(method[5:], re.IGNORECASE)
            weights = [1.0 if pat.search(getattr(s.typename, "__name__", str(s)))
                       else 0.0 for s in self.layer_specs]
            if sum(weights) == 0:
                raise ValueError(f"no layer matches {method}")
            return partition_balanced(weights, self.num_stages)
        raise NotImplementedError(f"partition_method {self.partition_method}")

    def stage_layers(self, stage_id: int) -> List[LayerSpec]:
        return self.layer_specs[self.parts[stage_id]:self.parts[stage_id + 1]]

    def stage_of_layer(self, layer_idx: int) -> int:
        for s in range(self.num_stages):
            if self.parts[s] <= layer_idx < self.parts[s + 1]:
                return s
        raise IndexError(layer_idx)

    def tied_keys(self) -> List[str]:
        keys = []
        for s in self.layer_specs:
            if isinstance(s, TiedLayerSpec) and s.key not in keys:
                keys.append(s.key)
        return keys

    def homogeneous_span(self) -> tuple:
        """(start, end) of the maximal run of identical specs — the pipelined
        region for SPMD execution. Identical = same type + same ctor args."""
        n = len(self.layer_specs)
        best = (0, 0)
        i = 0
        while i < n:
            j = i + 1
            si = self.layer_specs[i]
            while j < n:
                sj = self.layer_specs[j]
                same = (type(si) is type(sj) and si.typename is sj.typename
                        and si.module_args == sj.module_args
                        and si.module_kwargs == sj.module_kwargs)
                if not same:
                    break
                j += 1
            if j - i > best[1] - best[0]:
                best = (i, j)
            i = j
        return best
