"""1F1B pipeline execution — hand-scheduled forward/backward interleave.

The GPipe executor (spmd.py) differentiates THROUGH a lax.scan, so autodiff
saves every tick's carry: activation memory grows with n_micro. This module
is the reference's actual 1F1B regime (runtime/pipe/schedule.py TrainSchedule
+ engine.py _exec_schedule): gradients are computed by a hand-written
interleave where each stage holds at most ``pp`` saved boundary inputs —
activation memory ∝ stages, not microbatches — and backward recomputes the
stage body from the saved input (the reference holds outputs instead; the
recompute trades one extra forward for not storing internals, the same deal
as its activation checkpointing interleave).

Mechanics, all inside one SPMD program over the 'pipe' mesh axis:
  * a host-side event simulation produces clock-aligned instruction tables
    (fwd/bwd micro id per [tick, stage], plus the matching receive tables);
    one tick = one compute slot, sends land one tick later — the alignment
    TrainSchedule's abstract clock doesn't guarantee;
  * the scan body does (masked) one forward + one backward per tick: ring
    buffers hold received activations/cotangents and saved inputs, keyed by
    micro % pp; jax.vjp of the stage body yields dx (sent upstream via the
    reversed ppermute) and accumulated param grads;
  * the last stage computes the per-micro loss in-tick and seeds its own
    backward; the loss head's grads psum over 'pipe' at the end.

Because no AD runs through the scan or the collectives, the boundary stays
in the COMPUTE dtype (bf16) end to end — the f32 crossing the GPipe path
needs to dodge the low-precision-collective transpose bug does not apply.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

PyTree = Any


def build_1f1b_tables(n_micro: int, pp: int
                      ) -> Dict[str, np.ndarray]:
    """Clock-aligned 1F1B tables via event simulation.

    Returns arrays [T, pp]: fwd[t,s] / bwd[t,s] = micro id computed (-1 =
    bubble), recv_f[t,s] = micro id whose activation ARRIVES at (t,s) from
    s-1 (sent at t-1), recv_b[t,s] = cotangent arriving from s+1. Every
    stage obeys: warmup of (pp-1-s) forwards, then backward-priority
    alternation (the reference TrainSchedule discipline, schedule.py:151).
    """
    slots = min(pp, n_micro)
    fwd_done = -np.ones((pp, n_micro), np.int64)    # tick fwd finished
    bwd_done = -np.ones((pp, n_micro), np.int64)
    fwd_next = [0] * pp
    bwd_next = [0] * pp
    rows_f, rows_b = [], []
    t = 0
    while any(b < n_micro for b in bwd_next):
        row_f = [-1] * pp
        row_b = [-1] * pp
        for s in range(pp):
            f, b = fwd_next[s], bwd_next[s]
            # a tick holds one forward AND one backward (the executor's scan
            # body computes both — that IS the 1F1B steady state); the ring
            # capacity caps in-flight forwards
            if f < n_micro and f - b < slots and (
                    s == 0 or 0 <= fwd_done[s - 1, f] < t):
                row_f[s] = f
                fwd_done[s, f] = t
                fwd_next[s] += 1
            if b < n_micro and (
                    (s == pp - 1 and 0 <= fwd_done[s, b] <= t)
                    or (s < pp - 1 and 0 <= bwd_done[s + 1, b] < t)):
                row_b[s] = b
                bwd_done[s, b] = t
                bwd_next[s] += 1
        rows_f.append(row_f)
        rows_b.append(row_b)
        t += 1
        if t > 6 * (n_micro + pp) + 8:
            raise RuntimeError("1F1B schedule failed to converge")
    fwd = np.asarray(rows_f, np.int32)
    bwd = np.asarray(rows_b, np.int32)
    T = fwd.shape[0]
    recv_f = -np.ones_like(fwd)
    recv_b = -np.ones_like(bwd)
    recv_f[1:, 1:] = fwd[:-1, :-1]
    recv_b[1:, :-1] = bwd[:-1, 1:]
    return {"fwd": fwd, "bwd": bwd, "recv_f": recv_f, "recv_b": recv_b,
            "ticks": T}


def pipeline_1f1b_value_and_grad(
        stage_fn: Callable[[PyTree, jnp.ndarray], jnp.ndarray],
        loss_fn: Callable[[PyTree, jnp.ndarray, jnp.ndarray], jnp.ndarray],
        stage_params: PyTree,
        head_params: PyTree,
        micros: jnp.ndarray,
        labels: jnp.ndarray,
        *,
        mesh,
        pp: int,
        pipe_axis: str = "pipe"
) -> Tuple[jnp.ndarray, PyTree, PyTree, jnp.ndarray]:
    """One 1F1B pass. Returns (mean loss, stage grads, head grads, dmicros).

    stage_fn(one_stage_params, x [mb, ...]) -> y      every stage's body
    loss_fn(head_params, y, labels_micro) -> scalar   LAST stage only (head
        + per-micro loss; its grads seed the backward)
    micros [n_micro, mb, ...] stage-0 inputs (e.g. embedded tokens);
    labels [n_micro, ...] per-micro targets; dmicros lets the caller
    backprop the embedding outside the pipe.
    """
    n_micro = micros.shape[0]
    tables = build_1f1b_tables(n_micro, pp)
    fwd_t = jnp.asarray(tables["fwd"])
    bwd_t = jnp.asarray(tables["bwd"])
    rf_t = jnp.asarray(tables["recv_f"])
    rb_t = jnp.asarray(tables["recv_b"])
    T = tables["ticks"]
    slots = min(pp, n_micro)                    # 1F1B in-flight bound

    def inner(stage_params, head_params, micros, labels):
        local = jax.tree.map(lambda x: x[0], stage_params)
        stage = jax.lax.axis_index(pipe_axis)
        mshape = micros.shape[1:]
        zero_m = jnp.zeros(mshape, micros.dtype)

        rings = {
            "in_act": jnp.zeros((slots,) + mshape, micros.dtype),
            "in_grad": jnp.zeros((slots,) + mshape, micros.dtype),
            "saved_x": jnp.zeros((slots,) + mshape, micros.dtype),
        }
        grads0 = jax.tree.map(lambda x: jnp.zeros_like(x, jnp.float32), local)
        hgrads0 = jax.tree.map(lambda x: jnp.zeros_like(x, jnp.float32),
                               head_params)
        dmicros0 = jnp.zeros_like(micros)
        loss0 = jnp.zeros((), jnp.float32)
        send0 = (zero_m, zero_m)                # (fwd payload, bwd payload)

        down = [(i, i + 1) for i in range(pp - 1)]
        up = [(i + 1, i) for i in range(pp - 1)]

        def stage_bwd(xb, lab, ring_dy, is_last):
            """ONE stage VJP per tick: the head's loss/cotangent is computed
            separately (loss_fn reduces locally — no collectives), and a
            where selects the head's dy on the last stage vs the ring's dy
            elsewhere before the single backward through the stage body."""
            y, stage_vjp = jax.vjp(lambda p, x: stage_fn(p, x), local, xb)
            loss, head_vjp = jax.vjp(
                lambda h, yy: loss_fn(h, yy, lab), head_params, y)
            dh, head_dy = head_vjp(jnp.ones((), loss.dtype))
            dy = jnp.where(is_last, head_dy.astype(y.dtype),
                           ring_dy.astype(y.dtype))
            dp, dx = stage_vjp(dy)
            return loss, dp, dh, dx

        def tick(carry, t):
            rings, grads, hgrads, dmicros, loss_acc, send = carry
            prev_y, prev_dx = send

            # -- receive what was sent last tick ------------------------------
            got_f = jax.lax.ppermute(prev_y, pipe_axis, down)
            # chain the second permute on the first: independent collectives
            # may be scheduled in different orders on different devices,
            # deadlocking the rendezvous (observed on the 8-device CPU
            # runtime); the zero-valued dependency forces a global order
            token = jnp.zeros((), prev_dx.dtype) * jnp.sum(got_f).astype(
                prev_dx.dtype)
            got_b = jax.lax.ppermute(prev_dx + token, pipe_axis, up)
            rf = rf_t[t, stage]
            rb = rb_t[t, stage]
            rings["in_act"] = jnp.where(
                rf >= 0,
                jax.lax.dynamic_update_index_in_dim(
                    rings["in_act"], got_f, jnp.maximum(rf, 0) % slots, 0),
                rings["in_act"])
            rings["in_grad"] = jnp.where(
                rb >= 0,
                jax.lax.dynamic_update_index_in_dim(
                    rings["in_grad"], got_b, jnp.maximum(rb, 0) % slots, 0),
                rings["in_grad"])

            # -- forward ------------------------------------------------------
            f_id = fwd_t[t, stage]
            f_on = f_id >= 0
            f_slot = jnp.maximum(f_id, 0) % slots
            x = jnp.where(stage == 0,
                          micros[jnp.maximum(f_id, 0)],
                          rings["in_act"][f_slot])
            y = stage_fn(local, x)
            rings["saved_x"] = jnp.where(
                f_on,
                jax.lax.dynamic_update_index_in_dim(rings["saved_x"], x,
                                                    f_slot, 0),
                rings["saved_x"])

            # -- backward -----------------------------------------------------
            b_id = bwd_t[t, stage]
            b_on = b_id >= 0
            b_slot = jnp.maximum(b_id, 0) % slots
            xb = rings["saved_x"][b_slot]
            lab = labels[jnp.maximum(b_id, 0)]
            dy = rings["in_grad"][b_slot]
            is_last = stage == pp - 1

            # executed UNCONDITIONALLY on every rank with where-selects: a
            # lax.cond here diverges by pipe rank, and any collective XLA
            # partitions into a branch would deadlock the rendezvous
            lloss, dp, dh, dx = stage_bwd(xb, lab, dy, is_last)
            mask = b_on.astype(jnp.float32)
            last_f = is_last.astype(jnp.float32)
            grads = jax.tree.map(
                lambda g, d: g + mask * d.astype(jnp.float32), grads, dp)
            hgrads = jax.tree.map(
                lambda g, d: g + (mask * last_f) * d.astype(jnp.float32),
                hgrads, dh)
            loss_acc = loss_acc + jnp.where(b_on & is_last,
                                            lloss.astype(jnp.float32), 0.0)
            dx = dx.astype(micros.dtype)
            # stage 0's dx is the embedding cotangent
            dmicros = jnp.where(
                b_on & (stage == 0),
                jax.lax.dynamic_update_index_in_dim(
                    dmicros, dx, jnp.maximum(b_id, 0), 0),
                dmicros)

            send = (jnp.where(f_on, y, zero_m).astype(micros.dtype),
                    jnp.where(b_on, dx, zero_m))
            return (rings, grads, hgrads, dmicros, loss_acc, send), None

        carry0 = (rings, grads0, hgrads0, dmicros0, loss0, send0)
        (rings, grads, hgrads, dmicros, loss_acc, _), _ = jax.lax.scan(
            tick, carry0, jnp.arange(T))

        # loss + head grads live on the last stage; dmicros on stage 0 —
        # psum replicates (the masks above zero the other stages' terms)
        loss = jax.lax.psum(loss_acc, pipe_axis) / n_micro
        hgrads = jax.tree.map(
            lambda g: jax.lax.psum(g / n_micro, pipe_axis), hgrads)
        dmicros = jax.lax.psum(dmicros.astype(jnp.float32),
                               pipe_axis).astype(micros.dtype) / n_micro
        grads = jax.tree.map(lambda g: g[None] / n_micro, grads)
        return loss, grads, hgrads, dmicros

    spec_params = jax.tree.map(lambda _: P(pipe_axis), stage_params)
    mapped = jax.shard_map(
        inner, mesh=mesh,
        in_specs=(spec_params, P(), P(), P()),
        out_specs=(P(), spec_params, P(), P()),
        axis_names={pipe_axis},
        check_vma=False)
    return mapped(stage_params, head_params, micros, labels)
