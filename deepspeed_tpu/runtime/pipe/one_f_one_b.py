"""1F1B pipeline execution — hand-scheduled forward/backward interleave.

The GPipe executor (spmd.py) differentiates THROUGH a lax.scan, so autodiff
saves every tick's carry: activation memory grows with n_micro. This module
is the reference's actual 1F1B regime (runtime/pipe/schedule.py TrainSchedule
+ engine.py _exec_schedule): gradients are computed by a hand-written
interleave where each stage holds at most ``pp`` saved boundary inputs —
activation memory ∝ stages, not microbatches. Two backward modes:

  * ``store_outputs=False`` (default): backward recomputes the stage body
    from the saved input — one extra forward per micro per stage, nothing
    but the [mb, ...] boundary stored (the same deal as the reference's
    activation-checkpointing interleave, module.py:309).
  * ``store_outputs=True``: the forward tick runs the stage body under
    jax.vjp and the residuals ride slot rings to the backward tick — no
    recompute (the reference's own store-outputs design,
    engine.py:630-781), at the cost of holding ~pp ticks of stage-internal
    residuals live (benchmarks/pipeline_bench.py measures the trade).

Generality (round-3 Missing #3 closed): per-micro side inputs (attention
masks, dropout rng keys) ride along via ``extras``; MoE's load-balance aux
scalar flows through the manual backward via ``with_aux``/``aux_cotangent``;
an fp16 ``loss_scale`` seeds the backward (grads come out scaled, the
engine's standard unscale/overflow tail applies); any per-micro last-stage
loss_fn is accepted.

Mechanics, all inside one SPMD program over the 'pipe' mesh axis:
  * a host-side event simulation produces clock-aligned instruction tables
    (fwd/bwd micro id per [tick, stage], plus the matching receive tables);
    one tick = one compute slot, sends land one tick later — the alignment
    TrainSchedule's abstract clock doesn't guarantee;
  * the scan body does (masked) one forward + one backward per tick: ring
    buffers hold received activations/cotangents and saved inputs (or vjp
    residuals), keyed by micro % pp; the stage vjp yields dx (sent upstream
    via the reversed ppermute) and accumulated param grads;
  * the last stage computes the per-micro loss in-tick and seeds its own
    backward; the loss head's grads psum over 'pipe' at the end.

Because no AD runs through the scan or the collectives, the boundary stays
in the COMPUTE dtype (bf16) end to end — the f32 crossing the GPipe path
needs to dodge the low-precision-collective transpose bug does not apply.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

# the schedule/placement split (round 13): the clock tables live in the
# schedule layer — this module is the SPMD *placement* of that schedule.
# Re-exported here for backwards compatibility (benchmarks, tests).
from .schedule import build_1f1b_tables

PyTree = Any


def pipeline_1f1b_value_and_grad(
        stage_fn: Callable,
        loss_fn: Callable[[PyTree, jnp.ndarray, jnp.ndarray], jnp.ndarray],
        stage_params: PyTree,
        head_params: PyTree,
        micros: jnp.ndarray,
        labels: PyTree,
        *,
        mesh,
        pp: int,
        pipe_axis: str = "pipe",
        extras: Optional[PyTree] = None,
        with_aux: bool = False,
        aux_cotangent: float = 0.0,
        loss_scale=None,
        store_outputs: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, PyTree, PyTree, jnp.ndarray]:
    """One 1F1B pass. Returns (mean task loss, mean aux, stage grads,
    head grads, dmicros).

    stage_fn(one_stage_params, x [mb, ...], extra, stage_idx) -> y, or
        (y, aux_scalar) when with_aux — every stage's body. ``extra`` is the
        per-micro slice of ``extras`` (attention masks, rng keys, ...);
        ``stage_idx`` is this rank's pipe index (for rng folding).
    loss_fn(head_params, y, labels_micro) -> scalar   LAST stage only (head
        + per-micro loss; its grads seed the backward)
    micros [n_micro, mb, ...] stage-0 inputs (e.g. embedded tokens);
    labels: pytree of [n_micro, ...] per-micro targets; dmicros lets the
    caller backprop the embedding outside the pipe.
    loss_scale: optional scalar seeding the backward (fp16) — grads and
        dmicros come out SCALED; aux_cotangent is scaled internally.
    """
    n_micro = micros.shape[0]
    tables = build_1f1b_tables(n_micro, pp)
    fwd_t = jnp.asarray(tables["fwd"])
    bwd_t = jnp.asarray(tables["bwd"])
    rf_t = jnp.asarray(tables["recv_f"])
    rb_t = jnp.asarray(tables["recv_b"])
    T = tables["ticks"]
    slots = min(pp, n_micro)                    # 1F1B in-flight bound
    if extras is None:
        extras = {}

    def inner(stage_params, head_params, micros, labels, extras):
        local = jax.tree.map(lambda x: x[0], stage_params)
        stage = jax.lax.axis_index(pipe_axis)
        mshape = micros.shape[1:]
        zero_m = jnp.zeros(mshape, micros.dtype)
        scale = (jnp.asarray(1.0, jnp.float32) if loss_scale is None
                 else loss_scale.astype(jnp.float32))
        aux_ct = jnp.asarray(aux_cotangent, jnp.float32) * scale

        def extra_of(mid):
            return jax.tree.map(lambda e: e[jnp.maximum(mid, 0)], extras)

        def body(p, x, extra):
            """Uniform (y, aux) stage body closure."""
            out = stage_fn(p, x, extra, stage)
            if with_aux:
                return out
            return out, jnp.zeros((), jnp.float32)

        rings = {
            "in_act": jnp.zeros((slots,) + mshape, micros.dtype),
            "in_grad": jnp.zeros((slots,) + mshape, micros.dtype),
        }
        res_treedef = None
        res_static = None
        static_vals = None
        if not store_outputs:
            rings["saved_x"] = jnp.zeros((slots,) + mshape, micros.dtype)
        if store_outputs:
            # probe the vjp residual structure (shapes are tick-invariant;
            # the probe computation is unused and DCE'd by XLA)
            _, vjp_probe = jax.vjp(
                lambda p, x: body(p, x, extra_of(jnp.asarray(0))),
                local, zero_m)
            res_leaves, res_treedef = jax.tree.flatten(vjp_probe)
            # residual leaves that ARE the stage weights (jax forwards the
            # kernels as residuals for dx = dy @ W^T) are tick-invariant:
            # ring-buffering them would hold slots x stage-params of live
            # copies — reinject the live values at backward instead.
            # (Identity matching catches pass-through leaves; residuals
            # DERIVED from weights — e.g. a sharding-constraint or dtype
            # cast output — still ride the rings, so the saving is partial
            # for bodies that transform their kernels before use.)
            param_ids = {id(l) for l in jax.tree.leaves(local)}
            res_static = [id(l) in param_ids for l in res_leaves]
            static_vals = [l for l, st in zip(res_leaves, res_static) if st]
            # the id() match is best-effort: if jax stops passing weights
            # through as identical objects (or the body casts/constrains
            # its kernels first), everything classifies dynamic and the
            # rings hold slots x stage-weights of live copies — the exact
            # memory this mode exists to bound. Make that degradation
            # loud instead of silent.
            par_bytes = sum(int(np.prod(l.shape)) * l.dtype.itemsize
                            for l in jax.tree.leaves(local))
            # degradation signal: WEIGHT-SHAPED residuals that failed the
            # id() match (a cast/constrained kernel riding the rings) —
            # plain activation residuals are the mode's normal cost and
            # must not trip this
            par_shapes = {l.shape for l in jax.tree.leaves(local)}
            stray_bytes = sum(
                int(np.prod(l.shape)) * l.dtype.itemsize
                for l, st in zip(res_leaves, res_static)
                if not st and l.shape in par_shapes)
            if par_bytes and stray_bytes >= par_bytes // 2:
                from ...utils.logging import warning_once
                warning_once(
                    "1F1B store_outputs: "
                    f"{stray_bytes / 1e6:.1f} MB/slot of weight-shaped "
                    "vjp residuals failed the tick-invariance match "
                    f"(stage params: {par_bytes / 1e6:.1f} MB; "
                    f"{sum(res_static)} of {len(res_leaves)} leaves "
                    "matched). The ring buffers hold that much live PER "
                    "SLOT — if memory matters here, use "
                    "backward='recompute'.")
            rings["res"] = [
                jnp.zeros((slots,) + l.shape, l.dtype)
                for l, st in zip(res_leaves, res_static) if not st]
            rings["out_y"] = jnp.zeros((slots,) + mshape, micros.dtype)

        grads0 = jax.tree.map(lambda x: jnp.zeros_like(x, jnp.float32), local)
        hgrads0 = jax.tree.map(lambda x: jnp.zeros_like(x, jnp.float32),
                               head_params)
        dmicros0 = jnp.zeros_like(micros)
        loss0 = jnp.zeros((), jnp.float32)
        aux0 = jnp.zeros((), jnp.float32)
        send0 = (zero_m, zero_m)                # (fwd payload, bwd payload)

        down = [(i, i + 1) for i in range(pp - 1)]
        up = [(i + 1, i) for i in range(pp - 1)]

        def head_bwd(y, lab):
            loss, head_vjp = jax.vjp(
                lambda h, yy: loss_fn(h, yy, lab), head_params, y)
            dh, head_dy = head_vjp(scale.astype(loss.dtype))
            return loss, dh, head_dy

        def tick(carry, t):
            rings, grads, hgrads, dmicros, loss_acc, aux_acc, send = carry
            prev_y, prev_dx = send

            # -- receive what was sent last tick ------------------------------
            got_f = jax.lax.ppermute(prev_y, pipe_axis, down)
            # chain the second permute on the first: independent collectives
            # may be scheduled in different orders on different devices,
            # deadlocking the rendezvous (observed on the 8-device CPU
            # runtime); the zero-valued dependency forces a global order
            token = jnp.zeros((), prev_dx.dtype) * jnp.sum(got_f).astype(
                prev_dx.dtype)
            got_b = jax.lax.ppermute(prev_dx + token, pipe_axis, up)
            rf = rf_t[t, stage]
            rb = rb_t[t, stage]
            rings["in_act"] = jnp.where(
                rf >= 0,
                jax.lax.dynamic_update_index_in_dim(
                    rings["in_act"], got_f, jnp.maximum(rf, 0) % slots, 0),
                rings["in_act"])
            rings["in_grad"] = jnp.where(
                rb >= 0,
                jax.lax.dynamic_update_index_in_dim(
                    rings["in_grad"], got_b, jnp.maximum(rb, 0) % slots, 0),
                rings["in_grad"])

            # -- forward ------------------------------------------------------
            f_id = fwd_t[t, stage]
            f_on = f_id >= 0
            f_slot = jnp.maximum(f_id, 0) % slots
            x = jnp.where(stage == 0,
                          micros[jnp.maximum(f_id, 0)],
                          rings["in_act"][f_slot])
            f_extra = extra_of(f_id)
            if store_outputs:
                (y, f_aux), f_vjp = jax.vjp(
                    lambda p, xx: body(p, xx, f_extra), local, x)
                dyn = [l for l, st in zip(jax.tree.flatten(f_vjp)[0],
                                          res_static) if not st]
                rings["res"] = [
                    jnp.where(f_on,
                              jax.lax.dynamic_update_index_in_dim(
                                  r, l, f_slot, 0), r)
                    for r, l in zip(rings["res"], dyn)]
                rings["out_y"] = jnp.where(
                    f_on,
                    jax.lax.dynamic_update_index_in_dim(rings["out_y"], y,
                                                        f_slot, 0),
                    rings["out_y"])
            else:
                y, f_aux = body(local, x, f_extra)
                rings["saved_x"] = jnp.where(
                    f_on,
                    jax.lax.dynamic_update_index_in_dim(rings["saved_x"], x,
                                                        f_slot, 0),
                    rings["saved_x"])
            aux_acc = aux_acc + jnp.where(f_on, f_aux.astype(jnp.float32),
                                          0.0)

            # -- backward -----------------------------------------------------
            b_id = bwd_t[t, stage]
            b_on = b_id >= 0
            b_slot = jnp.maximum(b_id, 0) % slots
            lab = jax.tree.map(lambda L: L[jnp.maximum(b_id, 0)], labels)
            ring_dy = rings["in_grad"][b_slot]
            is_last = stage == pp - 1
            b_extra = extra_of(b_id)

            # executed UNCONDITIONALLY on every rank with where-selects: a
            # lax.cond here diverges by pipe rank, and any collective XLA
            # partitions into a branch would deadlock the rendezvous
            if store_outputs:
                yb = rings["out_y"][b_slot]
                lloss, dh, head_dy = head_bwd(yb, lab)
                dy = jnp.where(is_last, head_dy.astype(yb.dtype),
                               ring_dy.astype(yb.dtype))
                # interleave the live (tick-invariant) weight residuals
                # with the ring-buffered dynamic ones, in probe order
                ring_it = iter([r[b_slot] for r in rings["res"]])
                stat_it = iter(static_vals)
                res_now = [next(stat_it) if st else next(ring_it)
                           for st in res_static]
                b_vjp = jax.tree.unflatten(res_treedef, res_now)
                dp, dx = b_vjp((dy, aux_ct))
            else:
                xb = rings["saved_x"][b_slot]
                (y2, _aux2), stage_vjp = jax.vjp(
                    lambda p, xx: body(p, xx, b_extra), local, xb)
                lloss, dh, head_dy = head_bwd(y2, lab)
                dy = jnp.where(is_last, head_dy.astype(y2.dtype),
                               ring_dy.astype(y2.dtype))
                dp, dx = stage_vjp((dy, aux_ct))
            mask = b_on.astype(jnp.float32)
            last_f = is_last.astype(jnp.float32)
            grads = jax.tree.map(
                lambda g, d: g + mask * d.astype(jnp.float32), grads, dp)
            hgrads = jax.tree.map(
                lambda g, d: g + (mask * last_f) * d.astype(jnp.float32),
                hgrads, dh)
            loss_acc = loss_acc + jnp.where(b_on & is_last,
                                            lloss.astype(jnp.float32), 0.0)
            dx = dx.astype(micros.dtype)
            # stage 0's dx is the embedding cotangent
            dmicros = jnp.where(
                b_on & (stage == 0),
                jax.lax.dynamic_update_index_in_dim(
                    dmicros, dx, jnp.maximum(b_id, 0), 0),
                dmicros)

            send = (jnp.where(f_on, y, zero_m).astype(micros.dtype),
                    jnp.where(b_on, dx, zero_m))
            return (rings, grads, hgrads, dmicros, loss_acc, aux_acc,
                    send), None

        carry0 = (rings, grads0, hgrads0, dmicros0, loss0, aux0, send0)
        (rings, grads, hgrads, dmicros, loss_acc, aux_acc, _), _ = \
            jax.lax.scan(tick, carry0, jnp.arange(T))

        # loss + head grads live on the last stage; dmicros on stage 0; aux
        # accumulates per stage — psum replicates (the masks above zero the
        # other stages' terms)
        loss = jax.lax.psum(loss_acc, pipe_axis) / n_micro
        aux = jax.lax.psum(aux_acc, pipe_axis) / n_micro
        hgrads = jax.tree.map(
            lambda g: jax.lax.psum(g / n_micro, pipe_axis), hgrads)
        dmicros = jax.lax.psum(dmicros.astype(jnp.float32),
                               pipe_axis).astype(micros.dtype) / n_micro
        grads = jax.tree.map(lambda g: g[None] / n_micro, grads)
        return loss, aux, grads, hgrads, dmicros

    spec_params = jax.tree.map(lambda _: P(pipe_axis), stage_params)
    mapped = jax.shard_map(
        inner, mesh=mesh,
        in_specs=(spec_params, P(), P(), P(), P()),
        out_specs=(P(), P(), spec_params, P(), P()),
        axis_names={pipe_axis},
        check_vma=False)
    return mapped(stage_params, head_params, micros, labels, extras)
