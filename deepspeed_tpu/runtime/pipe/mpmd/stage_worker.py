"""One MPMD pipeline stage as a supervised OS process.

``python -m deepspeed_tpu.runtime.pipe.mpmd.stage_worker --stage S ...``
runs stage S of a pp-stage pipeline: it connects to the driver's
transfer star (channel.SocketChannel), interprets its own
``stage_instruction_stream`` per training step with the shared
per-stage programs (executor.build_stage_programs — byte-identical math
to the in-process executor), applies its LOCAL optimizer at each step
boundary, and checkpoints its own state every ``--save-interval`` steps
through the PR-3 durable-tag machinery (staging dir + digests +
completion marker + atomic publish), which is also what a RESTARTED
stage restores from.

Supervision plugs into the existing substrate, not a new one:

* every step stamps a STAGE-tagged heartbeat (phase STEP, gauge
  ``{"stage": S, ...}``) — ``dstpu health`` shows the STAGE column and
  RunSupervisor-style silence logic applies unchanged;
* a StallWatchdog bounds the step cadence: a wedged stage (collective
  hang, chaos ``pipe.stage_kill:hang``) exits rc 117 with a STALLED
  terminal record;
* SIGTERM stamps PREEMPTED and exits rc 114 (the preemption contract);
* transfer faults (``pipe.xfer``) surface as IOError → rc 1 (a counted
  crash the driver restarts).

Park/resync (the one-stage-restart protocol, driven by driver.py):
when a peer dies, the driver parks the survivors — a park control frame
surfaces mid-recv as ``ParkSignal`` or at the step top — and each
survivor ABANDONS its in-flight step (partial grad accumulation is
discarded; no optimizer update was applied, so nothing needs undoing),
acks, and waits. After the dead stage restarts from its newest durable
tag, the driver broadcasts ``resync(step=k)``; every survivor restores
its own stage state at tag k and training replays from step k — each
microbatch's update is applied exactly once. A survivor parked past
``--park-timeout`` exits rc 117 (a dead driver must not strand live
stages).

The built-in ``toy`` spec (tanh-MLP stages + linear head, data
generated deterministically per (seed, step)) is what the tests and the
2-proc reference runs use; real models plug in by registering a spec
callable via ``--spec module:attr`` returning the same dict shape.
"""

from __future__ import annotations

# graftlint: disable-file=TPU013 (a stage worker is a SINGLE-process jax
# runtime by construction — its only peers are other OS processes reached
# through the socket channel, never collectives; the checkpoint helpers'
# process_allgather arm is unreachable at jax.process_count()==1, so the
# collective-order-divergence model does not apply to this file)

import argparse
import importlib
import os
import signal
import sys
from typing import Any, Dict, Optional

import numpy as np

from ....exit_codes import PREEMPTION_EXIT_CODE


def toy_spec(args) -> Dict[str, Any]:
    """Deterministic toy pipeline: pp tanh-MLP stages + linear head over
    H-dim activations. Every field derives from (--seed, step), so two
    runs — or one run crossing a restart — see identical params and
    data."""
    import jax.numpy as jnp

    H, mb = args.hidden, args.mb
    rng = np.random.RandomState(args.seed)
    stage_inits = []
    for s in range(args.pp):
        stage_inits.append({
            "w": jnp.asarray(rng.randn(H, H) * 0.3, jnp.float32),
            "b": jnp.asarray(rng.randn(H) * 0.1, jnp.float32)})
    head_init = {"v": jnp.asarray(rng.randn(H) * 0.5, jnp.float32)}

    def stage_fn(p, x, extra, stage):
        return jnp.tanh(x @ p["w"] + p["b"])

    def loss_fn(head_p, y, lab, ctx):
        return jnp.mean((y @ head_p["v"] - lab) ** 2)

    def data(step):
        drng = np.random.default_rng(args.seed * 100003 + step)
        micros = jnp.asarray(
            drng.standard_normal((args.n_micro, mb, H)), jnp.float32)
        labels = jnp.asarray(
            drng.standard_normal((args.n_micro, mb)), jnp.float32)
        return micros, labels

    return {"stage_fn": stage_fn, "loss_fn": loss_fn,
            "stage_init": stage_inits[args.stage], "head_init": head_init,
            "data": data}


def _load_spec(args):
    if args.spec == "toy":
        return toy_spec(args)
    mod, _, attr = args.spec.partition(":")
    fn = getattr(importlib.import_module(mod), attr)
    return fn(args)


# ------------------------------------------------------------- checkpointing

_TAG = "global_step"


def _save_stage_state(ckpt_dir: str, done: int, state) -> None:
    """Durable per-stage save through the PR-3 primitives: stage into
    <tag>.tmp, digest + completion marker, atomic publish, latest."""
    import json
    from ...checkpointing import (META_FILE, STAGING_SUFFIX, publish_tag,
                                  quarantine_staging, save_tree,
                                  write_completion_marker, write_latest)
    tag = f"{_TAG}{done}"
    stage_dir = os.path.join(ckpt_dir, tag + STAGING_SUFFIX)
    os.makedirs(stage_dir, exist_ok=True)
    try:
        save_tree(state, os.path.join(stage_dir, "model_states.npz"))
        with open(os.path.join(stage_dir, META_FILE), "w") as f:
            json.dump({"step": done, "stage_checkpoint": True}, f)
        write_completion_marker(stage_dir, num_shards=1)
        publish_tag(ckpt_dir, tag)
    except BaseException as e:
        # a torn save (chaos ckpt.* failpoints, full disk, preemption)
        # must not strand <tag>.tmp where the next save's makedirs would
        # merge fresh shards into stale ones — same discipline as the
        # trainer's save path
        quarantine_staging(stage_dir, reason=f"stage save failed: {e!r}")
        raise
    write_latest(ckpt_dir, tag)


def _load_stage_state(ckpt_dir: str, like, tag: Optional[str] = None):
    """(state, steps_done) from ``tag`` or the newest intact tag (the
    PR-3 verified loader path: digests checked, torn tags skipped).
    Returns (None, 0) when nothing restorable exists."""
    from ...checkpointing import load_tree, resolve_load_tag, verify_tag
    if tag is None:
        try:
            tag = resolve_load_tag(ckpt_dir)
        except (FileNotFoundError, OSError, RuntimeError, ValueError):
            return None, 0
        if tag is None:
            return None, 0
    else:
        if verify_tag(os.path.join(ckpt_dir, tag)) is not None:
            raise IOError(f"resync tag {tag} failed verification")
    state = load_tree(os.path.join(ckpt_dir, tag, "model_states.npz"), like)
    return state, int(tag[len(_TAG):])


# ------------------------------------------------------------------- worker


def run_worker(args) -> int:
    import jax
    from ....testing import chaos
    from ...heartbeat import (HEARTBEAT_DIR_ENV, PHASE_EXIT, PHASE_PREEMPTED,
                              PHASE_STEP, HeartbeatWriter)
    from ...watchdog import STALL_EXIT_CODE, StallWatchdog
    from ..schedule import (BackwardPass, ForwardPass, LoadMicroBatch,
                            RecvActivation, RecvGrad, SendActivation,
                            SendGrad, build_tables, stage_instruction_stream)
    from ....ops.optimizers import adam
    from .channel import (ChannelClosed, ChannelTimeout, ParkSignal,
                          SocketChannel)
    from .executor import build_stage_programs

    s, pp = args.stage, args.pp
    last = s == pp - 1
    spec = _load_spec(args)
    opt = adam(lr=args.lr)

    params: Dict[str, Any] = {"stage": spec["stage_init"]}
    if last:
        params["head"] = spec["head_init"]
    opt_state = opt.init(params)
    # the step rides as shape (1,): the npz flat-dict roundtrip does not
    # preserve 0-d scalars
    state_like = {"params": params, "opt": opt_state,
                  "step": np.zeros((1,), np.int64)}

    restored, done = _load_stage_state(args.ckpt_dir, state_like)
    if restored is not None:
        params, opt_state = restored["params"], restored["opt"]
        done = int(np.asarray(restored["step"]).reshape(-1)[0])
    else:
        # durable step-0 state: a resync to step 0 must be restorable
        _save_stage_state(args.ckpt_dir, 0, {
            "params": params, "opt": opt_state,
            "step": np.asarray([0], np.int64)})
        done = 0

    hb = None
    if os.environ.get(HEARTBEAT_DIR_ENV):
        hb = HeartbeatWriter(os.environ[HEARTBEAT_DIR_ENV], rank=s)
    # rolling per-step wall-time gauge (round 15, runtime/straggler.py):
    # rides the STAGE-tagged records so `dstpu health` shows RATE and the
    # cross-stage straggler detector can compare clock ticks — at MPMD
    # scale one slow stage stalls every downstream stage, and only the
    # RELATIVE view (this stage vs the world) can name the culprit
    from ...straggler import STEP_MS_GAUGE, StepClock
    step_clock = StepClock()

    def on_sigterm(signum, frame):
        if hb is not None:
            # direct terminal write (not stamp_terminal) so the STAGE
            # gauge survives onto the final record — `dstpu health`
            # answers "which stage" even post-mortem
            hb.write(PHASE_PREEMPTED, 0, force=True, lock_timeout=2.0,
                     extra={"stage": s})
        os._exit(PREEMPTION_EXIT_CODE)

    signal.signal(signal.SIGTERM, on_sigterm)

    watchdog = None
    if args.stall_timeout > 0:
        watchdog = StallWatchdog(args.stall_timeout, heartbeat=hb).start()

    chan = SocketChannel((args.driver_host, args.driver_port), s,
                         resume_step=done)
    progs = build_stage_programs(spec["stage_fn"], spec["loss_fn"], s, pp)
    tables = build_tables(args.schedule, args.n_micro, pp)
    stream = stage_instruction_stream(tables, s)
    jnp = jax.numpy
    f32 = jnp.float32
    scale = jnp.asarray(1.0, f32)
    aux_ct = jnp.asarray(0.0, f32)

    import contextlib

    def _recv(kind, mid):
        # a wait AT THE TRANSFER BARRIER is not a stall: it is bounded
        # by barrier_timeout (and interruptible by a park), so the
        # step-cadence watchdog suspends across it — compute wedges are
        # the watchdog's jurisdiction, late peers are the channel's
        ctx = (watchdog.suspended() if watchdog is not None
               else contextlib.nullcontext())
        with ctx:
            return jnp.asarray(chan.recv(kind, s, mid,
                                         timeout=args.barrier_timeout))

    def run_step(k):
        """One schedule pass; returns (grads, loss|None). Raises
        ParkSignal / ChannelTimeout / IOError per the contract above."""
        micros, labels = spec["data"](k)
        in_act, in_grad, saved_x = {}, {}, {}
        out_y, out_dx = {}, {}
        acc = jax.tree.map(lambda x: jnp.zeros(x.shape, f32),
                           params["stage"])
        hacc = (jax.tree.map(lambda x: jnp.zeros(x.shape, f32),
                             params["head"]) if last else None)
        lacc = jnp.zeros((), f32)
        for cmds in stream:
            for inst in cmds:
                mid = inst.buffer_id
                if isinstance(inst, RecvActivation):
                    in_act[mid] = _recv("act", mid)
                elif isinstance(inst, RecvGrad):
                    in_grad[mid] = _recv("grad", mid)
                elif isinstance(inst, LoadMicroBatch):
                    in_act[mid] = micros[mid]
                elif isinstance(inst, ForwardPass):
                    x = in_act.pop(mid)
                    saved_x[mid] = x
                    if last:
                        # the fused last_bwd recomputes the body; a fwd
                        # dispatch here would be pure double compute
                        continue
                    y, _aux = progs["fwd"](params["stage"], x, {})
                    out_y[mid] = y
                elif isinstance(inst, SendActivation):
                    chan.send("act", s, s + 1, mid,
                              np.asarray(out_y.pop(mid)))
                elif isinstance(inst, BackwardPass):
                    xb = saved_x.pop(mid)
                    if last:
                        nonlocal_acc = progs["last_bwd"](
                            params["stage"], params["head"], xb, {},
                            labels[mid], (), scale, aux_ct,
                            acc, hacc, lacc)
                        acc, hacc, lacc, dx = nonlocal_acc
                    else:
                        dy = in_grad.pop(mid)
                        acc, dx = progs["bwd"](params["stage"], xb, {},
                                               dy, aux_ct, acc)
                    if s > 0:
                        out_dx[mid] = dx
                elif isinstance(inst, SendGrad):
                    chan.send("grad", s, s - 1, mid,
                              np.asarray(out_dx.pop(mid)))
        grads = {"stage": jax.tree.map(lambda g: g / args.n_micro, acc)}
        if last:
            grads["head"] = jax.tree.map(lambda g: g / args.n_micro, hacc)
        loss = float(jax.device_get(lacc)) / args.n_micro if last else None
        return grads, loss

    def park_and_resync():
        """The survivor half of one-stage restart: ack the park, wait
        (bounded) for resync, restore this stage's state at the resync
        step. Returns the step to resume from."""
        chan.send_control({"cmd": "parked", "stage": s})
        if watchdog is not None:
            watchdog.suspend()
        try:
            ctrl = chan.wait_control("resync", timeout=args.park_timeout)
        except ChannelTimeout:
            if hb is not None:
                from ...heartbeat import PHASE_STALLED
                hb.write(PHASE_STALLED, 0, force=True, lock_timeout=2.0,
                         extra={"stage": s})
            sys.exit(STALL_EXIT_CODE)
        finally:
            if watchdog is not None:
                watchdog.resume()
        r = int(ctrl["step"])
        # the new generation: frames from the abandoned step are stale
        chan.generation = int(ctrl.get("gen", chan.generation + 1))
        restored, _ = _load_stage_state(args.ckpt_dir, state_like,
                                        tag=f"{_TAG}{r}")
        chan.clear_data()
        # the parked window must not read as a (giant) step in the
        # step_ms gauge — re-baseline at the next step boundary
        step_clock.reset()
        return r, restored

    k = done
    step_arr = jnp.asarray(0, jnp.int32)
    while k < args.steps:
        ctrl = chan.poll_control(0.0)
        if ctrl is not None:
            if ctrl.get("cmd") == "stop":
                break
            if ctrl.get("cmd") == "park":
                k, restored = park_and_resync()
                params, opt_state = restored["params"], restored["opt"]
                continue
        # the chaos hook the one-stage-restart matrix arms (keyed by
        # stage, so `match=1` takes out stage 1 only)
        chaos.failpoint("pipe.stage_kill", key=str(s))
        gauge = step_clock.mark()
        if hb is not None:
            extra = {"stage": s}
            if gauge is not None:
                extra[STEP_MS_GAUGE] = gauge
            hb.write(PHASE_STEP, k, extra=extra)
        try:
            grads, loss = run_step(k)
        except ParkSignal:
            k, restored = park_and_resync()
            params, opt_state = restored["params"], restored["opt"]
            continue
        except ChannelTimeout:
            # parked at the transfer barrier past the deadline with no
            # park/resync word from the driver: the stall contract
            if hb is not None:
                from ...heartbeat import PHASE_STALLED
                hb.write(PHASE_STALLED, k, force=True, lock_timeout=2.0,
                         extra={"stage": s})
            return STALL_EXIT_CODE
        except ChannelClosed:
            return 1
        params, opt_state = opt.update(grads, opt_state, params,
                                       step_arr + k)
        if watchdog is not None:
            watchdog.beat(step=k)
        k += 1
        if args.save_interval > 0 and k % args.save_interval == 0:
            _save_stage_state(args.ckpt_dir, k, {
                "params": params, "opt": opt_state,
                "step": np.asarray([k], np.int64)})
        if last and loss is not None:
            print(f'mpmd_step: {{"step": {k - 1}, "loss": {loss:.8f}}}',
                  flush=True)

    chan.send_control({"cmd": "done", "stage": s})
    if watchdog is not None:
        watchdog.stop()
    if hb is not None:
        hb.write(PHASE_EXIT, k, force=True, lock_timeout=2.0,
                 extra={"stage": s})
    chan.close()
    return 0


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="mpmd.stage_worker")
    p.add_argument("--stage", type=int, required=True)
    p.add_argument("--pp", type=int, required=True)
    p.add_argument("--n-micro", type=int, default=4, dest="n_micro")
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--schedule", default="1f1b", choices=["gpipe", "1f1b"])
    p.add_argument("--driver-host", default="127.0.0.1", dest="driver_host")
    p.add_argument("--driver-port", type=int, required=True,
                   dest="driver_port")
    p.add_argument("--ckpt-dir", required=True, dest="ckpt_dir")
    p.add_argument("--save-interval", type=int, default=1,
                   dest="save_interval")
    p.add_argument("--spec", default="toy",
                   help="'toy' or module:attr returning the spec dict")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--lr", type=float, default=1e-2)
    p.add_argument("--hidden", type=int, default=8)
    p.add_argument("--mb", type=int, default=2)
    p.add_argument("--park-timeout", type=float, default=60.0,
                   dest="park_timeout")
    p.add_argument("--barrier-timeout", type=float, default=60.0,
                   dest="barrier_timeout")
    p.add_argument("--stall-timeout", type=float, default=0.0,
                   dest="stall_timeout",
                   help="watchdog step deadline; 0 = unbounded")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    os.makedirs(args.ckpt_dir, exist_ok=True)
    return run_worker(args)


if __name__ == "__main__":
    sys.exit(main())
