"""MPMD pipeline placement — per-stage programs over explicit transfers.

The SPMD placement (``..spmd`` / ``..one_f_one_b``) compiles the whole
stacked-stage pipeline as ONE program over the 'pipe' mesh axis: every
host compiles everything and the stages share a single failure domain.
This package is the second placement of the same schedules
(``..schedule.build_tables``): each stage is its own jit program on its
own submesh (in-process) or its own process (cross-host), connected by
an explicit activation/grad transfer channel — the shape the reference
DeepSpeed itself executes (``runtime/pipe/engine.py`` instruction
schedules + p2p), and the scalable one for pod-of-pods over DCN
(2412.14374). See docs/PIPELINE.md.

Layers:
  * channel.py  — the transfer seam: LocalChannel (in-process
    device-to-device via jax.device_put) and SocketChannel (host-bounce
    TCP star through the driver — the CPU-testable cross-process
    reference path). Both declare the ``pipe.xfer`` failpoint.
  * executor.py — MPMDPipeline: per-stage compiled fwd/bwd programs
    interpreting :func:`..schedule.stage_instruction_stream`; a
    drop-in value_and_grad with the SPMD 1F1B executor's contract.
  * stage_worker.py — one stage as a supervised OS process: heartbeats
    (STAGE gauge), per-stage checkpoints, park/resync protocol,
    rc 0/114/117/118 contract.
  * driver.py — MPMDStageSupervisor: spawns/supervises the per-stage
    workers, routes transfers, restarts ONLY a dead stage and resyncs
    the survivors from the last per-stage checkpoint.
"""

from .channel import (ChannelClosed, ChannelTimeout, LocalChannel,
                      SocketChannel)
from .executor import MPMDPipeline, mpmd_value_and_grad
from .driver import MPMDStageSupervisor, StageWorkerSpec

__all__ = [
    "ChannelClosed", "ChannelTimeout", "LocalChannel", "SocketChannel",
    "MPMDPipeline", "mpmd_value_and_grad", "MPMDStageSupervisor",
    "StageWorkerSpec",
]
