"""Inter-stage transfer channel — the explicit seam of the MPMD placement.

The SPMD pipeline moves activations with ``lax.ppermute`` *inside* one
compiled program; the MPMD placement moves them BETWEEN programs, so the
transfer is a first-class host-visible object with a failure mode of its
own. Since round 18 both implementations are thin adapters over the
unified transfer fabric (:mod:`deepspeed_tpu.runtime.fabric`) — the
framing, CRC trailer, generation fencing, reconnect/backoff, and the
``net.*`` chaos surface all live THERE; this module only adds the
pipeline's demux (per-(kind, micro) FIFOs, control side-queue) and the
npy payload codec:

* :class:`LocalChannel` — in-process: payloads are jax Arrays handed
  device-to-device via the local endpoint's ``device_put`` place hook
  onto the receiving stage's submesh placement (on TPU an ICI/DCN copy;
  on the CPU backend a host copy — either way the boundary crossing is
  explicit and auditable, which is what graftlint TPU014 polices inside
  compiled step paths).
* :class:`SocketChannel` — cross-process host bounce: numpy payloads
  ride fabric frames over ONE TCP connection to the driver, which
  routes stage→stage (a star, so a restarted stage just reconnects —
  no peer rewiring). This is the CPU-testable reference path;
  device-to-device DCN transport slots in behind the same fabric
  interface.

Ordering contract: the clock tables send each edge's payloads in strictly
increasing micro order, so a FIFO per (kind, edge) suffices; ``recv``
verifies the micro id it pops and raises on a schedule violation instead
of silently consuming the wrong tensor.

Failure injection: every send and recv traverses the ``pipe.xfer``
failpoint (keyed ``"<kind>:<src>-><dst>"``), the chaos hook the recovery
matrix in tests/test_mpmd.py arms — and, below it, the fabric's
``net.*`` failpoints. A recv past its deadline raises
:class:`ChannelTimeout` — the "peer parked at the transfer barrier"
signal the park/resync protocol (driver.py) is built on.
"""

from __future__ import annotations

import io
import time
from collections import defaultdict, deque
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ....testing import chaos
# re-exported: the exceptions are the channel's public contract, now
# owned by the fabric (stage_worker, driver, and tests import them here)
from ...fabric import (ChannelClosed, ChannelTimeout,  # noqa: F401
                       LocalEndpoint, RedialPolicy, SocketEndpoint,
                       read_frame, write_frame)

#: transfer kinds — activations flow downstream, cotangents upstream
KIND_ACT = "act"
KIND_GRAD = "grad"


class LocalChannel:
    """In-process FIFO channel with explicit per-stage placement.

    ``placements``: optional {stage: jax.sharding.Sharding} — when given,
    every payload is ``jax.device_put`` onto the RECEIVING stage's
    placement at send time (the device-to-device hop, applied by the
    local endpoint's place hook). Without it the payload is handed over
    as-is (single-submesh tests).
    """

    def __init__(self, placements: Optional[Dict[int, Any]] = None):
        self.placements = placements or {}
        self._ep = LocalEndpoint(ident="pipe", place=self._place)
        self._q: Dict[Tuple[str, int], deque] = defaultdict(deque)

    def _place(self, meta: dict, payload):
        sh = self.placements.get(meta.get("dst"))
        if sh is not None:
            import jax
            payload = jax.device_put(payload, sh)
        return payload

    def send(self, kind: str, src: int, dst: int, micro: int,
             payload) -> None:
        edge = f"{kind}:{src}->{dst}"
        chaos.failpoint("pipe.xfer", key=edge)
        self._ep.send({"kind": kind, "src": src, "dst": dst,
                       "micro": int(micro)}, payload, key=edge)

    def _drain(self) -> None:
        while self._ep.pending():
            meta, payload = self._ep.recv(timeout=0.0)
            self._q[(meta["kind"], meta["dst"])].append(
                (meta["micro"], payload))

    def recv(self, kind: str, dst: int, micro: int,
             timeout: Optional[float] = None):
        self._drain()
        q = self._q[(kind, dst)]
        if not q:
            # in-process execution is synchronous: an empty queue is a
            # schedule bug, not a slow peer
            raise ChannelTimeout(
                f"no {kind} payload queued for stage {dst} (micro {micro})")
        got, payload = q.popleft()
        if got != micro:
            raise RuntimeError(
                f"schedule violation: stage {dst} expected {kind} of micro "
                f"{micro}, channel delivered micro {got}")
        return payload

    def pending(self, kind: str, dst: int) -> int:
        self._drain()
        return len(self._q[(kind, dst)])

    def clear(self) -> None:
        """Drop every queued payload (park: the in-flight step is
        abandoned, its transfers must not leak into the replay)."""
        self._ep.clear()
        self._q.clear()


# ------------------------------------------------------------ payload codec

def _to_bytes(arr) -> bytes:
    bio = io.BytesIO()
    np.save(bio, np.asarray(arr), allow_pickle=False)
    return bio.getvalue()


def _from_bytes(raw: bytes) -> np.ndarray:
    return np.load(io.BytesIO(raw), allow_pickle=False)


class SocketChannel:
    """One stage's endpoint of the host-bounce star (see module
    docstring). The fabric :class:`SocketEndpoint` owns the connection:
    dial backoff, CRC framing, the bounded write lock, mid-stream
    redial, and generation fencing (stale frames from an abandoned
    park/resync generation are dropped at receipt, inside the
    endpoint).

    Data frames ({kind, src, dst, micro} + npy payload) interleave with
    CONTROL frames ({cmd: park|resync|stop, ...}) from the driver on the
    same connection; :meth:`recv` parks control frames on a side queue
    for the worker loop to poll (``poll_control``), and a control frame
    that arrives while blocked in recv surfaces as :class:`ParkSignal`
    so the worker abandons its in-flight step immediately.
    """

    def __init__(self, driver_addr: Tuple[str, int], stage: int,
                 resume_step: int = 0, connect_timeout: float = 30.0):
        self.stage = stage
        # the driver's welcome hands the CURRENT park/resync generation
        # — a restarted stage must stamp its frames so the parked
        # survivors accept them. NOT the step number: healthy
        # pipelining crosses step boundaries (a fast upstream stage
        # legitimately sends step k+1 activations while downstream
        # finishes step k).
        self._ep = SocketEndpoint(
            driver_addr, ident=f"stage-{stage}",
            hello={"stage": stage, "resume_step": int(resume_step)},
            connect_timeout=connect_timeout,
            redial=RedialPolicy(attempts=2, base=0.05, dial_timeout=2.0))
        self._data: Dict[Tuple[str, int], deque] = defaultdict(deque)
        self._control: deque = deque()

    @property
    def generation(self) -> int:
        """Park/resync generation — lives in the fabric endpoint (it
        stamps every data frame and fences receipt); the resync control
        path assigns it here."""
        return self._ep.generation

    @generation.setter
    def generation(self, gen: int) -> None:
        self._ep.generation = int(gen)

    def send(self, kind: str, src: int, dst: int, micro: int,
             payload, lock_timeout: float = 30.0) -> None:
        edge = f"{kind}:{src}->{dst}"
        chaos.failpoint("pipe.xfer", key=edge)
        self._ep.send({"kind": kind, "src": src, "dst": dst,
                       "micro": int(micro)},
                      _to_bytes(np.asarray(payload)),
                      key=edge, lock_timeout=lock_timeout)

    def send_control(self, meta: dict, lock_timeout: float = 30.0) -> None:
        self._ep.send(meta, b"", lock_timeout=lock_timeout)

    def _pump_one(self, timeout: Optional[float]) -> None:
        meta, payload = self._ep.recv(timeout)
        if "cmd" in meta:
            self._control.append(meta)
        else:
            self._data[(meta["kind"], meta["micro"])].append(
                _from_bytes(payload))

    def recv(self, kind: str, dst: int, micro: int,
             timeout: Optional[float] = None) -> np.ndarray:
        assert dst == self.stage
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            q = self._data.get((kind, int(micro)))
            if q:
                return q.popleft()
            if self._control:
                # a park/stop arrived while we were waiting at the
                # barrier — surface it, the step is over
                raise ParkSignal(self._control[0].get("cmd", "park"))
            left = (None if deadline is None
                    else max(0.0, deadline - time.monotonic()))
            if left == 0.0:
                raise ChannelTimeout(
                    f"stage {self.stage}: no {kind} for micro {micro} "
                    f"within {timeout}s")
            self._pump_one(left)

    def poll_control(self, timeout: float = 0.0) -> Optional[dict]:
        """Next control frame if one is queued (or arrives within
        ``timeout``); data frames pumped meanwhile stay queued."""
        if timeout == 0.0:
            # opportunistic: one pump attempt, then answer
            if not self._control:
                try:
                    self._pump_one(0.001)
                except (ChannelTimeout, ChannelClosed):
                    pass
            return self._control.popleft() if self._control else None
        deadline = time.monotonic() + timeout
        while True:
            if self._control:
                return self._control.popleft()
            left = max(0.0, deadline - time.monotonic())
            if left == 0.0:
                return None
            try:
                self._pump_one(left)
            except ChannelTimeout:
                return None

    def wait_control(self, cmd: str, timeout: float) -> dict:
        """Block until a control frame with ``cmd`` arrives (frames for
        other commands are consumed and dropped — park acks races)."""
        deadline = time.monotonic() + timeout
        while True:
            left = deadline - time.monotonic()
            if left <= 0:
                raise ChannelTimeout(f"no '{cmd}' control within {timeout}s")
            got = self.poll_control(timeout=left)
            if got is not None and got.get("cmd") == cmd:
                return got

    def clear_data(self) -> None:
        self._data.clear()

    def close(self) -> None:
        self._ep.close()


class ParkSignal(Exception):
    """Raised out of a blocked recv when the driver parks the pipeline —
    the worker abandons the in-flight step and enters the park loop."""

    def __init__(self, cmd: str):
        super().__init__(cmd)
        self.cmd = cmd
