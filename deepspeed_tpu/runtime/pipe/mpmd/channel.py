"""Inter-stage transfer channel — the explicit seam of the MPMD placement.

The SPMD pipeline moves activations with ``lax.ppermute`` *inside* one
compiled program; the MPMD placement moves them BETWEEN programs, so the
transfer is a first-class host-visible object with a failure mode of its
own. Two implementations share one interface:

* :class:`LocalChannel` — in-process: payloads are jax Arrays handed
  device-to-device via ``jax.device_put`` onto the receiving stage's
  submesh placement (on TPU this is an ICI/DCN copy; on the CPU backend a
  host copy — either way the boundary crossing is explicit and auditable,
  which is what graftlint TPU014 polices inside compiled step paths).
* :class:`SocketChannel` — cross-process host bounce: numpy payloads ride
  a length-prefixed JSON+bytes frame over ONE TCP connection to the
  driver, which routes stage→stage (a star, so a restarted stage just
  reconnects — no peer rewiring). This is the CPU-testable reference
  path; device-to-device DCN transport slots in behind the same
  interface.

Ordering contract: the clock tables send each edge's payloads in strictly
increasing micro order, so a FIFO per (kind, edge) suffices; ``recv``
verifies the micro id it pops and raises on a schedule violation instead
of silently consuming the wrong tensor.

Failure injection: every send and recv traverses the ``pipe.xfer``
failpoint (keyed ``"<kind>:<src>-><dst>"``), the chaos hook the recovery
matrix in tests/test_mpmd.py arms. A recv past its deadline raises
:class:`ChannelTimeout` — the "peer parked at the transfer barrier"
signal the park/resync protocol (driver.py) is built on.
"""

from __future__ import annotations

import io
import json
import socket
import struct
import threading
import time
from collections import defaultdict, deque
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ....testing import chaos

#: transfer kinds — activations flow downstream, cotangents upstream
KIND_ACT = "act"
KIND_GRAD = "grad"


class ChannelTimeout(IOError):
    """recv() exceeded its deadline — the sending peer is late or dead."""


class ChannelClosed(IOError):
    """The transport is gone (peer hangup / driver teardown)."""


class LocalChannel:
    """In-process FIFO channel with explicit per-stage placement.

    ``placements``: optional {stage: jax.sharding.Sharding} — when given,
    every payload is ``jax.device_put`` onto the RECEIVING stage's
    placement at send time (the device-to-device hop). Without it the
    payload is handed over as-is (single-submesh tests).
    """

    def __init__(self, placements: Optional[Dict[int, Any]] = None):
        self._q: Dict[Tuple[str, int], deque] = defaultdict(deque)
        self.placements = placements or {}

    def send(self, kind: str, src: int, dst: int, micro: int,
             payload) -> None:
        chaos.failpoint("pipe.xfer", key=f"{kind}:{src}->{dst}")
        sh = self.placements.get(dst)
        if sh is not None:
            import jax
            payload = jax.device_put(payload, sh)
        self._q[(kind, dst)].append((micro, payload))

    def recv(self, kind: str, dst: int, micro: int,
             timeout: Optional[float] = None):
        q = self._q[(kind, dst)]
        if not q:
            # in-process execution is synchronous: an empty queue is a
            # schedule bug, not a slow peer
            raise ChannelTimeout(
                f"no {kind} payload queued for stage {dst} (micro {micro})")
        got, payload = q.popleft()
        if got != micro:
            raise RuntimeError(
                f"schedule violation: stage {dst} expected {kind} of micro "
                f"{micro}, channel delivered micro {got}")
        return payload

    def pending(self, kind: str, dst: int) -> int:
        return len(self._q[(kind, dst)])

    def clear(self) -> None:
        """Drop every queued payload (park: the in-flight step is
        abandoned, its transfers must not leak into the replay)."""
        self._q.clear()


# ---------------------------------------------------------------- wire format

def _pack_frame(meta: dict, payload: bytes = b"") -> bytes:
    head = json.dumps(meta, sort_keys=True).encode()
    return struct.pack("!II", len(head), len(payload)) + head + payload


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ChannelClosed("peer closed the transfer connection")
        buf += chunk
    return buf


def read_frame(sock: socket.socket) -> Tuple[dict, bytes]:
    hlen, plen = struct.unpack("!II", _read_exact(sock, 8))
    meta = json.loads(_read_exact(sock, hlen).decode())
    payload = _read_exact(sock, plen) if plen else b""
    return meta, payload


def write_frame(sock: socket.socket, meta: dict, payload: bytes = b"") -> None:
    sock.sendall(_pack_frame(meta, payload))


def _to_bytes(arr) -> bytes:
    bio = io.BytesIO()
    np.save(bio, np.asarray(arr), allow_pickle=False)
    return bio.getvalue()


def _from_bytes(raw: bytes) -> np.ndarray:
    return np.load(io.BytesIO(raw), allow_pickle=False)


class SocketChannel:
    """One stage's endpoint of the host-bounce star (see module docstring).

    Data frames ({kind, src, dst, micro} + npy payload) interleave with
    CONTROL frames ({cmd: park|resync|stop, ...}) from the driver on the
    same connection; :meth:`recv` parks control frames on a side queue
    for the worker loop to poll (``poll_control``), and a control frame
    that arrives while blocked in recv surfaces as :class:`ParkSignal`
    so the worker abandons its in-flight step immediately.
    """

    def __init__(self, driver_addr: Tuple[str, int], stage: int,
                 resume_step: int = 0, connect_timeout: float = 30.0):
        self.stage = stage
        #: park/resync generation — stamped on every data frame; frames
        #: from another generation are DROPPED at receipt (a peer's last
        #: sends before a park must never leak into the replayed step).
        #: Deliberately NOT the step number: healthy pipelining crosses
        #: step boundaries (a fast upstream stage legitimately sends
        #: step k+1 activations while downstream finishes step k).
        self.generation = 0
        self._lock = threading.Lock()
        deadline = time.monotonic() + connect_timeout
        last_err: Optional[Exception] = None
        while True:
            try:
                self._sock = socket.create_connection(driver_addr, timeout=5.0)
                break
            except OSError as e:          # driver not listening yet
                last_err = e
                if time.monotonic() >= deadline:
                    raise ChannelClosed(
                        f"stage {stage}: cannot reach driver at "
                        f"{driver_addr}: {last_err}")
                time.sleep(0.05)
        self._sock.settimeout(None)
        self._data: Dict[Tuple[str, int], deque] = defaultdict(deque)
        self._control: deque = deque()
        write_frame(self._sock, {"cmd": "hello", "stage": stage,
                                 "resume_step": int(resume_step)})
        # the driver answers with the CURRENT generation — a restarted
        # stage must stamp its frames so the parked survivors accept them
        welcome = self.wait_control("welcome", timeout=connect_timeout)
        self.generation = int(welcome.get("gen", 0))

    def send(self, kind: str, src: int, dst: int, micro: int,
             payload, lock_timeout: float = 30.0) -> None:
        chaos.failpoint("pipe.xfer", key=f"{kind}:{src}->{dst}")
        arr = np.asarray(payload)
        self._write({"kind": kind, "src": src, "dst": dst,
                     "micro": int(micro), "gen": self.generation},
                    _to_bytes(arr), lock_timeout)

    def send_control(self, meta: dict, lock_timeout: float = 30.0) -> None:
        self._write(meta, b"", lock_timeout)

    def _write(self, meta: dict, payload: bytes,
               lock_timeout: float) -> None:
        # bounded: a driver wedged mid-read keeps sendall — and with it
        # the frame lock — stuck; a writer starved this long is facing a
        # dead driver, and OSError is what a dead socket raises anyway
        if not self._lock.acquire(timeout=lock_timeout):
            raise OSError(
                f"channel write lock starved for {lock_timeout}s "
                "(driver wedged mid-frame?)")
        try:
            write_frame(self._sock, meta, payload)
        finally:
            self._lock.release()

    def _pump_one(self, timeout: Optional[float]) -> None:
        self._sock.settimeout(timeout)
        try:
            meta, payload = read_frame(self._sock)
        except socket.timeout:
            raise ChannelTimeout("transfer barrier deadline exceeded")
        finally:
            self._sock.settimeout(None)
        if "cmd" in meta:
            self._control.append(meta)
        elif meta.get("gen", self.generation) == self.generation:
            self._data[(meta["kind"], meta["micro"])].append(
                _from_bytes(payload))
        # else: a stale frame from an abandoned generation — dropped

    def recv(self, kind: str, dst: int, micro: int,
             timeout: Optional[float] = None) -> np.ndarray:
        assert dst == self.stage
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            q = self._data.get((kind, int(micro)))
            if q:
                return q.popleft()
            if self._control:
                # a park/stop arrived while we were waiting at the
                # barrier — surface it, the step is over
                raise ParkSignal(self._control[0].get("cmd", "park"))
            left = (None if deadline is None
                    else max(0.0, deadline - time.monotonic()))
            if left == 0.0:
                raise ChannelTimeout(
                    f"stage {self.stage}: no {kind} for micro {micro} "
                    f"within {timeout}s")
            self._pump_one(left)

    def poll_control(self, timeout: float = 0.0) -> Optional[dict]:
        """Next control frame if one is queued (or arrives within
        ``timeout``); data frames pumped meanwhile stay queued."""
        if timeout == 0.0:
            # opportunistic: one pump attempt, then answer
            if not self._control:
                try:
                    self._pump_one(0.001)
                except (ChannelTimeout, ChannelClosed):
                    pass
            return self._control.popleft() if self._control else None
        deadline = time.monotonic() + timeout
        while True:
            if self._control:
                return self._control.popleft()
            left = max(0.0, deadline - time.monotonic())
            if left == 0.0:
                return None
            try:
                self._pump_one(left)
            except ChannelTimeout:
                return None

    def wait_control(self, cmd: str, timeout: float) -> dict:
        """Block until a control frame with ``cmd`` arrives (frames for
        other commands are consumed and dropped — park acks races)."""
        deadline = time.monotonic() + timeout
        while True:
            left = deadline - time.monotonic()
            if left <= 0:
                raise ChannelTimeout(f"no '{cmd}' control within {timeout}s")
            got = self.poll_control(timeout=left)
            if got is not None and got.get("cmd") == cmd:
                return got

    def clear_data(self) -> None:
        self._data.clear()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class ParkSignal(Exception):
    """Raised out of a blocked recv when the driver parks the pipeline —
    the worker abandons the in-flight step and enters the park loop."""

    def __init__(self, cmd: str):
        super().__init__(cmd)
        self.cmd = cmd
