"""MPMD pipeline executor — per-stage jit programs over explicit transfers.

The SPMD 1F1B executor (``..one_f_one_b``) compiles ONE stacked-stage
program over the 'pipe' mesh axis; this module is the same *schedule*
under the other *placement*: each stage owns its own jit-compiled
forward and fused forward+backward programs on its own submesh, and a
host-side interpreter walks :func:`..schedule.stage_instruction_stream`
tick by tick, moving activations and cotangents through an explicit
:mod:`channel`. Nothing here touches ``shard_map`` or collectives — a
stage program only ever sees its own devices, which is exactly why a
stage can die, recompile, and rejoin alone (driver.py) and why this
path runs on jax builds whose SPMD pipeline cannot (the 0.4.x
``jax.shard_map`` gap).

Numerical contract: identical accumulation ORDER to the SPMD 1F1B
executor — grads and the last-stage loss accumulate in backward-table
tick order, the aux side channel in forward-table order — so the two
placements are loss-parity-testable against each other (and against
plain autodiff of the stacked stages; tests/test_mpmd.py pins both).
Backward is the recompute regime (the fused per-stage program re-runs
the stage body under ``jax.vjp`` from the saved boundary input — the
SPMD executor's default); the SPMD-only ``store`` residual-ring mode is
refused loudly at the engine seam.

Dispatch is host-sequential but execution is not: jax dispatch is
async, and stage programs live on disjoint devices, so downstream ticks
overlap upstream ones exactly as the clock tables intend.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..schedule import (BackwardPass, ForwardPass, LoadMicroBatch,
                        RecvActivation, RecvGrad, SendActivation, SendGrad,
                        build_tables, stage_instruction_stream)
from .channel import LocalChannel

PyTree = Any


def stage_submeshes(mesh: Mesh, pp: int, pipe_axis: str = "pipe"
                    ) -> List[Mesh]:
    """Split a global mesh along its pipe axis into one submesh per stage
    (the remaining axes survive, so intra-stage dp/tp device sets are
    preserved)."""
    names = list(mesh.axis_names)
    if pipe_axis not in names:
        raise ValueError(f"mesh {names} has no '{pipe_axis}' axis")
    i = names.index(pipe_axis)
    if mesh.devices.shape[i] != pp:
        raise ValueError(f"mesh '{pipe_axis}' axis is "
                         f"{mesh.devices.shape[i]}, expected pp={pp}")
    rest = [n for n in names if n != pipe_axis]
    subs = []
    for s in range(pp):
        dev = np.take(mesh.devices, s, axis=i)
        if not rest:
            subs.append(Mesh(dev.reshape(1), ("stage",)))
        else:
            subs.append(Mesh(dev, tuple(rest)))
    return subs


def build_stage_programs(stage_fn: Callable, loss_fn: Optional[Callable],
                         stage: int, pp: int, with_aux: bool = False
                         ) -> Dict[str, Any]:
    """The three compiled programs ONE stage needs — shared by the
    in-process executor and the cross-process stage worker, so both
    placements run byte-identical per-stage math.

    fwd(p, x, extra) -> (y, aux)
    bwd(p, x, extra, dy, aux_ct, acc) -> (acc', dx)           mid stages
    last_bwd(p, head_p, x, extra, lab, ctx, scale, aux_ct,
             acc, hacc, lacc) -> (acc', hacc', lacc', dx)     last stage

    Backward is the fused recompute regime: the stage body re-runs under
    ``jax.vjp`` from the saved boundary input (the SPMD executor's
    default mode), so nothing but [mb, ...] boundaries is ever stored
    between ticks.
    """
    from ....comm_plan.runtime import local_region
    f32 = jnp.float32
    if with_aux:
        def body(p, x, e):
            return stage_fn(p, x, e, stage)
    else:
        def body(p, x, e):
            return stage_fn(p, x, e, stage), jnp.zeros((), f32)

    # every program traces under local_region: a stage program is by
    # definition shard-LOCAL, so the model's global-mesh
    # _spec_constraint sites must no-op (the submesh is not the mesh
    # those specs name) — same seam the comm-plan unreduced trace uses
    def fwd(p, x, extra):
        with local_region():
            return body(p, x, extra)

    def bwd(p, x, extra, dy, aux_ct, acc):
        with local_region():
            (y, _aux), vjp = jax.vjp(
                lambda pl, xl: body(pl, xl, extra), p, x)
            dp, dx = vjp((dy.astype(y.dtype), aux_ct))
        acc = jax.tree.map(lambda a, d: a + d.astype(f32), acc, dp)
        return acc, dx.astype(x.dtype)

    progs = {"fwd": jax.jit(fwd), "bwd": jax.jit(bwd, donate_argnums=(5,)),
             "last_bwd": None}
    if stage == pp - 1 and loss_fn is not None:
        def last_bwd(p, head_p, x, extra, lab, ctx, scale, aux_ct,
                     acc, hacc, lacc):
            with local_region():
                (y, _aux), vjp = jax.vjp(
                    lambda pl, xl: body(pl, xl, extra), p, x)
                loss, hvjp = jax.vjp(
                    lambda h, yy: loss_fn(h, yy, lab, ctx), head_p, y)
                dh, head_dy = hvjp(scale.astype(loss.dtype))
                dp, dx = vjp((head_dy.astype(y.dtype), aux_ct))
            acc = jax.tree.map(lambda a, d: a + d.astype(f32), acc, dp)
            hacc = jax.tree.map(lambda a, d: a + d.astype(f32), hacc, dh)
            lacc = lacc + loss.astype(f32)
            return acc, hacc, lacc, dx.astype(x.dtype)
        progs["last_bwd"] = jax.jit(last_bwd, donate_argnums=(8, 9, 10))
    return progs


class MPMDPipeline:
    """Per-stage programs for one (stage_fn, loss_fn) pipeline.

    Built ONCE and reused across steps — the per-stage jits are cached on
    the instance, so a training loop pays compile exactly once per stage.

    stage_fn(one_stage_params, x, extra, stage_idx) -> y  (or (y, aux)
        when ``with_aux``) — the same body contract as both SPMD
        executors.
    loss_fn(head_params, y, labels_micro, ctx) -> scalar — LAST stage
        only. ``ctx`` is a per-call traced pytree (e.g. the global
        valid-token count) so batch-dependent loss constants never bake
        into the trace.
    devices: explicit one-device-per-stage placement (toy/tests);
    mesh: a global mesh with a '{pipe_axis}' axis of size pp (engine) —
        exactly one of the two.
    Payloads within a stage are replicated over its submesh (the
    CPU-testable reference placement; intra-stage sharded transfers ride
    the same channel seam later).
    """

    def __init__(self, stage_fn: Callable, loss_fn: Callable, *,
                 pp: int, schedule: str = "1f1b",
                 mesh: Optional[Mesh] = None,
                 devices: Optional[List] = None,
                 pipe_axis: str = "pipe",
                 with_aux: bool = False,
                 channel=None,
                 recv_timeout: Optional[float] = None):
        if (mesh is None) == (devices is None):
            raise ValueError("pass exactly one of mesh= or devices=")
        if devices is not None:
            if len(devices) != pp:
                raise ValueError(f"{len(devices)} devices for pp={pp}")
            self.submeshes = [Mesh(np.asarray([d]), ("stage",))
                              for d in devices]
        else:
            self.submeshes = stage_submeshes(mesh, pp, pipe_axis)
        self.pp = pp
        self.schedule = schedule
        self.with_aux = with_aux
        self.recv_timeout = recv_timeout
        self.placements = {s: NamedSharding(self.submeshes[s], P())
                           for s in range(pp)}
        self.channel = channel if channel is not None else LocalChannel(
            placements=self.placements)
        self._stage_fn = stage_fn
        self._loss_fn = loss_fn
        self._streams: Dict[Tuple[int, str], list] = {}
        self._progs = [build_stage_programs(stage_fn, loss_fn, s, pp,
                                            with_aux=with_aux)
                       for s in range(pp)]
        self._fwd = [p["fwd"] for p in self._progs]
        self._bwd = [p["bwd"] for p in self._progs]
        self._last_bwd = self._progs[pp - 1]["last_bwd"]

    # ---------------------------------------------------------------- helpers

    def _stream(self, n_micro: int):
        key = (n_micro, self.schedule)
        if key not in self._streams:
            tables = build_tables(self.schedule, n_micro, self.pp)
            self._streams[key] = [stage_instruction_stream(tables, s)
                                  for s in range(self.pp)]
        return self._streams[key]

    def _place(self, s: int, tree):
        return jax.tree.map(
            lambda x: jax.device_put(x, self.placements[s]), tree)

    # ------------------------------------------------------------------- step

    def value_and_grad(self, stage_params: PyTree, head_params: PyTree,
                       micros, labels, *,
                       extras: Optional[PyTree] = None,
                       loss_ctx: PyTree = (),
                       aux_cotangent: float = 0.0,
                       loss_scale=None):
        """One full pipeline step under the built schedule. Same contract
        as ``pipeline_1f1b_value_and_grad``: returns (mean task loss,
        mean aux, stage grads [pp, ...], head grads, dmicros) — grads
        SCALED when ``loss_scale`` seeds the backward."""
        pp = self.pp
        n_micro = int(micros.shape[0])
        streams = self._stream(n_micro)
        extras = {} if extras is None else extras
        f32 = jnp.float32

        scale_f = (1.0 if loss_scale is None
                   else float(jax.device_get(loss_scale)))
        aux_ct_f = float(aux_cotangent) * scale_f

        local = [self._place(s, jax.tree.map(lambda x, s=s: x[s],
                                             stage_params))
                 for s in range(pp)]
        head_local = self._place(pp - 1, head_params)
        extras_s = [self._place(s, extras) for s in range(pp)]
        labels_last = self._place(pp - 1, labels)
        ctx_last = self._place(pp - 1, loss_ctx)
        scale = jnp.asarray(scale_f, f32)
        aux_ct = jnp.asarray(aux_ct_f, f32)

        acc = [jax.tree.map(lambda x: jnp.zeros(x.shape, f32), loc)
               for loc in local]
        acc = [self._place(s, a) for s, a in enumerate(acc)]
        hacc = self._place(pp - 1, jax.tree.map(
            lambda x: jnp.zeros(x.shape, f32), head_local))
        lacc = self._place(pp - 1, jnp.zeros((), f32))
        aux_acc = [self._place(s, jnp.zeros((), f32)) for s in range(pp)]

        in_act: List[Dict[int, Any]] = [dict() for _ in range(pp)]
        in_grad: List[Dict[int, Any]] = [dict() for _ in range(pp)]
        saved_x: List[Dict[int, Any]] = [dict() for _ in range(pp)]
        out_y: List[Dict[int, Any]] = [dict() for _ in range(pp)]
        out_dx: List[Dict[int, Any]] = [dict() for _ in range(pp)]
        dmicros: Dict[int, Any] = {}

        def extra_of(s, mid):
            return jax.tree.map(lambda e: e[mid], extras_s[s])

        T = len(streams[0])
        ch = self.channel
        for t in range(T):
            for s in range(pp):
                for inst in streams[s][t]:
                    mid = inst.buffer_id
                    if isinstance(inst, RecvActivation):
                        in_act[s][mid] = ch.recv(
                            "act", s, mid, timeout=self.recv_timeout)
                    elif isinstance(inst, RecvGrad):
                        in_grad[s][mid] = ch.recv(
                            "grad", s, mid, timeout=self.recv_timeout)
                    elif isinstance(inst, LoadMicroBatch):
                        in_act[s][mid] = jax.device_put(
                            micros[mid], self.placements[s])
                    elif isinstance(inst, ForwardPass):
                        x = in_act[s].pop(mid)
                        saved_x[s][mid] = x
                        if s == pp - 1 and not self.with_aux:
                            # the fused last_bwd recomputes this body
                            # anyway and no aux rides the fwd tick —
                            # dispatching the forward here would be pure
                            # double compute on the critical-path stage
                            continue
                        y, aux = self._fwd[s](local[s], x, extra_of(s, mid))
                        aux_acc[s] = aux_acc[s] + aux
                        if s < pp - 1:
                            out_y[s][mid] = y
                    elif isinstance(inst, SendActivation):
                        ch.send("act", s, s + 1, mid, out_y[s].pop(mid))
                    elif isinstance(inst, BackwardPass):
                        xb = saved_x[s].pop(mid)
                        if s == pp - 1:
                            acc[s], hacc, lacc, dx = self._last_bwd(
                                local[s], head_local, xb, extra_of(s, mid),
                                jax.tree.map(lambda L: L[mid], labels_last),
                                ctx_last, scale, aux_ct,
                                acc[s], hacc, lacc)
                        else:
                            dy = in_grad[s].pop(mid)
                            acc[s], dx = self._bwd[s](
                                local[s], xb, extra_of(s, mid), dy, aux_ct,
                                acc[s])
                        if s == 0:
                            dmicros[mid] = dx
                        else:
                            out_dx[s][mid] = dx
                    elif isinstance(inst, SendGrad):
                        ch.send("grad", s, s - 1, mid, out_dx[s].pop(mid))

        # -- outputs (the host-bounce gather: per-stage results re-assemble
        # on host — the reference-path analogue of the SPMD psum tail)
        loss = jnp.asarray(jax.device_get(lacc), f32) / n_micro
        aux = sum(float(jax.device_get(a)) for a in aux_acc) / n_micro
        aux = jnp.asarray(aux, f32)
        grads = _stack_stage_trees([jax.device_get(a) for a in acc])
        grads = jax.tree.map(lambda g: jnp.asarray(g) / n_micro, grads)
        hgrads = jax.tree.map(lambda g: jnp.asarray(jax.device_get(g))
                              / n_micro, hacc)
        dm = np.stack([np.asarray(jax.device_get(dmicros[m]))
                       for m in range(n_micro)])
        dm = jnp.asarray(dm).astype(micros.dtype) / n_micro
        return loss, aux, grads, hgrads, dm


def _stack_stage_trees(per_stage: List[PyTree]) -> PyTree:
    """[tree_of_stage_0, ...] -> tree with a leading [pp] dim per leaf."""
    leaves0, treedef = jax.tree.flatten(per_stage[0])
    stacked = []
    for i in range(len(leaves0)):
        stacked.append(np.stack(
            [np.asarray(jax.tree.leaves(t)[i]) for t in per_stage]))
    return jax.tree.unflatten(treedef, stacked)


def mpmd_value_and_grad(stage_fn: Callable, loss_fn: Callable,
                        stage_params: PyTree, head_params: PyTree,
                        micros, labels, *,
                        pp: int,
                        mesh: Optional[Mesh] = None,
                        devices: Optional[List] = None,
                        schedule: str = "1f1b",
                        pipe_axis: str = "pipe",
                        extras: Optional[PyTree] = None,
                        with_aux: bool = False,
                        aux_cotangent: float = 0.0,
                        loss_scale=None,
                        loss_ctx: PyTree = (),
                        channel=None):
    """One-shot functional wrapper (tests, parity oracles): builds an
    :class:`MPMDPipeline` and runs a single step. Training loops should
    hold the pipeline object instead — it caches the per-stage compiles.
    """
    pipe = MPMDPipeline(stage_fn, loss_fn, pp=pp, schedule=schedule,
                        mesh=mesh, devices=devices, pipe_axis=pipe_axis,
                        with_aux=with_aux, channel=channel)
    return pipe.value_and_grad(stage_params, head_params, micros, labels,
                               extras=extras, loss_ctx=loss_ctx,
                               aux_cotangent=aux_cotangent,
                               loss_scale=loss_scale)
