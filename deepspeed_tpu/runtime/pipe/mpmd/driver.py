"""MPMD stage supervisor — spawn, route, and single-stage restart.

The launcher-side half of the MPMD pipeline's elasticity story. Where
RunSupervisor (launcher/supervisor.py) tears the WORLD down on first
failure — correct for an SPMD program whose ranks are one failure
domain — stages of an MPMD pipeline are independent programs, so the
right response to a dead stage is to restart THAT stage and park the
rest. This supervisor:

* spawns one ``stage_worker`` process per stage (per-stage argv/env via
  :class:`StageWorkerSpec` — chaos specs ride the env exactly like the
  launcher's DSTPU_* forwarding);
* owns the transfer star: every worker holds ONE TCP connection here,
  and a router thread forwards data frames stage→stage — a restarted
  stage simply reconnects, no peer rewiring (the host-bounce reference
  topology; device-to-device DCN slots in behind the same channel
  interface);
* supervises through the EXISTING substrate: worker rc's follow the
  0/114/117/118 contract (114 restarts uncounted, 117/crash restarts
  counted against ``max_restarts``, 118 aborts the world), and the
  per-stage heartbeat channel (STAGE gauge) is shared with
  ``dstpu health``;
* on a counted death runs the park/resync protocol: survivors park (in
  place — their processes, compiles, and connections survive), the dead
  stage restarts and restores its newest durable tag, then every stage
  resyncs to that step and training replays from there — each
  microbatch applied exactly once (tests/test_mpmd.py pins the loss
  trajectory against an uninjected twin).

Exit code: 0 when every stage finishes; otherwise the triggering rc
aggregated RunSupervisor-style (integrity 118 > voluntary crash rc >
stall 117 > preemption 114).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence

from ....exit_codes import INTEGRITY_EXIT_CODE, PREEMPTION_EXIT_CODE
from ...fabric import HubConn, read_frame
from ...watchdog import STALL_EXIT_CODE


class StageWorkerSpec:
    """Per-stage launch description: extra argv appended to the common
    worker command and env overlaid on the inherited environment.
    ``env_first`` applies ONLY to the initial spawn, not to restarts —
    a one-shot chaos spec must not re-arm in the restarted process
    (fresh processes re-read DSTPU_CHAOS with fresh hit counters)."""

    def __init__(self, extra_argv: Optional[Sequence[str]] = None,
                 env: Optional[Dict[str, str]] = None,
                 env_first: Optional[Dict[str, str]] = None):
        self.extra_argv = list(extra_argv or [])
        self.env = dict(env or {})
        self.env_first = dict(env_first or {})
        self._spawned = False


class _StageConn(HubConn):
    """Hub-side stage connection — the fabric :class:`HubConn` (bounded
    write lock: a peer wedged mid-read starves later senders into the
    OSError a dead peer raises anyway) plus the stage's resume step."""

    def __init__(self, sock: socket.socket, resume_step: int):
        super().__init__(sock)
        self.resume_step = resume_step


class MPMDStageSupervisor:
    """See module docstring. ``worker_argv_base`` is the common command
    prefix (without --stage/--driver-port); the supervisor appends
    per-stage arguments and its own port."""

    def __init__(self, pp: int, *,
                 workdir: str,
                 steps: int,
                 n_micro: int = 4,
                 schedule: str = "1f1b",
                 specs: Optional[List[StageWorkerSpec]] = None,
                 worker_argv_base: Optional[List[str]] = None,
                 max_restarts: int = 2,
                 grace_secs: float = 5.0,
                 park_ack_timeout: float = 20.0,
                 restart_timeout: float = 60.0,
                 heartbeat_dir: Optional[str] = None,
                 heartbeat_timeout: float = 0.0,
                 log_dir: Optional[str] = None,
                 worker_args: Optional[List[str]] = None):
        self.pp = pp
        self.workdir = workdir
        self.steps = steps
        self.n_micro = n_micro
        self.schedule = schedule
        self.specs = specs or [StageWorkerSpec() for _ in range(pp)]
        if len(self.specs) != pp:
            raise ValueError(f"{len(self.specs)} specs for pp={pp}")
        self.max_restarts = max_restarts
        self.grace_secs = grace_secs
        self.park_ack_timeout = park_ack_timeout
        self.restart_timeout = restart_timeout
        self.heartbeat_dir = heartbeat_dir
        self.heartbeat_timeout = heartbeat_timeout
        self.log_dir = log_dir
        self.worker_args = list(worker_args or [])
        #: None = the default -c bootstrap (sys.path injection); a custom
        #: base argv replaces the whole command prefix
        self._base = worker_argv_base
        self.procs: List[Optional[subprocess.Popen]] = [None] * pp
        self.conns: Dict[int, _StageConn] = {}
        self.restarts = [0] * pp
        self.preemptions = [0] * pp
        self.generation = 0
        self.parked: set = set()
        self.done: set = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._server: Optional[socket.socket] = None
        self.port: Optional[int] = None
        self._logs: List[Optional[object]] = [None] * pp

    # -------------------------------------------------------------- plumbing

    def _worker_cmd(self, stage: int) -> List[str]:
        ckpt = os.path.join(self.workdir, f"stage{stage}")
        argv = [
            "--stage", str(stage), "--pp", str(self.pp),
            "--n-micro", str(self.n_micro), "--steps", str(self.steps),
            "--schedule", self.schedule,
            "--driver-port", str(self.port),
            "--ckpt-dir", ckpt,
        ] + self.worker_args + self.specs[stage].extra_argv
        if self._base is not None:
            return self._base + argv
        # the worker must import this package regardless of the
        # supervisor's cwd — via sys.path INSIDE the child, never
        # PYTHONPATH: an inherited PYTHONPATH pointing at the repo
        # shadows TPU-plugin deps during the child's sitecustomize
        # (documented in .claude/skills/verify)
        import deepspeed_tpu
        pkg_root = os.path.dirname(os.path.dirname(deepspeed_tpu.__file__))
        boot = ("import sys; sys.path.insert(0, {root!r}); "
                "from deepspeed_tpu.runtime.pipe.mpmd.stage_worker "
                "import main; raise SystemExit(main({argv!r}))").format(
                    root=pkg_root, argv=argv)
        return [sys.executable, "-c", boot]

    def _spawn(self, stage: int) -> None:
        spec = self.specs[stage]
        env = dict(os.environ)
        env.update(spec.env)
        if not spec._spawned:
            env.update(spec.env_first)
            spec._spawned = True
        if self.heartbeat_dir:
            env["DSTPU_HEARTBEAT_DIR"] = self.heartbeat_dir
        out = None
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
            if self._logs[stage] is None:
                self._logs[stage] = open(
                    os.path.join(self.log_dir, f"stage{stage}.log"), "ab")
            out = self._logs[stage]
        self.procs[stage] = subprocess.Popen(
            self._worker_cmd(stage), env=env, stdout=out,
            stderr=subprocess.STDOUT if out else None)

    def _router(self) -> None:
        """Accept stage connections and forward frames. One reader thread
        per connection keeps the star simple; writes serialize per-conn."""
        while not self._stop.is_set():
            try:
                self._server.settimeout(0.2)
                sock, _ = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(sock,),
                             daemon=True).start()

    def _serve_conn(self, sock: socket.socket) -> None:
        stage = None
        try:
            meta, _ = read_frame(sock)
            if meta.get("cmd") != "hello":
                sock.close()
                return
            stage = int(meta["stage"])
            conn = _StageConn(sock, int(meta.get("resume_step", 0)))
            with self._lock:
                self.conns[stage] = conn
                self.parked.discard(stage)
                gen = self.generation
            # hand the joiner the current park/resync generation so its
            # frames are accepted by peers that lived through restarts
            conn.send({"cmd": "welcome", "gen": gen})
            while not self._stop.is_set():
                meta, payload = read_frame(sock)
                if "cmd" in meta:
                    with self._lock:
                        if meta["cmd"] == "parked":
                            self.parked.add(int(meta["stage"]))
                        elif meta["cmd"] == "done":
                            self.done.add(int(meta["stage"]))
                    continue
                dst = int(meta["dst"])
                with self._lock:
                    target = self.conns.get(dst)
                if target is not None:
                    try:
                        target.send(meta, payload)
                    except OSError:
                        pass        # dst died; its restart will resync
        except OSError:
            pass                    # reader ends when the peer goes away
        finally:
            if stage is not None:
                with self._lock:
                    if self.conns.get(stage) is not None \
                            and self.conns[stage].sock is sock:
                        del self.conns[stage]

    def _broadcast(self, meta: dict, exclude: Optional[int] = None) -> None:
        with self._lock:
            targets = [c for st, c in self.conns.items() if st != exclude]
        for c in targets:
            try:
                c.send(meta)
            except OSError:
                pass

    # ------------------------------------------------------------------- run

    def start(self) -> "MPMDStageSupervisor":
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(("127.0.0.1", 0))
        self._server.listen(self.pp + 2)
        self.port = self._server.getsockname()[1]
        threading.Thread(target=self._router, daemon=True).start()
        for s in range(self.pp):
            self._spawn(s)
        return self

    def run(self) -> int:
        if self._server is None:
            self.start()
        try:
            return self._run()
        finally:
            self._teardown()

    def _run(self) -> int:
        done_deadline = None
        while True:
            time.sleep(0.05)
            with self._lock:
                all_done = len(self.done) == self.pp
            rcs = [(s, p.poll()) for s, p in enumerate(self.procs)
                   if p is not None]
            exited = {s: rc for s, rc in rcs if rc is not None}
            if all_done:
                # every stage reported done: the run's RESULT is final.
                # Drain process exits (bounded by grace), then return 0
                # even if a worker's post-done teardown died or wedged —
                # hb write / close hiccups must not hang or fail a
                # finished run (_teardown kills any straggler).
                if done_deadline is None:
                    done_deadline = time.monotonic() + self.grace_secs
                if all(p is None or p.poll() is not None
                       for p in self.procs) or \
                        time.monotonic() >= done_deadline:
                    return 0
                continue
            if len(exited) == self.pp and \
                    all(rc == 0 for rc in exited.values()):
                return 0
            dead = [(s, rc) for s, rc in exited.items()
                    if rc != 0 and s not in self.done]
            if not dead:
                self._check_heartbeat_silence()
                continue
            s, rc = dead[0]
            if rc == INTEGRITY_EXIT_CODE:
                return INTEGRITY_EXIT_CODE
            counted = rc != PREEMPTION_EXIT_CODE
            if counted:
                self.restarts[s] += 1
                if self.restarts[s] > self.max_restarts:
                    return STALL_EXIT_CODE if rc == STALL_EXIT_CODE else rc
            else:
                self.preemptions[s] += 1
            if not self._recover(s):
                return STALL_EXIT_CODE

    def _check_heartbeat_silence(self) -> None:
        """A stage whose heartbeat went silent past the deadline is
        wedged-but-alive: kill it so the rc path takes over (the kill
        surfaces as a counted death and the stage restarts)."""
        if not (self.heartbeat_dir and self.heartbeat_timeout > 0):
            return
        from ...heartbeat import stale_ranks
        for rec in stale_ranks(self.heartbeat_dir, self.heartbeat_timeout):
            s = int(rec["rank"])
            p = self.procs[s] if 0 <= s < self.pp else None
            if p is not None and p.poll() is None:
                p.kill()

    def _recover(self, stage: int) -> bool:
        """Park survivors -> restart ``stage`` -> resync everyone to the
        restarted stage's restored step. True on success. The parked set
        is sticky until resync: a survivor still parked from a previous
        (failed) recovery round counts as acked."""
        with self._lock:
            self.conns.pop(stage, None)
            self.generation += 1
        self._broadcast({"cmd": "park"}, exclude=stage)
        live = [s for s in range(self.pp)
                if s != stage and s not in self.done]
        deadline = time.monotonic() + self.park_ack_timeout
        while time.monotonic() < deadline:
            with self._lock:
                if all(s in self.parked for s in live):
                    break
            time.sleep(0.02)
        self._spawn(stage)
        deadline = time.monotonic() + self.restart_timeout
        while time.monotonic() < deadline:
            with self._lock:
                conn = self.conns.get(stage)
            if conn is not None:
                break
            if self.procs[stage].poll() is not None:
                # died again before hello: surface the fresh rc to the
                # main loop so the restart budget sees every death
                return True
            time.sleep(0.02)
        else:
            return False
        resume = conn.resume_step
        with self._lock:
            gen = self.generation
        self._broadcast({"cmd": "resync", "step": int(resume), "gen": gen},
                        exclude=stage)
        with self._lock:
            self.parked.clear()
        return True

    def _teardown(self) -> None:
        self._stop.set()
        for p in self.procs:
            if p is not None and p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + self.grace_secs
        for p in self.procs:
            if p is None:
                continue
            while p.poll() is None and time.monotonic() < deadline:
                time.sleep(0.02)
            if p.poll() is None:
                p.kill()
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass
        for f in self._logs:
            if f is not None:
                try:
                    f.close()
                except OSError:
                    pass
