"""PipelineEngine — train/eval over the SPMD pipeline.

Capability parity with the reference's ``runtime/pipe/engine.py``
(PipelineEngine(DeepSpeedEngine): train_batch/eval_batch as the only public
step APIs, micro_batches == gradient_accumulation_steps, forward/backward/step
redirected). Two placements of the same schedules (round 13,
docs/PIPELINE.md):

* ``pipeline.placement="spmd"`` (default): one jitted train step whose
  pipeline loop lives inside the model's apply (models/pipeline.py +
  runtime/pipe/spmd.py / one_f_one_b.py); XLA overlaps the ppermute
  transfers with stage compute.
* ``pipeline.placement="mpmd"``: the reference's own shape — an
  instruction-stream interpreter over per-stage programs and an explicit
  transfer layer (runtime/pipe/mpmd) — as a host-driven step plus one
  jitted finalize tail.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ..engine import DeepSpeedEngine


class PipelineEngine(DeepSpeedEngine):
    """Engine whose model pipelines its own microbatch loop.

    The model's apply consumes the FULL global batch (splitting it into
    pipeline microbatches internally), so the parent's gas-scan is bypassed:
    one apply == gas microbatches == one optimizer step.
    """

    def _make_train_step(self):
        schedule = self.config.pipeline.schedule
        if schedule not in ("gpipe", "1f1b"):
            raise ValueError(f"unknown pipeline.schedule '{schedule}' "
                             "(gpipe | 1f1b)")
        placement = getattr(self.config.pipeline, "placement", "spmd")
        if placement not in ("spmd", "mpmd"):
            raise ValueError(f"unknown pipeline.placement '{placement}' "
                             "(spmd | mpmd)")
        mpmd = placement == "mpmd"
        if mpmd and not hasattr(self.module, "mpmd_value_and_grad"):
            raise ValueError(
                "pipeline.placement='mpmd' needs a model exposing "
                "mpmd_value_and_grad (models.pipeline.PipelinedTransformer)")
        use_1f1b = schedule == "1f1b" and not mpmd
        if use_1f1b and not hasattr(self.module, "train_value_and_grad"):
            raise ValueError(
                "pipeline.schedule='1f1b' needs a model exposing "
                "train_value_and_grad (models.pipeline.PipelinedTransformer); "
                "this module only supports the gpipe schedule")
        custom_loss = None
        aux_weight = None
        if use_1f1b or mpmd:
            from ..engine import _default_loss_fn
            from ...models.transformer import causal_lm_loss
            lf = self.loss_fn
            if getattr(lf, "_moe_loss", False):
                # MoE losses split: the aux term is computed by the executor
                # itself (the scalar rides the pipe); only the BASE task
                # loss goes to the last stage
                aux_weight = lf._moe_aux_weight
                lf = lf._moe_base_loss
            if lf not in (causal_lm_loss, _default_loss_fn):
                # a user loss runs per-micro at the last stage (per-micro
                # losses averaged — the reference _aggregate_total_loss)
                custom_loss = lf
                if getattr(getattr(self.module, "cfg", None),
                           "moe_experts", 0) > 0 and aux_weight is None:
                    # the 1F1B executor computes the aux term itself (the
                    # scalar rides the pipe) and hands the last stage BARE
                    # logits — a gpipe-style loss_fn expecting the model's
                    # (logits, aux) tuple would silently index the batch
                    # dim instead, and one folding aux in itself would
                    # double-count it
                    raise ValueError(
                        "the hand-scheduled pipeline executors (1f1b / "
                        "placement='mpmd') with an MoE model need the loss "
                        "built by models.make_moe_loss(aux_weight, "
                        "base_loss=...): the executor computes the aux "
                        "term itself and passes the base loss bare logits, "
                        "so a raw loss_fn written against the model's "
                        "(logits, aux) output would misread its input.")
                from ...utils.logging import warning_once
                warning_once(
                    "the hand-scheduled pipeline executors (1f1b / "
                    "placement='mpmd') compute a custom loss_fn "
                    "PER MICROBATCH and average the results (the "
                    "reference's _aggregate_total_loss semantics). For "
                    "per-token-mean losses this equals the full-batch "
                    "value; losses normalized over data-dependent counts "
                    "(e.g. valid -100-masked tokens) will weight micros "
                    "differently than the gpipe schedule's full-batch "
                    "evaluation.")

        if mpmd:
            return self._make_train_step_mpmd(schedule, custom_loss,
                                              aux_weight)

        def train_step(state, batch, rng, lr_arg):
            if use_1f1b:
                # hand-scheduled interleave: loss+grads straight from the
                # 1F1B executor (runtime/pipe/one_f_one_b), no AD through
                # the pipeline scan. fp16: the scale seeds the backward and
                # grads come out scaled — _finalize_step's standard
                # unscale/overflow tail applies.
                loss, grads = self.module.train_value_and_grad(
                    state.params, batch, mesh=self.mesh, rng=rng,
                    loss_scale=(state.scale.scale
                                if self.loss_scaler.enabled else None),
                    loss_fn=custom_loss, aux_weight=aux_weight)
            else:
                def scaled_loss(p):
                    out = self.apply_fn(p, batch, rng, True)
                    loss = self.loss_fn(out, batch)
                    return (loss * state.scale.scale).astype(jnp.float32), loss

                grads, loss = jax.grad(scaled_loss, has_aux=True)(state.params)
            grads = jax.tree.map(
                lambda g, s: jax.lax.with_sharding_constraint(
                    g.astype(jnp.float32), s), grads, self.grad_shardings)
            # loss is already the mean over all microbatches -> n_micro=1
            new_state, metrics = self._finalize_step(state, grads, 1.0, lr_arg)
            metrics["loss"] = loss
            return new_state, metrics

        return jax.jit(train_step, donate_argnums=(0,))

    def _make_train_step_mpmd(self, schedule, custom_loss, aux_weight):
        """MPMD placement: the step is HOST-driven — the executor walks
        the per-stage instruction streams calling each stage's own
        compiled program (runtime/pipe/mpmd/executor), so there is no
        single whole-pipeline jit to build. Only the shared finalize
        tail (unscale/clip/optimize/skip — identical math to every other
        step path) is one compiled program over the global mesh.
        """
        finalize = None

        def train_step(state, batch, rng, lr_arg):
            nonlocal finalize
            loss, grads = self.module.mpmd_value_and_grad(
                state.params, batch, mesh=self.mesh, rng=rng,
                loss_scale=(state.scale.scale
                            if self.loss_scaler.enabled else None),
                loss_fn=custom_loss, aux_weight=aux_weight,
                schedule=schedule)
            if finalize is None:
                def _finalize(state, grads, lr_arg):
                    grads = jax.tree.map(
                        lambda g, s: jax.lax.with_sharding_constraint(
                            g.astype(jnp.float32), s),
                        grads, self.grad_shardings)
                    return self._finalize_step(state, grads, 1.0, lr_arg)
                finalize = jax.jit(_finalize, donate_argnums=(0,))
            new_state, metrics = finalize(state, grads, lr_arg)
            metrics["loss"] = loss
            return new_state, metrics

        return train_step

    def train_batch(self, data_iter_or_batch) -> Dict[str, Any]:
        batch = (next(data_iter_or_batch)
                 if hasattr(data_iter_or_batch, "__next__")
                 else data_iter_or_batch)
        if self.optimizer is None:
            raise RuntimeError("PipelineEngine needs an optimizer")
        batch = self.shard_batch(batch)
        self.tput_timer.start()
        self.state, metrics = self._train_step(self.state, batch,
                                               self.next_rng(),
                                               self._current_lr())
        self.tput_timer.stop(sync=metrics["loss"])
        self._after_step(metrics)
        return metrics

    def eval_batch(self, data_iter_or_batch):
        batch = (next(data_iter_or_batch)
                 if hasattr(data_iter_or_batch, "__next__")
                 else data_iter_or_batch)
        batch = self.shard_batch(batch)
        return self._eval_step(self.state.params, batch, self.next_rng(),
                               self.state.step)

    # the reference redirects these for pipeline engines (engine.py:1246-1256)
    def forward(self, *a, **k):
        raise RuntimeError("PipelineEngine: use train_batch/eval_batch instead "
                           "of forward()")

    def backward(self, *a, **k):
        raise RuntimeError("PipelineEngine: use train_batch/eval_batch instead "
                           "of backward()")

    def step(self, *a, **k):
        raise RuntimeError("PipelineEngine: use train_batch/eval_batch instead "
                           "of step()")
