"""Pipeline instruction schedules — pure-Python, device-free.

Capability parity with the reference's ``runtime/pipe/schedule.py``
(PipeSchedule ABC, TrainSchedule 1F1B, InferenceSchedule, instruction vocab).

This module is the SCHEDULE half of the schedule/placement split (round 13):
it decides *what ticks happen* — which microbatch each stage forwards,
backwards, sends and receives at every clock tick — while the placement
layer decides *where they execute*:

  * SPMD placement (spmd.py GPipe scan, one_f_one_b.py 1F1B interleave):
    one stacked-stage program over the 'pipe' mesh axis; the clock tables
    built here drive the masked scan body, transfers are ``lax.ppermute``.
  * MPMD placement (mpmd/): each stage is its OWN jit program on its own
    submesh or process, and :func:`stage_instruction_stream` renders the
    same clock tables as per-stage instruction lists — the reference's
    ``_exec_schedule`` shape — interpreted tick by tick against an
    explicit transfer channel.

Both placements execute the SAME tables (``build_1f1b_tables`` /
``build_gpipe_tables``), which is what makes them loss-parity-testable
against each other. The legacy generator schedules (TrainSchedule etc.)
remain as the reference-API view; ``stage_instruction_stream`` is the
clock-aligned equivalent the executors actually consume.
"""

from __future__ import annotations

from typing import Dict, Iterator, List

import numpy as np


# -- instruction vocabulary (reference: schedule.py:336-476) ------------------

class PipeInstruction:
    def __init__(self, **kwargs):
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        args = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
        return f"{type(self).__name__}({args})"

    def __eq__(self, other):
        return type(self) is type(other) and self.kwargs == other.kwargs


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class LoadMicroBatch(PipeInstruction):
    pass


class BufferOpInstruction(PipeInstruction):
    pass


class ForwardPass(BufferOpInstruction):
    pass


class BackwardPass(BufferOpInstruction):
    pass


class SendActivation(BufferOpInstruction):
    pass


class RecvActivation(BufferOpInstruction):
    pass


class SendGrad(BufferOpInstruction):
    pass


class RecvGrad(BufferOpInstruction):
    pass


# -- schedules ---------------------------------------------------------------

class PipeSchedule:
    """Yields, per clock tick, the list of instructions one stage executes."""

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        if not 0 <= stage_id < stages:
            raise ValueError(f"stage_id {stage_id} out of range for {stages} stages")
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    @property
    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    @property
    def is_last_stage(self) -> bool:
        return self.stage_id == self.stages - 1

    def num_pipe_buffers(self) -> int:
        return self.micro_batches

    def steps(self) -> Iterator[List[PipeInstruction]]:
        raise NotImplementedError

    def __iter__(self):
        return self.steps()

    def _buffer_idx(self, micro_batch_id: int) -> int:
        return micro_batch_id % self.num_pipe_buffers()


class InferenceSchedule(PipeSchedule):
    """Forward-only fill-drain: each tick forwards one microbatch downstream."""

    def num_pipe_buffers(self) -> int:
        return 2

    def steps(self):
        total = self.micro_batches + self.stages - 1
        for tick in range(total):
            cmds: List[PipeInstruction] = []
            mb = tick - self.stage_id
            if 0 <= mb < self.micro_batches:
                buf = self._buffer_idx(mb)
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(buffer_id=buf))
                else:
                    cmds.append(RecvActivation(buffer_id=buf))
                cmds.append(ForwardPass(buffer_id=buf))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buffer_id=buf))
            yield cmds


class TrainSchedule(PipeSchedule):
    """1F1B: warm up with (stages-1-stage_id) forwards, then alternate 1
    forward / 1 backward, drain remaining backwards, then reduce + step.

    Bubble fraction = (stages-1)/(micro_batches+stages-1), identical to the
    reference's schedule (schedule.py:182-289).
    """

    def num_pipe_buffers(self) -> int:
        # in-flight activations this stage must hold (reference: 289)
        return max(2, min(self.micro_batches, self.stages - self.stage_id))

    def _forwards_before_first_backward(self) -> int:
        return min(self.micro_batches, self.stages - self.stage_id)

    def steps(self):
        m, s, sid = self.micro_batches, self.stages, self.stage_id
        warmup = min(m, s - 1 - sid)
        fwd_id, bwd_id = 0, 0
        # clock-aligned: stage sid idles sid ticks before its first forward
        for _ in range(sid):
            yield []
        # warmup forwards
        for _ in range(warmup):
            yield self._fwd_cmds(fwd_id)
            fwd_id += 1
        # steady state: 1F1B
        while fwd_id < m:
            yield self._fwd_cmds(fwd_id) + self._bwd_cmds(bwd_id)
            fwd_id += 1
            bwd_id += 1
        # drain backwards
        while bwd_id < m:
            yield self._bwd_cmds(bwd_id)
            bwd_id += 1
        yield [ReduceTiedGrads(), ReduceGrads(), OptimizerStep()]

    def _fwd_cmds(self, mb: int) -> List[PipeInstruction]:
        buf = self._buffer_idx(mb)
        cmds: List[PipeInstruction] = []
        if self.is_first_stage:
            cmds.append(LoadMicroBatch(buffer_id=buf))
        else:
            cmds.append(RecvActivation(buffer_id=buf))
        cmds.append(ForwardPass(buffer_id=buf))
        if not self.is_last_stage:
            cmds.append(SendActivation(buffer_id=buf))
        return cmds

    def _bwd_cmds(self, mb: int) -> List[PipeInstruction]:
        buf = self._buffer_idx(mb)
        cmds: List[PipeInstruction] = []
        if not self.is_last_stage:
            cmds.append(RecvGrad(buffer_id=buf))
        cmds.append(BackwardPass(buffer_id=buf))
        if not self.is_first_stage:
            cmds.append(SendGrad(buffer_id=buf))
        return cmds


class DataParallelSchedule(PipeSchedule):
    """Degenerate single-stage schedule (reference: schedule.py:477+)."""

    def num_pipe_buffers(self) -> int:
        return 1

    def steps(self):
        for mb in range(self.micro_batches):
            yield [LoadMicroBatch(buffer_id=0), ForwardPass(buffer_id=0),
                   BackwardPass(buffer_id=0)]
        yield [ReduceGrads(), OptimizerStep()]


def bubble_fraction(micro_batches: int, stages: int) -> float:
    """Idle fraction of the GPipe/1F1B pipeline."""
    return (stages - 1) / (micro_batches + stages - 1)


# -- clock-aligned tick tables (the schedule/placement seam) ------------------

def build_1f1b_tables(n_micro: int, pp: int) -> Dict[str, np.ndarray]:
    """Clock-aligned 1F1B tables via event simulation.

    Returns arrays [T, pp]: fwd[t,s] / bwd[t,s] = micro id computed (-1 =
    bubble), recv_f[t,s] = micro id whose activation ARRIVES at (t,s) from
    s-1 (sent at t-1), recv_b[t,s] = cotangent arriving from s+1. Every
    stage obeys: warmup of (pp-1-s) forwards, then backward-priority
    alternation (the reference TrainSchedule discipline, schedule.py:151).

    Consumed by BOTH placements: the SPMD 1F1B executor's masked scan body
    (one_f_one_b.py) and the MPMD per-stage interpreter (mpmd/executor.py,
    via :func:`stage_instruction_stream`).
    """
    slots = min(pp, n_micro)
    fwd_done = -np.ones((pp, n_micro), np.int64)    # tick fwd finished
    bwd_done = -np.ones((pp, n_micro), np.int64)
    fwd_next = [0] * pp
    bwd_next = [0] * pp
    rows_f, rows_b = [], []
    t = 0
    while any(b < n_micro for b in bwd_next):
        row_f = [-1] * pp
        row_b = [-1] * pp
        for s in range(pp):
            f, b = fwd_next[s], bwd_next[s]
            # a tick holds one forward AND one backward (the executor's scan
            # body computes both — that IS the 1F1B steady state); the ring
            # capacity caps in-flight forwards
            if f < n_micro and f - b < slots and (
                    s == 0 or 0 <= fwd_done[s - 1, f] < t):
                row_f[s] = f
                fwd_done[s, f] = t
                fwd_next[s] += 1
            if b < n_micro and (
                    (s == pp - 1 and 0 <= fwd_done[s, b] <= t)
                    or (s < pp - 1 and 0 <= bwd_done[s + 1, b] < t)):
                row_b[s] = b
                bwd_done[s, b] = t
                bwd_next[s] += 1
        rows_f.append(row_f)
        rows_b.append(row_b)
        t += 1
        if t > 6 * (n_micro + pp) + 8:
            raise RuntimeError("1F1B schedule failed to converge")
    fwd = np.asarray(rows_f, np.int32)
    bwd = np.asarray(rows_b, np.int32)
    T = fwd.shape[0]
    recv_f = -np.ones_like(fwd)
    recv_b = -np.ones_like(bwd)
    recv_f[1:, 1:] = fwd[:-1, :-1]
    recv_b[1:, :-1] = bwd[:-1, 1:]
    return {"fwd": fwd, "bwd": bwd, "recv_f": recv_f, "recv_b": recv_b,
            "ticks": T}


def build_gpipe_tables(n_micro: int, pp: int) -> Dict[str, np.ndarray]:
    """Clock-aligned GPipe tables: full forward fill/drain, then the full
    backward wave in reverse pipeline direction — same array contract as
    :func:`build_1f1b_tables`, so the MPMD interpreter runs either
    schedule through one code path. In-flight forwards reach ``n_micro``
    (the GPipe memory regime), unlike 1F1B's ``min(pp, n_micro)`` bound.
    """
    T_f = n_micro + pp - 1
    T = T_f + n_micro + pp - 1
    fwd = -np.ones((T, pp), np.int32)
    bwd = -np.ones((T, pp), np.int32)
    for t in range(T_f):
        for s in range(pp):
            m = t - s
            if 0 <= m < n_micro:
                fwd[t, s] = m
    # backward: stage pp-1 leads (micro m at T_f+m); stage s waits
    # (pp-1-s) extra ticks for the cotangent to ripple upstream
    for m in range(n_micro):
        for s in range(pp):
            bwd[T_f + m + (pp - 1 - s), s] = m
    recv_f = -np.ones_like(fwd)
    recv_b = -np.ones_like(bwd)
    recv_f[1:, 1:] = fwd[:-1, :-1]
    recv_b[1:, :-1] = bwd[:-1, 1:]
    return {"fwd": fwd, "bwd": bwd, "recv_f": recv_f, "recv_b": recv_b,
            "ticks": T}


def build_tables(schedule: str, n_micro: int, pp: int) -> Dict[str, np.ndarray]:
    """Tick tables for a named schedule ('gpipe' | '1f1b')."""
    if schedule == "1f1b":
        return build_1f1b_tables(n_micro, pp)
    if schedule == "gpipe":
        return build_gpipe_tables(n_micro, pp)
    raise ValueError(f"unknown pipeline schedule {schedule!r} (gpipe | 1f1b)")


def stage_instruction_stream(tables: Dict[str, np.ndarray], stage: int,
                             ) -> List[List[PipeInstruction]]:
    """Render ONE stage's view of the clock tables as per-tick instruction
    lists — the reference's ``_exec_schedule`` shape, using the same
    instruction vocabulary the generator schedules yield. ``buffer_id``
    carries the MICRO id (the MPMD interpreter keys its buffers by micro;
    the legacy generators' ``micro % num_pipe_buffers`` ring indexing is a
    placement concern, not a schedule one).

    Receives are ordered before computes within a tick (the payload was
    sent one tick earlier and must be consumed before the matching
    forward/backward fires).
    """
    pp = tables["fwd"].shape[1]
    if not 0 <= stage < pp:
        raise ValueError(f"stage {stage} out of range for {pp} stages")
    out: List[List[PipeInstruction]] = []
    for t in range(int(tables["ticks"])):
        cmds: List[PipeInstruction] = []
        rf = int(tables["recv_f"][t, stage])
        rb = int(tables["recv_b"][t, stage])
        f = int(tables["fwd"][t, stage])
        b = int(tables["bwd"][t, stage])
        if rf >= 0:
            cmds.append(RecvActivation(buffer_id=rf))
        if rb >= 0:
            cmds.append(RecvGrad(buffer_id=rb))
        if f >= 0:
            if stage == 0:
                cmds.append(LoadMicroBatch(buffer_id=f))
            cmds.append(ForwardPass(buffer_id=f))
            if stage < pp - 1:
                cmds.append(SendActivation(buffer_id=f))
        if b >= 0:
            cmds.append(BackwardPass(buffer_id=b))
            if stage > 0:
                cmds.append(SendGrad(buffer_id=b))
        out.append(cmds)
    return out
