"""Pipeline instruction schedules — pure-Python, device-free.

Capability parity with the reference's ``runtime/pipe/schedule.py``
(PipeSchedule ABC, TrainSchedule 1F1B, InferenceSchedule, instruction vocab).
On TPU the *execution* of pipeline parallelism is a single SPMD program
(spmd.py: collective-permute microbatch loop compiled by XLA), so these
schedules are not interpreted per-rank at runtime the way the reference's
``_exec_schedule`` does — they exist as the analyzable/testable model of the
pipeline (bubble accounting, buffer counts, schedule visualization) and for
API parity. The instruction vocabulary matches the reference's names.
"""

from __future__ import annotations

from typing import Iterator, List


# -- instruction vocabulary (reference: schedule.py:336-476) ------------------

class PipeInstruction:
    def __init__(self, **kwargs):
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        args = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
        return f"{type(self).__name__}({args})"

    def __eq__(self, other):
        return type(self) is type(other) and self.kwargs == other.kwargs


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class LoadMicroBatch(PipeInstruction):
    pass


class BufferOpInstruction(PipeInstruction):
    pass


class ForwardPass(BufferOpInstruction):
    pass


class BackwardPass(BufferOpInstruction):
    pass


class SendActivation(BufferOpInstruction):
    pass


class RecvActivation(BufferOpInstruction):
    pass


class SendGrad(BufferOpInstruction):
    pass


class RecvGrad(BufferOpInstruction):
    pass


# -- schedules ---------------------------------------------------------------

class PipeSchedule:
    """Yields, per clock tick, the list of instructions one stage executes."""

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        if not 0 <= stage_id < stages:
            raise ValueError(f"stage_id {stage_id} out of range for {stages} stages")
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    @property
    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    @property
    def is_last_stage(self) -> bool:
        return self.stage_id == self.stages - 1

    def num_pipe_buffers(self) -> int:
        return self.micro_batches

    def steps(self) -> Iterator[List[PipeInstruction]]:
        raise NotImplementedError

    def __iter__(self):
        return self.steps()

    def _buffer_idx(self, micro_batch_id: int) -> int:
        return micro_batch_id % self.num_pipe_buffers()


class InferenceSchedule(PipeSchedule):
    """Forward-only fill-drain: each tick forwards one microbatch downstream."""

    def num_pipe_buffers(self) -> int:
        return 2

    def steps(self):
        total = self.micro_batches + self.stages - 1
        for tick in range(total):
            cmds: List[PipeInstruction] = []
            mb = tick - self.stage_id
            if 0 <= mb < self.micro_batches:
                buf = self._buffer_idx(mb)
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(buffer_id=buf))
                else:
                    cmds.append(RecvActivation(buffer_id=buf))
                cmds.append(ForwardPass(buffer_id=buf))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buffer_id=buf))
            yield cmds


class TrainSchedule(PipeSchedule):
    """1F1B: warm up with (stages-1-stage_id) forwards, then alternate 1
    forward / 1 backward, drain remaining backwards, then reduce + step.

    Bubble fraction = (stages-1)/(micro_batches+stages-1), identical to the
    reference's schedule (schedule.py:182-289).
    """

    def num_pipe_buffers(self) -> int:
        # in-flight activations this stage must hold (reference: 289)
        return max(2, min(self.micro_batches, self.stages - self.stage_id))

    def _forwards_before_first_backward(self) -> int:
        return min(self.micro_batches, self.stages - self.stage_id)

    def steps(self):
        m, s, sid = self.micro_batches, self.stages, self.stage_id
        warmup = min(m, s - 1 - sid)
        fwd_id, bwd_id = 0, 0
        # clock-aligned: stage sid idles sid ticks before its first forward
        for _ in range(sid):
            yield []
        # warmup forwards
        for _ in range(warmup):
            yield self._fwd_cmds(fwd_id)
            fwd_id += 1
        # steady state: 1F1B
        while fwd_id < m:
            yield self._fwd_cmds(fwd_id) + self._bwd_cmds(bwd_id)
            fwd_id += 1
            bwd_id += 1
        # drain backwards
        while bwd_id < m:
            yield self._bwd_cmds(bwd_id)
            bwd_id += 1
        yield [ReduceTiedGrads(), ReduceGrads(), OptimizerStep()]

    def _fwd_cmds(self, mb: int) -> List[PipeInstruction]:
        buf = self._buffer_idx(mb)
        cmds: List[PipeInstruction] = []
        if self.is_first_stage:
            cmds.append(LoadMicroBatch(buffer_id=buf))
        else:
            cmds.append(RecvActivation(buffer_id=buf))
        cmds.append(ForwardPass(buffer_id=buf))
        if not self.is_last_stage:
            cmds.append(SendActivation(buffer_id=buf))
        return cmds

    def _bwd_cmds(self, mb: int) -> List[PipeInstruction]:
        buf = self._buffer_idx(mb)
        cmds: List[PipeInstruction] = []
        if not self.is_last_stage:
            cmds.append(RecvGrad(buffer_id=buf))
        cmds.append(BackwardPass(buffer_id=buf))
        if not self.is_first_stage:
            cmds.append(SendGrad(buffer_id=buf))
        return cmds


class DataParallelSchedule(PipeSchedule):
    """Degenerate single-stage schedule (reference: schedule.py:477+)."""

    def num_pipe_buffers(self) -> int:
        return 1

    def steps(self):
        for mb in range(self.micro_batches):
            yield [LoadMicroBatch(buffer_id=0), ForwardPass(buffer_id=0),
                   BackwardPass(buffer_id=0)]
        yield [ReduceGrads(), OptimizerStep()]


def bubble_fraction(micro_batches: int, stages: int) -> float:
    """Idle fraction of the GPipe/1F1B pipeline."""
    return (stages - 1) / (micro_batches + stages - 1)
