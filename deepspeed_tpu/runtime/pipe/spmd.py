"""SPMD pipeline execution — GPipe as one compiled program over the 'pipe' axis.

The reference executes pipelines MPMD-style: each rank interprets an
instruction schedule and exchanges activations over NCCL P2P
(runtime/pipe/engine.py:1360 _exec_schedule + p2p.py). On TPU the idiomatic
equivalent is a *single* SPMD program: stage bodies are stacked along a
leading dim sharded over the mesh's 'pipe' axis, and a `lax.scan` over clock
ticks moves activations stage→stage with `lax.ppermute` over ICI neighbors.
Autodiff through the scan+ppermute yields the reverse pipeline (backward
ticks) without hand-scheduling — XLA's transpose of a collective permute is
the reversed permute, so the 1F1B-style interleave is recovered by the
compiler's scheduler rather than an instruction interpreter.

Bubble: (pp-1)/(n_micro+pp-1), identical to the reference's TrainSchedule
(schedule.py — see runtime/pipe/schedule.py:bubble_fraction).

Memory: like GPipe, live activations scale with in-flight microbatches;
wrap `stage_fn` in `jax.checkpoint` (remat=True) to keep only per-tick
boundaries, the analogue of the reference's per-layer activation
checkpointing interleave (module.py:309).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

PyTree = Any


def pipeline_apply(stage_fn: Callable,
                   stage_params: PyTree,
                   micros: jnp.ndarray,
                   *,
                   mesh,
                   pp: int,
                   remat: bool = False,
                   pipe_axis: str = "pipe",
                   with_aux: bool = False,
                   extras: PyTree = None):
    """Run stacked pipeline stages over microbatches.

    stage_fn(params_of_one_stage, x, extra, stage_idx) -> y   applies ONE
      stage's layer stack. ``extra`` is the per-micro slice of ``extras``
      (attention masks, dropout rng keys, ... — {} when extras is None);
      ``stage_idx`` is the rank's pipe index (for rng folding).
      (with_aux=True: -> (y, aux_scalar) — a per-stage additive side channel
      e.g. the MoE load-balance loss; aux rides the pipe next to the
      activations and sums across stages per microbatch)
    stage_params: pytree with leading dim pp on every leaf (sharded over pipe)
    micros: [n_micro, micro_batch, ...] activations entering stage 0
    extras: optional pytree of [n_micro, ...] per-micro side inputs
    returns [n_micro, micro_batch, ...] outputs of the last stage (plus the
    summed aux scalar when with_aux), replicated over the pipe axis.
    """
    n_micro = micros.shape[0]
    if extras is None:
        extras = {}
    base_fn = stage_fn
    if not with_aux:
        def base_fn(p, x, e, s):  # noqa: F811 - uniform (y, aux) contract
            return stage_fn(p, x, e, s), jnp.zeros((), jnp.float32)
    fn = jax.checkpoint(base_fn) if remat else base_fn

    if pp == 1:
        one = jax.tree.map(lambda x: x[0], stage_params)
        outs, auxes = jax.lax.map(
            lambda mi: fn(one, mi[0],
                          jax.tree.map(lambda e: e[mi[1]], extras), 0),
            (micros, jnp.arange(n_micro)))
        # MEAN over microbatches: the per-layer aux is a token-mean, so the
        # pipelined aux must match the pp=1 model batch-for-batch
        return (outs, jnp.mean(auxes)) if with_aux else outs

    compute_dtype = micros.dtype

    def inner(params, micros, extras):
        # the boundary crossing is f32 (see psum note below); compute in the
        # original dtype inside
        micros = micros.astype(compute_dtype)
        local = jax.tree.map(lambda x: x[0], params)  # this rank's stage
        stage = jax.lax.axis_index(pipe_axis)
        n_ticks = n_micro + pp - 1
        state = jnp.zeros_like(micros[0])
        aux_state = jnp.zeros((), jnp.float32)
        outs = jnp.zeros_like(micros)
        aux_outs = jnp.zeros((n_micro,), jnp.float32)

        def tick(carry, t):
            state, aux_state, outs, aux_outs = carry
            # shift activations downstream (stage pp-1 sends nowhere; the
            # GPipe fill/drain means its output was already emitted)
            recv = jax.lax.ppermute(state, pipe_axis,
                                    [(i, i + 1) for i in range(pp - 1)])
            # chained on recv: two independent collectives can be scheduled
            # in different orders per device, deadlocking the rendezvous
            # (same hazard one_f_one_b.py documents)
            tok = jnp.sum(recv).astype(jnp.float32) * 0.0
            recv_aux = jax.lax.ppermute(aux_state + tok, pipe_axis,
                                        [(i, i + 1) for i in range(pp - 1)])
            inject = micros[jnp.clip(t, 0, n_micro - 1)]
            is_first = (stage == 0)
            x = jnp.where(is_first, inject, recv)
            aux_in = jnp.where(is_first, 0.0, recv_aux)
            # the micro at stage s on tick t is t - s (GPipe fill/drain)
            mid = jnp.clip(t - stage, 0, n_micro - 1)
            extra = jax.tree.map(lambda e: e[mid], extras)
            y, aux = fn(local, x, extra, stage)
            aux = aux_in + aux.astype(jnp.float32)
            # last stage emits microbatch t-(pp-1) at tick t
            emit_idx = jnp.clip(t - (pp - 1), 0, n_micro - 1)
            emit = jnp.logical_and(stage == pp - 1, t >= pp - 1)
            outs = jnp.where(
                emit,
                jax.lax.dynamic_update_index_in_dim(outs, y, emit_idx, 0),
                outs)
            aux_outs = jnp.where(
                emit,
                jax.lax.dynamic_update_index_in_dim(aux_outs, aux, emit_idx,
                                                    0),
                aux_outs)
            return (y, aux, outs, aux_outs), None

        (_, _, outs, aux_outs), _ = jax.lax.scan(
            tick, (state, aux_state, outs, aux_outs), jnp.arange(n_ticks))
        # replicate the last stage's buffer across pipe ranks. The psum runs
        # in f32: low-precision collectives inside partial-auto shard_map hit
        # an XLA SPMD bug ("Invalid binary instruction opcode copy") — the
        # same reason the micros boundary is f32 (the transpose of a
        # pipe-replicated input is a psum of its cotangent over pipe). The
        # per-tick ppermute stays in the compute dtype, so steady-state ICI
        # traffic is unaffected.
        mask = (stage == pp - 1)
        outs = jax.lax.psum(
            jnp.where(mask, outs.astype(jnp.float32), 0.0), pipe_axis)
        aux_total = jax.lax.psum(jnp.where(mask, jnp.mean(aux_outs), 0.0),
                                 pipe_axis)
        return outs, aux_total

    out, aux_total = jax.shard_map(
        inner,
        mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(pipe_axis), stage_params), P(),
                  P()),
        out_specs=(P(), P()),
        axis_names={pipe_axis},
        check_vma=False,
    )(stage_params, micros.astype(jnp.float32), extras)
    out = out.astype(compute_dtype)
    return (out, aux_total) if with_aux else out


def stack_stage_params(per_layer_params: PyTree, pp: int) -> PyTree:
    """[L, ...]-stacked per-layer params -> [pp, L/pp, ...] per-stage stacks."""

    def reshape(x):
        L = x.shape[0]
        if L % pp != 0:
            raise ValueError(f"layer count {L} not divisible by {pp} stages")
        return x.reshape((pp, L // pp) + x.shape[1:])

    return jax.tree.map(reshape, per_layer_params)


def unstack_stage_params(stage_params: PyTree) -> PyTree:
    """[pp, L/pp, ...] -> [L, ...] (checkpoint/interop layout)."""
    return jax.tree.map(
        lambda x: x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:]),
        stage_params)
