"""Training-integrity sentinel — detect wrong numbers, not just dead ranks.

PRs 3-6 made the run hard to *crash*: verified checkpoints, phase-aware
watchdogs, heartbeat liveness, host blacklisting. None of it defends
against a run that keeps stepping while producing *wrong numbers* — a
poisoned batch spiking the loss, optimizer state quietly diverging, or a
TPU chip silently corrupting data (SDC). This module is the detection and
remediation layer on top of that substrate:

**Detection.** The compiled train step already computes the global grad
norm; with the ``integrity`` config section enabled it also computes the
update norm and param norm in-jit, and every step's scalars ride
``_after_step``'s existing single batched ``device_get`` (TPU001 stays
green — the hot path gains no extra device sync). The host-side
:class:`TrainingSentinel` keeps rolling ROBUST statistics per metric
(median/MAD z-score — a single spike cannot drag the baseline the way a
mean/std would) with a warmup before any verdict and a cooldown so one
event counts once.

**Remediation ladder** (each rung strictly stronger, each rung observable):

1. **skip** — the sentinel feeds the step a grad-norm ceiling derived
   from its rolling stats; the compiled step skips the update in-jit when
   the raw global norm exceeds it, through the SAME keep-old-state path
   the fp16 loss scaler and the bf16 non-finite guard use. A single
   poisoned batch costs one skipped step and zero state damage.
2. **rollback** — ``rollback_after`` strikes inside ``strike_window``
   steps (anomalies that did NOT get skipped damage state slowly) roll
   the engine back to the newest intact checkpoint via the PR-3 verified
   loader. The data pipeline is NOT rewound — the poisoned span is
   deterministically fast-forwarded past (see
   ``DeepSpeedDataLoader.fast_forward`` / ``engine.data_position``).
3. **abort** — a spike that reproduces after a rollback is not data, it
   is the run (bad lr, bad init, bad hardware): raise
   :class:`TrainingIntegrityError`, whose ``exit_code``
   (:data:`INTEGRITY_EXIT_CODE`) launch.py turns into a distinct rc so
   supervisors and the elastic agent can tell "diverged" from "crashed".

The PR-3 ``nonfinite_guard`` streak/abort is FOLDED into this ladder as
one code path: ``TrainState.nonfinite_streak`` counts consecutive
in-jit-skipped steps of ANY kind (overflow, non-finite, sentinel spike),
and :meth:`TrainingSentinel.observe` raises :class:`NonFiniteError`
(a :class:`TrainingIntegrityError`) when it reaches the configured bound.
``nonfinite_guard.abort_after`` remains as a deprecated config alias for
``integrity.nonfinite_abort_after``.

**Cross-replica SDC audit.** Every ``integrity.audit_interval`` steps the
engine runs a bit-exact in-jit checksum over every fully-replicated leaf
of params + master + optimizer state. A replicated leaf is stored
per-device and the checksum program contains no collectives, so every
device computes the checksum of ITS OWN bytes — a silent bit-flip on one
chip yields a minority checksum. :func:`compare_replica_checksums` does
the majority vote; the implicated rank stamps an ``SDC`` flag into its
heartbeat record (the elastic agent's blacklist evidence), and every rank
aborts with :data:`INTEGRITY_EXIT_CODE` so the relaunch resumes from the
last audited-clean checkpoint (``last_audited_clean`` marker, maintained
by the engine after every clean audit).

reference counterpart: DeepSpeed ships only the loss-scaler skip and the
eigenvalue probe for this failure class; the ladder, the robust detector,
and the replica audit are TPU-native (SDC at pod scale is a measured,
recurring failure mode).
"""

from __future__ import annotations

import math
from collections import Counter, deque
from typing import Dict, Iterable, List, Optional, Tuple

from ..utils.logging import logger

#: rc for an integrity abort (ladder rung 3, or a detected SDC) — distinct
#: from clean 0, preemption, and stall: the run is *wrong*, not dead or
#: slow, and must not silently relaunch into the same divergence without
#: the operator being able to tell. Re-exported from the single-source
#: contract module.
from ..exit_codes import INTEGRITY_EXIT_CODE  # noqa: E402

#: heartbeat flag stamped by a rank whose device(s) lost the checksum
#: majority vote — the elastic agent and supervisors read it as blacklist
#: evidence against that rank's host.
SDC_FLAG = "SDC"

#: sentinel verdicts (observe() return values)
OK = "ok"
COOLDOWN = "cooldown"       # anomaly inside the cooldown window: no new strike
STRIKE = "strike"           # anomaly recorded (rung 1 already acted in-jit)
ROLLBACK = "rollback"       # rung 2: caller must restore the last intact tag


class TrainingIntegrityError(RuntimeError):
    """The remediation ladder ran out of rungs: a spike reproduced after a
    rollback, a rollback was needed but no checkpoint exists, or a
    cross-replica SDC audit failed. ``exit_code`` is the process rc
    contract (launch.py maps an uncaught integrity error onto it)."""

    exit_code = INTEGRITY_EXIT_CODE


class NonFiniteError(TrainingIntegrityError):
    """The non-finite/skip streak guard tripped: ``abort_after``
    consecutive steps were skipped in-jit (inf/nan grads, or sentinel
    spikes). Each of those steps left params/optimizer untouched, so the
    last checkpoint — and even the live state — is still clean to restart
    from."""


def _median(vals) -> float:
    """Median of a non-empty sequence — shared by the rolling baselines
    here and the cross-rank straggler detector (runtime/straggler.py)."""
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


class RollingRobust:
    """Rolling median/MAD over the last ``window`` accepted samples.

    Robust by construction: a handful of outliers cannot drag the median
    or inflate the MAD the way they would a mean/std, so the detector's
    baseline survives the very anomalies it exists to catch."""

    #: MAD -> sigma for a normal distribution
    _K = 1.4826

    def __init__(self, window: int):
        self.buf: deque = deque(maxlen=max(4, int(window)))

    def __len__(self) -> int:
        return len(self.buf)

    def push(self, x: float) -> None:
        self.buf.append(float(x))

    def stats(self) -> Optional[Tuple[float, float]]:
        """(median, robust sigma), or None with < 4 samples. The sigma is
        floored so a perfectly-flat warmup (MAD 0) cannot turn the first
        jitter into an anomaly."""
        if len(self.buf) < 4:
            return None
        vals = list(self.buf)
        med = _median(vals)
        mad = _median([abs(v - med) for v in vals])
        sigma = self._K * mad
        floor = max(abs(med), 1.0) * 1e-3
        return med, max(sigma, floor)

    def zscore(self, x: float) -> Optional[float]:
        st = self.stats()
        if st is None:
            return None
        med, sigma = st
        return (x - med) / sigma

    def threshold(self, zmax: float) -> Optional[float]:
        st = self.stats()
        if st is None:
            return None
        med, sigma = st
        return med + zmax * sigma


class TrainingSentinel:
    """Host half of the integrity layer: consumes the per-step host
    metrics (one batched pull), keeps the rolling robust stats, hands the
    engine the next step's in-jit skip ceiling, and walks the remediation
    ladder. See the module docstring for the ladder semantics."""

    def __init__(self, cfg):
        self.cfg = cfg
        self.enabled = bool(cfg.enabled)
        self.nonfinite_abort_after = int(cfg.nonfinite_abort_after)
        # grad_norm is tracked whenever the skip rung is on, even if the
        # user dropped it from cfg.metrics — the in-jit ceiling is derived
        # from ITS rolling stats, and a configured-on rung that silently
        # never arms is worse than an extra tracked scalar
        tracked = list(cfg.metrics)
        if cfg.skip and "grad_norm" not in tracked:
            tracked.append("grad_norm")
        self.stats: Dict[str, RollingRobust] = {
            m: RollingRobust(cfg.window) for m in tracked}
        self.accepted = 0               # clean samples folded into the window
        self.strikes: deque = deque()   # steps at which anomalies struck
        self.cooldown_until = -1
        self.rollbacks_done = 0         # rollbacks since the last clean stretch
        self.last_rollback_step: Optional[int] = None
        self.last_clean_audit_step: Optional[int] = None
        self.sdc_detected = False
        self.last_verdict = OK
        self.last_anomaly: Optional[str] = None

    # ------------------------------------------------------------ engine feed

    @property
    def wants_every_step(self) -> bool:
        """With the detector on, every step's scalars must reach the host
        (still ONE batched pull per step); streak-only mode keeps the
        print-step cadence the PR-3 guard shipped with."""
        return self.enabled

    @property
    def metric_keys(self) -> Tuple[str, ...]:
        """Metric names the engine folds into its batched device_get."""
        keys = ["loss", "lr", "grad_norm", "loss_scale"]
        if self.nonfinite_abort_after > 0 or self.enabled:
            keys.append("nonfinite_streak")
        if self.enabled:
            keys += [m for m in self.stats if m not in keys]
            keys += ["overflow", "anomaly_skip"]
        return tuple(dict.fromkeys(keys))

    def spike_limit(self) -> Optional[float]:
        """Grad-norm ceiling for the NEXT compiled step's in-jit skip
        (ladder rung 1); +inf while warming up so the arg structure — and
        the compiled program — never changes shape mid-run."""
        if not self.enabled or not self.cfg.skip:
            return None
        if "grad_norm" not in self.stats or \
                self.accepted < self.cfg.warmup_steps:
            return math.inf
        thr = self.stats["grad_norm"].threshold(self.cfg.zmax)
        return math.inf if thr is None else float(thr)

    # -------------------------------------------------------------- detection

    def _armed(self) -> bool:
        return self.accepted >= self.cfg.warmup_steps

    def observe(self, step: int, host: Dict[str, float]) -> str:
        """Walk the ladder for one step's host metrics. Raises
        :class:`NonFiniteError` on the skip-streak bound and
        :class:`TrainingIntegrityError` when a rollback-grade anomaly
        reproduces after ``abort_after_rollbacks`` rollbacks; otherwise
        returns a verdict (the engine performs ROLLBACK itself — it owns
        the checkpoint dir and the data pipeline)."""
        streak = int(host.get("nonfinite_streak", 0) or 0)
        if 0 < self.nonfinite_abort_after <= streak:
            raise NonFiniteError(
                f"{streak} consecutive non-finite/skipped steps at global "
                f"step {step} "
                f"(integrity.nonfinite_abort_after="
                f"{self.nonfinite_abort_after}); the run has diverged — "
                "restart from the last checkpoint with a lower lr / higher "
                "warmup")
        if not self.enabled:
            self.last_verdict = OK
            return OK

        anomalies: List[str] = []
        skipped = bool(host.get("anomaly_skip", 0)) or bool(
            host.get("overflow", 0))
        if host.get("anomaly_skip", 0):
            anomalies.append("in-jit grad-norm spike (batch skipped)")
        clean_values: List[Tuple[str, float]] = []
        for m in self.stats:
            if m not in host:
                continue
            v = float(host[m])
            if not math.isfinite(v):
                if not skipped:
                    anomalies.append(f"{m} non-finite")
                continue
            z = self.stats[m].zscore(v) if self._armed() else None
            if z is not None and z > self.cfg.zmax:
                anomalies.append(f"{m}={v:.6g} (robust z={z:.1f} > "
                                 f"{self.cfg.zmax:g})")
            elif not skipped:
                clean_values.append((m, v))

        if not anomalies:
            for m, v in clean_values:
                self.stats[m].push(v)
            if clean_values:
                self.accepted += 1
            if self.rollbacks_done and self.last_rollback_step is not None \
                    and step - self.last_rollback_step > self.cfg.strike_window:
                # a clean stretch after a rollback retires the "reproduced
                # post-rollback" abort arm — the rollback worked
                self.rollbacks_done = 0
            self.last_verdict = OK
            return OK

        self.last_anomaly = "; ".join(anomalies)
        if step < self.cooldown_until:
            self.last_verdict = COOLDOWN
            return COOLDOWN
        self.cooldown_until = step + self.cfg.cooldown_steps
        self.strikes.append(step)
        while self.strikes and self.strikes[0] < step - self.cfg.strike_window:
            self.strikes.popleft()
        logger.warning(
            "integrity sentinel: anomaly at step %d (%s) — strike %d/%d "
            "in the last %d steps", step, self.last_anomaly,
            len(self.strikes), self.cfg.rollback_after,
            self.cfg.strike_window)
        if len(self.strikes) >= self.cfg.rollback_after:
            self.strikes.clear()
            if self.rollbacks_done >= self.cfg.abort_after_rollbacks:
                raise TrainingIntegrityError(
                    f"anomaly reproduced after {self.rollbacks_done} "
                    f"rollback(s) at step {step} ({self.last_anomaly}); "
                    "the divergence is not the data — aborting with rc "
                    f"{INTEGRITY_EXIT_CODE} (inspect lr/init/hardware "
                    "before resuming)")
            self.last_verdict = ROLLBACK
            return ROLLBACK
        self.last_verdict = STRIKE
        return STRIKE

    # ------------------------------------------------------------ remediation

    def note_rollback(self, restored_step: int) -> None:
        """Called by the engine AFTER the verified restore: the ladder
        advances one rung, the strike window resets, and a post-rollback
        cooldown absorbs the detector's view of the restored state."""
        self.rollbacks_done += 1
        self.last_rollback_step = restored_step
        self.strikes.clear()
        self.cooldown_until = restored_step + self.cfg.cooldown_steps

    def note_clean_audit(self, step: int) -> None:
        self.last_clean_audit_step = step


# ---------------------------------------------------------------------------
# Cross-replica SDC audit: host-side vote over per-device checksums
# ---------------------------------------------------------------------------

def compare_replica_checksums(values: Iterable[Tuple[str, int]]
                              ) -> List[str]:
    """Majority vote over ``(replica_key, checksum)`` pairs: the keys whose
    checksum lost the vote — the implicated replicas. With no strict
    winner (e.g. a 1-vs-1 mismatch across two replicas) EVERY key is
    implicated: the mismatch is certain, the culprit is not, and
    supervision must treat both copies as suspect rather than guess."""
    pairs = list(values)
    if len(pairs) < 2:
        return []
    counts = Counter(v for _, v in pairs)
    if len(counts) == 1:
        return []
    ranked = counts.most_common()
    top, top_n = ranked[0]
    if len(ranked) > 1 and ranked[1][1] == top_n:
        return [k for k, _ in pairs]
    return [k for k, v in pairs if v != top]


#: name of the marker file (inside a checkpoint save dir) naming the
#: newest tag that existed at the last CLEAN cross-replica audit — the
#: tag a post-SDC relaunch should resume from (tags written after the
#: last clean audit may carry the corruption that the audit later caught).
LAST_AUDITED_CLEAN_FILE = "last_audited_clean"


def write_last_audited_clean(save_dir: str, tag: str) -> None:
    """Atomic marker update (tmp + replace, like the `latest` pointer).
    Failures are swallowed: the marker is an optimization of WHERE to
    resume, never a condition for resuming at all."""
    import os
    try:
        tmp = os.path.join(save_dir, LAST_AUDITED_CLEAN_FILE + ".tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(tag)
        os.replace(tmp, os.path.join(save_dir, LAST_AUDITED_CLEAN_FILE))
    except OSError as e:
        logger.warning("integrity: cannot write %s marker under %s: %s",
                       LAST_AUDITED_CLEAN_FILE, save_dir, e)


def read_last_audited_clean(save_dir: str) -> Optional[str]:
    import os
    path = os.path.join(save_dir, LAST_AUDITED_CLEAN_FILE)
    try:
        with open(path, encoding="utf-8") as f:
            tag = f.read().strip()
    except OSError:
        return None
    return tag or None
