"""LR schedules: LRRangeTest, OneCycle, WarmupLR, WarmupDecayLR.

Capability parity with the reference's ``runtime/lr_schedules.py`` (854 LoC of
stateful torch schedulers). Rebuilt as pure step->lr functions so the schedule
evaluates *inside* the jitted train step (no host round-trip per step); a thin
stateful wrapper preserves the reference's ``lr_scheduler.step()/get_lr()`` API.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Optional

import jax.numpy as jnp

VALID_SCHEDULES = ["LRRangeTest", "OneCycle", "WarmupLR", "WarmupDecayLR"]


def warmup_lr(warmup_min_lr: float = 0.0,
              warmup_max_lr: float = 0.001,
              warmup_num_steps: int = 1000,
              warmup_type: str = "log") -> Callable:
    """reference: lr_schedules.py:704 WarmupLR (log or linear warmup, then flat)."""
    warmup_num_steps = max(warmup_num_steps, 2)

    def fn(step):
        s = jnp.asarray(step, jnp.float32)
        frac = jnp.clip(s / warmup_num_steps, 0.0, 1.0)
        if warmup_type == "log":
            # log(1+s)/log(1+W) ramp, as in the reference's inverse_log_warm_up
            frac = jnp.log1p(s) / math.log(1 + warmup_num_steps)
            frac = jnp.clip(frac, 0.0, 1.0)
        return warmup_min_lr + (warmup_max_lr - warmup_min_lr) * frac

    return fn


def warmup_decay_lr(total_num_steps: int,
                    warmup_min_lr: float = 0.0,
                    warmup_max_lr: float = 0.001,
                    warmup_num_steps: int = 1000,
                    warmup_type: str = "log") -> Callable:
    """reference: lr_schedules.py:800 WarmupDecayLR (warmup then linear decay to 0)."""
    warm = warmup_lr(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)

    def fn(step):
        s = jnp.asarray(step, jnp.float32)
        decay = jnp.clip((total_num_steps - s) / max(total_num_steps - warmup_num_steps, 1),
                         0.0, 1.0)
        return jnp.where(s < warmup_num_steps, warm(s), warmup_max_lr * decay)

    return fn


def lr_range_test(lr_range_test_min_lr: float = 1e-3,
                  lr_range_test_step_size: int = 2000,
                  lr_range_test_step_rate: float = 1.0,
                  lr_range_test_staircase: bool = False) -> Callable:
    """reference: lr_schedules.py:308 LRRangeTest (Smith's LR range sweep)."""

    def fn(step):
        s = jnp.asarray(step, jnp.float32) / lr_range_test_step_size
        if lr_range_test_staircase:
            s = jnp.floor(s)
        return lr_range_test_min_lr * (1.0 + s * lr_range_test_step_rate)

    return fn


def one_cycle(cycle_min_lr: float = 1e-3,
              cycle_max_lr: float = 1e-2,
              cycle_first_step_size: int = 2000,
              cycle_second_step_size: Optional[int] = None,
              cycle_first_stair_count: int = 0,
              cycle_second_stair_count: Optional[int] = None,
              decay_step_size: int = 0,
              decay_lr_rate: float = 0.0,
              **_ignored) -> Callable:
    """reference: lr_schedules.py:415 OneCycle (triangular cycle + optional decay).

    Momentum cycling (cycle_momentum) is accepted but handled by the engine's
    optimizer wiring, not here."""
    second = cycle_second_step_size if cycle_second_step_size is not None else cycle_first_step_size
    total_cycle = cycle_first_step_size + second

    def fn(step):
        s = jnp.asarray(step, jnp.float32)
        up = jnp.clip(s / cycle_first_step_size, 0.0, 1.0)
        down = jnp.clip((s - cycle_first_step_size) / second, 0.0, 1.0)
        in_cycle_lr = jnp.where(
            s <= cycle_first_step_size,
            cycle_min_lr + (cycle_max_lr - cycle_min_lr) * up,
            cycle_max_lr - (cycle_max_lr - cycle_min_lr) * down)
        if decay_step_size > 0:
            decay_steps = jnp.maximum(s - total_cycle, 0.0) / decay_step_size
            post = cycle_min_lr / (1.0 + decay_lr_rate * decay_steps)
        else:
            post = jnp.asarray(cycle_min_lr, jnp.float32)
        return jnp.where(s <= total_cycle, in_cycle_lr, post)

    return fn


_SCHEDULE_BUILDERS: Dict[str, Callable] = {
    "warmuplr": warmup_lr,
    "warmupdecaylr": warmup_decay_lr,
    "lrrangetest": lr_range_test,
    "onecycle": one_cycle,
}


def build_schedule(sched_type: Optional[str], params: Optional[dict] = None,
                   base_lr: Optional[float] = None) -> Optional[Callable]:
    """Build a step->lr function from a ds_config `scheduler` section."""
    if sched_type is None:
        return None
    key = sched_type.lower()
    if key not in _SCHEDULE_BUILDERS:
        raise ValueError(f"Unknown scheduler '{sched_type}'. Known: {VALID_SCHEDULES}")
    return _SCHEDULE_BUILDERS[key](**(params or {}))


class LRScheduler:
    """Stateful wrapper preserving the reference's scheduler API (step/get_lr/state_dict)."""

    def __init__(self, fn: Callable, last_step: int = 0):
        self.fn = fn
        self.last_step = last_step

    def step(self, increment: int = 1):
        self.last_step += increment

    def get_lr(self):
        return [float(self.fn(self.last_step))]

    def get_last_lr(self):
        return self.get_lr()

    def state_dict(self):
        return {"last_step": self.last_step}

    def load_state_dict(self, sd):
        self.last_step = sd["last_step"]
