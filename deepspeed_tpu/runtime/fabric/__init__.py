"""One fault-tolerant transfer fabric (round 18).

The repo's single cross-boundary channel layer. Before this package the
MPMD star (`runtime/pipe/mpmd/channel.py`) and the disagg block handoff
(`serving/disagg.py`) each carried their own framing and retry code;
now both — and the process-placement serving fleet — ride ONE
:class:`Endpoint` contract with one failure model:

* length-prefixed frames with a CRC32 trailer (:mod:`.frame`) — a
  corrupted frame is a peer-fatal :class:`FrameCorrupt`, never silent
  garbage;
* generation-fenced delivery — a reconnected peer's stale in-flight
  frames are dropped at receipt;
* bounded jittered reconnect/backoff on dial and mid-stream ``OSError``
  (:class:`RedialPolicy`), per-recv deadlines raising
  :class:`ChannelTimeout`;
* peer-death verdicts stay in the PR-6 heartbeat channel — the fabric
  reports LINK state only;
* the six ``net.*`` chaos failpoints live at this layer, so every
  transport inherits the same fault-injection surface.

Backends: :class:`LocalEndpoint` (in-process queue + ``device_put``,
the CPU-testable reference) and :class:`SocketEndpoint` /
:class:`HubConn` (the hardened TCP star). docs/RESILIENCE.md §"The
transfer fabric" has the delivery contract and the failpoint table.
"""

from .endpoint import (ChannelClosed, ChannelTimeout, Endpoint,
                       FrameCorrupt, RedialPolicy, WriteLockStarved)
from .frame import pack_frame, read_frame, write_frame
from .local import LocalEndpoint
from .sockets import HubConn, SocketEndpoint

__all__ = ["Endpoint", "LocalEndpoint", "SocketEndpoint", "HubConn",
           "RedialPolicy", "ChannelTimeout", "ChannelClosed",
           "FrameCorrupt", "WriteLockStarved",
           "pack_frame", "read_frame", "write_frame"]
