"""Socket fabric backend — the hardened star spoke.

One TCP connection to a hub (the MPMD driver's router, the process
fleet's dispatcher). The handshake is hello/welcome: the spoke sends
``{"cmd": "hello", "ident": ..., **hello}``, the hub answers
``{"cmd": "welcome", "gen": G}`` and G becomes the spoke's generation —
every data frame is stamped with it, and both directions drop data
frames from any other generation at receipt (a reconnected peer's
stale in-flight frames can never leak into the new epoch).

Failure handling (the hardening the bespoke transports used to
half-implement each):

* dial: backoff-retried until the connect deadline (``net.connect``
  fires per attempt), then :class:`ChannelClosed`;
* mid-stream ``OSError`` on send OR recv: the :class:`RedialPolicy`
  ladder — bounded attempts, exponential jittered backoff, full
  re-handshake (fresh generation) — and the failed send is re-issued
  on the new connection with the NEW generation, so a
  maybe-delivered duplicate of the old frame is fenced out at the
  receiver; exhausted attempts raise :class:`ChannelClosed`;
* recv deadline: :class:`ChannelTimeout`;
* CRC mismatch: :class:`FrameCorrupt` (peer-fatal, no redial — the
  stream is desynchronized);
* writes serialize under a BOUNDED per-connection lock — a peer wedged
  mid-read starves the next writer into :class:`WriteLockStarved`
  (an ``OSError``) instead of wedging it.
"""

from __future__ import annotations

import select
import socket
import threading
import time
from collections import deque
from typing import Optional, Tuple

from ...testing import chaos
from .endpoint import (ChannelClosed, ChannelTimeout, Endpoint,
                       FrameCorrupt, RedialPolicy, WriteLockStarved)
from .frame import read_frame, write_frame


class SocketEndpoint(Endpoint):
    """Spoke endpoint of the star (module docstring has the contract).

    ``hello`` is extra meta for the handshake frame (the MPMD channel
    rides ``stage``/``resume_step`` on it); ``redial=None`` disables
    mid-stream reconnect (first link loss is peer-fatal)."""

    def __init__(self, addr: Tuple[str, int], ident: str, *,
                 hello: Optional[dict] = None,
                 connect_timeout: float = 30.0,
                 redial: Optional[RedialPolicy] = None,
                 fence: bool = True,
                 lock_timeout: float = 30.0):
        self.addr = addr
        self.ident = ident
        self.generation = 0
        self._hello = dict(hello or {})
        self._redial = redial
        self._fence = fence
        self._lock_timeout = float(lock_timeout)
        self._wlock = threading.Lock()
        self._pending: deque = deque()   # control frames read pre-welcome
        self._closed = False
        self._sock: Optional[socket.socket] = None
        self._dial(connect_timeout)

    # ------------------------------------------------------------- dialing

    def _dial(self, budget: float) -> None:
        """Connect + handshake within ``budget`` seconds, backoff-retrying
        refused dials (the hub may still be binding, or mid-restart)."""
        deadline = time.monotonic() + budget
        attempt = 0
        last_err: Optional[Exception] = None
        while True:
            try:
                chaos.failpoint("net.connect", key=self.ident)
                sock = socket.create_connection(self.addr, timeout=5.0)
                break
            except OSError as e:
                last_err = e
                if time.monotonic() >= deadline:
                    raise ChannelClosed(
                        f"{self.ident}: cannot reach hub at "
                        f"{self.addr}: {last_err}")
                RedialPolicy(base=0.05, cap=0.5).sleep(attempt)
                attempt += 1
        self._sock = sock
        try:
            sock.settimeout(None)
            write_frame(sock, {"cmd": "hello", "ident": self.ident,
                               **self._hello})
            welcome = self._read_until_welcome(
                max(0.1, deadline - time.monotonic()))
        except ChannelTimeout:
            # no welcome: the half-open socket must not outlive the
            # failed handshake — a leaked fd per redial attempt adds up
            try:
                sock.close()
            finally:
                self._sock = None
            raise
        except OSError as e:
            try:
                sock.close()
            finally:
                self._sock = None
            raise ChannelClosed(
                f"{self.ident}: handshake with hub failed: {e}")
        self.generation = int(welcome.get("gen", 0))

    def _read_until_welcome(self, timeout: float) -> dict:
        """Consume frames until the welcome; control frames seen first
        are parked for recv (a broadcast can race the handshake)."""
        deadline = time.monotonic() + timeout
        while True:
            left = deadline - time.monotonic()
            if left <= 0:
                raise ChannelTimeout(
                    f"{self.ident}: no welcome within {timeout}s")
            self._sock.settimeout(left)
            try:
                meta, payload = read_frame(self._sock)
            except socket.timeout:
                raise ChannelTimeout(
                    f"{self.ident}: no welcome within {timeout}s")
            finally:
                self._sock.settimeout(None)
            if meta.get("cmd") == "welcome":
                return meta
            self._pending.append((meta, payload))

    def _redial_or_raise(self, err: Exception, attempt: int) -> int:
        pol = self._redial
        if self._closed or pol is None or attempt >= pol.attempts:
            raise ChannelClosed(
                f"{self.ident}: link lost"
                + (f" and {attempt} redial(s) exhausted" if pol else "")
                + f": {err}")
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        pol.sleep(attempt)
        self._dial(pol.dial_timeout)     # fresh generation via welcome
        return attempt + 1

    # ---------------------------------------------------------------- send

    def send(self, meta: dict, payload: bytes = b"", *,
             key: Optional[str] = None,
             lock_timeout: Optional[float] = None) -> None:
        k = key or self.ident
        chaos.failpoint("net.slow", key=k)
        chaos.failpoint("net.send", key=k)
        bound = self._lock_timeout if lock_timeout is None else lock_timeout
        attempt = 0
        while True:
            try:
                chaos.failpoint("net.partition", key=k)
                # the frame is packed INSIDE the retry loop: a redial
                # bumps the generation, and the re-sent frame must carry
                # the new one (the maybe-delivered original is fenced)
                self._locked_write(
                    dict(meta, gen=self.generation), payload, bound, k)
                return
            except (WriteLockStarved, FrameCorrupt):
                raise                    # not link faults — no redial
            except OSError as e:
                attempt = self._redial_or_raise(e, attempt)

    def _locked_write(self, meta: dict, payload: bytes,
                      lock_timeout: float, key: str) -> None:
        if not self._wlock.acquire(timeout=lock_timeout):
            raise WriteLockStarved(
                f"{self.ident}: channel write lock starved for "
                f"{lock_timeout}s (peer wedged mid-frame?)")
        try:
            write_frame(self._sock, meta, payload, key=key)
        finally:
            self._wlock.release()

    # ---------------------------------------------------------------- recv

    def recv(self, timeout: Optional[float] = None, *,
             key: Optional[str] = None) -> Tuple[dict, bytes]:
        k = key or self.ident
        deadline = None if timeout is None else time.monotonic() + timeout
        attempt = 0
        while True:
            if self._pending:
                meta, payload = self._pending.popleft()
            else:
                left = (None if deadline is None
                        else max(0.0, deadline - time.monotonic()))
                if left == 0.0:
                    # timeout=0 is a POLL, not a no-op: a frame already
                    # on the wire must be deliverable (the serve loop
                    # drains commands between engine steps this way)
                    try:
                        readable, _, _ = select.select(
                            [self._sock], [], [], 0)
                    except (OSError, ValueError):
                        readable = []
                    if not readable:
                        raise ChannelTimeout(
                            f"{self.ident}: transfer barrier deadline "
                            f"exceeded ({timeout}s)")
                    # readable: a frame header is in flight — bound the
                    # read anyway (a peer wedged mid-frame must not
                    # wedge the poll)
                    left = 1.0
                try:
                    chaos.failpoint("net.partition", key=k)
                    meta, payload = self._read_one(left)
                except ChannelTimeout:
                    raise
                except FrameCorrupt:
                    raise                # peer-fatal, stream torn
                except OSError as e:
                    if self._closed:
                        raise ChannelClosed(
                            f"{self.ident}: endpoint closed")
                    attempt = self._redial_or_raise(e, attempt)
                    continue
            if meta.get("cmd") == "welcome":
                # hub-side epoch bump mid-stream (park/resync hands the
                # new generation through the control path instead)
                self.generation = int(meta.get("gen", self.generation))
                continue
            chaos.failpoint("net.recv", key=k)
            if "cmd" not in meta and self._fence and \
                    meta.get("gen", self.generation) != self.generation:
                continue    # stale-generation data frame — dropped
            return meta, payload

    def _read_one(self, timeout: Optional[float]
                  ) -> Tuple[dict, bytes]:
        self._sock.settimeout(timeout)
        try:
            return read_frame(self._sock)
        except socket.timeout:
            raise ChannelTimeout(
                "transfer barrier deadline exceeded")
        finally:
            try:
                self._sock.settimeout(None)
            except OSError:
                pass                     # socket died mid-read

    # ------------------------------------------------------------ lifecycle

    def close(self) -> None:
        self._closed = True
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass


class HubConn:
    """Hub-side half of one spoke connection: framed writes under a
    BOUNDED per-connection lock (a spoke wedged mid-read starves the
    next writer into an ``OSError`` instead of wedging the router).
    The hub's accept loop reads the hello itself (it routes on it) and
    answers with the welcome carrying the spoke's generation."""

    def __init__(self, sock: socket.socket, ident: str = "",
                 gen: int = 0):
        self.sock = sock
        self.ident = ident
        self.gen = int(gen)
        self.wlock = threading.Lock()

    def send(self, meta: dict, payload: bytes = b"",
             lock_timeout: float = 5.0) -> None:
        if not self.wlock.acquire(timeout=lock_timeout):
            raise WriteLockStarved(
                f"hub connection write lock starved for {lock_timeout}s "
                f"(peer wedged mid-frame?)")
        try:
            write_frame(self.sock, meta, payload,
                        key=self.ident or None)
        finally:
            self.wlock.release()

    def welcome(self, lock_timeout: float = 5.0, **extra) -> None:
        self.send({"cmd": "welcome", "gen": self.gen, **extra},
                  lock_timeout=lock_timeout)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
