"""Local fabric backend — in-process queue + ``device_put``.

The CPU-testable reference: frames are passed BY REFERENCE through a
bounded-lock deque (zero-copy — the disagg block handoff moves pool
block ownership, not tensor bytes), with an optional ``place`` hook
that ``jax.device_put``\\ s the payload onto the receiving side's
placement at send time (the explicit device-to-device hop the MPMD
LocalChannel audits). No framing, no CRC — there is no wire — but the
same ``net.send`` / ``net.recv`` / ``net.slow`` chaos surface as the
socket backend, so every in-process matrix exercises the identical
failure model the cross-process one does.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, List, Optional, Tuple

from ...testing import chaos
from .endpoint import ChannelClosed, ChannelTimeout, Endpoint

#: bound on internal queue-lock holds — the critical sections are
#: pointer swaps; a starved waiter is facing a wedged holder
_MU_TIMEOUT = 5.0


class LocalEndpoint(Endpoint):
    """Loopback endpoint: ``send`` appends to the queue, ``recv`` pops.

    ``recv(timeout=0)`` is non-blocking (in-process pipelines are
    synchronous — an empty queue is a schedule bug, surfaced as an
    immediate :class:`ChannelTimeout`); a positive timeout waits on the
    queue condition (the handoff consumer's deadline-aware pop).
    ``place(meta, payload)`` runs at send time under no lock."""

    def __init__(self, ident: str = "local",
                 place: Optional[Callable[[dict, Any], Any]] = None,
                 fence: bool = False):
        self.ident = ident
        self.generation = 0
        self._place = place
        self._fence = fence
        self._q: deque = deque()
        self._mu = threading.Lock()
        self._cond = threading.Condition(self._mu)
        self._closed = False

    def send(self, meta: dict, payload: Any = b"", *,
             key: Optional[str] = None, **kw) -> None:
        k = key or self.ident
        chaos.failpoint("net.slow", key=k)
        chaos.failpoint("net.send", key=k)
        if self._closed:
            raise ChannelClosed(f"{self.ident}: endpoint closed")
        if self._place is not None:
            payload = self._place(meta, payload)
        frame = (dict(meta, gen=self.generation), payload)
        with self._cond:
            self._q.append(frame)
            self._cond.notify()

    def recv(self, timeout: Optional[float] = 0.0, *,
             key: Optional[str] = None) -> Tuple[dict, Any]:
        chaos.failpoint("net.recv", key=key or self.ident)
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                while self._q:
                    meta, payload = self._q.popleft()
                    if self._fence and "cmd" not in meta and \
                            meta.get("gen", self.generation) \
                            != self.generation:
                        continue        # stale epoch — dropped at receipt
                    return meta, payload
                if self._closed:
                    raise ChannelClosed(f"{self.ident}: endpoint closed")
                left = (1.0 if deadline is None
                        else deadline - time.monotonic())
                if left <= 0:
                    raise ChannelTimeout(
                        f"{self.ident}: no frame within {timeout}s")
                self._cond.wait(timeout=min(left, 1.0))

    # ------------------------------------------------- queue introspection
    # (the handoff's bounded-capacity and deadline-shed logic lives in
    # its owner; the fabric exposes the primitives)

    def pending(self) -> int:
        with self._mu:
            return len(self._q)

    def peek_all(self) -> List[Tuple[dict, Any]]:
        with self._mu:
            return list(self._q)

    def purge(self, pred: Callable[[dict, Any], bool]
              ) -> List[Tuple[dict, Any]]:
        """Remove and return every queued frame matching ``pred`` —
        the deadline-shed primitive (atomic under the queue lock)."""
        with self._mu:
            hit = [f for f in self._q if pred(f[0], f[1])]
            if hit:
                self._q = deque(f for f in self._q
                                if not pred(f[0], f[1]))
        return hit

    def clear(self) -> List[Tuple[dict, Any]]:
        """Drop every queued frame (park: in-flight transfers of an
        abandoned step must not leak into the replay)."""
        with self._mu:
            dropped = list(self._q)
            self._q.clear()
        return dropped

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
