"""The fabric's channel contract: one `Endpoint`, one failure model.

Every cross-boundary transfer in the repo — MPMD inter-stage
activations, the disagg prefill→decode block handoff, the
process-placement fleet's request/token streams — rides an
:class:`Endpoint`. The contract:

* ``send(meta, payload)`` / ``recv(timeout)`` / ``close()`` — frames are
  (JSON meta, bytes-or-object payload) pairs, delivered FIFO per
  connection.
* **Generation fencing.** Every data frame is stamped with the
  endpoint's current ``generation`` (handed out by the hub at
  handshake, bumped on every reconnect and on park/resync). ``recv``
  drops data frames whose generation is not current — a reconnected
  peer's stale in-flight frames can never leak into the new epoch.
  Control frames (any meta carrying ``"cmd"``) bypass the fence.
* **Bounded jittered reconnect.** A dial failure backs off and retries
  until the connect deadline; a mid-stream ``OSError`` (link partition,
  peer reset) runs the :class:`RedialPolicy` ladder — bounded attempts,
  exponential backoff with jitter — and resumes with a FRESH generation
  from the hub's welcome. Exhausted attempts raise
  :class:`ChannelClosed`, the peer-fatal verdict.
* **Per-recv deadlines.** ``recv(timeout=...)`` past its deadline raises
  :class:`ChannelTimeout` — the "peer late or dead at the barrier"
  signal the park/resync protocol and the fleet requeue path consume.
* **Liveness stays in the heartbeat channel.** The fabric reports LINK
  verdicts only (``ChannelTimeout`` / ``ChannelClosed``); whether the
  PEER is dead is decided by the PR-6 heartbeat channel
  (``runtime/heartbeat.py`` stale_ranks / terminal records) — a
  partitioned link must not be mistaken for a dead process.

Fault injection: the six ``net.*`` failpoints (connect/send/recv/
corrupt/partition/slow — see ``testing/chaos.py``) are traversed at
THIS layer, so every transport inherits the same chaos surface.
"""

from __future__ import annotations

import random
import time
from typing import Any, Optional, Tuple


class ChannelTimeout(IOError):
    """recv() exceeded its deadline — the sending peer is late or dead."""


class ChannelClosed(IOError):
    """The transport is gone (peer hangup / hub teardown / redial
    ladder exhausted)."""


class FrameCorrupt(OSError):
    """A frame failed its CRC32 check — peer-fatal: the stream can no
    longer be trusted (a torn frame desynchronizes the length-prefixed
    framing). Callers treat it exactly like a dead peer."""


class WriteLockStarved(OSError):
    """The bounded per-connection write lock could not be acquired — a
    peer wedged mid-read keeps ``sendall`` (and with it the frame lock)
    stuck; a writer starved past the bound is facing a dead peer and
    fails like one."""


class RedialPolicy:
    """Bounded jittered reconnect ladder for mid-stream link loss.

    ``attempts`` redials, sleeping ``min(cap, base * 2**k)`` scaled by a
    uniform ``1 ± jitter/2`` factor between tries (jitter decorrelates a
    fleet of spokes re-dialing a restarted hub). ``dial_timeout`` bounds
    each redial's connect phase — deliberately shorter than the initial
    connect budget: a redial races a supervisor that may already be
    restarting this process."""

    def __init__(self, attempts: int = 2, base: float = 0.05,
                 cap: float = 1.0, jitter: float = 0.5,
                 dial_timeout: float = 2.0):
        self.attempts = int(attempts)
        self.base = float(base)
        self.cap = float(cap)
        self.jitter = float(jitter)
        self.dial_timeout = float(dial_timeout)

    def delay(self, attempt: int) -> float:
        d = min(self.cap, self.base * (2.0 ** attempt))
        return d * (1.0 + self.jitter * (random.random() - 0.5))

    def sleep(self, attempt: int) -> None:
        time.sleep(max(0.0, self.delay(attempt)))


class Endpoint:
    """The abstract channel endpoint (module docstring has the
    contract). Backends: :class:`~.local.LocalEndpoint` (in-process
    queue + ``device_put`` — the CPU-testable reference) and
    :class:`~.sockets.SocketEndpoint` (the hardened TCP star spoke)."""

    ident: str = "endpoint"
    generation: int = 0

    def send(self, meta: dict, payload: Any = b"", *,
             key: Optional[str] = None, **kw) -> None:
        raise NotImplementedError

    def recv(self, timeout: Optional[float] = None, *,
             key: Optional[str] = None) -> Tuple[dict, Any]:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError

    def __enter__(self) -> "Endpoint":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
