"""Fabric wire format — length-prefixed JSON+bytes frames, CRC32 trailer.

    !II  (head_len, payload_len)
    head_len bytes of JSON meta
    payload_len bytes of payload
    !I   CRC32 over head + payload

The trailer is the corruption fence: a flipped bit anywhere in the frame
raises :class:`FrameCorrupt` (an ``OSError``) at receipt instead of
feeding silent garbage into ``np.frombuffer``/``np.load`` — peer-fatal,
because a torn frame also desynchronizes the length prefix and nothing
after it can be trusted. This module is the ONLY framing code in the
repo; the MPMD star, its driver router, and the process fleet all call
these four functions.

``net.corrupt`` (flag mode, keyed by the sender's ident) flips one
payload bit AFTER the CRC is computed — on-wire corruption, proven
caught at the receiving end.
"""

from __future__ import annotations

import json
import socket
import struct
import zlib
from typing import Optional, Tuple

from ...testing import chaos
from .endpoint import ChannelClosed, FrameCorrupt

_HDR = struct.Struct("!II")
_CRC = struct.Struct("!I")


def pack_frame(meta: dict, payload: bytes = b"", *,
               key: Optional[str] = None) -> bytes:
    head = json.dumps(meta, sort_keys=True).encode()
    crc = zlib.crc32(payload, zlib.crc32(head)) & 0xFFFFFFFF
    if chaos.flag("net.corrupt", key=key):
        # on-wire bit flip, injected AFTER the trailer was computed —
        # the receiver's CRC check must catch it
        if payload:
            payload = bytes([payload[0] ^ 0x01]) + payload[1:]
        else:
            head = bytes([head[0] ^ 0x01]) + head[1:]
    return _HDR.pack(len(head), len(payload)) + head + payload \
        + _CRC.pack(crc)


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ChannelClosed("peer closed the transfer connection")
        buf += chunk
    return buf


def read_frame(sock: socket.socket) -> Tuple[dict, bytes]:
    hlen, plen = _HDR.unpack(_read_exact(sock, _HDR.size))
    head = _read_exact(sock, hlen)
    payload = _read_exact(sock, plen) if plen else b""
    want, = _CRC.unpack(_read_exact(sock, _CRC.size))
    got = zlib.crc32(payload, zlib.crc32(head)) & 0xFFFFFFFF
    if got != want:
        raise FrameCorrupt(
            f"frame CRC mismatch (want {want:#010x}, got {got:#010x}) — "
            "corrupted link, stream unrecoverable")
    return json.loads(head.decode()), payload


def write_frame(sock: socket.socket, meta: dict, payload: bytes = b"", *,
                key: Optional[str] = None) -> None:
    sock.sendall(pack_frame(meta, payload, key=key))
