"""In-worker liveness watchdog — turn a silent hang into a diagnosable exit.

In a one-process-per-host multi-controller job a single wedged rank (a
deadlocked collective, a hung host, a dead coordinator) stalls EVERY rank:
all of them sit inside a collective waiting for the straggler, forever.
Durable checkpoints (PR 3) don't help if nothing ever exits — supervision
needs a liveness signal.

Round 4 shipped a single armed/unarmed stall clock fed by ``engine.step()``
— which left the whole pre-first-step window (XLA compile hangs, wedged
sharded restores) unbounded. This round replaces it with a PHASE-AWARE
watchdog: the worker lifecycle is explicit phases (INIT → RESTORE →
COMPILE → STEP → SAVE, runtime/heartbeat.py), each with its OWN deadline:

========  =============================================  ==================
phase     covers                                         config key
========  =============================================  ==================
INIT      jax.distributed rendezvous                     ``DSTPU_INIT_TIMEOUT`` / :func:`init_deadline`
RESTORE   ``engine.load_checkpoint``                     ``watchdog.restore_timeout``
COMPILE   first ``train_batch`` entry → first completion ``watchdog.compile_timeout``
STEP      steady-state step gaps                         ``watchdog.stall_timeout``
SAVE      ``engine.save_checkpoint``                     ``watchdog.save_timeout``
========  =============================================  ==================

A deadline of 0 leaves that phase unbounded (the round-4 semantics for
everything but STEP). On expiry the watchdog dumps EVERY thread's stack
(``faulthandler`` — the hang is usually in a collective or an IO thread),
stamps a terminal ``STALLED`` heartbeat record if a writer is attached
(launcher-side supervisors read it to keep the rc contract), and exits
:data:`STALL_EXIT_CODE`.

**Single rc-117 path**: every deadline in this module — phase deadlines
and :func:`init_deadline` — fires through one guarded :func:`_fire`
implementation. A process where two timers expire in the same instant
(an init deadline racing an armed watchdog used to be two independent
``threading.Timer``/thread exits) performs exactly one dump-and-exit;
the loser returns without side effects.

Exit-code contract (docs/RESILIENCE.md): 0 = clean,
``PREEMPTION_EXIT_CODE`` (114) = checkpointed-and-resumable,
``STALL_EXIT_CODE`` (117) = wedged (counts against the elastic agent's
``max_restarts`` — a stall is a failure, not a preemption).

reference counterpart: torch-elastic's watchdog/healthcheck timers on the
agent; placing the heartbeat IN the worker is what lets a jax_graft
worker self-report before the collective deadlock propagates.
"""

from __future__ import annotations

import contextlib
import faulthandler
import io
import os
import sys
import threading
import time
from typing import Callable, Dict, Optional

from .heartbeat import (PHASE_COMPILE, PHASE_INIT, PHASE_RESTORE, PHASE_SAVE,
                        PHASE_STALLED, PHASE_STEP)

#: Exit code meaning "this worker made no progress within its current
#: phase's deadline". Distinct from Python's 0-2, shell signal codes
#: (>=128), chaos.KILL_EXIT_CODE and PREEMPTION_EXIT_CODE. Re-exported
#: from the single-source contract module.
from ..exit_codes import STALL_EXIT_CODE  # noqa: E402


def _dump_stacks(stream, reason: str) -> None:
    """All-threads stack dump. Best-effort: diagnostics must never mask
    the exit itself. faulthandler (async-signal-safe, the production
    path) needs a real fd; fd-less streams (tests, redirected stderr)
    fall back to a pure-Python dump via sys._current_frames()."""
    try:
        stream.write(f"\n=== dstpu watchdog: {reason} — "
                     "dumping all thread stacks ===\n")
        stream.flush()
        try:
            stream.fileno()
            faulthandler.dump_traceback(file=stream, all_threads=True)
        except (AttributeError, OSError, ValueError, io.UnsupportedOperation):
            import traceback
            names = {t.ident: t.name for t in threading.enumerate()}
            for tid, frame in sys._current_frames().items():
                stream.write(f"\nThread {names.get(tid, '?')} ({tid}):\n")
                traceback.print_stack(frame, file=stream)
        stream.flush()
    except Exception:
        pass


#: bound on the terminal-stamp lock acquisition inside :func:`_fire` —
#: the writer's refresher may hold the lock wedged in dead-storage I/O,
#: and the rc-117 exit must never wait on diagnostics
_STAMP_LOCK_TIMEOUT = 5.0

# The process-wide rc-117 once-guard. Held (not re-released) when the
# exit_fn actually exits the process; released afterwards for test
# exit_fns that return, so independent tests can each observe a fire.
_fire_lock = threading.Lock()
_fire_in_progress = False


def _fire(stream, reason: str, exit_fn: Callable[[int], None],
          heartbeat=None, step: int = 0) -> bool:
    """THE rc-117 exit path. Returns False (without any side effects) if
    another deadline in this process is already mid-exit — the fix for
    an init deadline and an armed phase watchdog double-firing."""
    global _fire_in_progress
    # bounded: the guard only brackets flag flips, so a starved acquire
    # means another deadline is mid-exit (or the interpreter is dying) —
    # either way this fire yields rather than wedging the exit path
    if not _fire_lock.acquire(timeout=_STAMP_LOCK_TIMEOUT):
        return False
    try:
        if _fire_in_progress:
            return False
        _fire_in_progress = True
    finally:
        _fire_lock.release()
    try:
        _dump_stacks(stream, reason)
        if heartbeat is not None:
            try:
                # the final word: launcher-side supervisors read STALLED
                # to restore rc 117 through schedulers that flatten rcs.
                # Bounded lock: the writer's refresher may itself be the
                # wedge (dead NFS blocks inside _flush WITHOUT raising),
                # and an exit path that waits on a diagnostics lock would
                # turn the guaranteed rc-117 exit back into a hang
                heartbeat.write(PHASE_STALLED, step, force=True,
                                lock_timeout=_STAMP_LOCK_TIMEOUT)
            except Exception:
                pass
        exit_fn(STALL_EXIT_CODE)
        return True
    finally:
        # same bound on the reset: a test exit_fn that returns must not
        # leave the NEXT fire waiting forever if the guard is starved
        if _fire_lock.acquire(timeout=_STAMP_LOCK_TIMEOUT):
            _fire_in_progress = False
            _fire_lock.release()


class StallWatchdog:
    """Phase-aware deadline monitor.

    ``enter_phase(p)`` moves the lifecycle clock into phase ``p`` and
    restarts it; ``beat()`` marks progress WITHIN the current phase (the
    engine's step path calls it per optimizer step). A gap longer than
    the current phase's deadline — ``stall_timeout`` for STEP,
    ``phase_timeouts[p]`` otherwise, 0 = unbounded — fires the single
    rc-117 path. ``suspended()`` brackets operations whose duration is
    legitimately unbounded regardless of phase (the preemption grace
    window); leaving the bracket re-arms the clock from now.
    """

    def __init__(self,
                 stall_timeout: float,
                 poll_interval: Optional[float] = None,
                 exit_fn: Optional[Callable[[int], None]] = None,
                 stream=None,
                 phase_timeouts: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 heartbeat=None,
                 phase: str = PHASE_STEP):
        self.timeouts: Dict[str, float] = {PHASE_STEP: float(stall_timeout)}
        for k, v in (phase_timeouts or {}).items():
            self.timeouts[k] = float(v)
        positive = [t for t in self.timeouts.values() if t > 0]
        if not positive:
            raise ValueError(
                "watchdog needs at least one positive deadline (0 disables "
                "a phase at the config layer, not here)")
        self.stall_timeout = float(stall_timeout)
        self.poll_interval = (float(poll_interval) if poll_interval
                              else max(min(positive) / 4.0, 0.05))
        self.labels = dict(labels or {})
        self.heartbeat = heartbeat
        self._exit_fn = exit_fn or os._exit
        self._stream = stream if stream is not None else sys.stderr
        self._lock = threading.Lock()
        self._last_beat = time.monotonic()
        self._phase = phase
        self._step = 0
        self._suspends = 0          # nested suspensions (save inside grace)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.fired = False          # observable by in-process tests

    # ------------------------------------------------------------- lifecycle

    def start(self) -> "StallWatchdog":
        if self._thread is not None:
            return self
        # fresh event per start: start() after stop() must arm a REAL
        # monitor, not a thread that sees the stale stop flag and dies
        self._stop = threading.Event()
        self._last_beat = time.monotonic()
        self._thread = threading.Thread(target=self._run,
                                        name="dstpu-stall-watchdog",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=self.poll_interval * 4)
        self._thread = None

    # ------------------------------------------------------------ heartbeat

    @property
    def phase(self) -> str:
        with self._lock:
            return self._phase

    def enter_phase(self, phase: str, step: Optional[int] = None) -> None:
        """Move the lifecycle clock into ``phase`` and restart it. The
        old phase's elapsed time is never charged to the new one."""
        with self._lock:
            self._phase = phase
            if step is not None:
                self._step = int(step)
            self._last_beat = time.monotonic()

    @contextlib.contextmanager
    def phase_scope(self, phase: str):
        """Bracket a bounded section (a RESTORE or SAVE): enter the
        phase, and on exit return to the prior phase with a fresh clock —
        the section's duration must not count toward the next gap."""
        with self._lock:
            prev = self._phase
        self.enter_phase(phase)
        try:
            yield self
        finally:
            self.enter_phase(prev)

    def beat(self, step: Optional[int] = None) -> None:
        with self._lock:
            self._last_beat = time.monotonic()
            if step is not None:
                self._step = int(step)

    def suspend(self) -> None:
        with self._lock:
            self._suspends += 1

    def resume(self) -> None:
        with self._lock:
            self._suspends = max(0, self._suspends - 1)
            # the suspended window must not count toward the NEXT gap
            self._last_beat = time.monotonic()

    @contextlib.contextmanager
    def suspended(self):
        """Bracket a legitimately unbounded section: the watchdog cannot
        fire inside, and the clock restarts on exit."""
        self.suspend()
        try:
            yield self
        finally:
            self.resume()

    # ----------------------------------------------------------------- loop

    def _describe(self, phase: str, gap: float, timeout: float) -> str:
        if phase in self.labels:
            return (f"{self.labels[phase]} did not complete within "
                    f"{timeout:.1f}s")
        if phase == PHASE_STEP:
            return (f"no step progress for {gap:.1f}s "
                    f"(stall_timeout={timeout:.1f}s)")
        key = {PHASE_INIT: "init", PHASE_RESTORE: "restore",
               PHASE_COMPILE: "compile", PHASE_SAVE: "save"}.get(
                   phase, phase.lower())
        return (f"phase {phase} made no progress for {gap:.1f}s "
                f"({key}_timeout={timeout:.1f}s)")

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval):
            with self._lock:
                if self._suspends > 0:
                    continue
                phase = self._phase
                step = self._step
                gap = time.monotonic() - self._last_beat
            timeout = self.timeouts.get(phase, 0.0)
            if timeout <= 0 or gap <= timeout:
                continue
            if _fire(self._stream, self._describe(phase, gap, timeout),
                     self._exit_fn, heartbeat=self.heartbeat, step=step):
                self.fired = True
            return          # fired (or lost the race to another deadline)


@contextlib.contextmanager
def init_deadline(timeout: float,
                  what: str = "jax.distributed.initialize",
                  exit_fn: Optional[Callable[[int], None]] = None,
                  stream=None):
    """Hard deadline around process bootstrap. ``timeout <= 0`` is a
    no-op (opt-in knob). If the body doesn't finish in time, dump all
    stacks and exit ``STALL_EXIT_CODE`` — a worker that never rendezvoused
    holds no state worth saving, and the fast distinct exit is what lets
    the supervisor tear the launch down instead of waiting forever.

    Implemented as a one-phase :class:`StallWatchdog` pinned to INIT, so
    the deadline rides the same poll loop and the same guarded
    :func:`_fire` path as every other phase — there is no second timer
    implementation that could double-exit."""
    if timeout is None or timeout <= 0:
        yield
        return
    wd = StallWatchdog(stall_timeout=0.0,
                       poll_interval=min(float(timeout) / 4.0, 1.0),
                       exit_fn=exit_fn, stream=stream,
                       phase_timeouts={PHASE_INIT: float(timeout)},
                       labels={PHASE_INIT: what},
                       phase=PHASE_INIT)
    wd.start()
    try:
        yield
    finally:
        wd.stop()
