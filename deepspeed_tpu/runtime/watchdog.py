"""In-worker stall watchdog — turn a silent hang into a diagnosable exit.

In a one-process-per-host multi-controller job a single wedged rank (a
deadlocked collective, a hung host, a dead coordinator) stalls EVERY rank:
all of them sit inside a collective waiting for the straggler, forever.
Durable checkpoints (PR 3) don't help if nothing ever exits — supervision
needs a liveness signal. This module provides two:

- :class:`StallWatchdog`: a daemon thread fed by ``engine.step()``
  progress (``beat()``). If no heartbeat arrives within ``stall_timeout``
  seconds it dumps EVERY thread's stack via ``faulthandler`` (the hang is
  usually in a collective or an IO thread, not the main thread) and exits
  with :data:`STALL_EXIT_CODE` — a distinct rc so the launcher-side
  supervisor and the elastic agent can tell "wedged" from "crashed" from
  "preempted". The watchdog SUSPENDS during checkpoint saves and the
  preemption grace window: slow-but-progressing IO must never be misread
  as a hang.

- :func:`init_deadline`: a bounded window around
  ``jax.distributed.initialize`` (launch.py / comm.py). A dead or
  unreachable coordinator makes initialize block forever with zero
  diagnostics; under a deadline the worker dumps stacks and exits with
  the stall rc instead, so the supervisor tears the launch down fast.

Exit-code contract (docs/RESILIENCE.md): 0 = clean,
``PREEMPTION_EXIT_CODE`` (114) = checkpointed-and-resumable,
``STALL_EXIT_CODE`` (117) = wedged (counts against the elastic agent's
``max_restarts`` — a stall is a failure, not a preemption).

reference counterpart: torch-elastic's watchdog/healthcheck timers on the
agent; placing the heartbeat IN the worker is what lets a jax_graft
worker self-report before the collective deadlock propagates.
"""

from __future__ import annotations

import contextlib
import faulthandler
import io
import os
import sys
import threading
import time
from typing import Callable, Optional

#: Exit code meaning "this worker made no step progress within the stall
#: timeout". Distinct from Python's 0-2, shell signal codes (>=128),
#: chaos.KILL_EXIT_CODE (13) and PREEMPTION_EXIT_CODE (114).
STALL_EXIT_CODE = 117


def _dump_stacks(stream, reason: str) -> None:
    """All-threads stack dump. Best-effort: diagnostics must never mask
    the exit itself. faulthandler (async-signal-safe, the production
    path) needs a real fd; fd-less streams (tests, redirected stderr)
    fall back to a pure-Python dump via sys._current_frames()."""
    try:
        stream.write(f"\n=== dstpu watchdog: {reason} — "
                     "dumping all thread stacks ===\n")
        stream.flush()
        try:
            stream.fileno()
            faulthandler.dump_traceback(file=stream, all_threads=True)
        except (AttributeError, OSError, ValueError, io.UnsupportedOperation):
            import traceback
            names = {t.ident: t.name for t in threading.enumerate()}
            for tid, frame in sys._current_frames().items():
                stream.write(f"\nThread {names.get(tid, '?')} ({tid}):\n")
                traceback.print_stack(frame, file=stream)
        stream.flush()
    except Exception:
        pass


class StallWatchdog:
    """Heartbeat-fed stall detector.

    ``beat()`` is called from the engine's step path; a gap longer than
    ``stall_timeout`` seconds (while not suspended) dumps stacks and calls
    ``exit_fn(STALL_EXIT_CODE)`` (default ``os._exit`` — a wedged process
    cannot be trusted to unwind). ``suspended()`` brackets operations
    whose duration is legitimately unbounded by step time (checkpoint
    saves, the preemption grace window); leaving the bracket re-arms the
    clock from now, so save time is never charged to the next step.
    """

    def __init__(self,
                 stall_timeout: float,
                 poll_interval: Optional[float] = None,
                 exit_fn: Optional[Callable[[int], None]] = None,
                 stream=None):
        if stall_timeout <= 0:
            raise ValueError("stall_timeout must be > 0 (0 disables the "
                             "watchdog at the config layer, not here)")
        self.stall_timeout = float(stall_timeout)
        self.poll_interval = (float(poll_interval) if poll_interval
                              else max(self.stall_timeout / 4.0, 0.05))
        self._exit_fn = exit_fn or os._exit
        self._stream = stream if stream is not None else sys.stderr
        self._lock = threading.Lock()
        self._last_beat = time.monotonic()
        self._suspends = 0          # nested suspensions (save inside grace)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.fired = False          # observable by in-process tests

    # ------------------------------------------------------------- lifecycle

    def start(self) -> "StallWatchdog":
        if self._thread is not None:
            return self
        # fresh event per start: start() after stop() must arm a REAL
        # monitor, not a thread that sees the stale stop flag and dies
        self._stop = threading.Event()
        self._last_beat = time.monotonic()
        self._thread = threading.Thread(target=self._run,
                                        name="dstpu-stall-watchdog",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=self.poll_interval * 4)
        self._thread = None

    # ------------------------------------------------------------ heartbeat

    def beat(self) -> None:
        with self._lock:
            self._last_beat = time.monotonic()

    def suspend(self) -> None:
        with self._lock:
            self._suspends += 1

    def resume(self) -> None:
        with self._lock:
            self._suspends = max(0, self._suspends - 1)
            # the suspended window must not count toward the NEXT gap
            self._last_beat = time.monotonic()

    @contextlib.contextmanager
    def suspended(self):
        """Bracket a save (or any legitimately slow section): the watchdog
        cannot fire inside, and the clock restarts on exit."""
        self.suspend()
        try:
            yield self
        finally:
            self.resume()

    # ----------------------------------------------------------------- loop

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval):
            with self._lock:
                if self._suspends > 0:
                    continue
                gap = time.monotonic() - self._last_beat
            if gap <= self.stall_timeout:
                continue
            self.fired = True
            _dump_stacks(self._stream,
                         f"no step progress for {gap:.1f}s "
                         f"(stall_timeout={self.stall_timeout:.1f}s)")
            self._exit_fn(STALL_EXIT_CODE)
            return          # test exit_fns return instead of exiting


@contextlib.contextmanager
def init_deadline(timeout: float,
                  what: str = "jax.distributed.initialize",
                  exit_fn: Optional[Callable[[int], None]] = None,
                  stream=None):
    """Hard deadline around process bootstrap. ``timeout <= 0`` is a
    no-op (opt-in knob). If the body doesn't finish in time, dump all
    stacks and exit ``STALL_EXIT_CODE`` — a worker that never rendezvoused
    holds no state worth saving, and the fast distinct exit is what lets
    the supervisor tear the launch down instead of waiting forever."""
    if timeout is None or timeout <= 0:
        yield
        return
    exit_fn = exit_fn or os._exit
    out = stream if stream is not None else sys.stderr

    def _expired():
        _dump_stacks(out, f"{what} did not complete within {timeout:.1f}s")
        exit_fn(STALL_EXIT_CODE)

    timer = threading.Timer(timeout, _expired)
    timer.daemon = True
    timer.start()
    try:
        yield
    finally:
        timer.cancel()
