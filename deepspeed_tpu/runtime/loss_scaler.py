"""Static + dynamic fp16 loss scaling, functional-style.

Capability parity with the reference's ``runtime/fp16/loss_scaler.py``
(LossScaler / DynamicLossScaler): scale the loss before backward, detect
inf/nan in grads, skip the step and halve the scale on overflow, double after
``scale_window`` clean steps. State is a small pytree carried through the
jitted train step (no Python-side branching — overflow handling is lax.cond
inside the compiled program, so the TPU never syncs to host mid-step).
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp


class LossScaleState(NamedTuple):
    scale: jnp.ndarray          # f32 scalar
    good_steps: jnp.ndarray     # i32 scalar — consecutive overflow-free steps
    hysteresis: jnp.ndarray     # i32 scalar — remaining tolerated overflows

    @classmethod
    def identity(cls) -> "LossScaleState":
        """Scale 1.0 — the no-scaling placeholder threaded through steps
        whose scaler is disabled."""
        return cls(scale=jnp.asarray(1.0, jnp.float32),
                   good_steps=jnp.asarray(0, jnp.int32),
                   hysteresis=jnp.asarray(1, jnp.int32))


class LossScaler:
    """Unified static/dynamic scaler. static = dynamic with growth disabled."""

    def __init__(self,
                 static_scale: float = 0.0,
                 initial_scale_power: int = 16,
                 scale_window: int = 1000,
                 min_scale: float = 1.0,
                 hysteresis: int = 2,
                 scale_factor: float = 2.0,
                 enabled: bool = True):
        self.enabled = enabled
        self.dynamic = enabled and static_scale == 0.0
        self.initial_scale = (static_scale if static_scale > 0.0 else
                              2.0 ** initial_scale_power) if enabled else 1.0
        self.scale_window = scale_window
        self.min_scale = min_scale
        self.init_hysteresis = hysteresis
        self.scale_factor = scale_factor

    def init(self) -> LossScaleState:
        return LossScaleState(
            scale=jnp.asarray(self.initial_scale, jnp.float32),
            good_steps=jnp.asarray(0, jnp.int32),
            hysteresis=jnp.asarray(self.init_hysteresis, jnp.int32))

    def scale_loss(self, loss, state: LossScaleState):
        return loss * state.scale if self.enabled else loss

    def unscale(self, grads, state: LossScaleState):
        if not self.enabled:
            return grads
        inv = 1.0 / state.scale
        return jax.tree.map(lambda g: g.astype(jnp.float32) * inv, grads)

    @staticmethod
    def has_overflow(grads) -> jnp.ndarray:
        """Global inf/nan check over the grad pytree (reference:
        CHECK_OVERFLOW / has_overflow_serial, stage_1_and_2.py:1710)."""
        leaves = jax.tree.leaves(grads)
        finite = jnp.stack([jnp.all(jnp.isfinite(g)) for g in leaves]).all()
        return ~finite

    def update(self, state: LossScaleState, overflow) -> LossScaleState:
        """Post-step scale adjustment (reference: DynamicLossScaler.update_scale)."""
        if not self.dynamic:
            return state

        def on_overflow(s):
            hyst = s.hysteresis - 1
            new_scale = jnp.where(hyst <= 0,
                                  jnp.maximum(s.scale / self.scale_factor, self.min_scale),
                                  s.scale)
            return LossScaleState(scale=new_scale, good_steps=jnp.asarray(0, jnp.int32),
                                  hysteresis=jnp.maximum(hyst, 1))

        def on_ok(s):
            good = s.good_steps + 1
            grow = good >= self.scale_window
            return LossScaleState(
                scale=jnp.where(grow, s.scale * self.scale_factor, s.scale),
                good_steps=jnp.where(grow, 0, good).astype(jnp.int32),
                hysteresis=jnp.asarray(self.init_hysteresis, jnp.int32))

        return jax.lax.cond(overflow, on_overflow, on_ok, state)
