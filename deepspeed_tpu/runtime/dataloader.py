"""Data loading: deterministic distributed batching over indexable datasets.

Capability parity with the reference's ``runtime/dataloader.py``
(DeepSpeedDataLoader wiring DistributedSampler, RepeatingLoader). On TPU with a
single-controller jit step, every process loads the *global* batch layout and
the engine shards it over the mesh — so the "sampler" is a deterministic
permutation shared by seed, not a per-rank torch sampler.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, Optional

import numpy as np


class RepeatingLoader:
    """reference: runtime/dataloader.py:16 — wraps an iterator to restart on StopIteration."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


def _default_collate(samples):
    """Stack a list of samples (tuples/dicts/arrays) into batch arrays."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: np.stack([np.asarray(s[k]) for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(np.stack([np.asarray(s[i]) for s in samples])
                           for i in range(len(first)))
    return np.stack([np.asarray(s) for s in samples])


class DeepSpeedDataLoader:
    """Batched, shuffled, epoch-aware loader. reference: runtime/dataloader.py:39."""

    def __init__(self,
                 dataset,
                 batch_size: int,
                 shuffle: bool = True,
                 seed: int = 42,
                 drop_last: bool = True,
                 collate_fn: Optional[Callable] = None,
                 data_sampler=None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn or _default_collate
        self.data_sampler = data_sampler
        self.epoch = 0
        self.len = len(dataset) // batch_size if drop_last else \
            (len(dataset) + batch_size - 1) // batch_size

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __len__(self):
        return self.len

    def __iter__(self) -> Iterator[Any]:
        n = len(self.dataset)
        if self.data_sampler is not None:
            order = np.asarray(list(iter(self.data_sampler)))
        elif self.shuffle:
            order = np.random.RandomState(self.seed + self.epoch).permutation(n)
        else:
            order = np.arange(n)
        limit = self.len * self.batch_size if self.drop_last else n
        for start in range(0, limit, self.batch_size):
            idx = order[start:start + self.batch_size]
            yield self.collate_fn([self.dataset[int(i)] for i in idx])
        self.epoch += 1
