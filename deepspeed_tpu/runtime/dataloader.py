"""Data loading: deterministic distributed batching over indexable datasets.

Capability parity with the reference's ``runtime/dataloader.py``
(DeepSpeedDataLoader wiring DistributedSampler, RepeatingLoader). On TPU with a
single-controller jit step, every process loads the *global* batch layout and
the engine shards it over the mesh — so the "sampler" is a deterministic
permutation shared by seed, not a per-rank torch sampler.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, Optional

import numpy as np


class RepeatingLoader:
    """reference: runtime/dataloader.py:16 — wraps an iterator to restart on StopIteration."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)

    def fast_forward(self, num_batches: int) -> None:
        """Deterministically position the stream as if ``num_batches`` had
        been consumed — the resume half of ``engine.data_position``: after
        a rollback-abort or SDC relaunch the restored state must NOT
        re-see the batches it already trained on (the poisoned span among
        them). Delegates to the wrapped loader's own ``fast_forward`` when
        it has one (epoch-aware, O(1)); otherwise drains ``num_batches``
        items (correct for any iterator, O(n))."""
        ff = getattr(self.loader, "fast_forward", None)
        if callable(ff):
            ff(num_batches)
            self.data_iter = iter(self.loader)
            return
        for _ in range(int(num_batches)):
            next(self)


def _default_collate(samples):
    """Stack a list of samples (tuples/dicts/arrays) into batch arrays."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: np.stack([np.asarray(s[k]) for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(np.stack([np.asarray(s[i]) for s in samples])
                           for i in range(len(first)))
    return np.stack([np.asarray(s) for s in samples])


class DeepSpeedDataLoader:
    """Batched, shuffled, epoch-aware loader. reference: runtime/dataloader.py:39."""

    def __init__(self,
                 dataset,
                 batch_size: int,
                 shuffle: bool = True,
                 seed: int = 42,
                 drop_last: bool = True,
                 collate_fn: Optional[Callable] = None,
                 data_sampler=None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn or _default_collate
        self.data_sampler = data_sampler
        self.epoch = 0
        self._start_batch = 0       # in-epoch offset set by fast_forward
        self.len = len(dataset) // batch_size if drop_last else \
            (len(dataset) + batch_size - 1) // batch_size

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __len__(self):
        return self.len

    def fast_forward(self, num_batches: int) -> None:
        """O(1) deterministic reposition: the loader behaves as if
        ``num_batches`` global batches had already been drawn — same
        epoch boundary, same per-epoch permutation (seed + epoch), so a
        resumed run sees exactly the batches a never-interrupted run
        would see next. Feeds ``engine.fast_forward_dataloader`` at
        resume (docs/RESILIENCE.md: the poisoned span is skipped, not
        replayed).

        With an external ``data_sampler`` the guarantee holds only if
        the sampler derives its order from ``set_epoch`` (the torch
        idiom — ``__iter__`` forwards the epoch); a sampler carrying
        hidden iteration state of its own cannot be repositioned from
        here, so the resume may re-see consumed batches."""
        if self.len <= 0:
            return
        num_batches = max(0, int(num_batches))
        self.epoch = num_batches // self.len
        self._start_batch = num_batches % self.len
        if self.data_sampler is not None and not callable(
                getattr(self.data_sampler, "set_epoch", None)):
            from ..utils.logging import warning_once
            warning_once(
                f"fast_forward with a {type(self.data_sampler).__name__} "
                "sampler that has no set_epoch(): the resumed order "
                "depends on the sampler's own state — the skipped span "
                "may be partially re-seen")

    def __iter__(self) -> Iterator[Any]:
        n = len(self.dataset)
        if self.data_sampler is not None:
            # epoch-aware samplers (the torch set_epoch idiom) re-derive
            # their order from the epoch — which also makes fast_forward's
            # multi-epoch reposition honest for them
            se = getattr(self.data_sampler, "set_epoch", None)
            if callable(se):
                se(self.epoch)
            order = np.asarray(list(iter(self.data_sampler)))
        elif self.shuffle:
            order = np.random.RandomState(self.seed + self.epoch).permutation(n)
        else:
            order = np.arange(n)
        limit = self.len * self.batch_size if self.drop_last else n
        first = self._start_batch * self.batch_size
        self._start_batch = 0       # one partial epoch, then full ones
        for start in range(first, limit, self.batch_size):
            idx = order[start:start + self.batch_size]
            yield self.collate_fn([self.dataset[int(i)] for i in idx])
        self.epoch += 1
