"""Explicit-collective 1-bit optimizers — OneBitAdam / OneBitLamb with real
wire-byte savings.

Role of the reference's ``runtime/fp16/onebit/adam.py`` + ``onebit/lamb.py``
over the compressed comm backends (``runtime/comm/nccl.py:52-204``): after a
warmup stage of exact Adam/LAMB, the variance term freezes and the per-step
exchange becomes the COMPRESSED momentum (packed sign bits + scales through
runtime/comm/compressed.compressed_allreduce) instead of a full-precision
gradient allreduce — ~32x fewer bytes on the wire.

The SPMD engine's default grad sync lets XLA insert psums, which cannot be
compressed. This runner therefore owns the whole train step: local (per-DP-
rank) grads come out of a shard_map unsummed, the momentum update runs on the
stacked per-rank grads, and the only cross-rank traffic in the compression
stage is the 1-bit exchange. Warmup/compression are two separately-jitted
programs switched host-side at freeze_step (a static branch — no dead
collectives in either HLO, which also makes the wire-byte accounting in
tests/test_onebit.py auditable from the compiled module).

Composition envelope: pure DP mesh.  fp16 loss scaling composes (the
reference default — onebit/adam.py:11 runs under FP16_Optimizer): the scale
rides into the local grad stage, overflow is detected on the global norm and
the whole update (including the compressed exchange) is skipped under
``lax.cond`` while the scale state adjusts.  ZeRO stage 1 composes: the
optimizer state (m/v and friends) is sharded leaf-dim-0 across the DP axis —
XLA turns the momentum update into reduce-scatter + sharded math + param
all-gather, the standard ZeRO-1 wire pattern.  ZeRO>=2 stays out: sharding
GRADS would defeat the stacked-per-rank layout the compressed exchange needs.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .comm.compressed import chunk_elems, compressed_allreduce
from ..utils.jax_compat import shard_map as _shard_map

PyTree = Any


def stacked_local_grads(runner, params, micros, rng, scale):
    """shard_map over the DP axis: grads stacked [n, ...] (dim0 sharded),
    NO cross-rank reduction — the whole point of the explicit-collective
    optimizers. Shared by the 1-bit (OneBitRunner) and 0/1
    (ZeroOneRunner) gradient stages.

    ``scale`` is the fp16 loss scale (1.0 when scaling is off): the loss
    is scaled inside the backward and the stacked grads come out UNSCALED
    (divided back out with the gas normalization), so inf/nan from a
    genuine fp16 overflow still propagates for detection. Returns
    (grads_st, loss_st, sq_st), every leaf stacked per-rank on dim0."""
    gas = runner.gas

    def local(params, micros_l, rng, scale):
        r = jax.random.fold_in(rng, lax.axis_index(runner.axis))
        rngs = jax.random.split(r, gas)

        def body(acc, xs):
            micro, rr = xs
            cparams = jax.tree.map(
                lambda p: p.astype(runner.compute_dtype), params)

            def lossf(p):
                out = runner.apply_fn(p, micro, rr, True)
                # scale in f32: casting the scale itself to fp16 turns
                # 2^16 into inf and every step would spuriously overflow
                return runner.loss_fn(out, micro).astype(jnp.float32) * scale

            l, g = jax.value_and_grad(lossf)(cparams)
            return jax.tree.map(
                lambda a, gg: a + gg.astype(jnp.float32), acc, g), l

        zero = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                            params)
        gsum, losses = lax.scan(body, zero, (micros_l, rngs))
        g = jax.tree.map(lambda x: x[None] / (gas * scale), gsum)
        sq = sum(jnp.sum(jnp.square(x)) for x in jax.tree.leaves(g))
        return g, (jnp.mean(losses) / scale)[None], sq[None]

    mapped = _shard_map(
        local, mesh=runner.mesh,
        in_specs=(P(), P(None, runner.axis), P(), P()),
        out_specs=(P(runner.axis), P(runner.axis), P(runner.axis)),
        axis_names={runner.axis}, check_vma=False)
    return mapped(params, micros, rng, scale)


class OneBitRunner:
    """Owns optimizer state + the two-stage compiled train step."""

    def __init__(self,
                 kind: str,                      # "adam" | "lamb"
                 hyper: Dict,
                 mesh,
                 axis: str,
                 apply_fn: Callable,
                 loss_fn: Callable,
                 gas: int,
                 compute_dtype=jnp.float32,
                 grad_clip: float = 0.0,
                 loss_scaler=None,
                 zero_stage: int = 0):
        self.kind = kind
        self.mesh = mesh
        self.axis = axis
        self.n = mesh.shape[axis]
        self.gas = gas
        self.apply_fn = apply_fn
        self.loss_fn = loss_fn
        self.compute_dtype = compute_dtype
        self.grad_clip = grad_clip
        self.loss_scaler = loss_scaler          # LossScaler or None
        self.zero_stage = int(zero_stage)

        h = dict(hyper or {})
        self.lr = float(h.pop("lr", 1e-3))
        b = h.pop("betas", (0.9, 0.999))
        self.betas = (float(b[0]), float(b[1]))
        self.eps = float(h.pop("eps", 1e-8))
        self.weight_decay = float(h.pop("weight_decay", 0.0))
        self.freeze_step = int(h.pop("freeze_step", 100))
        self.max_coeff = float(h.pop("max_coeff", 10.0))
        self.min_coeff = float(h.pop("min_coeff", 0.01))
        self.coeff_beta = float(h.pop("coeff_beta", 0.9))
        self.factor_max = float(h.pop("factor_max", 4.0))
        self.factor_min = float(h.pop("factor_min", 0.5))
        self.factor_threshold = float(h.pop("factor_threshold", 0.1))

        self._step_warm = None
        self._step_frozen = None

    # -- state ---------------------------------------------------------------

    def _mv_sharding(self, p) -> NamedSharding:
        """ZeRO-1: shard optimizer-state leaves dim-0 across DP where the
        size divides (reference granularity: partition what fits evenly,
        replicate the rest); stage 0 replicates everything."""
        if self.zero_stage >= 1 and np.ndim(p) >= 1 \
                and p.shape[0] % self.n == 0:
            return NamedSharding(self.mesh, P(self.axis))
        return NamedSharding(self.mesh, P())

    def init_state(self, params_f32: PyTree) -> Dict[str, PyTree]:
        rep = NamedSharding(self.mesh, P())
        sh = NamedSharding(self.mesh, P(self.axis))
        mv = lambda: jax.tree.map(
            lambda p: jax.device_put(jnp.zeros(p.shape, jnp.float32),
                                     self._mv_sharding(p)), params_f32)
        state = {"m": mv(), "v": mv()}
        state["w_err"] = jax.tree.map(
            lambda p: jax.device_put(jnp.zeros((self.n, p.size), jnp.float32), sh),
            params_f32)
        state["s_err"] = jax.tree.map(
            lambda p: jax.device_put(
                jnp.zeros((self.n, chunk_elems(p.size, self.n)), jnp.float32), sh),
            params_f32)
        if self.kind == "lamb":
            state["v_fresh"] = mv()
            scalar = lambda val: jax.tree.map(
                lambda p: jnp.asarray(val, jnp.float32), params_f32)
            state["coeff_freeze"] = jax.device_put(scalar(0.0), rep)
            state["last_factor"] = jax.device_put(scalar(1.0), rep)
        return state

    # -- the per-rank grad stage ---------------------------------------------

    def _local_grads(self, params, micros, rng, scale):
        grads_st, loss_st, sq_st = stacked_local_grads(
            self, params, micros, rng, scale)
        return grads_st, jnp.mean(loss_st), sq_st

    # -- update math ---------------------------------------------------------

    def _mv_constrain(self, tree):
        """Pin optimizer-state outputs to their ZeRO-1 shardings so donation
        round-trips don't let XLA drift them to replicated."""
        if self.zero_stage < 1:
            return tree
        return jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(
                x, self._mv_sharding(x)), tree)

    def _warm_update(self, params, state, grads_st, lr):
        b1, b2 = self.betas
        g_mean = jax.tree.map(lambda g: jnp.mean(g, 0), grads_st)  # psum here
        new_m = self._mv_constrain(jax.tree.map(
            lambda m, g: b1 * m + (1 - b1) * g, state["m"], g_mean))
        new_v = self._mv_constrain(jax.tree.map(
            lambda v, g: b2 * v + (1 - b2) * g * g, state["v"], g_mean))
        out = dict(state, m=new_m, v=new_v)
        if self.kind == "adam":
            # reference OnebitAdam applies NO bias correction (onebit/adam.py)
            new_p = jax.tree.map(
                lambda p, m, v: p - lr * (m / (jnp.sqrt(v) + self.eps) +
                                          self.weight_decay * p),
                params, new_m, new_v)
            return new_p, out
        # lamb warmup: full trust-ratio LAMB + coeff EMA tracking
        from ..ops.optimizers import lamb_warm_leaf

        def leaf(p, m, v, cf):
            upd, coeff, new_cf = lamb_warm_leaf(
                p, m, v, cf, eps=self.eps, weight_decay=self.weight_decay,
                min_coeff=self.min_coeff, max_coeff=self.max_coeff,
                coeff_beta=self.coeff_beta)
            return p - lr * coeff * upd, new_cf

        flat_p, treedef = jax.tree.flatten(params)
        res = [leaf(p, m, v, cf) for p, m, v, cf in zip(
            flat_p, treedef.flatten_up_to(new_m), treedef.flatten_up_to(new_v),
            treedef.flatten_up_to(state["coeff_freeze"]))]
        out["coeff_freeze"] = treedef.unflatten([r[1] for r in res])
        out["v_fresh"] = new_v
        return treedef.unflatten([r[0] for r in res]), out

    def _frozen_update(self, params, state, grads_st, lr):
        """Compression stage: the ONLY cross-rank traffic per leaf is the
        1-bit momentum exchange (+ f32 scales).

        ZeRO-1 note: the error-feedback exchange needs the FULL momentum on
        every rank (m_locals = b1*m + (1-b1)*g_local), so after the freeze m
        lives replicated — one all-gather at the transition, none after.
        The variance v (frozen, read-only here) and lamb's v_fresh keep
        their ZeRO-1 shardings, so the state-memory saving persists on the
        v-side leaves."""
        b1, b2 = self.betas
        flat_p, treedef = jax.tree.flatten(params)
        m_l = treedef.flatten_up_to(state["m"])
        v_l = treedef.flatten_up_to(state["v"])
        g_l = treedef.flatten_up_to(grads_st)
        we_l = treedef.flatten_up_to(state["w_err"])
        se_l = treedef.flatten_up_to(state["s_err"])

        new_p, new_m, new_we, new_se = [], [], [], []
        extras = {}
        if self.kind == "lamb":
            vf_l = treedef.flatten_up_to(state["v_fresh"])
            cf_l = treedef.flatten_up_to(state["coeff_freeze"])
            lf_l = treedef.flatten_up_to(state["last_factor"])
            new_vf, new_lf = [], []

        for j, (p, m, g_st, we, se) in enumerate(
                zip(flat_p, m_l, g_l, we_l, se_l)):
            m_locals = b1 * m[None] + (1 - b1) * g_st       # [n, ...]
            m_new, we2, se2 = compressed_allreduce(
                m_locals, we, se, mesh=self.mesh, axis=self.axis)
            new_m.append(m_new)
            new_we.append(we2)
            new_se.append(se2)
            v = v_l[j]
            if self.kind == "adam":
                upd = m_new / (jnp.sqrt(v) + self.eps) + self.weight_decay * p
                new_p.append(p - lr * upd)
                continue
            # lamb compression stage (reference onebit/lamb.py:337-386);
            # per-leaf math shared with ops/optimizers.onebit_lamb
            from ..ops.optimizers import lamb_frozen_leaf
            upd, factor, vf = lamb_frozen_leaf(
                p, m, m_new, v, vf_l[j], lf_l[j], b1=b1, b2=b2, eps=self.eps,
                weight_decay=self.weight_decay, factor_min=self.factor_min,
                factor_max=self.factor_max,
                factor_threshold=self.factor_threshold)
            new_p.append(p - lr * (cf_l[j] * factor) * upd)
            new_vf.append(vf)
            new_lf.append(factor)

        # commit the replicated layout of the frozen-phase m: without this
        # pin, XLA's layout choice under ZeRO-1 may re-shard m and pay a
        # re-gather every step (the docstring's "one all-gather at the
        # transition" contract)
        rep = NamedSharding(self.mesh, P())
        new_m = [jax.lax.with_sharding_constraint(m, rep) for m in new_m]
        out = dict(state,
                   m=treedef.unflatten(new_m),
                   w_err=treedef.unflatten(new_we),
                   s_err=treedef.unflatten(new_se))
        if self.kind == "lamb":
            out["v_fresh"] = self._mv_constrain(treedef.unflatten(new_vf))
            out["last_factor"] = treedef.unflatten(new_lf)
        return treedef.unflatten(new_p), out

    # -- compiled steps -------------------------------------------------------

    def _build(self, frozen: bool):
        scaling = self.loss_scaler is not None and self.loss_scaler.enabled

        def step(params, state, micros, rng, lr, scale_state):
            scale = (scale_state.scale if scaling
                     else jnp.asarray(1.0, jnp.float32))
            grads_st, loss, sq_st = self._local_grads(params, micros, rng,
                                                      scale)
            # norm: in the compression stage, avoid the full f32 allreduce the
            # exact global norm would cost (it would dwarf the 1-bit savings)
            # — use sqrt(mean of per-rank ||g_local||^2), a scalar psum. The
            # warmup stage gets the exact norm for free off the mean grads.
            if frozen:
                norm = jnp.sqrt(jnp.mean(sq_st))
            else:
                norm = jnp.sqrt(sum(
                    jnp.sum(jnp.square(jnp.mean(g, 0)))
                    for g in jax.tree.leaves(grads_st)))
            if self.grad_clip > 0:
                coef = jnp.minimum(self.grad_clip / (norm + 1e-6), 1.0)
                grads_st = jax.tree.map(lambda g: g * coef, grads_st)

            def do_update(args):
                params, state, grads_st = args
                if frozen:
                    new_p, new_s = self._frozen_update(params, state,
                                                       grads_st, lr)
                else:
                    new_p, new_s = self._warm_update(params, state,
                                                     grads_st, lr)
                # ZeRO-1 sharded m/v make the raw update come out sharded;
                # params stay replicated (the all-gather IS the ZeRO-1 wire
                # pattern)
                if self.zero_stage >= 1:
                    rep = NamedSharding(self.mesh, P())
                    new_p = jax.lax.with_sharding_constraint(new_p, rep)
                return new_p, new_s

            if scaling:
                # fp16 overflow: skip the WHOLE update (momentum, compressed
                # exchange, params) and let the scaler state react — the
                # reference's FP16_Optimizer skip path (onebit runs under it,
                # onebit/adam.py:11)
                overflow = ~jnp.isfinite(norm)
                new_p, new_s = lax.cond(
                    overflow, lambda a: (a[0], a[1]), do_update,
                    (params, state, grads_st))
                new_scale_state = self.loss_scaler.update(scale_state,
                                                          overflow)
            else:
                overflow = jnp.asarray(False)
                new_p, new_s = do_update((params, state, grads_st))
                new_scale_state = scale_state
            return new_p, new_s, loss, norm, overflow, new_scale_state

        return jax.jit(step, donate_argnums=(0, 1))

    def step(self, params, state, micros, rng, lr, global_step: int,
             scale_state=None
             ) -> Tuple[PyTree, Dict, jnp.ndarray, jnp.ndarray,
                        jnp.ndarray, Any]:
        from .loss_scaler import LossScaleState
        if scale_state is None:
            # with an enabled scaler the caller must not silently train at
            # scale 1.0 — start from the scaler's own initial state
            scale_state = (self.loss_scaler.init()
                           if self.loss_scaler is not None
                           and self.loss_scaler.enabled
                           else LossScaleState.identity())
        frozen = global_step >= self.freeze_step
        if frozen:
            if self._step_frozen is None:
                self._step_frozen = self._build(True)
            fn = self._step_frozen
        else:
            if self._step_warm is None:
                self._step_warm = self._build(False)
            fn = self._step_warm
        return fn(params, state, micros, rng,
                  jnp.asarray(lr, jnp.float32), scale_state)

    # -- auditability ---------------------------------------------------------

    def collective_bytes(self, params, state, micros, rng,
                         frozen: bool) -> int:
        """Total bytes moved by cross-replica collectives in one compiled
        step — parsed from the optimized HLO, so the 1/32 wire claim is a
        measured property, not a docstring."""
        from .loss_scaler import LossScaleState
        fn = self._build(frozen)
        scale_state = LossScaleState.identity()
        lowered = jax.jit(
            lambda p, s, mi, r, lr, ss: fn(p, s, mi, r, lr, ss)).lower(
            params, state, micros, rng, jnp.asarray(self.lr, jnp.float32),
            scale_state)
        txt = lowered.compile().as_text()
        return hlo_collective_bytes(txt)


_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "bf16": 2,
                "f16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
                "f64": 8}


def hlo_collective_bytes(hlo_text: str) -> int:
    """Sum output bytes of cross-replica collective ops in optimized HLO.

    Async pairs are handled: '-start' op tuples are (operand, result, ...
    context scalars) — only the result (element 1) is counted — and '-done'
    ops (which alias the start's buffers) are skipped, so bytes aren't
    double- or triple-counted on real TPU HLO."""
    import re
    total = 0
    pat = re.compile(
        r"=\s*(?:\(([^)]*)\)|([a-z0-9]+)\[([0-9,]*)\][^ ]*)\s*"
        r"(all-reduce|all-gather|all-to-all|reduce-scatter|"
        r"collective-permute)(-start|-done)?\b")
    shape_pat = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")
    for mt in pat.finditer(hlo_text):
        suffix = mt.group(5)
        if suffix == "-done":
            continue
        if mt.group(1) is not None:      # tuple result
            shapes = shape_pat.findall(mt.group(1))
            if suffix == "-start" and len(shapes) > 1:
                # async-start tuples are (operand, result[, u32 context
                # scalars]); the wire payload is the RESULT at index 1 —
                # the last element can be a context scalar
                shapes = shapes[1:2]
        else:
            shapes = [(mt.group(2), mt.group(3))]
        for dt, dims in shapes:
            if dt not in _DTYPE_BYTES:
                continue
            numel = 1
            for d in dims.split(","):
                if d.strip():
                    numel *= int(d)
            total += numel * _DTYPE_BYTES[dt]
    return total
