"""TrainState — the engine's complete training state as one pytree.

Replaces the mutable state scattered across the reference's engine/optimizer
objects (fp16 flat buffers, partitioned master weights, loss-scale counters,
global step) with a single immutable pytree that flows through the jitted
train step and is the unit of checkpointing.
"""

from __future__ import annotations

from typing import Any, Dict

import flax.struct
import jax.numpy as jnp

from .loss_scaler import LossScaleState


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray                 # i32 global step counter
    params: Any                       # compute-dtype params (ZeRO-3: sharded)
    master: Any                       # fp32 master params (ZeRO>=1: sharded); may alias params
    opt_state: Dict[str, Any]         # optimizer state (ZeRO>=1: sharded)
    scale: LossScaleState             # fp16 loss-scale state
    skipped_steps: jnp.ndarray        # i32 count of overflow-skipped steps
    # i32 CONSECUTIVE non-finite (skipped) steps — the bf16 divergence
    # signal (no loss scaler there to react); None in externally built
    # states is treated as 0
    nonfinite_streak: Any = None
