"""Rank heartbeat channel — per-rank liveness records on a shared directory.

The run-supervision stack (PR 4) can see a rank *exit* (RunSupervisor) and
a rank *stop stepping* (StallWatchdog), but both signals have blind spots:
a rank wedged BEFORE its first completed step never arms the step
watchdog, and the pdsh/slurm/openmpi backends hide every rank behind one
scheduler process whose pipe stays silent while the pod hangs. This
module is the third signal: every rank periodically appends a small JSON
record describing *where it is* to a per-rank file under a shared
``--heartbeat-dir``; launcher-side consumers (``HeartbeatMonitor`` /
``BackendSupervisor`` in launcher/supervisor.py, ``dstpu health``) read
the records to tell "slow compile" from "wedged" without any worker
cooperation beyond the writes.

Record schema (one JSON object per line, newest last)::

    {"rank": 3, "host": "worker-3", "pid": 4711,
     "phase": "STEP", "step": 120, "ts": 1754200000.0}

Records may carry an optional ``gauges`` sub-dict of small phase-specific
load counters (the SERVE phase stamps ``{"queue": …, "active": …,
"lanes": …}``) and a sticky ``flags`` list (integrity evidence).

Design constraints:

- **Crash-evidence quality.** The file is rewritten via tmp + atomic
  ``os.replace`` so a reader never sees a torn record, and the last
  record survives the writer's death — it IS the post-mortem ("rank 3
  died in RESTORE at step 0").
- **Bounded.** Only the newest ``keep_records`` records are retained;
  a month-long run cannot grow the file.
- **Harmless.** A heartbeat is diagnostics: any ``OSError`` (full disk,
  dead NFS — or the ``hb.write`` chaos failpoint simulating either) is
  swallowed after a warning. Losing the signal degrades supervision to
  PR-4 behavior; it must never kill a healthy rank.
- **Throttled.** Same-phase writes within ``min_interval`` seconds are
  dropped so a fast step loop doesn't turn the shared filesystem into a
  hot path. Phase TRANSITIONS always write.

Terminal phases: a rank that exits through a supervised path stamps WHY
as its final record — ``STALLED`` (watchdog rc 117), ``PREEMPTED``
(SIGTERM handler, rc 114), ``EXIT`` (clean close). Backend supervisors
use these to keep the rc 114/117 contract on launchers whose scheduler
flattens exit codes (docs/RESILIENCE.md).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ..testing import chaos
from ..utils.logging import logger

# Lifecycle phases, in nominal order. INIT covers process bootstrap
# (jax.distributed rendezvous); RESTORE a checkpoint load; COMPILE the
# window between the first train_batch entry and the first completed
# step (XLA compile + sharded-restore materialization); STEP the steady
# state; SAVE a checkpoint write.
PHASE_INIT = "INIT"
PHASE_RESTORE = "RESTORE"
PHASE_COMPILE = "COMPILE"
PHASE_STEP = "STEP"
PHASE_SAVE = "SAVE"
#: the serving loop's steady state (round 8): one record per loop
#: iteration cadence — the serving analog of STEP, so the watchdog /
#: health stack supervises a long-lived server the way it supervises
#: training (serving/engine.py stamps it; watchdog.serve_timeout bounds it)
PHASE_SERVE = "SERVE"
#: terminal phases — the final record of a rank that died supervised
PHASE_STALLED = "STALLED"
PHASE_PREEMPTED = "PREEMPTED"
PHASE_EXIT = "EXIT"

PHASES = (PHASE_INIT, PHASE_RESTORE, PHASE_COMPILE, PHASE_STEP, PHASE_SAVE,
          PHASE_SERVE)
TERMINAL_PHASES = (PHASE_STALLED, PHASE_PREEMPTED, PHASE_EXIT)

#: env var carrying the shared heartbeat directory to every worker
#: (dstpu --heartbeat-dir exports it; the DSTPU_ prefix already forwards)
HEARTBEAT_DIR_ENV = "DSTPU_HEARTBEAT_DIR"

#: env var carrying THIS rank's hostfile-vocabulary host name.
#: launch.py sets it (per worker process, from world_info) so records
#: name hosts the way the OPERATOR's hostfile does — the elastic agent's
#: blacklist and the supervisors' attribution compare against hostfile
#: members, and ``socket.gethostname()`` (FQDN, or an alias the hostfile
#: never uses) would silently never match.
HEARTBEAT_HOST_ENV = "DSTPU_HEARTBEAT_HOST"

#: env var overriding THIS worker's channel rank. Normally the rank is
#: the caller's jax.process_index(), but a worker running OUTSIDE a
#: jax.distributed world (a chaos child sharing a channel with siblings,
#: a single-process twin in a multi-worker test rig) reads process index
#: 0 — every sibling would fight over rank0.hb. The launcher-side
#: consumers only care that records land in distinct per-rank files.
HEARTBEAT_RANK_ENV = "DSTPU_HEARTBEAT_RANK"

_SUFFIX = ".hb"


def heartbeat_path(directory: str, rank: int) -> str:
    return os.path.join(directory, f"rank{int(rank)}{_SUFFIX}")


class HeartbeatWriter:
    """One rank's liveness reporter. See module docstring for contract.

    A background *refresher* thread re-stamps the newest record's ``ts``
    every ``refresh_interval`` seconds while the phase is non-terminal:
    the main thread is BLOCKED inside XLA during a long compile (and
    inside a collective during a wedge), so without the refresher every
    slow phase would read as launcher-side silence. With it, silence
    means "process or host dead" — phase *progress* is the in-worker
    watchdog's jurisdiction, which stamps a terminal ``STALLED`` record
    when it shoots a wedge. ``refresh_interval=0`` disables the thread
    (tests that need records to go stale on command)."""

    def __init__(self, directory: str, rank: int, host: Optional[str] = None,
                 min_interval: float = 1.0, keep_records: int = 50,
                 refresh_interval: float = 15.0, clock=None):
        self.directory = directory
        self.rank = int(rank)
        self.host = host or _hostname()
        self.min_interval = float(min_interval)
        self.refresh_interval = float(refresh_interval)
        self._records: deque = deque(maxlen=max(1, int(keep_records)))
        self._flags: List[str] = []     # sticky marks (e.g. "SDC"), carried
        #                                 by every subsequent record
        self._clock = clock or time.time
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._refresher: Optional[threading.Thread] = None
        self._last_write = 0.0
        self._last_phase: Optional[str] = None
        self._warned = False
        try:
            os.makedirs(directory, exist_ok=True)
        except OSError as e:
            self._warn(e)

    @classmethod
    def from_env(cls, rank: int, host: Optional[str] = None
                 ) -> Optional["HeartbeatWriter"]:
        """A writer iff the launcher exported a heartbeat dir, else None
        (the channel is opt-in: it needs a filesystem every host shares).

        If launch.py registered a process-level writer for this rank
        (:func:`set_process_writer`), that writer is ADOPTED instead of
        creating a second one: two live refreshers would fight over the
        rank file, and closing the first would leave the record
        unrefreshed through the user script's import/setup window.

        ``DSTPU_HEARTBEAT_RANK`` overrides ``rank`` (see
        :data:`HEARTBEAT_RANK_ENV`: workers outside a jax.distributed
        world all read process index 0)."""
        env_rank = os.environ.get(HEARTBEAT_RANK_ENV, "")
        if env_rank:
            try:
                rank = int(env_rank)
            except ValueError:
                logger.warning("heartbeat: ignoring non-integer %s=%r",
                               HEARTBEAT_RANK_ENV, env_rank)
        existing = _process_writer
        if existing is not None and existing.rank == int(rank):
            return existing
        directory = os.environ.get(HEARTBEAT_DIR_ENV, "")
        if not directory:
            return None
        return cls(directory, rank, host=host)

    @property
    def path(self) -> str:
        return heartbeat_path(self.directory, self.rank)

    def write(self, phase: str, step: int, force: bool = False,
              lock_timeout: Optional[float] = None,
              extra: Optional[dict] = None) -> bool:
        """Record {rank, host, phase, step, ts}. Returns True if a record
        was actually written (False = throttled or swallowed failure).

        ``extra`` carries small phase-specific gauges under a ``gauges``
        sub-dict (round 11: the serving loop's queue-depth / active-lane
        counts), so ``dstpu health`` can show LOAD as well as liveness —
        namespaced so a gauge can never shadow a schema key.

        Exit paths (the watchdog's rc-117 fire, the preemption signal
        handler) must pass ``lock_timeout``: the writer lock may be held
        by a refresher wedged in dead-storage I/O — or, under a signal
        handler, by the very write frame the signal interrupted on this
        same thread — and an exit path that blocks forever on a
        diagnostics lock defeats the exit it exists to report. On
        timeout the record is dropped (the process is dying anyway;
        silence or the scheduler rc carries the verdict)."""
        if lock_timeout is None:
            # unbounded by DESIGN for steady-state callers (the refresher
            # thread, per-step writes); exit paths pass lock_timeout —
            # enforced at their call sites by TPU019's bounded-API check
            self._lock.acquire()  # graftlint: disable=TPU019
        elif not self._lock.acquire(timeout=lock_timeout):
            if phase in TERMINAL_PHASES:
                self._stop.set()
            return False
        try:
            now = self._clock()
            if (not force and phase == self._last_phase
                    and now - self._last_write < self.min_interval):
                return False
            rec = {"rank": self.rank, "host": self.host, "pid": os.getpid(),
                   "phase": phase, "step": int(step), "ts": now}
            if extra:
                rec["gauges"] = {str(k): v for k, v in extra.items()}
            if self._flags:
                rec["flags"] = list(self._flags)
            self._records.append(rec)
            transition = phase != self._last_phase
            self._last_phase = phase
            ok = self._flush(durable=transition or phase in TERMINAL_PHASES)
            if ok:
                self._last_write = now
        finally:
            self._lock.release()
        if phase in TERMINAL_PHASES:
            self._stop.set()            # the final word needs no refresh
        elif self.refresh_interval > 0:
            self._ensure_refresher()
        return ok

    def close(self) -> None:
        self._stop.set()

    def add_flag(self, flag: str, step: int = 0,
                 lock_timeout: Optional[float] = None) -> bool:
        """Stamp a STICKY mark (e.g. ``SDC``) onto this rank's record:
        the flag rides every record written from now on, so launcher-side
        consumers (supervisor/agent blacklist evidence, ``dstpu health``)
        see it no matter which record they read last. Re-writes the
        current phase immediately (forced) so the evidence is durable
        before the caller acts on it — integrity aborts exit right
        after stamping."""
        with self._lock:
            if flag not in self._flags:
                self._flags.append(flag)
            phase = self._last_phase or PHASE_INIT
            last = self._records[-1] if self._records else None
            step = int(last.get("step", step)) if last is not None else step
            # carry the newest record's gauges: a STRAGGLER flag whose
            # re-write dropped the step_ms gauge would erase the very
            # evidence it marks
            gauges = dict(last.get("gauges") or {}) if last else None
        return self.write(phase, step, force=True, lock_timeout=lock_timeout,
                          extra=gauges or None)

    def stamp_terminal(self, phase: str,
                       lock_timeout: Optional[float] = None) -> bool:
        """Append a terminal record reusing the newest record's step — the
        writer's owner is done and ``phase`` is the final word. A no-op
        when a terminal phase already stands (the engine's EXIT/PREEMPTED
        conclusion must not be overwritten by launch.py's fallback).
        ``lock_timeout`` bounds the lock as in :meth:`write`."""
        if lock_timeout is None:
            # unbounded only for non-exit callers; exit paths pass
            # lock_timeout (TPU019 flags the call sites that don't)
            self._lock.acquire()  # graftlint: disable=TPU019
        elif not self._lock.acquire(timeout=lock_timeout):
            self._stop.set()
            return False
        try:
            last = self._records[-1] if self._records else None
            if last is not None and last.get("phase") in TERMINAL_PHASES:
                self._stop.set()
                return False
            step = int(last.get("step", 0)) if last is not None else 0
        finally:
            self._lock.release()
        return self.write(phase, step, force=True, lock_timeout=lock_timeout)

    def _flush(self, durable: bool = True) -> bool:
        """Rewrite the rank file atomically from the in-memory records.
        Caller holds the lock.

        ``durable=False`` skips the fsync: steady-state STEP re-writes
        and refresher re-stamps hit the SHARED filesystem every second
        from the training hot path, and an fsync there (NFS: tens of ms)
        is charged straight to step time on every rank. Losing an
        unsynced re-stamp to a host crash just reads as silence — which
        is exactly what a dead host should read as. Phase transitions
        and terminal stamps stay durable: they ARE the post-mortem."""
        try:
            # the heartbeat-loss failpoint: an armed hb.write makes this
            # rank go silent exactly like a dead NFS mount would
            chaos.failpoint("hb.write")
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                for r in self._records:
                    f.write(json.dumps(r, sort_keys=True) + "\n")
                if durable:
                    f.flush()
                    os.fsync(f.fileno())
            os.replace(tmp, self.path)
            return True
        except OSError as e:
            self._warn(e)
            return False

    def _ensure_refresher(self) -> None:
        if self._refresher is not None and self._refresher.is_alive():
            return
        self._refresher = threading.Thread(target=self._refresh_loop,
                                           name="dstpu-heartbeat",
                                           daemon=True)
        self._refresher.start()

    def _refresh_loop(self) -> None:
        while not self._stop.wait(self.refresh_interval):
            with self._lock:
                if not self._records or \
                        self._records[-1]["phase"] in TERMINAL_PHASES:
                    continue
                # re-stamp (not append): "still alive in this phase"
                self._records[-1] = dict(self._records[-1],
                                         ts=self._clock())
                if self._flush(durable=False):
                    self._last_write = self._records[-1]["ts"]

    def _warn(self, err) -> None:
        if not self._warned:
            self._warned = True
            logger.warning(
                "heartbeat: write to %s failed (%s) — liveness reporting "
                "degraded for rank %d (training unaffected)",
                self.directory, err, self.rank)


#: the process-level writer launch.py hands off to the engine — kept
#: alive (refresher included) across the runpy boundary so the INIT
#: record cannot go stale while the user script is still importing /
#: building the model, before any engine exists to take over.
_process_writer: Optional[HeartbeatWriter] = None


def set_process_writer(writer: Optional[HeartbeatWriter]) -> None:
    global _process_writer
    _process_writer = writer


def _hostname() -> str:
    name = os.environ.get(HEARTBEAT_HOST_ENV, "")
    if name:
        return name
    import socket
    try:
        return socket.gethostname()
    except OSError:
        return "unknown"


def clear_channel(directory: str) -> None:
    """Remove every rank record (and stranded tmp) from the channel — the
    launcher-side start of a NEW supervised run attempt. The channel is
    run-scoped evidence: a STALLED record or a stale non-terminal record
    left by a previous attempt in a reused directory must never be read
    as this run's verdict (a clean degraded relaunch would reconstruct
    rc 117 forever) or trip the silence monitor at t=0. Failures are
    swallowed: an uncleanable share degrades to pre-clear behavior."""
    try:
        names = os.listdir(directory)
    except OSError:
        return
    for name in names:
        if name.startswith("rank") and (name.endswith(_SUFFIX)
                                        or name.endswith(_SUFFIX + ".tmp")):
            try:
                os.unlink(os.path.join(directory, name))
            except OSError:
                pass


def read_heartbeats(directory: str) -> Dict[int, dict]:
    """Latest record per rank: {rank: record}. Unreadable or torn files
    are skipped (the atomic replace makes torn files rare; a reader must
    still never crash on a half-dead share)."""
    out: Dict[int, dict] = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        if not (name.startswith("rank") and name.endswith(_SUFFIX)):
            continue
        try:
            with open(os.path.join(directory, name), encoding="utf-8") as f:
                lines = [ln for ln in f.read().splitlines() if ln.strip()]
            rec = json.loads(lines[-1]) if lines else None
        except (OSError, ValueError, IndexError):
            continue
        if isinstance(rec, dict) and "rank" in rec:
            out[int(rec["rank"])] = rec
    return out


def rec_host(rec: dict, rank_hosts: List[str],
             known_hosts: Optional[List[str]] = None) -> Optional[str]:
    """Best host attribution for a record — THE shared rank->host recovery
    used by RunSupervisor, BackendSupervisor, and the elastic agent, so
    blacklist evidence lands on the same host no matter which consumer
    read the record. The self-reported host wins when it is usable
    (non-empty and, when ``known_hosts`` is given, in that vocabulary —
    e.g. an out-of-band gethostname() FQDN the hostfile never uses is
    NOT usable); otherwise the rank's position in ``rank_hosts``, the
    world-ordered hosts the run was actually launched over."""
    host = rec.get("host")
    rank = rec.get("rank")
    usable = bool(host) and (known_hosts is None or host in known_hosts)
    if not usable and isinstance(rank, int) and 0 <= rank < len(rank_hosts):
        return rank_hosts[rank]
    return host


def record_age(rec: dict, now: Optional[float] = None) -> float:
    """Seconds since this record was written (clock-skew tolerant: never
    negative)."""
    now = time.time() if now is None else now
    return max(0.0, now - float(rec.get("ts", 0.0)))


def stale_ranks(directory: str, timeout: float,
                now: Optional[float] = None,
                records: Optional[Dict[int, dict]] = None) -> List[dict]:
    """Records older than ``timeout`` whose phase is non-terminal — ranks
    that were alive, said so, and then went silent. Terminal records are
    *conclusions*, not silence (a rank that stamped PREEMPTED and exited
    is not wedged, however old its record gets). ``records`` lets a
    caller that already holds a snapshot avoid a second directory read."""
    now = time.time() if now is None else now
    out = []
    if records is None:
        records = read_heartbeats(directory)
    for rank in sorted(records):
        rec = records[rank]
        if rec.get("phase") in TERMINAL_PHASES:
            continue
        if record_age(rec, now) > timeout:
            out.append(rec)
    return out


def terminal_records(directory: str) -> Dict[int, dict]:
    """Ranks whose LAST word was a terminal phase — the evidence backend
    supervisors use to reconstruct the rc contract after a scheduler
    flattened the real exit codes."""
    return {rank: rec for rank, rec in read_heartbeats(directory).items()
            if rec.get("phase") in TERMINAL_PHASES}


def flagged_ranks(directory: str,
                  flag: Optional[str] = None) -> Dict[int, dict]:
    """Ranks whose latest record carries integrity flags — the FLAGS
    column of ``dstpu health`` (any flag), and with ``flag=`` the
    blacklist feed: supervisors/agent filter to ``SDC``, the only mark
    that names a HOST, so the generic ``INTEGRITY`` abort mark (stamped
    by every rank of a diverged run) never strikes the innocent world."""
    return {rank: rec for rank, rec in read_heartbeats(directory).items()
            if rec.get("flags") and
            (flag is None or flag in rec["flags"])}
