"""Checkpoint save/load for TrainState pytrees — crash-safe by construction.

Capability parity with the reference's checkpoint layer (engine.py:2712-3489 +
runtime/checkpoint_engine/): tagged checkpoint dirs, a ``latest`` tag file,
model/optimizer state separation, client (lr-scheduler etc.) state, and
consolidation of sharded weights to a single fp32/16-bit state dict
(zero_to_fp32 / save_16bit_model equivalents).

Format: one ``.npz`` per state group + a JSON manifest of paths/dtypes/shapes.
Parameters are stored under their /-joined pytree paths — names, not partition
indices — so a checkpoint written under one mesh/ZeRO topology loads under any
other ("universal checkpoint by construction"; the reference needs the whole
``deepspeed/checkpoint/`` reshape machinery for this).

Durability model (round-3: crash/preemption resilience):

- every save writes into a ``<tag>.tmp`` staging dir; the final ``<tag>``
  dir appears via one ``os.replace`` — a reader can never observe a
  half-written tag;
- ``ckpt_meta.json`` (sha256 + size per file, shard count) is written LAST
  inside the staging dir, after the data files are fsync'd: a tag without
  its completion marker is by definition not a checkpoint;
- ``latest`` is rewritten atomically (tmp + replace) only after the tag is
  published, so it can never reference a tag missing its marker;
- ``load_checkpoint`` verifies the marker (and digests, by default) and on
  a corrupt/partial tag walks back to the newest intact one, repairing
  ``latest`` and logging what it skipped, instead of crashing;
- a failed save's staging dir is quarantined to ``<tag>.failed`` so the
  next save of the same tag starts clean.

Every crash-critical stage carries a named chaos failpoint
(``deepspeed_tpu.testing.chaos``) — see docs/RESILIENCE.md for the catalog
and tests/test_chaos.py for the crash-at-every-stage matrix.
"""

from __future__ import annotations

import json
import os
import zipfile
from typing import Any, Callable, Dict, List, Optional, Set, Tuple, Union

import jax
import numpy as np

from ..testing import chaos
from ..utils.logging import logger
from ..utils.partitioning import path_str

LATEST_FILE = "latest"
META_FILE = "meta.json"
CKPT_META_FILE = "ckpt_meta.json"
STAGING_SUFFIX = ".tmp"
QUARANTINE_SUFFIX = ".failed"
CKPT_FORMAT_VERSION = 1
_DTYPES_KEY = "__dtypes__"

try:
    import ml_dtypes
    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover - ml_dtypes ships with jax
    _BF16 = None

_NATIVE_DTYPES = (np.float32, np.float64, np.float16, np.int32, np.int64,
                  np.int8, np.uint8, np.uint16, np.bool_)


class CheckpointIntegrityError(RuntimeError):
    """An explicitly requested tag failed verification (missing completion
    marker, digest/size mismatch, missing shard files). Auto-resolution
    (``tag=None``) never raises this — it rolls back instead."""


def _gather_leaf(leaf) -> np.ndarray:
    """Host copy of a (possibly multi-host-sharded) array."""
    if hasattr(leaf, "is_fully_addressable") and not leaf.is_fully_addressable:
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(leaf, tiled=True))
    return np.asarray(jax.device_get(leaf))


def _tree_to_flat_dict(tree, lazy: bool = False
                       ) -> Dict[str, Union[np.ndarray, Callable]]:
    """Name-keyed view of a pytree. ``lazy=True`` defers each leaf's gather
    to a thunk so the streaming writer holds ONE leaf on host at a time —
    round-1 Weak #6: the eager whole-model gather was ~80GB host RAM for the
    6.7B ladder config."""
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        if callable(leaf):
            # already a thunk (offload tiers stream leaves off RAM/NVMe)
            flat[path_str(path)] = leaf if lazy else leaf()
        elif lazy:
            flat[path_str(path)] = (lambda l=leaf: _gather_leaf(l))
        else:
            flat[path_str(path)] = _gather_leaf(leaf)
    return flat


def write_flat_npz(flat: Dict[str, Union[np.ndarray, Callable]],
                   path: str) -> None:
    """Streaming npz writer: arrays (or thunks producing them) are written
    into the zip one at a time and freed. bfloat16 is stored AS bf16 (uint16
    bit pattern + a dtype manifest) — no 2x f32 upcast (round-1 Weak #6)."""
    from numpy.lib import format as npfmt
    dtypes: Dict[str, str] = {}
    with zipfile.ZipFile(path, "w", zipfile.ZIP_STORED, allowZip64=True) as zf:
        first = True
        for key, val in flat.items():
            arr = np.asarray(val() if callable(val) else val)
            if _BF16 is not None and arr.dtype == _BF16:
                dtypes[key] = "bfloat16"
                arr = arr.view(np.uint16)
            elif arr.dtype not in _NATIVE_DTYPES:
                arr = arr.astype(np.float32)
            with zf.open(key + ".npy", "w", force_zip64=True) as f:
                npfmt.write_array(f, np.ascontiguousarray(arr),
                                  allow_pickle=False)
            del arr
            if first:
                # fires after the first array so a raise/kill here leaves a
                # TRUNCATED file — the hardest partial for a loader to spot
                # without digests
                chaos.failpoint("ckpt.write")
                first = False
        meta = np.frombuffer(json.dumps(dtypes).encode(), dtype=np.uint8)
        with zf.open(_DTYPES_KEY + ".npy", "w") as f:
            npfmt.write_array(f, meta, allow_pickle=False)


def read_flat_npz(path: str) -> Dict[str, np.ndarray]:
    """Inverse of write_flat_npz (also reads plain np.savez archives)."""
    with np.load(path) as data:
        flat = {k: data[k] for k in data.files if k != _DTYPES_KEY}
        if _DTYPES_KEY in data.files:
            mapping = json.loads(bytes(data[_DTYPES_KEY]).decode())
            for k, dt in mapping.items():
                if dt == "bfloat16" and _BF16 is not None:
                    flat[k] = flat[k].view(_BF16)
    return flat


_MANIFEST_KEY = "__manifest__"


def shard_flat_dict(tree) -> Dict[str, np.ndarray]:
    """THIS process's shard pieces of ``tree`` as a flat dict (replica-0
    only, so replicated leaves are stored once across the job).  Each piece
    is keyed ``<leaf-path>::<n>`` with a manifest of global shapes + piece
    offsets — the per-host half of a sharded save: no process ever
    materializes a tensor it does not already hold (round-2 Weak #5: the
    rank-0 process_allgather save moved O(model) over the network per
    save)."""
    flat: Dict[str, np.ndarray] = {}
    manifest: Dict[str, Any] = {}
    for pathk, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = path_str(pathk)
        if callable(leaf):
            leaf = leaf()              # offload thunk: resolve one at a time
        pieces = []
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None:
            # host-numpy leaf (offload tiers): replicated by construction —
            # process 0 writes the single full piece, others skip, so the
            # loader's coverage accounting stays exact
            if jax.process_index() == 0:
                name = f"{key}::0"
                flat[name] = np.array(leaf)      # copy: async writers must
                pieces.append({"name": name,     # not see later mutations
                               "start": [0] * np.ndim(leaf)})
        else:
            n = 0
            for sh in shards:
                if sh.replica_id != 0:
                    continue
                name = f"{key}::{n}"
                flat[name] = np.asarray(sh.data)
                pieces.append({"name": name,
                               "start": [s.start or 0 for s in sh.index]})
                n += 1
        manifest[key] = {"shape": list(np.shape(leaf)), "pieces": pieces}
    flat[_MANIFEST_KEY] = np.frombuffer(
        json.dumps(manifest).encode(), dtype=np.uint8)
    return flat


def write_shard_npz(tree, path: str) -> None:
    write_flat_npz(shard_flat_dict(tree), path)


def load_sharded_tree(ckpt_dir: str, base: str, like, shardings=None,
                      expected_shards: Optional[int] = None):
    """Reassemble a tree from ``{base}-shard*.npz`` files, ONE LEAF AT A
    TIME (peak host memory = largest single tensor, never the model).
    ``expected_shards`` (from the checkpoint meta) guards against partial
    checkpoints; per-leaf element coverage is validated regardless, so a
    missing piece can never silently zero-fill a tensor region."""
    import glob as _glob
    import jax.numpy as jnp
    files = sorted(_glob.glob(os.path.join(ckpt_dir, base + "-shard*.npz")))
    if not files:
        raise FileNotFoundError(f"no {base}-shard*.npz under {ckpt_dir}")
    if expected_shards is not None and len(files) != expected_shards:
        raise FileNotFoundError(
            f"incomplete sharded checkpoint: found {len(files)} {base} "
            f"shard files under {ckpt_dir}, expected {expected_shards}. "
            "Multi-process restore requires every host's shard files in ONE "
            "directory (a shared filesystem, or per-host dirs rsynced "
            "together before load) — per-host local save dirs that were "
            "never merged produce exactly this error.")
    handles = [np.load(f) for f in files]
    try:
        merged: Dict[str, Tuple[int, Dict]] = {}    # key -> [(h_idx, piece)]
        dtmaps = []
        for hi, h in enumerate(handles):
            man = json.loads(bytes(h[_MANIFEST_KEY]).decode())
            dt = (json.loads(bytes(h[_DTYPES_KEY]).decode())
                  if _DTYPES_KEY in h.files else {})
            dtmaps.append(dt)
            for key, ent in man.items():
                slot = merged.setdefault(key, {"shape": ent["shape"],
                                               "pieces": []})
                for p in ent["pieces"]:
                    slot["pieces"].append((hi, p))

        def assemble(key, ref):
            ent = merged.get(key)
            if ent is None:
                raise KeyError(f"checkpoint missing parameter '{key}'")
            hi0, p0 = ent["pieces"][0]
            first = handles[hi0][p0["name"]]
            if dtmaps[hi0].get(p0["name"]) == "bfloat16" and _BF16 is not None:
                first = first.view(_BF16)
            out = np.zeros(tuple(ent["shape"]), first.dtype)
            covered = 0
            for hi, p in ent["pieces"]:
                arr = handles[hi][p["name"]]
                if dtmaps[hi].get(p["name"]) == "bfloat16" and _BF16 is not None:
                    arr = arr.view(_BF16)
                idx = tuple(slice(st, st + sz)
                            for st, sz in zip(p["start"], arr.shape))
                out[idx] = arr
                covered += arr.size
            if covered != out.size:
                raise ValueError(
                    f"sharded checkpoint pieces for '{key}' cover {covered} "
                    f"of {out.size} elements — missing shard data")
            if tuple(out.shape) != tuple(np.shape(ref)):
                raise ValueError(f"shape mismatch for '{key}': ckpt "
                                 f"{out.shape} vs model {np.shape(ref)}")
            return out

        leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(like)
        sh_flat = (treedef.flatten_up_to(shardings)
                   if shardings is not None else None)
        new_leaves = []
        for i, (pathk, ref) in enumerate(leaves_with_paths):
            arr = assemble(path_str(pathk), ref)
            dtype = ref.dtype if hasattr(ref, "dtype") else arr.dtype
            out = jnp.asarray(arr, dtype=dtype)
            if sh_flat is not None and sh_flat[i] is not None:
                out = jax.device_put(out, sh_flat[i])
            new_leaves.append(out)
            del arr
        return jax.tree_util.tree_unflatten(treedef, new_leaves)
    finally:
        for h in handles:
            h.close()


def _flat_dict_to_tree(flat: Dict[str, np.ndarray], like):
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(like)
    new_leaves = []
    for path, leaf in leaves_with_paths:
        key = path_str(path)
        if key not in flat:
            raise KeyError(f"checkpoint missing parameter '{key}'")
        arr = flat[key]
        if tuple(arr.shape) != tuple(np.shape(leaf)):
            raise ValueError(f"shape mismatch for '{key}': ckpt {arr.shape} vs "
                             f"model {np.shape(leaf)}")
        new_leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def save_tree(tree, path: str) -> None:
    write_flat_npz(_tree_to_flat_dict(tree, lazy=True), path)


def load_tree(path: str, like, shardings=None):
    import jax.numpy as jnp
    flat = read_flat_npz(path)
    tree = _flat_dict_to_tree(flat, like)

    def restore(arr, ref, sh=None):
        dtype = ref.dtype if hasattr(ref, "dtype") else arr.dtype
        out = jnp.asarray(arr, dtype=dtype)
        return jax.device_put(out, sh) if sh is not None else out

    if shardings is not None:
        return jax.tree.map(lambda arr, sh, ref: restore(arr, ref, sh),
                            tree, shardings, like)
    return jax.tree.map(lambda arr, ref: restore(arr, ref), tree, like)


# ---------------------------------------------------------------------------
# Durability primitives: fsync, digests, completion marker, atomic publish
# ---------------------------------------------------------------------------

def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    """fsync a directory so renames/creations inside it are durable; a
    filesystem that can't fsync directories (some network mounts) degrades
    to best-effort rather than failing the save."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def file_digest(path: str) -> str:
    """Streaming sha256 of a file's bytes."""
    import hashlib
    chaos.failpoint("ckpt.digest")
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def write_completion_marker(stage_dir: str, num_shards: int = 1) -> None:
    """Digest every data file in the staging dir, fsync them, then write
    ``ckpt_meta.json`` LAST (tmp + atomic replace + dir fsync). The marker's
    existence asserts "everything listed here was durable before I was"."""
    files: Dict[str, Dict[str, Any]] = {}
    for name in sorted(os.listdir(stage_dir)):
        if name in (CKPT_META_FILE, CKPT_META_FILE + ".tmp"):
            continue
        path = os.path.join(stage_dir, name)
        if not os.path.isfile(path):
            continue
        files[name] = {"sha256": file_digest(path),
                       "size": os.path.getsize(path)}
        _fsync_file(path)
    marker = {"format_version": CKPT_FORMAT_VERSION,
              "num_shards": num_shards,
              "files": files}
    chaos.failpoint("ckpt.marker")
    tmp = os.path.join(stage_dir, CKPT_META_FILE + ".tmp")
    with open(tmp, "w") as f:
        json.dump(marker, f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(stage_dir, CKPT_META_FILE))
    _fsync_dir(stage_dir)


def publish_tag(save_dir: str, tag: str) -> str:
    """Atomically promote ``<tag>.tmp`` to ``<tag>``. An existing tag dir
    (an overwrite-save of the same tag) is moved aside first so the final
    rename is still a single atomic transition."""
    stage = os.path.join(save_dir, tag + STAGING_SUFFIX)
    final = os.path.join(save_dir, tag)
    chaos.failpoint("ckpt.rename")
    if os.path.isdir(final):
        import shutil
        old = final + ".replaced"
        if os.path.isdir(old):
            shutil.rmtree(old)
        os.replace(final, old)
        os.replace(stage, final)
        shutil.rmtree(old, ignore_errors=True)
    else:
        os.replace(stage, final)
    _fsync_dir(save_dir)
    return final


def write_latest(save_dir: str, tag: str) -> None:
    """Atomic ``latest`` update: tmp file + fsync + replace + dir fsync —
    a crash leaves either the old pointer or the new one, never a
    truncated file."""
    chaos.failpoint("ckpt.latest")
    tmp = os.path.join(save_dir, LATEST_FILE + STAGING_SUFFIX)
    with open(tmp, "w") as f:
        f.write(tag)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(save_dir, LATEST_FILE))
    _fsync_dir(save_dir)


def quarantine_staging(stage_dir: str, reason: str = "") -> Optional[str]:
    """Move a failed save's staging dir to ``<tag>.failed`` so the next
    save of the same tag starts clean and the debris stays inspectable.
    Never raises (this runs on failure paths)."""
    try:
        if not os.path.isdir(stage_dir):
            return None
        if os.path.exists(os.path.join(stage_dir, CKPT_META_FILE)):
            # the marker is written LAST: its presence means every data
            # file is durable and only the publish failed — leave the
            # staging in place so the next load finishes the rename
            # (_recover_interrupted_publishes) instead of discarding the
            # newest checkpoint to the quarantine
            logger.error(
                "checkpoint save failed (%s) AFTER its staging dir was "
                "fully durable; leaving %s for publish recovery at the "
                "next load", reason or "see prior log", stage_dir)
            return None
        base = (stage_dir[:-len(STAGING_SUFFIX)]
                if stage_dir.endswith(STAGING_SUFFIX) else stage_dir)
        dst = base + QUARANTINE_SUFFIX
        n = 0
        while os.path.exists(dst):
            n += 1
            dst = f"{base}{QUARANTINE_SUFFIX}.{n}"
        os.replace(stage_dir, dst)
        logger.error("checkpoint save failed (%s): staging quarantined at %s",
                     reason or "see prior log", dst)
        return dst
    except OSError as e:  # pragma: no cover - double fault
        logger.error("could not quarantine %s: %s", stage_dir, e)
        return None


# ---------------------------------------------------------------------------
# Verification, tag enumeration, rollback, retention
# ---------------------------------------------------------------------------

def verify_tag(ckpt_dir: str, check_digests: bool = True) -> Optional[str]:
    """``None`` when the tag is intact, else a human-readable reason.

    Checks: readable ``meta.json``, completion marker present and readable,
    every listed file present with the recorded size (and sha256 when
    ``check_digests``), shard count consistent with the marker. Tags from
    before the marker format (no ``ckpt_meta.json``) pass on a structural
    check alone, with a warning — crash partials can't masquerade as them
    because partials only ever live in ``.tmp``/``.failed`` dirs."""
    import glob as _glob
    if not os.path.isdir(ckpt_dir):
        return "missing directory"
    try:
        with open(os.path.join(ckpt_dir, META_FILE)) as f:
            json.load(f)
    except (OSError, ValueError) as e:
        return f"unreadable {META_FILE} ({e.__class__.__name__})"
    marker_path = os.path.join(ckpt_dir, CKPT_META_FILE)
    if not os.path.exists(marker_path):
        has_model = (
            os.path.exists(os.path.join(ckpt_dir, "model_states.npz"))
            or _glob.glob(os.path.join(ckpt_dir, "model_states-shard*.npz")))
        if not has_model:
            return "no completion marker and no model_states data"
        logger.warning(
            "checkpoint %s predates the completion-marker format; loading "
            "without digest verification", ckpt_dir)
        return None
    try:
        with open(marker_path) as f:
            marker = json.load(f)
    except (OSError, ValueError) as e:
        return f"unreadable {CKPT_META_FILE} ({e.__class__.__name__})"
    files = marker.get("files", {})
    if not any(n.startswith("model_states") for n in files):
        # a marker that lists no model data (e.g. finalize ran against a
        # gutted staging dir) must not verify clean — resolve would pick
        # it as "newest intact" and the load would crash instead of
        # rolling back
        return "completion marker lists no model_states data"
    for name, info in files.items():
        path = os.path.join(ckpt_dir, name)
        if not os.path.exists(path):
            return f"missing file {name}"
        if os.path.getsize(path) != info.get("size"):
            return (f"size mismatch for {name}: {os.path.getsize(path)} != "
                    f"{info.get('size')}")
    num_shards = marker.get("num_shards")
    shard_files = [n for n in files if n.startswith("model_states-shard")]
    if shard_files and num_shards is not None \
            and len(shard_files) != num_shards:
        return (f"marker lists {len(shard_files)} model shards, "
                f"expected {num_shards}")
    if check_digests:
        for name, info in files.items():
            got = file_digest(os.path.join(ckpt_dir, name))
            if got != info.get("sha256"):
                return f"digest mismatch for {name}"
    return None


def _is_reserved_name(name: str) -> bool:
    return (name.endswith(STAGING_SUFFIX) or name.endswith(".replaced")
            or name.endswith(QUARANTINE_SUFFIX)
            or f"{QUARANTINE_SUFFIX}." in name)


def _tag_sort_key(save_dir: str, tag: str) -> Tuple[int, float]:
    step = -1
    try:
        with open(os.path.join(save_dir, tag, META_FILE)) as f:
            step = int(json.load(f).get("step", -1))
    except (OSError, ValueError):
        pass
    try:
        mtime = os.path.getmtime(os.path.join(save_dir, tag))
    except OSError:
        mtime = 0.0
    return (step, mtime)


def list_tags(save_dir: str) -> List[str]:
    """Published (non-staging, non-quarantined) tags, oldest -> newest by
    recorded step, then directory mtime."""
    if not os.path.isdir(save_dir):
        return []
    out = []
    for name in sorted(os.listdir(save_dir)):
        path = os.path.join(save_dir, name)
        if not os.path.isdir(path) or _is_reserved_name(name):
            continue
        if not (os.path.exists(os.path.join(path, META_FILE))
                or os.path.exists(os.path.join(path, CKPT_META_FILE))):
            continue
        out.append(name)
    out.sort(key=lambda t: _tag_sort_key(save_dir, t))
    return out


def _recover_interrupted_publishes(load_dir: str) -> None:
    """Finish publishes a crash interrupted. The marker is written LAST,
    so a ``<tag>.tmp`` that contains one is fully durable — the crash hit
    between the marker and the rename (or between the two renames of an
    overwrite-save, which also strands the old tag in ``<tag>.replaced``).
    Promote such staging dirs and sweep ``.replaced`` debris whose tag is
    live again. Never raises (recovery must not block a load)."""
    import shutil
    try:
        names = os.listdir(load_dir)
    except OSError:
        return
    for name in names:
        if not name.endswith(STAGING_SUFFIX):
            continue
        tag = name[:-len(STAGING_SUFFIX)]
        if not tag or tag == LATEST_FILE:
            continue
        stage = os.path.join(load_dir, name)
        if not os.path.isdir(stage) \
                or os.path.isdir(os.path.join(load_dir, tag)) \
                or not os.path.exists(os.path.join(stage, CKPT_META_FILE)):
            continue        # debris or not yet durable: leave for quarantine
        try:
            publish_tag(load_dir, tag)
            logger.warning("recovered interrupted publish of checkpoint "
                           "'%s' (marker was durable, rename was not)", tag)
        except OSError as e:
            logger.error("could not recover interrupted publish of %s: %s",
                         tag, e)
    for name in names:
        if name.endswith(".replaced") and os.path.isdir(
                os.path.join(load_dir, name[:-len(".replaced")])):
            shutil.rmtree(os.path.join(load_dir, name), ignore_errors=True)


def resolve_load_tag(load_dir: str, check_digests: bool = True) -> str:
    """The newest intact tag under ``load_dir``. Corrupt or partial tags
    are skipped (logged, left in place for forensics); if the survivor
    differs from what ``latest`` points at — a crash between publish and
    the pointer update, or a rolled-back corruption — ``latest`` is
    repaired to match."""
    _recover_interrupted_publishes(load_dir)
    latest = get_latest_tag(load_dir)
    tags = list_tags(load_dir)[::-1]                      # newest first
    if not tags:
        raise FileNotFoundError(
            f"no checkpoint tags under {load_dir}"
            + ("" if latest else f" (and no '{LATEST_FILE}' tag file)"))
    skipped: List[Tuple[str, str]] = []
    for tag in tags:
        reason = verify_tag(os.path.join(load_dir, tag),
                            check_digests=check_digests)
        if reason is not None:
            skipped.append((tag, reason))
            logger.warning("skipping corrupt checkpoint %s: %s",
                           os.path.join(load_dir, tag), reason)
            continue
        if tag != latest:
            logger.warning(
                "rolling back to newest intact checkpoint '%s' "
                "(%r pointed at %r%s)", tag, LATEST_FILE, latest,
                f"; skipped {[t for t, _ in skipped]}" if skipped else "")
            try:
                write_latest(load_dir, tag)
            except OSError as e:
                logger.error("could not repair %s: %s", LATEST_FILE, e)
        return tag
    detail = "; ".join(f"{t}: {r}" for t, r in skipped)
    raise FileNotFoundError(
        f"no intact checkpoint under {load_dir} ({detail})")


def prune_checkpoints(save_dir: str, keep_last: int, keep_every: int = 0,
                      protect: Optional[Set[str]] = None) -> List[str]:
    """Retention GC: keep the newest ``keep_last`` tags, every tag whose
    recorded step is a positive multiple of ``keep_every`` (0 disables the
    ladder), whatever ``latest`` points at, and ``protect``. Returns the
    removed tags. ``keep_last <= 0`` is a no-op (retention off)."""
    import shutil
    if keep_last <= 0:
        return []
    protect = set(protect or ())
    latest = get_latest_tag(save_dir)
    if latest:
        protect.add(latest)
    tags = list_tags(save_dir)                            # oldest -> newest
    keep = set(tags[-keep_last:]) | protect
    if keep_every > 0:
        for tag in tags:
            step, _ = _tag_sort_key(save_dir, tag)
            if step > 0 and step % keep_every == 0:
                keep.add(tag)
    removed = []
    for tag in tags:
        if tag in keep:
            continue
        shutil.rmtree(os.path.join(save_dir, tag), ignore_errors=True)
        removed.append(tag)
    if removed:
        logger.info("checkpoint retention: removed %s (keep_last=%d, "
                    "keep_every=%d)", removed, keep_last, keep_every)
    return removed


# ---------------------------------------------------------------------------
# Save / load
# ---------------------------------------------------------------------------

def _build_meta(state, client_state, master_aliases_params) -> Dict[str, Any]:
    streak = getattr(state, "nonfinite_streak", None)
    return {
        "master_aliases_params": master_aliases_params,
        "sharded": jax.process_count() > 1,
        "num_shards": jax.process_count(),
        "step": int(jax.device_get(state.step)),
        "skipped_steps": int(jax.device_get(state.skipped_steps)),
        "nonfinite_streak": (int(jax.device_get(streak))
                             if streak is not None else 0),
        "loss_scale": float(jax.device_get(state.scale.scale)),
        "scale_good_steps": int(jax.device_get(state.scale.good_steps)),
        "scale_hysteresis": int(jax.device_get(state.scale.hysteresis)),
        "client_state": client_state or {},
    }


def _write_meta(stage_dir: str, meta: Dict[str, Any]) -> None:
    chaos.failpoint("ckpt.meta")
    with open(os.path.join(stage_dir, META_FILE), "w") as f:
        json.dump(meta, f, indent=2)


def _finalize_tag(save_dir: str, tag: str, num_shards: int,
                  keep_last: Optional[int], keep_every: int) -> None:
    """Marker -> publish -> latest -> retention. Runs FIFO-ordered behind
    the data writes (inline for sync engines, on the single worker for
    async ones), so ``latest`` can only ever advance onto a tag whose data
    is fully on disk.

    IDEMPOTENT past the publish: the async engine retries OSError jobs,
    and a transient `latest` failure after a successful rename must not
    re-run the marker/rename against the now-vanished staging dir (the
    retry would fail forever and mis-report a durable checkpoint as
    failed)."""
    stage_dir = os.path.join(save_dir, tag + STAGING_SUFFIX)
    if os.path.isdir(stage_dir):
        write_completion_marker(stage_dir, num_shards=num_shards)
        publish_tag(save_dir, tag)
    elif not os.path.isdir(os.path.join(save_dir, tag)):
        raise FileNotFoundError(
            f"nothing to finalize for checkpoint '{tag}': neither "
            f"{stage_dir} nor a published tag exists")
    write_latest(save_dir, tag)
    logger.info(f"saved checkpoint {os.path.join(save_dir, tag)}")
    if keep_last:
        prune_checkpoints(save_dir, keep_last, keep_every, protect={tag})


def save_checkpoint(save_dir: str,
                    tag: str,
                    state,
                    client_state: Optional[Dict[str, Any]] = None,
                    master_aliases_params: bool = False,
                    ckpt_engine=None,
                    keep_last: Optional[int] = None,
                    keep_every: int = 0) -> str:
    """Write {save_dir}/{tag}/ atomically (staging dir + marker + rename);
    update ``latest`` only after the tag is fully durable.

    ``master_aliases_params``: fp32 training stores params once (the master copy
    IS the param tree); the alias is re-established at load.
    ``ckpt_engine``: a checkpoint.engine.CheckpointEngine — async engines do
    the file IO off-thread; the marker/rename/`latest` sequence is FIFO-ordered
    behind the writes on the engine's single worker, and a failed write
    quarantines the staging dir instead of publishing (commit() reports it).
    ``keep_last``/``keep_every``: retention GC after a successful publish."""
    ckpt_dir = os.path.join(save_dir, tag)
    stage_dir = ckpt_dir + STAGING_SUFFIX
    optim_group = {"opt_state": state.opt_state}
    if not master_aliases_params:
        optim_group["master"] = state.master
    if ckpt_engine is None:
        from ..checkpoint.engine import NpzCheckpointEngine
        ckpt_engine = NpzCheckpointEngine()
    if jax.process_count() > 1:
        return _save_checkpoint_multiprocess(
            save_dir, tag, state, optim_group, client_state,
            master_aliases_params, ckpt_engine, keep_last, keep_every)
    os.makedirs(save_dir, exist_ok=True)
    if os.path.isdir(stage_dir):
        # a previous save of this tag may still be writing (async): drain
        # it before touching the staging dir — rmtree under the worker's
        # open handles would let the OLD generation's queued finalize
        # publish a gutted dir. A healthy drain publishes the old save
        # (staging vanishes); what remains after is genuinely stale.
        ckpt_engine.commit(tag)
        if os.path.isdir(stage_dir):
            import shutil
            shutil.rmtree(stage_dir)
    os.makedirs(stage_dir)
    ckpt_engine.create(tag, stage_dir=stage_dir)
    # async engines must not race donated device buffers: gather to host
    # eagerly (leaf-wise), hand numpy to the writer thread
    lazy = getattr(ckpt_engine, "wants_lazy", True)
    # meta scalars are read eagerly for the same donation reason
    meta = _build_meta(state, client_state, master_aliases_params)
    try:
        ckpt_engine.save(_tree_to_flat_dict(state.params, lazy=lazy),
                         os.path.join(stage_dir, "model_states.npz"))
        ckpt_engine.save(_tree_to_flat_dict(optim_group, lazy=lazy),
                         os.path.join(stage_dir, "optim_states.npz"))
        ckpt_engine.run(lambda: _write_meta(stage_dir, meta),
                        label=os.path.join(stage_dir, META_FILE))
        ckpt_engine.run(
            lambda: _finalize_tag(save_dir, tag, 1, keep_last, keep_every),
            label=f"finalize:{tag}")
    except Exception as e:
        # sync engines raise inline; quarantine so the next save of this
        # tag starts clean, then surface the failure to the caller
        quarantine_staging(stage_dir, reason=f"{e.__class__.__name__}: {e}")
        raise
    return ckpt_dir


def _save_checkpoint_multiprocess(save_dir, tag, state, optim_group,
                                  client_state, master_aliases_params,
                                  ckpt_engine, keep_last, keep_every) -> str:
    """Sharded save: EVERY process writes its own addressable pieces
    (replica-0 dedup) into the SHARED staging dir; a global barrier —
    FIFO-ordered behind the writes on each rank — gates rank 0's
    marker/publish/`latest` so `latest` never points at a partially-written
    checkpoint. No cross-process gather happens at all."""
    from jax.experimental import multihost_utils
    ckpt_dir = os.path.join(save_dir, tag)
    stage_dir = ckpt_dir + STAGING_SUFFIX
    os.makedirs(save_dir, exist_ok=True)
    if jax.process_index() == 0 and os.path.isdir(stage_dir):
        import shutil
        shutil.rmtree(stage_dir)        # stale staging from a crashed save
    multihost_utils.sync_global_devices(f"ckpt-stage-{tag}")
    os.makedirs(stage_dir, exist_ok=True)
    # rank 0's commit() must not quarantine the SHARED staging dir while
    # other ranks may still be writing — aggregate failure handling happens
    # after the allgather barrier below, so no stage_dir is registered here
    ckpt_engine.create(tag)
    p = jax.process_index()
    # a rank-local write failure must NOT raise before the allgather below
    # — the surviving ranks would hang in the collective. Sync engines
    # raise inline from save(); catch and fold into the ok flag so every
    # rank reaches the barrier. (Async engines defer errors to commit().)
    local_ok = True
    try:
        # shard pieces are local host copies already (np.asarray of
        # addressable shards) — safe to hand to an async writer thread
        ckpt_engine.save(shard_flat_dict(state.params),
                         os.path.join(stage_dir, f"model_states-shard{p}.npz"))
        ckpt_engine.save(shard_flat_dict(optim_group),
                         os.path.join(stage_dir, f"optim_states-shard{p}.npz"))
    except Exception as e:
        logger.error("rank %d shard write for %s failed: %s", p, tag, e)
        local_ok = False
    # the barrier + finalize must run on the MAIN thread: a collective from
    # an async writer thread could interleave with train-step collectives in
    # different orders across ranks (deadlock), and the donated TrainState
    # must be read before the next step consumes it. Async engines therefore
    # drain here — multi-process saves are durable-on-return.
    ok = bool(ckpt_engine.commit(tag)) and local_ok
    # aggregate per-rank write success (the gather doubles as the
    # durability barrier): `latest` must never advance onto a checkpoint
    # any rank failed to write
    flags = multihost_utils.process_allgather(
        np.asarray([0 if not ok else 1], np.int32))
    if int(np.min(flags)) == 0:
        logger.error(
            f"sharded checkpoint {ckpt_dir}: a rank's shard write failed — "
            "leaving `latest` on the previous checkpoint")
        if p == 0:
            quarantine_staging(stage_dir, reason="a rank's shard write failed")
        return ckpt_dir
    if p == 0:
        try:
            _write_meta(stage_dir,
                        _build_meta(state, client_state,
                                    master_aliases_params))
            _finalize_tag(save_dir, tag, jax.process_count(),
                          keep_last, keep_every)
        except Exception as e:
            quarantine_staging(stage_dir,
                               reason=f"{e.__class__.__name__}: {e}")
            raise
    return ckpt_dir


def get_latest_tag(load_dir: str) -> Optional[str]:
    latest = os.path.join(load_dir, LATEST_FILE)
    if not os.path.exists(latest):
        return None
    try:
        with open(latest) as f:
            tag = f.read().strip()
    except OSError:
        return None
    return tag or None


def load_checkpoint(load_dir: str,
                    tag: Optional[str],
                    state,
                    param_shardings=None,
                    master_shardings=None,
                    opt_shardings=None,
                    verify: bool = True) -> Tuple[Any, Dict[str, Any]]:
    """Load into the structure of ``state`` (shardings reapplied). Returns
    (new_state, client_state).

    ``tag=None`` resumes from the newest intact tag, rolling back over
    corrupt/partial ones (see :func:`resolve_load_tag`). An explicit tag is
    verified and raises :class:`CheckpointIntegrityError` when corrupt —
    an explicitly requested checkpoint is user intent, not a resume
    heuristic, so silently substituting another would be wrong."""
    import jax.numpy as jnp
    if tag is None:
        tag = resolve_load_tag(load_dir, check_digests=verify)
    elif verify:
        reason = verify_tag(os.path.join(load_dir, tag))
        if reason is not None:
            raise CheckpointIntegrityError(
                f"checkpoint {os.path.join(load_dir, tag)} failed "
                f"verification: {reason}")
    ckpt_dir = os.path.join(load_dir, tag)
    with open(os.path.join(ckpt_dir, META_FILE)) as f:
        meta = json.load(f)
    sharded = not os.path.exists(os.path.join(ckpt_dir, "model_states.npz"))

    def _load(base, like, shardings):
        if sharded:
            return load_sharded_tree(ckpt_dir, base, like, shardings,
                                     expected_shards=meta.get("num_shards"))
        return load_tree(os.path.join(ckpt_dir, base + ".npz"), like,
                         shardings)

    params = _load("model_states", state.params, param_shardings)
    if meta.get("master_aliases_params"):
        optim = {"master": params,
                 "opt_state": _load("optim_states",
                                    {"opt_state": state.opt_state},
                                    {"opt_state": opt_shardings}
                                    if opt_shardings is not None else None)["opt_state"]}
    else:
        optim = _load("optim_states",
                      {"master": state.master, "opt_state": state.opt_state},
                      {"master": master_shardings, "opt_state": opt_shardings}
                      if master_shardings is not None else None)
    from .loss_scaler import LossScaleState
    new_state = state.replace(
        step=jnp.asarray(meta["step"], jnp.int32),
        skipped_steps=jnp.asarray(meta["skipped_steps"], jnp.int32),
        nonfinite_streak=jnp.asarray(meta.get("nonfinite_streak", 0),
                                     jnp.int32),
        params=params,
        master=optim["master"],
        opt_state=optim["opt_state"],
        scale=LossScaleState(
            scale=jnp.asarray(meta["loss_scale"], jnp.float32),
            good_steps=jnp.asarray(meta["scale_good_steps"], jnp.int32),
            hysteresis=jnp.asarray(meta["scale_hysteresis"], jnp.int32)))
    logger.info(f"loaded checkpoint {ckpt_dir} at step {meta['step']}")
    return new_state, meta.get("client_state", {})


def consolidated_fp32_state_dict(state) -> Dict[str, np.ndarray]:
    """Gather master weights to one host fp32 dict (zero_to_fp32 equivalent,
    reference utils/zero_to_fp32.py + _zero3_consolidated_16bit_state_dict)."""
    return _tree_to_flat_dict(state.master)


def save_16bit_model(state, path: str) -> None:
    """reference: engine.save_16bit_model (engine.py:3479)."""
    save_tree(state.params, path)
