"""Checkpoint save/load for TrainState pytrees.

Capability parity with the reference's checkpoint layer (engine.py:2712-3489 +
runtime/checkpoint_engine/): tagged checkpoint dirs, a ``latest`` tag file,
model/optimizer state separation, client (lr-scheduler etc.) state, and
consolidation of sharded weights to a single fp32/16-bit state dict
(zero_to_fp32 / save_16bit_model equivalents).

Format: one ``.npz`` per state group + a JSON manifest of paths/dtypes/shapes.
Parameters are stored under their /-joined pytree paths — names, not partition
indices — so a checkpoint written under one mesh/ZeRO topology loads under any
other ("universal checkpoint by construction"; the reference needs the whole
``deepspeed/checkpoint/`` reshape machinery for this).
"""

from __future__ import annotations

import json
import os
import zipfile
from typing import Any, Callable, Dict, Optional, Tuple, Union

import jax
import numpy as np

from ..utils.logging import logger
from ..utils.partitioning import path_str

LATEST_FILE = "latest"
_DTYPES_KEY = "__dtypes__"

try:
    import ml_dtypes
    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover - ml_dtypes ships with jax
    _BF16 = None

_NATIVE_DTYPES = (np.float32, np.float64, np.float16, np.int32, np.int64,
                  np.int8, np.uint8, np.uint16, np.bool_)


def _gather_leaf(leaf) -> np.ndarray:
    """Host copy of a (possibly multi-host-sharded) array."""
    if hasattr(leaf, "is_fully_addressable") and not leaf.is_fully_addressable:
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(leaf, tiled=True))
    return np.asarray(jax.device_get(leaf))


def _tree_to_flat_dict(tree, lazy: bool = False
                       ) -> Dict[str, Union[np.ndarray, Callable]]:
    """Name-keyed view of a pytree. ``lazy=True`` defers each leaf's gather
    to a thunk so the streaming writer holds ONE leaf on host at a time —
    round-1 Weak #6: the eager whole-model gather was ~80GB host RAM for the
    6.7B ladder config."""
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        if callable(leaf):
            # already a thunk (offload tiers stream leaves off RAM/NVMe)
            flat[path_str(path)] = leaf if lazy else leaf()
        elif lazy:
            flat[path_str(path)] = (lambda l=leaf: _gather_leaf(l))
        else:
            flat[path_str(path)] = _gather_leaf(leaf)
    return flat


def write_flat_npz(flat: Dict[str, Union[np.ndarray, Callable]],
                   path: str) -> None:
    """Streaming npz writer: arrays (or thunks producing them) are written
    into the zip one at a time and freed. bfloat16 is stored AS bf16 (uint16
    bit pattern + a dtype manifest) — no 2x f32 upcast (round-1 Weak #6)."""
    from numpy.lib import format as npfmt
    dtypes: Dict[str, str] = {}
    with zipfile.ZipFile(path, "w", zipfile.ZIP_STORED, allowZip64=True) as zf:
        for key, val in flat.items():
            arr = np.asarray(val() if callable(val) else val)
            if _BF16 is not None and arr.dtype == _BF16:
                dtypes[key] = "bfloat16"
                arr = arr.view(np.uint16)
            elif arr.dtype not in _NATIVE_DTYPES:
                arr = arr.astype(np.float32)
            with zf.open(key + ".npy", "w", force_zip64=True) as f:
                npfmt.write_array(f, np.ascontiguousarray(arr),
                                  allow_pickle=False)
            del arr
        meta = np.frombuffer(json.dumps(dtypes).encode(), dtype=np.uint8)
        with zf.open(_DTYPES_KEY + ".npy", "w") as f:
            npfmt.write_array(f, meta, allow_pickle=False)


def read_flat_npz(path: str) -> Dict[str, np.ndarray]:
    """Inverse of write_flat_npz (also reads plain np.savez archives)."""
    with np.load(path) as data:
        flat = {k: data[k] for k in data.files if k != _DTYPES_KEY}
        if _DTYPES_KEY in data.files:
            mapping = json.loads(bytes(data[_DTYPES_KEY]).decode())
            for k, dt in mapping.items():
                if dt == "bfloat16" and _BF16 is not None:
                    flat[k] = flat[k].view(_BF16)
    return flat


_MANIFEST_KEY = "__manifest__"


def shard_flat_dict(tree) -> Dict[str, np.ndarray]:
    """THIS process's shard pieces of ``tree`` as a flat dict (replica-0
    only, so replicated leaves are stored once across the job).  Each piece
    is keyed ``<leaf-path>::<n>`` with a manifest of global shapes + piece
    offsets — the per-host half of a sharded save: no process ever
    materializes a tensor it does not already hold (round-2 Weak #5: the
    rank-0 process_allgather save moved O(model) over the network per
    save)."""
    flat: Dict[str, np.ndarray] = {}
    manifest: Dict[str, Any] = {}
    for pathk, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = path_str(pathk)
        if callable(leaf):
            leaf = leaf()              # offload thunk: resolve one at a time
        pieces = []
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None:
            # host-numpy leaf (offload tiers): replicated by construction —
            # process 0 writes the single full piece, others skip, so the
            # loader's coverage accounting stays exact
            if jax.process_index() == 0:
                name = f"{key}::0"
                flat[name] = np.array(leaf)      # copy: async writers must
                pieces.append({"name": name,     # not see later mutations
                               "start": [0] * np.ndim(leaf)})
        else:
            n = 0
            for sh in shards:
                if sh.replica_id != 0:
                    continue
                name = f"{key}::{n}"
                flat[name] = np.asarray(sh.data)
                pieces.append({"name": name,
                               "start": [s.start or 0 for s in sh.index]})
                n += 1
        manifest[key] = {"shape": list(np.shape(leaf)), "pieces": pieces}
    flat[_MANIFEST_KEY] = np.frombuffer(
        json.dumps(manifest).encode(), dtype=np.uint8)
    return flat


def write_shard_npz(tree, path: str) -> None:
    write_flat_npz(shard_flat_dict(tree), path)


def load_sharded_tree(ckpt_dir: str, base: str, like, shardings=None,
                      expected_shards: Optional[int] = None):
    """Reassemble a tree from ``{base}-shard*.npz`` files, ONE LEAF AT A
    TIME (peak host memory = largest single tensor, never the model).
    ``expected_shards`` (from the checkpoint meta) guards against partial
    checkpoints; per-leaf element coverage is validated regardless, so a
    missing piece can never silently zero-fill a tensor region."""
    import glob as _glob
    import jax.numpy as jnp
    files = sorted(_glob.glob(os.path.join(ckpt_dir, base + "-shard*.npz")))
    if not files:
        raise FileNotFoundError(f"no {base}-shard*.npz under {ckpt_dir}")
    if expected_shards is not None and len(files) != expected_shards:
        raise FileNotFoundError(
            f"incomplete sharded checkpoint: found {len(files)} {base} "
            f"shard files under {ckpt_dir}, expected {expected_shards}. "
            "Multi-process restore requires every host's shard files in ONE "
            "directory (a shared filesystem, or per-host dirs rsynced "
            "together before load) — per-host local save dirs that were "
            "never merged produce exactly this error.")
    handles = [np.load(f) for f in files]
    try:
        merged: Dict[str, Tuple[int, Dict]] = {}    # key -> [(h_idx, piece)]
        dtmaps = []
        for hi, h in enumerate(handles):
            man = json.loads(bytes(h[_MANIFEST_KEY]).decode())
            dt = (json.loads(bytes(h[_DTYPES_KEY]).decode())
                  if _DTYPES_KEY in h.files else {})
            dtmaps.append(dt)
            for key, ent in man.items():
                slot = merged.setdefault(key, {"shape": ent["shape"],
                                               "pieces": []})
                for p in ent["pieces"]:
                    slot["pieces"].append((hi, p))

        def assemble(key, ref):
            ent = merged.get(key)
            if ent is None:
                raise KeyError(f"checkpoint missing parameter '{key}'")
            hi0, p0 = ent["pieces"][0]
            first = handles[hi0][p0["name"]]
            if dtmaps[hi0].get(p0["name"]) == "bfloat16" and _BF16 is not None:
                first = first.view(_BF16)
            out = np.zeros(tuple(ent["shape"]), first.dtype)
            covered = 0
            for hi, p in ent["pieces"]:
                arr = handles[hi][p["name"]]
                if dtmaps[hi].get(p["name"]) == "bfloat16" and _BF16 is not None:
                    arr = arr.view(_BF16)
                idx = tuple(slice(st, st + sz)
                            for st, sz in zip(p["start"], arr.shape))
                out[idx] = arr
                covered += arr.size
            if covered != out.size:
                raise ValueError(
                    f"sharded checkpoint pieces for '{key}' cover {covered} "
                    f"of {out.size} elements — missing shard data")
            if tuple(out.shape) != tuple(np.shape(ref)):
                raise ValueError(f"shape mismatch for '{key}': ckpt "
                                 f"{out.shape} vs model {np.shape(ref)}")
            return out

        leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(like)
        sh_flat = (treedef.flatten_up_to(shardings)
                   if shardings is not None else None)
        new_leaves = []
        for i, (pathk, ref) in enumerate(leaves_with_paths):
            arr = assemble(path_str(pathk), ref)
            dtype = ref.dtype if hasattr(ref, "dtype") else arr.dtype
            out = jnp.asarray(arr, dtype=dtype)
            if sh_flat is not None and sh_flat[i] is not None:
                out = jax.device_put(out, sh_flat[i])
            new_leaves.append(out)
            del arr
        return jax.tree_util.tree_unflatten(treedef, new_leaves)
    finally:
        for h in handles:
            h.close()


def _flat_dict_to_tree(flat: Dict[str, np.ndarray], like):
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(like)
    new_leaves = []
    for path, leaf in leaves_with_paths:
        key = path_str(path)
        if key not in flat:
            raise KeyError(f"checkpoint missing parameter '{key}'")
        arr = flat[key]
        if tuple(arr.shape) != tuple(np.shape(leaf)):
            raise ValueError(f"shape mismatch for '{key}': ckpt {arr.shape} vs "
                             f"model {np.shape(leaf)}")
        new_leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def save_tree(tree, path: str) -> None:
    write_flat_npz(_tree_to_flat_dict(tree, lazy=True), path)


def load_tree(path: str, like, shardings=None):
    import jax.numpy as jnp
    flat = read_flat_npz(path)
    tree = _flat_dict_to_tree(flat, like)

    def restore(arr, ref, sh=None):
        dtype = ref.dtype if hasattr(ref, "dtype") else arr.dtype
        out = jnp.asarray(arr, dtype=dtype)
        return jax.device_put(out, sh) if sh is not None else out

    if shardings is not None:
        return jax.tree.map(lambda arr, sh, ref: restore(arr, ref, sh),
                            tree, shardings, like)
    return jax.tree.map(lambda arr, ref: restore(arr, ref), tree, like)


def save_checkpoint(save_dir: str,
                    tag: str,
                    state,
                    client_state: Optional[Dict[str, Any]] = None,
                    master_aliases_params: bool = False,
                    ckpt_engine=None) -> str:
    """Write {save_dir}/{tag}/ with model+optim npz and metadata; update `latest`.

    ``master_aliases_params``: fp32 training stores params once (the master copy
    IS the param tree); the alias is re-established at load.
    ``ckpt_engine``: a checkpoint.engine.CheckpointEngine — async engines do
    the file IO off-thread; `latest` lands only after the data is durable
    (the async engine's single FIFO worker orders it behind the writes)."""
    ckpt_dir = os.path.join(save_dir, tag)
    optim_group = {"opt_state": state.opt_state}
    if not master_aliases_params:
        optim_group["master"] = state.master
    if jax.process_count() > 1:
        # sharded save: EVERY process writes its own addressable pieces
        # (replica-0 dedup) through the configured checkpoint engine (async
        # engines do the IO off-thread); a global barrier — FIFO-ordered
        # behind the writes on each rank — gates rank 0's metadata+`latest`
        # so `latest` never points at a partially-written checkpoint. No
        # cross-process gather happens at all.
        if ckpt_engine is None:
            from ..checkpoint.engine import NpzCheckpointEngine
            ckpt_engine = NpzCheckpointEngine()
        os.makedirs(ckpt_dir, exist_ok=True)
        ckpt_engine.create(tag)
        p = jax.process_index()
        # shard pieces are local host copies already (np.asarray of
        # addressable shards) — safe to hand to an async writer thread
        ckpt_engine.save(shard_flat_dict(state.params),
                         os.path.join(ckpt_dir, f"model_states-shard{p}.npz"))
        ckpt_engine.save(shard_flat_dict(optim_group),
                         os.path.join(ckpt_dir, f"optim_states-shard{p}.npz"))
        # the barrier + meta must run on the MAIN thread: a collective from
        # an async writer thread could interleave with train-step
        # collectives in different orders across ranks (deadlock), and the
        # donated TrainState must be read before the next step consumes it.
        # Async engines therefore drain here — multi-process saves are
        # durable-on-return.
        ok = ckpt_engine.commit(tag)
        from jax.experimental import multihost_utils
        # aggregate per-rank write success (the gather doubles as the
        # durability barrier): `latest` must never advance onto a
        # checkpoint any rank failed to write
        flags = multihost_utils.process_allgather(
            np.asarray([1 if ok is not False else 0], np.int32))
        if int(np.min(flags)) == 0:
            logger.error(
                f"sharded checkpoint {ckpt_dir}: a rank's shard write "
                "failed — leaving `latest` on the previous checkpoint")
            return ckpt_dir
        if jax.process_index() == 0:
            _save_meta_and_latest(save_dir, ckpt_dir, tag, state,
                                  client_state, master_aliases_params)
        return ckpt_dir
    if jax.process_index() != 0:
        return ckpt_dir
    if ckpt_engine is None:
        from ..checkpoint.engine import NpzCheckpointEngine
        ckpt_engine = NpzCheckpointEngine()
    os.makedirs(ckpt_dir, exist_ok=True)
    ckpt_engine.create(tag)
    # async engines must not race donated device buffers: gather to host
    # eagerly (leaf-wise), hand numpy to the writer thread
    lazy = getattr(ckpt_engine, "wants_lazy", True)
    ckpt_engine.save(_tree_to_flat_dict(state.params, lazy=lazy),
                     os.path.join(ckpt_dir, "model_states.npz"))
    ckpt_engine.save(_tree_to_flat_dict(optim_group, lazy=lazy),
                     os.path.join(ckpt_dir, "optim_states.npz"))
    _save_meta_and_latest(save_dir, ckpt_dir, tag, state, client_state,
                          master_aliases_params, ckpt_engine=ckpt_engine)
    return ckpt_dir


def _save_meta_and_latest(save_dir, ckpt_dir, tag, state, client_state,
                          master_aliases_params, ckpt_engine=None) -> None:
    meta = {
        "master_aliases_params": master_aliases_params,
        "sharded": jax.process_count() > 1,
        "num_shards": jax.process_count(),
        "step": int(jax.device_get(state.step)),
        "skipped_steps": int(jax.device_get(state.skipped_steps)),
        "loss_scale": float(jax.device_get(state.scale.scale)),
        "scale_good_steps": int(jax.device_get(state.scale.good_steps)),
        "scale_hysteresis": int(jax.device_get(state.scale.hysteresis)),
        "client_state": client_state or {},
    }
    with open(os.path.join(ckpt_dir, "meta.json"), "w") as f:
        json.dump(meta, f, indent=2)

    def _write_latest():
        with open(os.path.join(save_dir, LATEST_FILE), "w") as f:
            f.write(tag)
        logger.info(f"saved checkpoint {ckpt_dir}")

    if ckpt_engine is None:
        _write_latest()
    else:
        ckpt_engine.run(_write_latest)   # async: FIFO-ordered behind writes


def get_latest_tag(load_dir: str) -> Optional[str]:
    latest = os.path.join(load_dir, LATEST_FILE)
    if not os.path.exists(latest):
        return None
    with open(latest) as f:
        return f.read().strip()


def load_checkpoint(load_dir: str,
                    tag: Optional[str],
                    state,
                    param_shardings=None,
                    master_shardings=None,
                    opt_shardings=None) -> Tuple[Any, Dict[str, Any]]:
    """Load into the structure of ``state`` (shardings reapplied). Returns
    (new_state, client_state)."""
    import jax.numpy as jnp
    if tag is None:
        tag = get_latest_tag(load_dir)
        if tag is None:
            raise FileNotFoundError(f"no 'latest' tag file in {load_dir}")
    ckpt_dir = os.path.join(load_dir, tag)
    with open(os.path.join(ckpt_dir, "meta.json")) as f:
        meta = json.load(f)
    sharded = not os.path.exists(os.path.join(ckpt_dir, "model_states.npz"))

    def _load(base, like, shardings):
        if sharded:
            return load_sharded_tree(ckpt_dir, base, like, shardings,
                                     expected_shards=meta.get("num_shards"))
        return load_tree(os.path.join(ckpt_dir, base + ".npz"), like,
                         shardings)

    params = _load("model_states", state.params, param_shardings)
    if meta.get("master_aliases_params"):
        optim = {"master": params,
                 "opt_state": _load("optim_states",
                                    {"opt_state": state.opt_state},
                                    {"opt_state": opt_shardings}
                                    if opt_shardings is not None else None)["opt_state"]}
    else:
        optim = _load("optim_states",
                      {"master": state.master, "opt_state": state.opt_state},
                      {"master": master_shardings, "opt_state": opt_shardings}
                      if master_shardings is not None else None)
    from .loss_scaler import LossScaleState
    new_state = state.replace(
        step=jnp.asarray(meta["step"], jnp.int32),
        skipped_steps=jnp.asarray(meta["skipped_steps"], jnp.int32),
        params=params,
        master=optim["master"],
        opt_state=optim["opt_state"],
        scale=LossScaleState(
            scale=jnp.asarray(meta["loss_scale"], jnp.float32),
            good_steps=jnp.asarray(meta["scale_good_steps"], jnp.int32),
            hysteresis=jnp.asarray(meta["scale_hysteresis"], jnp.int32)))
    logger.info(f"loaded checkpoint {ckpt_dir} at step {meta['step']}")
    return new_state, meta.get("client_state", {})


def consolidated_fp32_state_dict(state) -> Dict[str, np.ndarray]:
    """Gather master weights to one host fp32 dict (zero_to_fp32 equivalent,
    reference utils/zero_to_fp32.py + _zero3_consolidated_16bit_state_dict)."""
    return _tree_to_flat_dict(state.master)


def save_16bit_model(state, path: str) -> None:
    """reference: engine.save_16bit_model (engine.py:3479)."""
    save_tree(state.params, path)
