"""Profiling: XLA-cost-analysis flops profiler."""

from .flops_profiler import (FlopsProfiler, compiled_cost, get_model_profile,
                             params_breakdown, params_count)

__all__ = ["FlopsProfiler", "compiled_cost", "get_model_profile",
           "params_breakdown", "params_count"]
