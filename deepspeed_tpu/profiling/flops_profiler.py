"""Flops profiler — compiled-program cost analysis instead of module patching.

Capability parity with the reference's ``profiling/flops_profiler/profiler.py``
(1248 LoC of torch.nn.functional monkey-patching to count MACs per module).
On TPU the compiler already knows: XLA's cost analysis reports exact flops /
bytes for the compiled program, so profiling a jitted step is a query, not an
instrumentation pass. Per-module parameter breakdown comes from the params
pytree. The engine hook (`flops_profiler` config section: enabled/profile_step)
mirrors the reference's engine integration (engine.py:1782-1801).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

PyTree = Any


def compiled_cost(fn: Callable, *args, **kwargs) -> Dict[str, float]:
    """FLOPs / memory traffic of jit(fn)(*args) from XLA cost analysis."""
    lowered = jax.jit(fn).lower(*args, **kwargs)
    cost = lowered.compile().cost_analysis()
    if isinstance(cost, list):     # some backends return one dict per program
        cost = cost[0] if cost else {}
    return {
        "flops": float(cost.get("flops", 0.0)),
        "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
        "utilization_keys": len(cost),
    }


def params_count(params: PyTree) -> int:
    return int(sum(np.prod(p.shape) for p in jax.tree.leaves(params)))


def params_breakdown(params: PyTree, depth: int = 2) -> Dict[str, int]:
    """Parameter counts aggregated by path prefix (reference:
    print_model_profile's per-module tree, profiler.py:236)."""
    out: Dict[str, int] = {}
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    for path, leaf in flat:
        keys = []
        for p in path:
            keys.append(str(getattr(p, "key", getattr(p, "idx", p))))
        prefix = "/".join(keys[:depth])
        out[prefix] = out.get(prefix, 0) + int(np.prod(leaf.shape))
    return dict(sorted(out.items(), key=lambda kv: -kv[1]))


class FlopsProfiler:
    """Profile a train/eval step: flops, wall clock, achieved TFLOPS.

    Usage (engine-integrated via the `flops_profiler` config section, or
    standalone):
        prof = FlopsProfiler()
        stats = prof.profile(step_fn, state, batch)
    """

    def __init__(self, model_params: Optional[PyTree] = None):
        self.model_params = model_params
        self.last: Dict[str, float] = {}

    def profile(self, fn: Callable, *args, iters: int = 3, **kwargs) -> Dict:
        cost = compiled_cost(fn, *args, **kwargs)
        compiled = jax.jit(fn)
        out = compiled(*args, **kwargs)          # warmup (compile cached)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = compiled(*args, **kwargs)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / iters
        stats = {
            **cost,
            "latency_s": dt,
            "tflops_achieved": cost["flops"] / dt / 1e12 if dt > 0 else 0.0,
            "bandwidth_gbps": (cost["bytes_accessed"] / dt / 1e9
                               if dt > 0 else 0.0),
        }
        if self.model_params is not None:
            stats["params"] = params_count(self.model_params)
        self.last = stats
        return stats

    def print_model_profile(self, params: Optional[PyTree] = None,
                            depth: int = 2, top_modules: int = 10):
        params = params if params is not None else self.model_params
        lines = ["flops profiler " + "-" * 50]
        if params is not None:
            lines.append(f"params total: {params_count(params):,}")
            for name, n in list(params_breakdown(params, depth).items())[:top_modules]:
                lines.append(f"  {name:<40s} {n:>14,d}")
        for k, v in self.last.items():
            lines.append(f"{k:<20s} {v:,.4g}" if isinstance(v, float)
                         else f"{k:<20s} {v}")
        text = "\n".join(lines)
        print(text)
        return text


def get_model_profile(model, batch, loss_fn=None, train: bool = False):
    """One-call model profiling (reference: get_model_profile profiler.py).

    Returns (flops, macs, params) for a forward pass of `model` on `batch`.
    """
    params = model.init(jax.random.PRNGKey(0), batch)["params"]

    def fwd(p, b):
        out = model.apply({"params": p}, b)
        return loss_fn(out, b) if loss_fn is not None else out

    cost = compiled_cost(fwd, params, batch)
    flops = cost["flops"]
    return flops, flops / 2.0, params_count(params)
