"""Flops profiler — compiled-program cost analysis instead of module patching.

Capability parity with the reference's ``profiling/flops_profiler/profiler.py``
(1248 LoC of torch.nn.functional monkey-patching to count MACs per module).
On TPU the compiler already knows: XLA's cost analysis reports exact flops /
bytes for the compiled program, so profiling a jitted step is a query, not an
instrumentation pass. Per-module parameter breakdown comes from the params
pytree. The engine hook (`flops_profiler` config section: enabled/profile_step)
mirrors the reference's engine integration (engine.py:1782-1801).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

PyTree = Any


def compiled_cost(fn: Callable, *args, **kwargs) -> Dict[str, float]:
    """FLOPs / memory traffic of jit(fn)(*args) from XLA cost analysis."""
    lowered = jax.jit(fn).lower(*args, **kwargs)
    cost = lowered.compile().cost_analysis()
    if isinstance(cost, list):     # some backends return one dict per program
        cost = cost[0] if cost else {}
    return {
        "flops": float(cost.get("flops", 0.0)),
        "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
        "utilization_keys": len(cost),
    }


def params_count(params: PyTree) -> int:
    return int(sum(np.prod(p.shape) for p in jax.tree.leaves(params)))


def params_breakdown(params: PyTree, depth: int = 2) -> Dict[str, int]:
    """Parameter counts aggregated by path prefix (reference:
    print_model_profile's per-module tree, profiler.py:236)."""
    out: Dict[str, int] = {}
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    for path, leaf in flat:
        keys = []
        for p in path:
            keys.append(str(getattr(p, "key", getattr(p, "idx", p))))
        prefix = "/".join(keys[:depth])
        out[prefix] = out.get(prefix, 0) + int(np.prod(leaf.shape))
    return dict(sorted(out.items(), key=lambda kv: -kv[1]))


def _dot_flops(eqn) -> float:
    """2*M*N*K (x batch dims) for a dot_general equation."""
    dnums = eqn.params["dimension_numbers"]
    (lc, rc), (lb, rb) = dnums
    a, b = eqn.invars[0].aval, eqn.invars[1].aval
    batch = float(np.prod([a.shape[d] for d in lb], initial=1.0))
    k = float(np.prod([a.shape[d] for d in lc], initial=1.0))
    m = float(np.prod([a.shape[d] for d in range(a.ndim)
                       if d not in lc and d not in lb], initial=1.0))
    n = float(np.prod([b.shape[d] for d in range(b.ndim)
                       if d not in rc and d not in rb], initial=1.0))
    return 2.0 * batch * m * n * k


def _conv_flops(eqn) -> float:
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval            # kernel
    out_elems = float(np.prod(out.shape))
    # kernel work per output element = in_ch * spatial = total / out_ch;
    # the out-channel position comes from dimension_numbers (OIHW default
    # puts it FIRST, so shape[-1] would divide by a spatial dim)
    out_ch_dim = eqn.params["dimension_numbers"].rhs_spec[0]
    per_out = float(np.prod(rhs.shape)) / max(float(rhs.shape[out_ch_dim]), 1.0)
    return 2.0 * out_elems * per_out


def module_flops_breakdown(fn: Callable, *args, depth: int = 2,
                           **kwargs) -> Dict[str, float]:
    """Per-module FLOPS tree from the jaxpr's name stack.

    The reference gets its per-module MAC tree by monkey-patching
    ``torch.nn.functional`` (profiler.py:805 ``_patch_functionals``); under
    jax the traced program already carries the flax module path on every
    equation (``source_info.name_stack``), so the tree falls out of a jaxpr
    walk: dot/conv flops attributed to ``name_stack[:depth]``, scan bodies
    multiplied by trip count. Elementwise flops are not counted (matmuls
    dominate; XLA fuses the rest), so totals slightly undercount vs
    ``compiled_cost`` — use both: this for WHERE, that for the exact total.
    """
    jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)

    def scope(eqn) -> str:
        names = [getattr(e, "name", str(e))
                 for e in getattr(eqn.source_info.name_stack, "stack", ())]
        return "/".join(names[:depth]) if names else "<toplevel>"

    def add(acc, key, val):
        acc[key] = acc.get(key, 0.0) + val

    def walk(jxp, mult: float, acc: Dict[str, float]):
        for eqn in jxp.eqns:
            prim = eqn.primitive.name
            if prim == "dot_general":
                add(acc, scope(eqn), mult * _dot_flops(eqn))
            elif prim == "conv_general_dilated":
                add(acc, scope(eqn), mult * _conv_flops(eqn))
            elif prim == "scan":
                walk(eqn.params["jaxpr"].jaxpr, mult * eqn.params["length"],
                     acc)
            elif prim == "while":
                # trip count is dynamic; count one iteration
                walk(eqn.params["body_jaxpr"].jaxpr, mult, acc)
            elif prim == "cond":
                # exactly one branch executes: charge the costliest one
                branches = []
                for br in eqn.params["branches"]:
                    sub: Dict[str, float] = {}
                    walk(br.jaxpr, mult, sub)
                    branches.append(sub)
                for k, v in max(branches, default={},
                                key=lambda d: sum(d.values())).items():
                    add(acc, k, v)
            else:
                def recurse(v):
                    if isinstance(v, jax.extend.core.ClosedJaxpr):
                        walk(v.jaxpr, mult, acc)
                    elif hasattr(v, "eqns"):
                        walk(v, mult, acc)
                    elif isinstance(v, (tuple, list)):
                        for item in v:
                            recurse(item)
                for v in eqn.params.values():
                    recurse(v)

    out: Dict[str, float] = {}
    walk(jaxpr.jaxpr, 1.0, out)
    return dict(sorted(out.items(), key=lambda kv: -kv[1]))


class FlopsProfiler:
    """Profile a train/eval step: flops, wall clock, achieved TFLOPS.

    Usage (engine-integrated via the `flops_profiler` config section, or
    standalone):
        prof = FlopsProfiler()
        stats = prof.profile(step_fn, state, batch)
    """

    def __init__(self, model_params: Optional[PyTree] = None):
        self.model_params = model_params
        self.last: Dict[str, float] = {}

    def profile(self, fn: Callable, *args, iters: int = 3, **kwargs) -> Dict:
        cost = compiled_cost(fn, *args, **kwargs)
        compiled = jax.jit(fn)
        out = compiled(*args, **kwargs)          # warmup (compile cached)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = compiled(*args, **kwargs)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / iters
        stats = {
            **cost,
            "latency_s": dt,
            "tflops_achieved": cost["flops"] / dt / 1e12 if dt > 0 else 0.0,
            "bandwidth_gbps": (cost["bytes_accessed"] / dt / 1e9
                               if dt > 0 else 0.0),
        }
        if self.model_params is not None:
            stats["params"] = params_count(self.model_params)
        self.last = stats
        return stats

    def print_model_profile(self, params: Optional[PyTree] = None,
                            depth: int = 2, top_modules: int = 10,
                            fn: Optional[Callable] = None, fn_args=()):
        """reference: print_model_profile (profiler.py:236) — per-module
        params and, when `fn` is given, per-module FLOPS with % of total."""
        params = params if params is not None else self.model_params
        lines = ["flops profiler " + "-" * 50]
        if params is not None:
            lines.append(f"params total: {params_count(params):,}")
            for name, n in list(params_breakdown(params, depth).items())[:top_modules]:
                lines.append(f"  {name:<40s} {n:>14,d}")
        if fn is not None:
            tree = module_flops_breakdown(fn, *fn_args, depth=depth)
            total = sum(tree.values()) or 1.0
            lines.append(f"flops by module (dot/conv, analytic):")
            for name, f in list(tree.items())[:top_modules]:
                lines.append(f"  {name:<40s} {f:>14,.3e}  "
                             f"{100.0 * f / total:5.1f}%")
        for k, v in self.last.items():
            lines.append(f"{k:<20s} {v:,.4g}" if isinstance(v, float)
                         else f"{k:<20s} {v}")
        text = "\n".join(lines)
        print(text)
        return text


def get_model_profile(model, batch, loss_fn=None, train: bool = False):
    """One-call model profiling (reference: get_model_profile profiler.py).

    Returns (flops, macs, params) for a forward pass of `model` on `batch`.
    """
    params = model.init(jax.random.PRNGKey(0), batch)["params"]

    def fwd(p, b):
        out = model.apply({"params": p}, b)
        return loss_fn(out, b) if loss_fn is not None else out

    cost = compiled_cost(fwd, params, batch)
    flops = cost["flops"]
    return flops, flops / 2.0, params_count(params)
