"""Parallel experiment scheduler with resource reservations.

Role of the reference's ``autotuning/scheduler.py`` (ResourceManager +
run_job: experiments scheduled concurrently onto reserved node/GPU slots,
reference scheduler.py:114,319). TPU shape:

  * a **slot** is whatever one experiment needs — a chip set on this host
    (``{"devices": "0"}``), a remote host (``{"host": ...}``), or an
    abstract token for in-process runs. Slots are leased exclusively for
    the experiment's lifetime and returned on completion or failure.
  * experiments run on a thread per leased slot; the runner receives the
    slot so it can pin the launch (e.g. set JAX_VISIBLE_DEVICES / ssh to
    the host).
  * **losing configs are killed early**: once a config completes, any
    still-running experiment that exceeds ``kill_factor x`` the best
    completed wall time is aborted (slow configs are losing configs — the
    scheduler reclaims their slots instead of waiting out a 30x-slower
    OOM-thrash run). Runners observe this via the ``deadline`` callable
    they receive; the subprocess runner enforces it as a hard timeout.

The tuner loop stays waved: up to ``len(slots)`` candidates run
concurrently, results feed the (thread-safe) model-based tuner between
waves, so surrogate feedback still steers the search.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..utils.logging import logger


class ResourceManager:
    """Exclusive lease of experiment slots (reference ResourceManager:
    nodes + reservations; here a thread-safe free list)."""

    def __init__(self, slots: List[Dict[str, Any]]):
        if not slots:
            raise ValueError("need at least one resource slot")
        self._free: "queue.Queue[Dict]" = queue.Queue()
        for s in slots:
            self._free.put(dict(s))
        self.n_slots = len(slots)

    def acquire(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        return self._free.get(timeout=timeout)

    def release(self, slot: Dict[str, Any]) -> None:
        self._free.put(slot)


class ParallelScheduler:
    """Run a wave of experiments concurrently over the slot pool.

    runner(config, slot, deadline) -> metrics dict. ``deadline()`` returns
    the remaining seconds before this experiment is considered a losing
    config (None = no bound yet); runners should pass it to their
    subprocess timeout or poll it between steps.
    """

    def __init__(self, runner: Callable[..., Optional[Dict[str, float]]],
                 slots: List[Dict[str, Any]],
                 kill_factor: float = 3.0,
                 min_kill_time: float = 60.0):
        self.rm = ResourceManager(slots)
        self.runner = runner
        self.kill_factor = kill_factor
        self.min_kill_time = min_kill_time
        self._lock = threading.Lock()
        self._best_time: Optional[float] = None

    def _deadline_fn(self, started: float):
        def remaining() -> Optional[float]:
            with self._lock:
                if self._best_time is None:
                    return None
                budget = max(self.kill_factor * self._best_time,
                             self.min_kill_time)
            return budget - (time.monotonic() - started)
        return remaining

    def run_wave(self, experiments: List[Any]) -> None:
        """Run a list of Experiment objects (config/metrics/error fields)
        to completion, at most n_slots concurrently."""
        threads = []

        import inspect
        try:
            params = inspect.signature(self.runner).parameters.values()
            slot_aware = (len(params) >= 3 or any(
                p.kind in (inspect.Parameter.VAR_POSITIONAL,
                           inspect.Parameter.VAR_KEYWORD) for p in params))
        except (TypeError, ValueError):
            slot_aware = True

        def work(exp):
            slot = self.rm.acquire()
            started = time.monotonic()
            try:
                exp.slot = dict(slot)
                if slot_aware:
                    exp.metrics = self.runner(exp.config, slot,
                                              self._deadline_fn(started))
                else:
                    # slot-unaware runner (the in-process engine runner)
                    exp.metrics = self.runner(exp.config)
                elapsed = time.monotonic() - started
                with self._lock:
                    if exp.metrics is not None and (
                            self._best_time is None
                            or elapsed < self._best_time):
                        self._best_time = elapsed
            except Exception as e:       # OOM / kill / invalid composition
                exp.error = f"{type(e).__name__}: {e}"
                logger.warning("autotuning experiment %s failed: %s",
                               exp.name, exp.error[:200])
            finally:
                self.rm.release(slot)

        for exp in experiments:
            t = threading.Thread(target=work, args=(exp,), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()


def local_chip_slots(devices_per_slot: int = 0) -> List[Dict[str, Any]]:
    """Slot map for this host's visible accelerator(s): one slot per chip
    group (0 = all chips in one slot — the single-chip case)."""
    import jax
    n = len(jax.devices())
    if devices_per_slot <= 0 or devices_per_slot >= n:
        return [{"devices": ",".join(str(i) for i in range(n))}]
    if n % devices_per_slot:
        logger.warning(
            "local_chip_slots: %d chips do not divide into slots of %d — "
            "the last %d chip(s) stay unassigned", n, devices_per_slot,
            n % devices_per_slot)
    return [{"devices": ",".join(str(j) for j in range(i,
                                                       i + devices_per_slot))}
            for i in range(0, n - devices_per_slot + 1, devices_per_slot)]
