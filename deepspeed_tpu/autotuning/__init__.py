"""deepspeed_tpu.autotuning — automatic ds_config search.

reference: deepspeed/autotuning/ (Autotuner + tuner/ search strategies +
scheduler.py experiment runner).
"""

from .autotuner import (Autotuner, Experiment, GridSearchTuner, ModelBasedTuner, RandomTuner,
                        engine_runner, subprocess_runner)

__all__ = ["Autotuner", "Experiment", "GridSearchTuner", "ModelBasedTuner", "RandomTuner",
           "engine_runner", "subprocess_runner"]
