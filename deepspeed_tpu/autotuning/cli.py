"""ds_autotune CLI — script-mode autotuning entry point.

reference: `deepspeed --autotuning run user_script.py ...` (autotuning/README
flow). Usage:

    ds_autotune --config base_ds_config.json [--tuner gridsearch]
        [--mbs 1,2,4,8] [--stages 0,1,2,3] [--remat] [--trials 50]
        [--early-stopping 5] [--results-dir autotuning_results]
        -- python train.py --my-args ...

The command after ``--`` is launched once per experiment with
``--deepspeed_config <exp.json>`` appended; the engine writes its measured
throughput to $DS_AUTOTUNING_METRIC_FILE after autotuning.end_profile_step
and exits (runtime/engine.py _autotuning_hook).
"""

from __future__ import annotations

import argparse
import json
import sys

from .autotuner import Autotuner, default_tuning_space, subprocess_runner


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--" in argv:
        split = argv.index("--")
        argv, cmd = argv[:split], argv[split + 1:]
    else:
        cmd = []
    p = argparse.ArgumentParser(prog="ds_autotune")
    p.add_argument("--config", required=True, help="base ds_config json")
    p.add_argument("--tuner", default="gridsearch",
                   choices=["gridsearch", "random", "model"])
    p.add_argument("--mbs", default="", help="micro batch sizes, comma-sep")
    p.add_argument("--stages", default="", help="zero stages, comma-sep")
    p.add_argument("--remat", action="store_true",
                   help="also try activation checkpointing on")
    p.add_argument("--trials", type=int, default=50)
    p.add_argument("--early-stopping", type=int, default=0)
    p.add_argument("--exps-dir", default="autotuning_exps")
    p.add_argument("--results-dir", default="autotuning_results")
    p.add_argument("--timeout", type=int, default=1800)
    args = p.parse_args(argv)
    if not cmd:
        p.error("pass the training command after '--'")

    with open(args.config) as f:
        base = json.load(f)
    space = default_tuning_space(
        base,
        micro_batch_sizes=([int(x) for x in args.mbs.split(",")]
                           if args.mbs else None),
        zero_stages=([int(x) for x in args.stages.split(",")]
                     if args.stages else None),
        remat=[False, True] if args.remat else [False])
    tuner = Autotuner(base, subprocess_runner(cmd, args.exps_dir,
                                              args.timeout),
                      tuning_space=space, tuner_type=args.tuner,
                      num_trials=args.trials,
                      early_stopping=args.early_stopping,
                      results_dir=args.results_dir)
    exps = tuner.tune()
    best = tuner.best()
    print(f"ran {len(exps)} experiments; results in {args.results_dir}")
    if best is not None and best.metrics is not None:
        print(f"best: {best.name} -> {best.metrics}")
    else:
        print("no experiment succeeded")
        sys.exit(1)


if __name__ == "__main__":
    main()
